"""MemoryManager — the per-coordinator HBM budget authority.

Reference: the compute-node memory controller (src/compute/src/memory/
controller.rs) — a control loop that watches total memory against a
budget and tells the executor LRU caches how far to evict. Here the loop
runs at barrier collection (meta/barrier_manager.py calls `on_barrier`
once per completed epoch, when every executor is idle between epochs):

  * accounting is ALWAYS on — `state_bytes()` is pure host arithmetic
    over static pytree shapes, so per-executor and global gauges update
    every barrier at zero device cost;
  * eviction runs only when `hbm_budget_bytes > 0` and
    `memory_eviction_policy == 'lru'`: the worst offenders (largest
    accounted state) are asked to `memory_evict(target_bytes, epoch)`
    until the overage is covered, and occupancy-driven participants
    (dense sorted stores with fixed capacity) get a `memory_maintain`
    tick to spill ahead of their overflow cliff.

Participants are duck-typed executors:
  state_bytes() -> int                      required (registration key)
  memory_evict(target, epoch) -> int freed  optional (budget eviction)
  memory_maintain(epoch) -> None            optional (occupancy spill)
  memory_enable_lru() -> None               optional (start LRU tracking)
plus optional counters read for reports: mem_evicted_bytes,
mem_reload_count, mem_spilled_rows.
"""

from __future__ import annotations

from typing import Optional

from ..utils.metrics import (
    GLOBAL_METRICS, HBM_BUDGET_BYTES, HBM_EVICTED_BYTES, HBM_EVICTIONS,
    HBM_GUARD_PROTECTED, HBM_RELOADS, HBM_SPILLED_ROWS, HBM_STATE_BYTES,
)
from .accounting import format_bytes

POLICY_LRU = "lru"
POLICY_NONE = "none"


class ReloadGuard:
    """Reload-LFU guard (ROADMAP open item): probe-hot-but-never-dirty
    keys look cold to the dirty-bitmap LRU — they get evicted, the next
    probe reloads them, their fresh stamp ages out, and the cycle
    repeats, thrashing the host spill. The guard tracks read-through
    reloads per (executor scope, key); a key reloaded >= `threshold`
    times within the last `window` barriers is EXEMPT from the next
    eviction round — the executor keeps it device-resident (re-inserts
    it) instead of spilling.

    `scope` is any hashable the executor chooses (hash_agg uses
    `id(self)`, hash_join `(id(self), side)`) so key tuples never
    collide across executors or join sides. `window=0` disables the
    guard."""

    _MAX_EVENTS_PER_KEY = 4

    def __init__(self, window: int = 8, threshold: int = 2):
        self.window = int(window)
        self.threshold = int(threshold)
        self._seq = 0
        self._events: dict = {}       # scope -> {key: [barrier seq, ...]}
        self.protected_total = 0

    def on_barrier(self) -> None:
        self._seq += 1
        if self.window > 0 and self._seq % (2 * self.window) == 0:
            self._prune()

    def note(self, scope, keys) -> None:
        """Record a read-through reload of `keys` in `scope`."""
        if self.window <= 0:
            return
        d = self._events.setdefault(scope, {})
        for k in keys:
            lst = d.setdefault(k, [])
            lst.append(self._seq)
            if len(lst) > self._MAX_EVENTS_PER_KEY:
                del lst[:-self._MAX_EVENTS_PER_KEY]

    def is_protected(self, scope, key) -> bool:
        if self.window <= 0:
            return False
        lst = self._events.get(scope, {}).get(key)
        if not lst:
            return False
        lo = self._seq - self.window
        return sum(1 for s in lst if s >= lo) >= self.threshold

    def note_protected(self, n: int = 1) -> None:
        self.protected_total += n
        HBM_GUARD_PROTECTED.inc(n)

    def _prune(self) -> None:
        lo = self._seq - self.window
        for scope in list(self._events):
            d = self._events[scope]
            for k in [k for k, lst in d.items() if lst[-1] < lo]:
                del d[k]
            if not d:
                del self._events[scope]


def partition_budget(total_bytes: int, n_workers: int) -> int:
    """Cluster HBM budget -> per-worker share (cluster/meta_service.py):
    a cluster-level `SET hbm_budget_bytes` is an even split over the
    live compute nodes — contiguous vnode ranges give every worker the
    same expected state share, so an even split is the placement-
    matched policy. 0 (accounting only) stays 0 everywhere."""
    if total_bytes <= 0:
        return 0
    return max(1, int(total_bytes) // max(1, n_workers))


class MemoryManager:
    def __init__(self, budget_bytes: int = 0, policy: str = POLICY_LRU,
                 guard_window: int = 8, guard_threshold: int = 2):
        self.budget_bytes = int(budget_bytes)
        self.policy = policy
        self._participants: dict[str, object] = {}
        self.evictions = 0
        # reload-LFU guard shared by every participant (set on them as
        # `mem_guard` at registration)
        self.reload_guard = ReloadGuard(guard_window, guard_threshold)

    # ---------------------------------------------------------- config
    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0 and self.policy == POLICY_LRU

    def configure(self, budget_bytes: Optional[int] = None,
                  policy: Optional[str] = None) -> None:
        """SET hbm_budget_bytes / memory_eviction_policy (the ALTER SYSTEM
        analogue). Enabling starts LRU tracking on every registered
        participant; disabling stops NEW evictions but already-spilled
        state keeps its read-through reload path (dropping it would lose
        exactness)."""
        was = self.enabled
        if budget_bytes is not None:
            self.budget_bytes = int(budget_bytes)
        if policy is not None:
            if policy not in (POLICY_LRU, POLICY_NONE):
                raise ValueError(
                    f"unknown memory_eviction_policy {policy!r} "
                    f"(expected 'lru' or 'none')")
            self.policy = policy
        HBM_BUDGET_BYTES.set(float(self.budget_bytes))
        if self.enabled and not was:
            for p in self._participants.values():
                enable = getattr(p, "memory_enable_lru", None)
                if enable is not None:
                    enable()

    # ------------------------------------------------------ registration
    def register(self, name: str, participant) -> str:
        """Register a stateful executor; returns the (uniquified) name
        used for per-executor metrics and EXPLAIN output."""
        base, i = name, 1
        while name in self._participants:
            i += 1
            name = f"{base}#{i}"
        self._participants[name] = participant
        try:
            participant.mem_guard = self.reload_guard
        except AttributeError:
            pass
        if self.enabled:
            enable = getattr(participant, "memory_enable_lru", None)
            if enable is not None:
                enable()
        return name

    def unregister(self, name: str) -> None:
        p = self._participants.pop(name, None)
        if p is not None:
            # drop the labelled series entirely — a dead executor must
            # not linger in every future scrape
            GLOBAL_METRICS.remove("hbm_state_bytes", executor=name)

    # --------------------------------------------------------- reporting
    def total_bytes(self) -> int:
        return sum(p.state_bytes() for p in self._participants.values())

    def report(self) -> list[dict]:
        """Per-executor accounting rows (\\metrics / EXPLAIN / SHOW)."""
        rows = []
        for name, p in sorted(self._participants.items(),
                              key=lambda kv: -kv[1].state_bytes()):
            row = {
                "executor": name,
                "state_bytes": p.state_bytes(),
                "evicted_bytes": int(getattr(p, "mem_evicted_bytes", 0)),
                "reload_count": int(getattr(p, "mem_reload_count", 0)),
                "spilled_rows": int(getattr(p, "mem_spilled_rows", 0)),
                "guard_protected": int(
                    getattr(p, "mem_guard_protected", 0)),
            }
            # mesh-sharded executors split their state evenly over the
            # device mesh: surface the per-shard (= per-device HBM) share
            shards = int(getattr(p, "mem_shards", 0) or 0)
            if shards > 1:
                row["shards"] = shards
                row["shard_bytes"] = row["state_bytes"] // shards
            rows.append(row)
        return rows

    def render(self) -> list[str]:
        lines = [f"hbm budget: "
                 f"{format_bytes(self.budget_bytes) if self.budget_bytes else 'unset'}"
                 f" policy: {self.policy} "
                 f"total: {format_bytes(self.total_bytes())}"]
        for r in self.report():
            shards = (f" shards={r['shards']}x"
                      f"{format_bytes(r['shard_bytes'])}"
                      if r.get("shards") else "")
            lines.append(
                f"  {r['executor']}: state={format_bytes(r['state_bytes'])} "
                f"evicted={format_bytes(r['evicted_bytes'])} "
                f"reloads={r['reload_count']} "
                f"spilled_rows={r['spilled_rows']} "
                f"guard_protected={r['guard_protected']}{shards}")
        return lines

    # ------------------------------------------------------ control loop
    def on_barrier(self, epoch: int) -> None:
        """Barrier-collection hook: refresh gauges; under an exceeded
        budget, ask the worst offenders to evict. Runs synchronously on
        the event loop with every executor idle between epochs — eviction
        dispatches device programs and (rarely) blocks on a packed d2h
        fetch, exactly the per-barrier transfer discipline the watchdogs
        already follow."""
        if not self._participants:
            return
        self.reload_guard.on_barrier()
        total = 0
        spilled = 0
        for name, p in self._participants.items():
            b = p.state_bytes()
            total += b
            spilled += int(getattr(p, "mem_spilled_rows", 0))
            GLOBAL_METRICS.gauge("hbm_state_bytes", executor=name).set(
                float(b))
        HBM_STATE_BYTES.set(float(total))
        HBM_SPILLED_ROWS.set(float(spilled))
        HBM_BUDGET_BYTES.set(float(self.budget_bytes))
        if not self.enabled:
            return
        # occupancy-driven participants spill ahead of their cliff even
        # when the global budget still has headroom
        for p in self._participants.values():
            maintain = getattr(p, "memory_maintain", None)
            if maintain is not None:
                maintain(epoch)
        over = total - self.budget_bytes
        if over <= 0:
            return
        # worst offenders first (largest accounted state)
        for name, p in sorted(self._participants.items(),
                              key=lambda kv: -kv[1].state_bytes()):
            evict = getattr(p, "memory_evict", None)
            if evict is None:
                continue
            freed = int(evict(over, epoch) or 0)
            if freed > 0:
                self.evictions += 1
                HBM_EVICTIONS.inc()
                HBM_EVICTED_BYTES.inc(freed)
                over -= freed
            if over <= 0:
                break

    def note_reload(self, n_keys: int) -> None:
        """Executors report read-through reloads here (process counter;
        their own mem_reload_count feeds the per-executor report)."""
        HBM_RELOADS.inc(n_keys)

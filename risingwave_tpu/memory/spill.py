"""Host spill store — the landing zone for evicted device state.

One `HostSpill` per executor (per side, for joins): evicted rows are
fetched off the device ONCE (the packed-d2h discipline of utils/d2h.py)
and parked here keyed by the executor's logical key tuple, so a later
touch of an evicted key is a dict lookup, not a store scan. The durable
StateTable keeps its own copy of every spilled row (they were persisted
at the barrier that last dirtied them and eviction never deletes them),
which is what makes crash recovery exact: `recover()` rebuilds the FULL
state — resident and spilled — from the committed store, and the spill
dict is simply dropped.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional


class HostSpill:
    """key tuple -> list of row payload tuples (one for single-row-per-key
    executors like HashAgg, many for multimap executors like joins)."""

    def __init__(self):
        self._d: dict[tuple, list[tuple]] = {}
        self.rows = 0                    # payload rows currently parked

    def __len__(self) -> int:
        return len(self._d)

    def __bool__(self) -> bool:
        return bool(self._d)

    def __contains__(self, key: tuple) -> bool:
        return key in self._d

    def keys(self) -> Iterator[tuple]:
        return iter(self._d)

    def add(self, key: tuple, row: tuple) -> None:
        """Append one payload row under `key` (multimap semantics)."""
        self._d.setdefault(key, []).append(row)
        self.rows += 1

    def set(self, key: tuple, row: tuple) -> None:
        """Replace the payload for `key` (single-row semantics)."""
        prev = self._d.get(key)
        if prev is not None:
            self.rows -= len(prev)
        self._d[key] = [row]
        self.rows += 1

    def pop(self, key: tuple) -> list[tuple]:
        rows = self._d.pop(key, [])
        self.rows -= len(rows)
        return rows

    def take_touched(self, keys: Iterable[tuple]) -> dict[tuple, list[tuple]]:
        """Pop every spilled key present in `keys` (the read-through
        reload set for one drain). Dedups on the way."""
        out: dict[tuple, list[tuple]] = {}
        for k in keys:
            if k in self._d and k not in out:
                out[k] = self.pop(k)
        return out

    def purge(self, pred) -> list[tuple[tuple, list[tuple]]]:
        """Drop every (key, rows) where pred(key, rows) — watermark state
        cleaning of evicted ranges. Returns what was dropped so the caller
        can write the matching durable tombstones."""
        dead = [(k, rows) for k, rows in self._d.items() if pred(k, rows)]
        for k, rows in dead:
            del self._d[k]
            self.rows -= len(rows)
        return dead

    def clear(self) -> None:
        self._d.clear()
        self.rows = 0

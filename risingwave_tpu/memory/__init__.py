"""HBM memory management — accounting, LRU group eviction, host spill.

Reference analogues: `EstimateSize` accounting (src/common/src/
estimate_size/), the executor LRU caches (src/stream/src/cache/) and the
compute-node memory controller (src/compute/src/memory/) — collapsed here
into one subsystem sized for device-resident state: every stateful
executor reports the EXACT byte size of its jax state pytree, a
`MemoryManager` aggregates per-flow and globally, and when the total
crosses `hbm_budget_bytes` the coldest key groups spill to host with
transparent read-through reload.
"""

from .accounting import format_bytes, pytree_bytes
from .manager import MemoryManager
from .spill import HostSpill

__all__ = ["MemoryManager", "HostSpill", "pytree_bytes", "format_bytes"]

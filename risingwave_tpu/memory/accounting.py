"""Exact state-size accounting for device-resident pytrees.

Reference: src/common/src/estimate_size/ — RisingWave ESTIMATES heap sizes
because Rust collections hide their allocation; here every executor's
state is a jax pytree of fixed-shape arrays, so the size is EXACT:
sum(prod(shape) * itemsize) over the leaves. No estimation, no sampling.
"""

from __future__ import annotations

import math

import jax
import numpy as np


def pytree_bytes(tree) -> int:
    """Exact byte size of every array leaf in `tree` (host scalars and
    non-array leaves count 0). Pure host arithmetic over static shapes —
    never touches the device or forces a transfer."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += math.prod(shape) * np.dtype(dtype).itemsize
    return total


def format_bytes(n: int) -> str:
    """Human-readable bytes for EXPLAIN / \\metrics output."""
    f = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(f) < 1024.0 or unit == "GiB":
            return f"{f:.1f}{unit}" if unit != "B" else f"{int(f)}B"
        f /= 1024.0
    return f"{f:.1f}GiB"

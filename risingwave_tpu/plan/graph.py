"""Fragment-graph IR — the declarative seam between planning and execution.

Reference: `StreamNode` proto (proto/stream_plan.proto:730) is THE contract
between the frontend planner and the stream engine; fragments are the plan
cut at Exchange nodes (stream_fragmenter/mod.rs:116), each deployed as N
parallel actors over vnode bitmaps (proto/stream_plan.proto:834-876).

TPU build keeps the same shape, python-native: a `StreamGraph` of
`Fragment`s; each fragment is a tree of `Node`s (executor specs) whose
leaves may be `Exchange` refs consuming an upstream fragment's output.
`build_graph` (build.py) is the `from_proto`-style registry
(from_proto/mod.rs:105-126) that instantiates executors, channels,
dispatchers, actors, and state tables from this IR — the plugin seam every
later feature (frontend, scaling mutations, multi-host deploy) targets.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Union


@dataclass(frozen=True)
class Exchange:
    """Leaf input consuming the output of an upstream fragment."""

    upstream: int  # fragment id


@dataclass
class Node:
    """One executor spec: `kind` selects a registered builder, `args` are
    its kwargs (expression objects welcome — this IR is in-process; the
    wire form serializes them like expr.proto when remote deploy lands)."""

    kind: str
    args: dict = field(default_factory=dict)
    inputs: tuple[Union["Node", Exchange], ...] = ()

    def __post_init__(self):
        self.inputs = tuple(self.inputs)


@dataclass
class Fragment:
    """A pipeline-local executor tree plus its OUTPUT dispatch strategy.

    parallelism > 1 instantiates the tree once per actor; hash dispatch
    partitions by vnode(dist_keys) across the actor set, and every
    consumer of a parallel fragment merges its actors' outputs with
    barrier alignment (dispatch.rs / merge.rs semantics)."""

    fid: int
    root: Node
    dispatch: str = "simple"            # simple | broadcast | hash
    dist_key_indices: tuple[int, ...] = ()
    parallelism: int = 1
    # "host:port" of a fragment worker process — the build places this
    # fragment there over the DCN tier (stream/remote_fragment.py)
    remote_worker: object = None

    def __post_init__(self):
        assert self.dispatch in ("simple", "broadcast", "hash")
        if self.dispatch == "hash":
            assert self.dist_key_indices, "hash dispatch needs dist keys"
        assert self.parallelism >= 1


@dataclass
class StreamGraph:
    fragments: dict[int, Fragment] = field(default_factory=dict)

    def add(self, fragment: Fragment) -> Fragment:
        assert fragment.fid not in self.fragments
        self.fragments[fragment.fid] = fragment
        return fragment

    def edges(self) -> list[tuple[int, int, int]]:
        """(up_fid, down_fid, k) per Exchange LEAF, where k numbers the
        occurrences of the same (up, down) pair — a fragment may consume
        one upstream through several inputs (self-join), and each such
        edge needs its own channel set. Leaf order is the pre-order walk
        of each fragment tree (the same order build_graph walks)."""
        out: list[tuple[int, int, int]] = []
        for f in self.fragments.values():
            seen: dict[int, int] = {}

            def walk(n):
                if isinstance(n, Exchange):
                    k = seen.get(n.upstream, 0)
                    seen[n.upstream] = k + 1
                    out.append((n.upstream, f.fid, k))
                    return
                for i in n.inputs:
                    walk(i)
            walk(f.root)
        return out

    def consumers(self, fid: int) -> list[tuple[int, int]]:
        """(down_fid, k) edges consuming fragment `fid`, in edge order."""
        return [(d, k) for u, d, k in self.edges() if u == fid]

    def topo_order(self) -> list[int]:
        """Upstream-first order (DAG check included)."""
        deps: dict[int, set[int]] = {}
        for fid, f in self.fragments.items():
            ups: set[int] = set()

            def walk(n):
                if isinstance(n, Exchange):
                    ups.add(n.upstream)
                    return
                for i in n.inputs:
                    walk(i)
            walk(f.root)
            deps[fid] = ups
        out: list[int] = []
        seen: set[int] = set()
        visiting: set[int] = set()

        def visit(fid: int):
            if fid in seen:
                return
            if fid in visiting:
                raise ValueError(f"cycle through fragment {fid}")
            visiting.add(fid)
            for up in sorted(deps[fid]):
                visit(up)
            visiting.discard(fid)
            seen.add(fid)
            out.append(fid)
        for fid in sorted(self.fragments):
            visit(fid)
        return out


def render_node(node, depth: int = 0) -> list:
    """Plan-node tree as indented text (EXPLAIN + plan goldens)."""
    if isinstance(node, Exchange):
        return [f"{'  ' * depth}exchange({node.upstream})"]
    extra = ""
    if node.kind in ("sorted_join", "hash_join"):
        extra = (f" lkeys={node.args['left_key_indices']}"
                 f" rkeys={node.args['right_key_indices']}")
    if node.kind == "project":
        extra = f" names={node.args.get('names')}"
    out = [f"{'  ' * depth}{node.kind}{extra}"]
    for i in node.inputs:
        out.extend(render_node(i, depth + 1))
    return out


def render_graph(graph: "StreamGraph") -> list:
    """Whole fragment graph as text lines (reference: EXPLAIN output /
    the planner_test YAML snapshots, frontend/planner_test)."""
    lines = []
    for fid in sorted(graph.fragments):
        f = graph.fragments[fid]
        remote = (f" remote={f.remote_worker}"
                  if getattr(f, "remote_worker", None) else "")
        lines.append(
            f"fragment {fid} dispatch={f.dispatch} "
            f"parallelism={f.parallelism} "
            f"dist={tuple(f.dist_key_indices)}{remote}")
        for ln in render_node(f.root, 1):
            lines.append(ln)
    return lines

from .graph import Exchange, Fragment, Node, StreamGraph
from .build import BUILDERS, BuildEnv, Deployment, build_graph, register_builder

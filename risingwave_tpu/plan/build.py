"""Fragment-graph builder — the `from_proto` registry seam.

Reference: from_proto/mod.rs:105-126 (41-way `NodeBody` -> ExecutorBuilder
match) + LocalStreamManager::build_actors (task/stream_manager.rs:253):
recursively instantiate executors from the plan, wrap the fragment root in
its dispatcher, spawn actors, register everything with the barrier manager.

Deployment model (v1, single process): each fragment becomes
`parallelism` actors; inter-fragment edges are bounded channels; hash
dispatch partitions rows by vnode(dist_keys) across the consumer's actors
with the contiguous vnode->actor mapping (parallel/mesh.py); a consumer of
a parallel fragment merges with barrier alignment. State tables of a
parallel stateful fragment share one table id and split the vnode space by
bitmap — exactly the reference's vnode-partitioned state contract.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np

from ..common.types import DataType, Field as SchemaField, Schema
from ..common.vnode import VNODE_COUNT
from ..meta.barrier_manager import BarrierCoordinator
from ..parallel.mesh import shard_vnode_bitmaps, vnode_to_shard
from ..state.state_table import StateTable
from ..state.store import StateStore
from ..stream import (
    Actor, AppendOnlyDedupExecutor, BroadcastDispatcher, Channel,
    ChannelInput, FilterExecutor, GroupTopNExecutor, HashAggExecutor,
    HashDispatcher, HashJoinExecutor, HopWindowExecutor,
    MaterializeExecutor, MergeExecutor, ProjectExecutor, RowIdGenExecutor,
    SimpleAggExecutor, SimpleDispatcher, SortedJoinExecutor, SourceExecutor,
    StatelessSimpleAggExecutor,
)
from ..stream.executor import Executor
from .graph import Exchange, Fragment, Node, StreamGraph

BUILDERS: dict[str, Callable] = {}


def register_builder(kind: str):
    def deco(fn):
        BUILDERS[kind] = fn
        return fn
    return deco


class BuildEnv:
    """Shared build-time services: the state store, table-id allocation,
    and the barrier coordinator being wired up."""

    def __init__(self, store: StateStore, coord: BarrierCoordinator,
                 channel_capacity: int = 64, chunk_coalesce_max: int = 0,
                 partial_recovery: bool = True):
        self.store = store
        self.coord = coord
        self.channel_capacity = channel_capacity
        # > 0: exchange receivers (ChannelInput/Merge) pack runs of small
        # chunks up to this total capacity into one chunk per dispatch
        # (SET streaming_chunk_coalesce; common/chunk.py ChunkCoalescer)
        self.chunk_coalesce_max = chunk_coalesce_max
        # exchange channels keep a replay buffer of the not-yet-committed
        # message suffix so a failed terminal fragment can be rebuilt
        # alone and fed the in-flight interval again (Channel.enable_
        # replay; SET partial_recovery = 0 turns it off, every failure
        # then takes the full-recovery path)
        self.partial_recovery = partial_recovery
        self._next_table_id = 1
        self._next_actor_id = 1
        # session services for cross-MV nodes (stream_scan taps); set by
        # the owning Session, None in engine-level tests
        self.session = None
        self.pending_taps: list = []          # (upstream MvDef, Channel)
        self.pending_source_queues: list = []
        self.pending_enumerators: list = []    # broker split enumerators
        # label prefix for memory-manager registration — the Session sets
        # this to the MV/sink name around build_graph so EXPLAIN and
        # \metrics attribute HBM to the flow that owns it
        self.memory_scope: Optional[str] = None

    def alloc_table_id(self) -> int:
        t = self._next_table_id
        self._next_table_id += 1
        return t

    def alloc_actor_id(self) -> int:
        a = self._next_actor_id
        self._next_actor_id += 1
        return a

    def state_table(self, table_id: int, schema: Schema,
                    pk_indices: Sequence[int],
                    vnode_bitmap: Optional[np.ndarray] = None) -> StateTable:
        return StateTable(self.store, table_id=table_id, schema=schema,
                          pk_indices=pk_indices, vnode_bitmap=vnode_bitmap)


@dataclass
class ActorCtx:
    """Per-actor build context handed to node builders."""

    env: BuildEnv
    fragment: Fragment
    actor_id: int
    actor_idx: int            # position within the fragment [0, parallelism)
    vnode_bitmap: Optional[np.ndarray]
    table_ids: dict           # node id -> table id (shared across actors)

    def table_id(self, key) -> int:
        """Stable table id per plan node, shared by a fragment's actors.
        NOT dict.setdefault(key, alloc()) — that evaluates alloc() even on
        hits, burning ids per actor and making the id sequence depend on
        PARALLELISM, which breaks recovery/rescale (a rebuilt graph must
        find its tables at the same ids)."""
        if key not in self.table_ids:
            self.table_ids[key] = self.env.alloc_table_id()
        return self.table_ids[key]


@dataclass
class Deployment:
    coord: BarrierCoordinator
    actors: list[Actor] = field(default_factory=list)
    roots: dict[int, list[Executor]] = field(default_factory=dict)
    tasks: list[asyncio.Task] = field(default_factory=list)
    source_queues: list = field(default_factory=list)
    memory_names: list = field(default_factory=list)
    mesh_actor_ids: list = field(default_factory=list)
    mesh_chains: list = field(default_factory=list)    # chain labels
    # split enumerators created by this deployment's source builders
    # (broker discovery, connectors/broker.py) — unregistered on stop
    enumerators: list = field(default_factory=list)
    # ---- per-fragment recovery bookkeeping (frontend/session.py) ----
    actor_fragment: dict = field(default_factory=dict)   # actor_id -> fid
    frag_actor_ids: dict = field(default_factory=dict)   # fid -> [ids]
    frag_memory_names: dict = field(default_factory=dict)
    frag_source_queues: dict = field(default_factory=dict)
    frag_tables: dict = field(default_factory=dict)      # fid -> table map
    fragment_consumers: dict = field(default_factory=dict)
    replay_channels: list = field(default_factory=list)
    # fid -> [MeshIngestLog] — the fused fragments' replay points, so a
    # per-fragment rebuild swaps the old incarnation's log out of the
    # coordinator's trim pulse (stream/sharded_agg.py)
    frag_ingest_logs: dict = field(default_factory=dict)
    # ---- per-ACTOR bookkeeping (cluster worker rebuilds, where a
    # fragment's actors split across workers and rebuild individually)
    actor_memory_names: dict = field(default_factory=dict)
    actor_source_queues: dict = field(default_factory=dict)
    actor_root: dict = field(default_factory=dict)    # actor_id -> root
    # everything rebuild_fragment needs to re-run one fragment's build:
    # {"graph","env","channels","built_schema","consumers"}; None when
    # the deployment came from a path without rebuild support (cluster)
    rebuild_info: Optional[dict] = None

    def spawn(self) -> "Deployment":
        self.tasks = [a.spawn() for a in self.actors]
        return self

    async def stop(self) -> None:
        """Stop THIS deployment's actors (a shared coordinator may drive
        several deployments; the stop mutation names only ours) and
        deregister them so later barriers don't wait on the dead."""
        ids = {a.actor_id for a in self.actors}
        try:
            await self.coord.stop_all(ids)
        finally:
            # a failed coordinator raises before the stop barrier reaches
            # anyone; surviving actors must still be torn down, not leaked
            for t in self.tasks:
                if not t.done():
                    t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
            for a in self.actors:
                self.coord.actor_ids.discard(a.actor_id)
                # per-actor streaming series die with the actor (their
                # labels would otherwise linger in every future scrape)
                self.coord.stats.unregister(a.actor_id)
            for q in self.source_queues:
                if q in self.coord.source_queues:
                    self.coord.source_queues.remove(q)
            unreg_src = getattr(self.coord, "unregister_source_exec", None)
            if unreg_src is not None:
                for a in self.actors:
                    unreg_src(a.actor_id)
            unreg_en = getattr(self.coord,
                               "unregister_split_enumerator", None)
            if unreg_en is not None:
                for en in self.enumerators:
                    unreg_en(en)
            for n in self.memory_names:
                self.coord.memory.unregister(n)
            for a in self.mesh_actor_ids:
                self.coord.unregister_mesh_fragment(a)
            unreg_ch = getattr(self.coord, "unregister_mesh_chain", None)
            if unreg_ch is not None:
                for c in self.mesh_chains:
                    unreg_ch(c)
            unreg = getattr(self.coord, "unregister_replay_channels", None)
            if unreg is not None and self.replay_channels:
                unreg(self.replay_channels)


def _iter_executor_chain(root):
    """Every executor reachable from a fragment root through its
    input(s) — the registration walk for the memory manager."""
    seen: set[int] = set()
    stack = [root]
    while stack:
        node = stack.pop()
        if id(node) in seen or node is None:
            continue
        seen.add(id(node))
        yield node
        inp = getattr(node, "input", None)
        if inp is not None:
            stack.append(inp)
        for i in getattr(node, "inputs", ()) or ():
            stack.append(i)


def _register_memory(dep: Deployment, env: BuildEnv, root,
                     actor_id: int, fid=None) -> None:
    """Register every stateful executor in the chain (duck-typed on
    `state_bytes`) with the coordinator's MemoryManager, labelled by the
    owning flow so operators can see which MV owns the HBM."""
    scope = env.memory_scope or "flow"
    for ex in _iter_executor_chain(root):
        if hasattr(ex, "state_bytes"):
            name = env.coord.memory.register(
                f"{scope}/{ex.identity}@a{actor_id}", ex)
            dep.memory_names.append(name)
            dep.actor_memory_names.setdefault(actor_id, []).append(name)
            if fid is not None:
                dep.frag_memory_names.setdefault(fid, []).append(name)


def _register_mesh(dep: Deployment, env: BuildEnv, root,
                   actor_id: int, fid=None) -> None:
    """The fused mesh plane: an exchange -> sharded-executor chain that
    the builders lowered onto the device mesh announces itself to the
    barrier coordinator — the fragment's S shards collect every epoch as
    ONE actor (a single collective boundary), and /healthz + the
    mesh_profile gate can see the mesh topology. The executor's
    MeshIngestLog (the mesh-plane replay point) registers next to the
    exchange replay buffers so the commit pulse trims it to the
    uncommitted ingest suffix."""
    reg = getattr(env.coord, "register_mesh_fragment", None)
    if reg is None:
        return
    for ex in _iter_executor_chain(root):
        n = getattr(ex, "n_shards", 0)
        if n and getattr(ex, "mesh", None) is not None:
            reg(actor_id, n, getattr(ex, "identity", type(ex).__name__))
            dep.mesh_actor_ids.append(actor_id)
            ilog = getattr(ex, "ingest_log", None)
            if ilog is not None and getattr(env, "partial_recovery",
                                            True):
                reg2 = getattr(env.coord, "register_replay_channels",
                               None)
                if reg2 is not None:
                    reg2([ilog])
                    dep.replay_channels.append(ilog)
                    if fid is not None:
                        dep.frag_ingest_logs.setdefault(
                            fid, []).append(ilog)
            return                  # one registration per actor


def _fuse_join_sides(dep: Deployment, graph, env, consumers, c_fid, frag,
                     join, actors_by_id) -> None:
    """Two-input chain fusion for the sharded join: hollow eligible
    producer chains on BOTH sides independently. Each side gets its own
    per-side chain (`f<u>-f<c>s<side>`): the sides' producers differ, so
    one side may hollow while the other keeps its host stages — the
    fused program runs whichever preludes installed for the side it is
    tracing. Side order comes from the plan tree: the sorted_join node's
    input legs, each a direct Exchange leaf (an in-fragment subtree
    between exchange and join disqualifies that side — the built input
    is then not a ChannelInput)."""
    if not getattr(join, "mesh_shuffle", False):
        return
    legs = getattr(join, "inputs", ())
    if len(legs) != 2 or any(type(i).__name__ != "ChannelInput"
                             for i in legs):
        return

    def find_join(n):
        if isinstance(n, Exchange):
            return None
        if n.kind == "sorted_join":
            return n
        for i in n.inputs:
            r = find_join(i)
            if r is not None:
                return r
        return None

    jnode = find_join(frag.root)
    if jnode is None or len(jnode.inputs) != 2 \
            or not all(isinstance(i, Exchange) for i in jnode.inputs):
        return
    hollow = bool(getattr(join, "mesh_chain_fuse", True))
    for side, leg in enumerate(jnode.inputs):
        u_fid = leg.upstream
        uf = graph.fragments.get(u_fid)
        if (uf is None or uf.parallelism != 1
                or getattr(uf, "remote_worker", None)
                or len(consumers.get(u_fid, ())) != 1
                or len(dep.roots.get(u_fid, ())) != 1):
            continue
        stages, p_node = [], dep.roots[u_fid][0]
        while p_node is not None and hasattr(p_node, "mesh_prelude_fn"):
            stages.append(p_node)
            p_node = getattr(p_node, "input", None)
        if not stages or not (isinstance(p_node, SourceExecutor)
                              or type(p_node).__name__ == "ChannelInput"):
            continue
        chain = f"f{u_fid}-f{c_fid}s{side}"
        for s in stages:
            s.mesh_chain_hop = chain
            if hollow:
                s.mesh_hollow = True
        if hollow:
            if not join._mesh_preludes.get(side):
                join.set_mesh_preludes(
                    side, [s.mesh_prelude_fn() for s in reversed(stages)],
                    chain=chain)
            for aid in dep.frag_actor_ids.get(u_fid, []):
                a = actors_by_id.get(aid)
                if a is not None:
                    a.fence_exempt = True
        else:
            # host-plane fallback hops count against ONE chain name per
            # executor (last side registered); both chains still appear
            # in the coordinator's registry for the topology view
            join.mesh_chain = chain
        reg = getattr(env.coord, "register_mesh_chain", None)
        if reg is not None:
            c_aids = dep.frag_actor_ids.get(c_fid, [])
            reg(chain, (u_fid, c_fid), hollow,
                c_aids[0] if c_aids else -1)
            if chain not in dep.mesh_chains:
                dep.mesh_chains.append(chain)


def _fuse_mesh_chains(dep: Deployment, graph, env, consumers) -> None:
    """Mesh-resident pipelines: extend the per-fragment mesh plane to a
    whole producer -> shuffle -> consumer CHAIN. A singleton producer
    fragment whose executor chain is nothing but prelude-capable
    stateless stages (Project / HopWindow — `mesh_prelude_fn`) over a
    source, feeding exactly one sharded-agg fragment over a single
    ChannelInput leg, is HOLLOWED: its stages pass raw source chunks
    through untouched and their `_step_impl`s install as preludes INSIDE
    the consumer's fused shard_map program. The chain then runs
    device-resident end-to-end per barrier interval — the host touches
    only barrier control, the persist d2h, and the MeshIngestLog replay
    point (which now logs RAW source chunks, so a mesh-scope replay
    re-runs the hollowed stages too). The producer actor turns
    fence-exempt: it dispatches no device programs of its own, the
    consumer's fence covers the chain.

    Eligibility is conservative — any miss leaves the PR 8 per-fragment
    plane untouched: producer must be singleton, local, single-consumer
    (source-sharing fragments keep their host stages); Filter never
    qualifies (its UD/UI pair fixup reads across rows). With
    streaming_mesh_chain=0 the chain still REGISTERS and the host-hop
    counter still runs un-hollowed — that is the unfused comparison
    baseline scripts/mesh_profile.py measures against.

    Runs after build_graph and again after rebuild_fragment (idempotent:
    surviving hollow producers re-hollow, a surviving consumer keeps its
    installed preludes — the stage impls are pure and config-identical
    across incarnations)."""
    actors_by_id = {a.actor_id: a for a in dep.actors}
    for c_fid, roots in dep.roots.items():
        f = graph.fragments.get(c_fid)
        if f is None or len(roots) != 1 \
                or getattr(f, "remote_worker", None):
            continue
        # consumer: first sharded executor in the chain. Tuple-valued
        # _mesh_preludes is the single-input form (agg / top-N /
        # over-window); dict-valued marks the join's per-side variant,
        # which runs its own two-input eligibility walk
        sharded, node = None, roots[0]
        while node is not None:
            if isinstance(getattr(node, "_mesh_preludes", None), tuple) \
                    and getattr(node, "mesh", None) is not None:
                sharded = node
                break
            if isinstance(getattr(node, "_mesh_preludes", None), dict) \
                    and getattr(node, "mesh", None) is not None:
                _fuse_join_sides(dep, graph, env, consumers, c_fid, f,
                                 node, actors_by_id)
                break
            node = getattr(node, "input", None)
        if sharded is None or not getattr(sharded, "mesh_shuffle", False):
            continue
        if type(getattr(sharded, "input", None)).__name__ \
                != "ChannelInput":
            continue
        # the single upstream edge into this fragment
        ups = [u for u, cons in consumers.items()
               if any(d == c_fid for d, _k in cons)]
        if len(ups) != 1:
            continue
        u_fid = ups[0]
        uf = graph.fragments[u_fid]
        if (uf.parallelism != 1 or getattr(uf, "remote_worker", None)
                or len(consumers.get(u_fid, ())) != 1
                or len(dep.roots.get(u_fid, ())) != 1):
            continue
        # producer: only prelude-capable stages above the fragment's
        # inlet — either an in-fragment source or the channel leg from a
        # dedicated source fragment (the binder splits sources out, so
        # the common shape is source-fragment -> project-fragment ->
        # agg-fragment; hollowing the middle one is semantics-preserving
        # regardless of what feeds it: raw chunks pass through untouched)
        stages, p_node = [], dep.roots[u_fid][0]
        while p_node is not None and hasattr(p_node, "mesh_prelude_fn"):
            stages.append(p_node)
            p_node = getattr(p_node, "input", None)
        if not stages or not (isinstance(p_node, SourceExecutor)
                              or type(p_node).__name__ == "ChannelInput"):
            continue
        chain = f"f{u_fid}-f{c_fid}"
        hollow = bool(getattr(sharded, "mesh_chain_fuse", True))
        for s in stages:
            s.mesh_chain_hop = chain
            if hollow:
                s.mesh_hollow = True
        if hollow:
            if not sharded._mesh_preludes:
                # source-most stage runs first inside the fused program
                sharded.set_mesh_preludes(
                    [s.mesh_prelude_fn() for s in reversed(stages)],
                    chain=chain)
            for aid in dep.frag_actor_ids.get(u_fid, []):
                a = actors_by_id.get(aid)
                if a is not None:
                    a.fence_exempt = True
        else:
            sharded.mesh_chain = chain
        reg = getattr(env.coord, "register_mesh_chain", None)
        if reg is not None:
            c_aids = dep.frag_actor_ids.get(c_fid, [])
            reg(chain, (u_fid, c_fid), hollow,
                c_aids[0] if c_aids else -1)
            if chain not in dep.mesh_chains:
                dep.mesh_chains.append(chain)


def _build_fragment_actor(graph, env, dep, channels, built_schema,
                          f, fid, idx, actor_id, vnode_bitmap,
                          frag_tables, consumers):
    """Build ONE actor of fragment `f` (executor chain from the node
    tree, exchange legs resolved against the channel matrices, output
    dispatcher) and register it everywhere — the shared body of the
    initial `build_graph` loop and `rebuild_fragment` (per-fragment
    recovery re-runs exactly this with the ORIGINAL actor id and table
    map, so the rebuilt chain binds the same state)."""
    ctx = ActorCtx(env=env, fragment=f, actor_id=actor_id,
                   actor_idx=idx, vnode_bitmap=vnode_bitmap,
                   table_ids=frag_tables)
    # per-actor Exchange occurrence counters: the build walk visits
    # leaves in the same pre-order as StreamGraph.edges()
    edge_seen: dict[int, int] = {}

    def build_node(n):
        if isinstance(n, Exchange):
            k = edge_seen.get(n.upstream, 0)
            edge_seen[n.upstream] = k + 1
            up = graph.fragments[n.upstream]
            matrix = channels[(n.upstream, fid, k)]
            sch = built_schema[n.upstream]
            # terminate only on THIS actor's stop (a shared
            # coordinator routes other deployments' stops here too)
            stop_on = (lambda b, aid=ctx.actor_id: b.is_stop(aid))
            co = env.chunk_coalesce_max
            if up.dispatch == "simple" and up.parallelism > 1:
                # NoShuffle: 1:1 actor pairing
                return ChannelInput(matrix[idx][idx], sch,
                                    stop_on=stop_on, coalesce_max=co,
                                    actor_id=ctx.actor_id)
            chans = [matrix[u][idx] for u in range(up.parallelism)]
            if len(chans) == 1:
                return ChannelInput(chans[0], sch, stop_on=stop_on,
                                    coalesce_max=co,
                                    actor_id=ctx.actor_id)
            return MergeExecutor(chans, sch, stop_on=stop_on,
                                 coalesce_max=co)
        inputs = [build_node(i) for i in n.inputs]
        return BUILDERS[n.kind](dict(n.args), inputs, ctx, id(n))

    root = build_node(f.root)
    dep.roots[fid].append(root)
    _register_memory(dep, env, root, actor_id, fid=fid)
    _register_mesh(dep, env, root, actor_id, fid=fid)
    dispatcher = _dispatcher_for(graph, f, consumers[fid], channels, idx)
    env.coord.register_actor(actor_id)
    actor = Actor(actor_id, root, dispatcher, env.coord)
    # streaming-stats registration rides the same walk as the memory
    # manager's: per-actor series (metric_level=debug) appear labelled
    # by the owning flow
    env.coord.stats.register(env.memory_scope or "flow", actor, root)
    dep.actor_fragment[actor_id] = fid
    dep.frag_actor_ids.setdefault(fid, []).append(actor_id)
    return root, actor


def build_graph(graph: StreamGraph, env: BuildEnv) -> Deployment:
    env.pending_source_queues = []
    env.pending_enumerators = []
    dep = Deployment(coord=env.coord)
    # channels[(up_fid, down_fid, edge_k)][u_actor][d_actor] — one matrix
    # PER EXCHANGE EDGE, so a fragment consuming the same upstream twice
    # (self-join) gets independent channels on each input
    channels: dict[tuple[int, int, int], list[list[Channel]]] = {}
    built_schema: dict[int, Schema] = {}

    order = graph.topo_order()
    consumers = {fid: graph.consumers(fid) for fid in order}

    # allocate the channel matrices first (consumers may be built after
    # producers, but the producer's dispatcher needs the channels)
    replay = getattr(env, "partial_recovery", True)
    for fid in order:
        f = graph.fragments[fid]
        for d_fid, k in consumers[fid]:
            d = graph.fragments[d_fid]
            mat = [
                [Channel(env.channel_capacity) for _ in range(d.parallelism)]
                for _ in range(f.parallelism)]
            if replay and not getattr(d, "remote_worker", None):
                for row in mat:
                    for ch in row:
                        ch.enable_replay()
                        dep.replay_channels.append(ch)
            channels[(fid, d_fid, k)] = mat
    reg = getattr(env.coord, "register_replay_channels", None)
    if reg is not None and dep.replay_channels:
        # the coordinator trims every buffer at each checkpoint commit,
        # keeping the replay window == the uncommitted suffix
        reg(dep.replay_channels)

    for fid in order:
        f = graph.fragments[fid]
        dep.roots[fid] = []
        dep.fragment_consumers[fid] = list(consumers[fid])
        if getattr(f, "remote_worker", None):
            # DCN placement (stream/remote_fragment.py): the fragment
            # runs in a worker process; locally it appears as ONE actor
            # whose executor chain crosses the process boundary, so
            # barrier collection happens only after the round trip
            assert f.parallelism == 1, "remote fragments are singleton"
            actor_id = env.alloc_actor_id()
            in_chans, in_schemas = [], []
            edge_seen_r: dict = {}

            def walk(n):
                if isinstance(n, Exchange):
                    k = edge_seen_r.get(n.upstream, 0)
                    edge_seen_r[n.upstream] = k + 1
                    up = graph.fragments[n.upstream]
                    assert up.parallelism == 1, \
                        "remote fragment upstreams are singleton"
                    in_chans.append(channels[(n.upstream, fid, k)][0][0])
                    in_schemas.append(built_schema[n.upstream])
                    return
                for i in n.inputs:
                    walk(i)

            walk(f.root)
            out_schema = _infer_fragment_schema(graph, f, built_schema)
            from ..stream.remote_fragment import RemoteFragmentExecutor
            root = RemoteFragmentExecutor(
                f.remote_worker, f.root, in_chans, in_schemas, out_schema,
                actor_id=actor_id)
            built_schema[fid] = out_schema
            dep.roots[fid].append(root)
            dispatcher = _dispatcher_for(graph, f, consumers[fid],
                                         channels, 0)
            env.coord.register_actor(actor_id)
            actor = Actor(actor_id, root, dispatcher, env.coord)
            dep.actors.append(actor)
            env.coord.stats.register(env.memory_scope or "flow",
                                     actor, root)
            continue
        bitmaps = (shard_vnode_bitmaps(f.parallelism)
                   if f.parallelism > 1 else [None])
        # table ids are shared across a fragment's actors (vnode-split)
        frag_tables: dict = {}
        dep.frag_tables[fid] = frag_tables
        q_before = len(env.pending_source_queues)
        for idx in range(f.parallelism):
            actor_id = env.alloc_actor_id()
            root, actor = _build_fragment_actor(
                graph, env, dep, channels, built_schema, f, fid, idx,
                actor_id, bitmaps[idx], frag_tables, consumers)
            dep.actors.append(actor)
            if idx == 0:
                built_schema[fid] = root.schema
        dep.frag_source_queues[fid] = list(
            env.pending_source_queues[q_before:])
    dep.source_queues = list(env.pending_source_queues)
    dep.enumerators = list(env.pending_enumerators)
    _fuse_mesh_chains(dep, graph, env, consumers)
    dep.rebuild_info = {"graph": graph, "env": env, "channels": channels,
                        "built_schema": built_schema,
                        "consumers": consumers}
    return dep


def rebuild_fragment(dep: Deployment, fid: int) -> list[Actor]:
    """Per-fragment recovery: tear down ONE fragment's registrations and
    rebuild its actors in place — same actor ids, same table ids (the
    shared `frag_tables` map re-binds every durable table), same channel
    matrices (upstream producers keep their ends untouched). The caller
    (Session._partial_recover) has already cancelled the old tasks,
    discarded the fragment's staged writes, and arms channel replay
    AFTER this returns, BEFORE spawning the new actors. Mirrors the
    reference's partial/regional recovery, meta/src/barrier/recovery.rs
    (only the failed fragment's actors are recreated)."""
    info = dep.rebuild_info
    assert info is not None, "deployment has no rebuild support"
    graph, env = info["graph"], info["env"]
    channels, built_schema = info["channels"], info["built_schema"]
    consumers = info["consumers"]
    f = graph.fragments[fid]
    coord = env.coord

    # drop the old incarnation's per-fragment registrations
    for name in dep.frag_memory_names.pop(fid, []):
        coord.memory.unregister(name)
        if name in dep.memory_names:
            dep.memory_names.remove(name)
    for q in dep.frag_source_queues.pop(fid, []):
        if q in coord.source_queues:
            coord.source_queues.remove(q)
        if q in dep.source_queues:
            dep.source_queues.remove(q)
    old_ids = dep.frag_actor_ids.pop(fid)
    for aid in old_ids:
        coord.stats.unregister(aid)
        if aid in dep.mesh_actor_ids:
            coord.unregister_mesh_fragment(aid)
            dep.mesh_actor_ids.remove(aid)
    # the old incarnation's mesh replay point leaves the trim pulse —
    # the rebuilt executor registers a fresh one
    old_logs = dep.frag_ingest_logs.pop(fid, [])
    if old_logs:
        unreg = getattr(coord, "unregister_replay_channels", None)
        if unreg is not None:
            unreg(old_logs)
        dep.replay_channels = [c for c in dep.replay_channels
                               if not any(c is o for o in old_logs)]

    # rebuild with the ORIGINAL ids; builders re-read durable state at
    # their first barrier (the committed epoch — the caller discarded
    # this fragment's staged suffix)
    q_before = len(env.pending_source_queues)
    dep.roots[fid] = []
    bitmaps = (shard_vnode_bitmaps(f.parallelism)
               if f.parallelism > 1 else [None])
    frag_tables = dep.frag_tables[fid]
    by_id = {a.actor_id: i for i, a in enumerate(dep.actors)}
    new_actors = []
    for idx in range(f.parallelism):
        actor_id = old_ids[idx]
        _root, actor = _build_fragment_actor(
            graph, env, dep, channels, built_schema, f, fid, idx,
            actor_id, bitmaps[idx], frag_tables, consumers)
        dep.actors[by_id[actor_id]] = actor
        new_actors.append(actor)
    new_queues = env.pending_source_queues[q_before:]
    dep.frag_source_queues[fid] = list(new_queues)
    dep.source_queues.extend(new_queues)
    # re-fuse: a rebuilt producer re-hollows against the surviving
    # consumer; a rebuilt consumer re-installs preludes from the
    # surviving producer's stages (idempotent for untouched chains)
    _fuse_mesh_chains(dep, graph, env, consumers)
    return new_actors


def _dispatcher_for(graph, f, cons, channels, idx):
    """Output dispatcher for actor `idx` of fragment `f` (shared by the
    local and remote-fragment build paths)."""
    if not cons:
        return None
    per_consumer = []
    for d_fid, k in cons:
        d = graph.fragments[d_fid]
        outs = channels[(f.fid, d_fid, k)][idx]
        if f.dispatch == "hash":
            if d.parallelism == 1:
                # a singleton consumer needs no host-side vnode routing:
                # with one output every row lands there and update pairs
                # cannot split, so the per-chunk route program is pure
                # dispatch overhead. This is where the fused MESH
                # fragment's source-side dispatch goes on-device — the
                # consumer's shard_map ingest does the routing with an
                # in-mesh all_to_all instead (stream/sharded_*.py).
                per_consumer.append(SimpleDispatcher(outs[0]))
            else:
                per_consumer.append(HashDispatcher(
                    outs, f.dist_key_indices,
                    vnode_to_shard(d.parallelism)))
        elif f.dispatch == "broadcast":
            per_consumer.append(BroadcastDispatcher(outs))
        else:
            assert d.parallelism == f.parallelism, \
                "simple dispatch is 1:1 (NoShuffle)"
            per_consumer.append(SimpleDispatcher(outs[idx]))
    return (per_consumer[0] if len(per_consumer) == 1
            else FanoutDispatcher(per_consumer))


def _infer_fragment_schema(graph, frag, built_schema) -> Schema:
    """Planner-level schema of a fragment's output WITHOUT building its
    executors (the remote build needs it before the worker exists)."""
    def rec(n):
        if isinstance(n, Exchange):
            return built_schema[n.upstream]
        ins = [rec(i) for i in n.inputs]
        k = n.kind
        if k in ("sorted_join", "hash_join"):
            fields = tuple(ins[0]) + tuple(ins[1])
            oi = n.args.get("output_indices")
            if oi is not None:
                fields = tuple(fields[i] for i in oi)
            return Schema(fields)
        if k == "project":
            return Schema(tuple(
                SchemaField(nm, e.ret_type)
                for e, nm in zip(n.args["exprs"], n.args["names"])))
        if k in ("filter", "no_op", "dedup"):
            return ins[0]
        if k == "row_id_gen":
            return Schema(tuple(ins[0])
                          + (SchemaField("_row_id", DataType.SERIAL),))
        raise NotImplementedError(
            f"schema inference for remote fragment node {k!r}")
    return rec(frag.root)


class FanoutDispatcher:
    """One dispatcher per consumer fragment (reference DispatchExecutor
    holds a dispatcher LIST, dispatch.rs:421)."""

    def __init__(self, dispatchers):
        self.dispatchers = list(dispatchers)

    async def dispatch(self, msg) -> None:
        for d in self.dispatchers:
            await d.dispatch(msg)


# ----------------------------------------------------------------- builders

@register_builder("nexmark_source")
def _build_source(args, inputs, ctx: ActorCtx, key):
    from ..connectors import NexmarkGenerator
    from ..connectors.nexmark import NexmarkConfig
    from ..connectors.split import BlockSplitConnector

    barrier_q: asyncio.Queue = asyncio.Queue()
    ctx.env.coord.register_source(barrier_q)
    ctx.env.pending_source_queues.append(barrier_q)
    st = None
    if args.get("durable"):
        tid = ctx.table_id(key)
        st = ctx.env.state_table(
            tid, Schema((SchemaField("split_id", DataType.INT64),
                         SchemaField("offset", DataType.INT64))), (0,))
    P = ctx.fragment.parallelism
    name = args.get("source_name")
    rate = args.get("rate_limit")

    if args.get("connector") == "broker":
        ex = _build_broker_source(args, ctx, barrier_q, st, name, P, rate)
        ctx.env.coord.register_source_exec(ex)
        return ex

    def make_gen():
        if args.get("connector") == "jsonl":
            from ..connectors.file_source import (JsonlFileConnector,
                                                  parse_columns)
            return JsonlFileConnector(
                args["path"], parse_columns(args["columns"]),
                chunk_size=args.get("chunk_size", 256))
        if args.get("connector") == "tpch":
            from ..connectors.tpch import TpchGenerator
            return TpchGenerator(args["table"],
                                 chunk_size=args.get("chunk_size", 8192))
        cfg = (NexmarkConfig(**args.get("cfg", {}))
               if args.get("cfg") else None)
        return NexmarkGenerator(args["table"],
                                chunk_size=args.get("chunk_size", 8192),
                                **({"cfg": cfg} if cfg else {}))

    n_splits = int(args.get("splits", 1))
    assert n_splits >= P, \
        f"source parallelism {P} exceeds its {n_splits} split(s)"
    if n_splits == 1 and P == 1:
        ex = SourceExecutor(
            ctx.actor_id, make_gen(), barrier_q, state_table=st,
            emit_watermarks=args.get("emit_watermarks", False),
            watermark_lag_us=args.get("watermark_lag_us", 0),
            rate_limit_rows_per_barrier=args.get("rate_limit"),
            name=name)
        ctx.env.coord.register_source_exec(ex)
        return ex
    # split assignment: split k -> actor (k % P); a re-assigned split
    # recovers its committed offset wherever it lands (reference:
    # source_manager.rs split (re)assignment)
    my_splits = [(k, BlockSplitConnector(make_gen(), k, n_splits))
                 for k in range(n_splits) if k % P == ctx.actor_idx]
    ex = SourceExecutor(
        ctx.actor_id, barrier_queue=barrier_q, state_table=st,
        splits=my_splits,
        emit_watermarks=args.get("emit_watermarks", False),
        watermark_lag_us=args.get("watermark_lag_us", 0),
        rate_limit_rows_per_barrier=(None if rate is None
                                     else max(1, rate // P)),
        name=name)
    ctx.env.coord.register_source_exec(ex)
    return ex


def _build_broker_source(args, ctx: ActorCtx, barrier_q, st, name, P,
                         rate):
    """Broker-partition source (connectors/broker.py): splits ARE the
    topic's partitions as of build time (split k -> actor k % P, the
    standard rule), and ONE shared enumerator per fragment watches for
    partition growth — new splits arrive at a barrier via
    AddSplitsMutation, with offsets committed from that barrier on."""
    from ..connectors.broker import (BrokerPartitionConnector,
                                     BrokerSplitEnumerator)
    from ..connectors.file_source import parse_columns
    from ..broker.client import BrokerClient

    schema = parse_columns(args["columns"])
    brokers, topic = args["brokers"], args["topic"]
    chunk_size = int(args.get("chunk_size", 256))
    client = BrokerClient(brokers)
    # idempotent ensure: partition count only ever grows, so the live
    # count is >= the count the DDL was bound against
    n_parts = client.create_topic(topic=topic,
                                  partitions=int(args.get("partitions",
                                                          1)))
    client.close()
    assert n_parts >= P, \
        f"source parallelism {P} exceeds topic {topic!r}'s " \
        f"{n_parts} partition(s)"
    my_splits = [(k, BrokerPartitionConnector(brokers, topic, k, schema,
                                              chunk_size=chunk_size))
                 for k in range(n_parts) if k % P == ctx.actor_idx]
    interval_s = int(args.get("discovery_interval_ms", 1000)) / 1e3
    en = ctx.env.coord.split_enumerator(
        id(ctx.fragment),
        lambda: BrokerSplitEnumerator(
            brokers, topic, schema, chunk_size, P, n_parts,
            poll_interval_s=interval_s))
    en.register_actor(ctx.actor_idx, ctx.actor_id)
    en.observe_build(n_parts)
    pend = getattr(ctx.env, "pending_enumerators", None)
    if pend is not None and en not in pend:
        pend.append(en)
    return SourceExecutor(
        ctx.actor_id, barrier_queue=barrier_q, state_table=st,
        splits=my_splits,
        rate_limit_rows_per_barrier=(None if rate is None
                                     else max(1, int(rate) // P)),
        name=name)


@register_builder("project")
def _build_project(args, inputs, ctx, key):
    return ProjectExecutor(inputs[0], args["exprs"],
                           names=args.get("names"),
                           watermark_mapping=args.get("watermark_mapping"),
                           watermark_transforms=args.get("watermark_transforms"))


@register_builder("filter")
def _build_filter(args, inputs, ctx, key):
    return FilterExecutor(inputs[0], args["predicate"])


@register_builder("no_op")
def _build_no_op(args, inputs, ctx, key):
    from ..stream.misc import NoOpExecutor
    return NoOpExecutor(inputs[0])


@register_builder("hop_window")
def _build_hop(args, inputs, ctx, key):
    return HopWindowExecutor(inputs[0], time_col=args["time_col"],
                             window_slide_us=args["slide_us"],
                             window_size_us=args["size_us"],
                             output_indices=args.get("output_indices"))


def _agg_state_schema(in_schema: Schema, group_key_indices, agg_calls,
                      minput_k: int) -> Schema:
    from ..expr.agg import AggKind
    fields = [in_schema[i] for i in group_key_indices]
    for j, c in enumerate(agg_calls):
        if c.kind in (AggKind.MIN, AggKind.MAX) and not c.append_only:
            # retractable extrema persist their top-K value buffer
            fields += [SchemaField(f"s{j}v{k}", c.ret_type)
                       for k in range(minput_k)]
            fields += [SchemaField(f"s{j}c{k}", DataType.INT64)
                       for k in range(minput_k)]
            fields.append(SchemaField(f"s{j}lossy", DataType.INT64))
        else:
            fields.append(SchemaField(f"state{j}", c.ret_type))
    fields.append(SchemaField("_row_count", DataType.INT64))
    return Schema(tuple(fields))


@register_builder("hash_agg")
def _build_hash_agg(args, inputs, ctx: ActorCtx, key):
    st = None
    minput_k = args.get("minput_k", 32)
    if args.get("durable"):
        gk = tuple(args["group_key_indices"])
        sch = _agg_state_schema(inputs[0].schema, gk, args["agg_calls"],
                                minput_k)
        tid = ctx.table_id(key)
        st = ctx.env.state_table(tid, sch, tuple(range(len(gk))),
                                 vnode_bitmap=ctx.vnode_bitmap)
    md = args.get("mesh_devices", 1)
    if md > 1:
        from ..parallel.mesh import make_mesh
        from ..stream.sharded_agg import ShardedHashAggExecutor
        ex = ShardedHashAggExecutor(
            inputs[0], args["group_key_indices"], args["agg_calls"],
            mesh=make_mesh(md),
            capacity=args.get("capacity", 1 << 16) // md,
            state_table=st,
            group_key_names=args.get("group_key_names"),
            cleaning_watermark_col=args.get("cleaning_watermark_col"),
            watchdog_interval=args.get("watchdog_interval", 1),
            mesh_shuffle=bool(args.get("mesh_shuffle", True)),
            mesh_shuffle_slack=args.get("mesh_shuffle_slack", 0),
            mesh_shuffle_adaptive=bool(
                args.get("mesh_shuffle_adaptive", True)))
        # per-statement chain-fusion opt-out (streaming_mesh_chain=0):
        # the post-build fusion pass reads this off the executor
        ex.mesh_chain_fuse = bool(args.get("mesh_chain", True))
        return ex
    return HashAggExecutor(
        inputs[0], args["group_key_indices"], args["agg_calls"],
        capacity=args.get("capacity", 1 << 16),
        state_table=st,
        group_key_names=args.get("group_key_names"),
        cleaning_watermark_col=args.get("cleaning_watermark_col"),
        watchdog_interval=args.get("watchdog_interval", 1),
        minput_k=minput_k)


@register_builder("hash_join")
def _build_hash_join(args, inputs, ctx: ActorCtx, key):
    state_tables = None
    if args.get("durable"):
        tabs = []
        for s, inp in enumerate(inputs):
            tid = ctx.table_id((key, s))
            pk = tuple(args["left_pk_indices" if s == 0 else "right_pk_indices"])
            tabs.append(ctx.env.state_table(
                tid, inp.schema, pk, vnode_bitmap=ctx.vnode_bitmap))
        state_tables = tuple(tabs)
    return HashJoinExecutor(
        inputs[0], inputs[1],
        left_key_indices=args["left_key_indices"],
        right_key_indices=args["right_key_indices"],
        left_pk_indices=args["left_pk_indices"],
        right_pk_indices=args["right_pk_indices"],
        key_capacity=args.get("key_capacity", 1 << 14),
        row_capacity=args.get("row_capacity", 1 << 16),
        match_factor=args.get("match_factor", 2),
        condition=args.get("condition"),
        output_indices=args.get("output_indices"),
        state_tables=state_tables,
        clean_watermark_cols=args.get("clean_watermark_cols", (None, None)),
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("sorted_join")
def _build_sorted_join(args, inputs, ctx: ActorCtx, key):
    state_tables = None
    if args.get("durable"):
        tabs = []
        for s, inp in enumerate(inputs):
            tid = ctx.table_id((key, s))
            pk = tuple(args["left_pk_indices" if s == 0 else "right_pk_indices"])
            tabs.append(ctx.env.state_table(
                tid, inp.schema, pk, vnode_bitmap=ctx.vnode_bitmap))
        state_tables = tuple(tabs)
    md = args.get("mesh_devices", 1)
    cls = SortedJoinExecutor
    extra = {}
    if md > 1:
        from ..parallel.mesh import make_mesh
        from ..stream.sharded_join import ShardedSortedJoinExecutor
        cls = ShardedSortedJoinExecutor
        extra = dict(mesh=make_mesh(md),
                     mesh_shuffle=bool(args.get("mesh_shuffle", True)),
                     mesh_shuffle_slack=args.get("mesh_shuffle_slack", 0),
                     mesh_shuffle_adaptive=bool(
                         args.get("mesh_shuffle_adaptive", True)))
    ex = cls(
        inputs[0], inputs[1], **extra,
        left_key_indices=args["left_key_indices"],
        right_key_indices=args["right_key_indices"],
        left_pk_indices=args["left_pk_indices"],
        right_pk_indices=args["right_pk_indices"],
        capacity=args.get("capacity", 1 << 17) // md,
        match_factor=args.get("match_factor", 2),
        match_factors=args.get("match_factors"),
        condition=args.get("condition"),
        join_type=args.get("join_type", "inner"),
        output_indices=args.get("output_indices"),
        append_only=tuple(args.get("append_only", (False, False))),
        clean_watermark_cols=tuple(args.get("clean_watermark_cols",
                                            (None, None))),
        clean_specs=(tuple(args["clean_specs"])
                     if args.get("clean_specs") is not None else None),
        state_tables=state_tables,
        temporal=args.get("temporal", False),
        watchdog_interval=args.get("watchdog_interval", 1))
    if md > 1:
        # per-statement chain-fusion opt-out, read by _fuse_mesh_chains'
        # two-input walk (join-side producer hollowing)
        ex.mesh_chain_fuse = bool(args.get("mesh_chain", True))
    return ex


@register_builder("group_top_n")
def _build_top_n(args, inputs, ctx: ActorCtx, key):
    st = None
    if args.get("durable"):
        tid = ctx.table_id(key)
        gk = tuple(args.get("group_key_indices", ()))
        pk = gk + (args["order_col"],) + tuple(inputs[0].pk_indices)
        st = ctx.env.state_table(tid, inputs[0].schema,
                                 tuple(dict.fromkeys(pk)),
                                 vnode_bitmap=ctx.vnode_bitmap)
    return GroupTopNExecutor(
        inputs[0], args.get("group_key_indices", ()), args["order_col"],
        args["limit"], offset=args.get("offset", 0),
        descending=args.get("descending", False),
        capacity=args.get("capacity", 1 << 12),
        state_table=st,
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("general_over_window")
def _build_general_over_window(args, inputs, ctx: ActorCtx, key):
    from ..stream.general_over_window import GeneralOverWindowExecutor
    pk = tuple(args["pk_indices"])
    st = None
    if args.get("durable"):
        st = ctx.env.state_table(ctx.table_id(key), inputs[0].schema, pk,
                                 vnode_bitmap=ctx.vnode_bitmap)
    md = args.get("mesh_devices", 1)
    # no partition axis -> nothing to shard on: stay single-device
    if md > 1 and args["partition_by"]:
        from ..parallel.mesh import make_mesh
        from ..stream.sharded_over_window import ShardedOverWindowExecutor
        ex = ShardedOverWindowExecutor(
            inputs[0], args["partition_by"], args["order_specs"],
            args["windows"],
            capacity=args.get("capacity", 1 << 14) // md,
            state_table=st, pk_indices=pk,
            watchdog_interval=args.get("watchdog_interval", 1),
            mesh=make_mesh(md),
            mesh_shuffle=bool(args.get("mesh_shuffle", True)),
            mesh_shuffle_slack=args.get("mesh_shuffle_slack", 0),
            mesh_shuffle_adaptive=bool(
                args.get("mesh_shuffle_adaptive", True)))
        ex.mesh_chain_fuse = bool(args.get("mesh_chain", True))
        return ex
    return GeneralOverWindowExecutor(
        inputs[0], args["partition_by"], args["order_specs"],
        args["windows"], capacity=args.get("capacity", 1 << 14),
        state_table=st, pk_indices=pk,
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("eowc_over_window")
def _build_eowc_over_window(args, inputs, ctx: ActorCtx, key):
    from ..stream.eowc_over_window import EowcOverWindowExecutor
    pk = tuple(args["pk_indices"])
    st = ft = None
    if args.get("durable"):
        st = ctx.env.state_table(ctx.table_id((key, 0)), inputs[0].schema,
                                 pk, vnode_bitmap=ctx.vnode_bitmap)
        ft = ctx.env.state_table(
            ctx.table_id((key, 1)),
            Schema((SchemaField("slot", DataType.INT64),
                    SchemaField("emitted_to", DataType.INT64))), (0,))
    return EowcOverWindowExecutor(
        inputs[0], args["partition_by"], args["order_specs"],
        args["windows"], capacity=args.get("capacity", 1 << 14),
        state_table=st, frontier_table=ft, pk_indices=pk,
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("now")
def _build_now(args, inputs, ctx, key):
    from ..stream.dynamic import NowExecutor
    barrier_q: asyncio.Queue = asyncio.Queue()
    ctx.env.coord.register_source(barrier_q)
    ctx.env.pending_source_queues.append(barrier_q)
    return NowExecutor(barrier_q)


@register_builder("project_set")
def _build_project_set(args, inputs, ctx, key):
    from ..stream.project_set import ProjectSetExecutor
    return ProjectSetExecutor(inputs[0], args["items"],
                              max_rows_per_input=args.get("max_k", 16),
                              names=args.get("names"))


@register_builder("dynamic_filter")
def _build_dynamic_filter(args, inputs, ctx, key):
    from ..stream.dynamic import DynamicFilterExecutor
    return DynamicFilterExecutor(
        inputs[0], inputs[1], args["key_col"],
        op=args.get("op", "greater_than"),
        capacity=args.get("capacity", 1 << 14),
        pk_indices=args.get("pk_indices"),
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("dedup")
def _build_dedup(args, inputs, ctx: ActorCtx, key):
    st = None
    if args.get("durable"):
        tid = ctx.table_id(key)
        gk = tuple(args["dedup_key_indices"])
        sch = Schema(tuple(inputs[0].schema[i] for i in gk))
        st = ctx.env.state_table(tid, sch, tuple(range(len(gk))),
                                 vnode_bitmap=ctx.vnode_bitmap)
    return AppendOnlyDedupExecutor(
        inputs[0], args["dedup_key_indices"],
        capacity=args.get("capacity", 1 << 16), state_table=st,
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("simple_agg")
def _build_simple_agg(args, inputs, ctx: ActorCtx, key):
    st = None
    if args.get("durable"):
        calls = args["agg_calls"]
        fields = [SchemaField("slot", DataType.INT64)]
        fields += [SchemaField(f"state{j}", c.ret_type)
                   for j, c in enumerate(calls)]
        fields.append(SchemaField("_row_count", DataType.INT64))
        tid = ctx.table_id(key)
        st = ctx.env.state_table(tid, Schema(tuple(fields)), (0,))
    return SimpleAggExecutor(inputs[0], args["agg_calls"], state_table=st,
                             combine_partials=args.get("combine_partials",
                                                       False))


@register_builder("stateless_simple_agg")
def _build_stateless_agg(args, inputs, ctx, key):
    return StatelessSimpleAggExecutor(inputs[0], args["agg_calls"])


@register_builder("snapshot_join_agg")
def _build_snapshot_join_agg(args, inputs, ctx: ActorCtx, key):
    from ..stream.snapshot_join_agg import SnapshotJoinAggExecutor
    state_tables = None
    if args.get("durable"):
        fact_sch = Schema(
            (SchemaField("_pos", DataType.SERIAL),)
            + tuple(inputs[0].schema)
            + (SchemaField("_validbits", DataType.INT64),))
        dim_sch = Schema((SchemaField("_pos", DataType.SERIAL),
                          SchemaField("_key", DataType.INT64)))
        state_tables = (
            ctx.env.state_table(ctx.table_id((key, 0)), fact_sch, (0,)),
            ctx.env.state_table(ctx.table_id((key, 1)), dim_sch, (0,)))
    return SnapshotJoinAggExecutor(
        inputs[0], inputs[1],
        fact_key=args["fact_key"], dim_key=args["dim_key"],
        sub_agg_calls=args["sub_agg_calls"],
        sub_items=args["sub_items"], residue=args["residue"],
        final_agg_calls=args["final_agg_calls"],
        final_items=args["final_items"],
        out_names=args["out_names"], out_types=args["out_types"],
        fact_filter=args.get("fact_filter"),
        sub_filter=args.get("sub_filter"),
        dim_filter=args.get("dim_filter"),
        capacity=args.get("capacity", 1 << 17),
        dim_capacity=args.get("dim_capacity", 1 << 14),
        state_tables=state_tables,
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("row_id_gen")
def _build_row_id(args, inputs, ctx: ActorCtx, key):
    return RowIdGenExecutor(inputs[0], instance=ctx.actor_id)


@register_builder("stream_scan")
def _build_stream_scan(args, inputs, ctx: ActorCtx, key):
    """CREATE MV ... FROM <mv>: live tap on the upstream MV's root actor +
    snapshot backfill over its StorageTable (no_shuffle_backfill.rs)."""
    from ..state.storage_table import StorageTable
    from ..stream import Channel, ChannelInput
    from ..stream.backfill import BackfillExecutor, backfill_progress_schema
    session = ctx.env.session
    assert session is not None, "stream_scan needs a session catalog"
    mv = session.catalog.mvs[args["mv"]]
    ch = Channel(ctx.env.channel_capacity)
    mv.tap.add(ch)
    ctx.env.pending_taps.append((mv, ch))
    storage = StorageTable.for_state_table(mv.table)
    st = None
    if args.get("durable", True):
        sch = backfill_progress_schema(mv.schema, mv.pk_indices)
        st = ctx.env.state_table(ctx.table_id(key), sch, (0,))
    return BackfillExecutor(
        ChannelInput(ch, mv.schema,
                     stop_on=lambda b, aid=ctx.actor_id: b.is_stop(aid),
                     actor_id=ctx.actor_id),
        storage, state_table=st,
        batch_rows=args.get("batch_rows", 65536))


@register_builder("retract_top_n")
def _build_retract_top_n(args, inputs, ctx: ActorCtx, key):
    from ..stream.retract_top_n import RetractableTopNExecutor
    pk = tuple(args.get("pk_indices")
               or inputs[0].pk_indices
               or range(len(inputs[0].schema)))
    st = None
    if args.get("durable"):
        st = ctx.env.state_table(ctx.table_id(key), inputs[0].schema, pk,
                                 vnode_bitmap=ctx.vnode_bitmap)
    md = args.get("mesh_devices", 1)
    if md > 1:
        from ..parallel.mesh import make_mesh
        from ..stream.sharded_top_n import ShardedTopNExecutor
        ex = ShardedTopNExecutor(
            inputs[0], args.get("group_key_indices", ()),
            order_col=args.get("order_col"),
            order_specs=args.get("order_specs"),
            limit=args["limit"], offset=args.get("offset", 0),
            descending=args.get("descending", False),
            capacity=args.get("capacity", 1 << 14) // md,
            state_table=st, pk_indices=pk,
            watchdog_interval=args.get("watchdog_interval", 1),
            mesh=make_mesh(md),
            mesh_shuffle=bool(args.get("mesh_shuffle", True)),
            mesh_shuffle_slack=args.get("mesh_shuffle_slack", 0),
            mesh_shuffle_adaptive=bool(
                args.get("mesh_shuffle_adaptive", True)))
        ex.mesh_chain_fuse = bool(args.get("mesh_chain", True))
        return ex
    return RetractableTopNExecutor(
        inputs[0], args.get("group_key_indices", ()),
        order_col=args.get("order_col"),
        order_specs=args.get("order_specs"),
        limit=args["limit"], offset=args.get("offset", 0),
        descending=args.get("descending", False),
        capacity=args.get("capacity", 1 << 14),
        state_table=st, pk_indices=pk,
        watchdog_interval=args.get("watchdog_interval", 1))


@register_builder("sink")
def _build_sink(args, inputs, ctx: ActorCtx, key):
    from ..stream.sink import (BlackholeSink, CallbackSink,
                               DeviceBlackholeSinkExecutor, FileSink,
                               SinkExecutor)
    connector = args.get("connector", "blackhole")
    force = args.get("type") == "append-only" or str(
        args.get("force_append_only", "")).lower() in ("true", "1")
    if connector == "blackhole_device":
        return DeviceBlackholeSinkExecutor(inputs[0])
    if connector == "blackhole":
        target = BlackholeSink()
    elif connector == "file":
        target = FileSink(args["path"], schema=inputs[0].schema)
    elif connector == "callback":
        target = CallbackSink(args["callback"])
    elif connector == "broker":
        from ..connectors.broker import BrokerSink
        parts = int(args.get("partitions", 1))
        if parts > 1 and not force:
            # one delivery batch lands WHOLE in one partition (the
            # atomicity the seq-in-topic dedupe rests on), and a
            # consumer interleaves partitions arbitrarily — a
            # retraction in p0 racing its re-insert in p1 would make
            # the downstream state order-dependent. Inserts commute;
            # retractions need the single-partition total order.
            raise ValueError(
                "broker sink with partitions > 1 requires an "
                "append-only changelog (WITH type='append-only')")
        target = BrokerSink(args["brokers"], args["topic"],
                            schema=inputs[0].schema, partitions=parts)
        # cross-engine trace stamping: delivered batch metas carry this
        # engine's identity + epoch span so a downstream engine's
        # ingest links back (utils/trace.py stitch_chrome_traces)
        session = getattr(ctx.env, "session", None)
        target.engine_id = getattr(session, "engine_id", None) \
            or f"engine-{id(ctx.env) & 0xFFFF:04x}"
        target.tracer = ctx.env.coord.tracer
    else:
        raise ValueError(f"unknown sink connector {connector!r}")
    # Exactly-once via the changelog log store (logstore/): default for
    # file/callback targets on a meta-local (manifest-owning) store —
    # the epoch batch persists WITH the checkpoint and a background
    # delivery task writes it to the target after the commit. Blackhole
    # (the bench egress) skips the log by default: durably persisting
    # every epoch for a row counter is pure write amplification.
    # `WITH (exactly_once = 0/1)` overrides either way. A cluster
    # compute node never owns the manifest (it cannot observe meta's
    # commit point), so cluster sinks stay on the direct path — the
    # deploy-time guard in cluster/meta_service.py rejects an explicit
    # exactly_once request loudly instead of degrading silently.
    default_eo = connector in ("file", "callback", "broker")
    exactly_once = bool(int(args.get("exactly_once", default_eo)))
    log = hub = None
    if exactly_once and getattr(ctx.env.store, "manifest_owner", True):
        from ..logstore.log import SinkChangelog
        log = SinkChangelog(ctx.env.store, ctx.table_id((key, "log")),
                            inputs[0].schema)
        hub = ctx.env.coord.logstore
    return SinkExecutor(inputs[0], target, force_append_only=force,
                        log=log, hub=hub,
                        name=ctx.env.memory_scope or f"sink_a{ctx.actor_id}")


@register_builder("materialize")
def _build_materialize(args, inputs, ctx: ActorCtx, key):
    tid = ctx.table_id(key)
    st = ctx.env.state_table(tid, inputs[0].schema,
                             tuple(args.get("pk_indices",
                                            inputs[0].pk_indices)),
                             vnode_bitmap=ctx.vnode_bitmap)
    kw = {}
    if args.get("conflict") is not None:
        kw["conflict"] = args["conflict"]
    return MaterializeExecutor(inputs[0], st, **kw)


# ====================================================================
# Cluster (multi-process) build — cluster/: meta assigns fragments to
# compute nodes by vnode range; every process derives the SAME actor and
# state-table ids from the pickled graph alone (no id exchange), builds
# only its assigned actors, and cross-worker fragment edges ride the DCN
# tier (stream/remote_exchange.py).
# ====================================================================

def fragment_node_order(frag: Fragment) -> list:
    """The fragment's Node tree in the builder's visit order (post-order,
    inputs first — the order `build_node` constructs executors and the
    order builders request state-table ids). Exchange leaves excluded.
    Deterministic across processes: it depends only on tree SHAPE, which
    pickling preserves."""
    out = []

    def rec(n):
        if isinstance(n, Exchange):
            return
        for i in n.inputs:
            rec(i)
        out.append(n)

    rec(frag.root)
    return out


def _state_table_keys(kind: str, args: dict, key) -> list:
    """The exact `ctx.table_id(...)` keys the registered builder for
    `kind` will request, in request order — the single source of truth
    the deterministic pre-assigner shares with the builders above."""
    durable = bool(args.get("durable"))
    if kind in ("nexmark_source", "hash_agg", "group_top_n",
                "general_over_window", "dedup", "simple_agg",
                "retract_top_n"):
        return [key] if durable else []
    if kind in ("hash_join", "sorted_join", "eowc_over_window",
                "snapshot_join_agg"):
        return [(key, 0), (key, 1)] if durable else []
    if kind == "stream_scan":
        return [key] if args.get("durable", True) else []
    if kind == "materialize":
        return [key]
    return []


def assign_graph_ids(graph: StreamGraph, actor_id_base: int,
                     table_id_base: int):
    """Deterministically derive every actor id and state-table id of a
    graph from the graph alone: fragments in topo order, nodes in builder
    visit order, actors idx-ordered within a fragment. Meta and every
    compute node run this on the same pickled graph and agree on all ids
    without exchanging them (ids must agree — vnode-partitioned state
    tables are SHARED across workers, and stop mutations name global
    actor ids).

    Returns (actors, tables, next_actor_id, next_table_id) where
    `actors[fid]` is the fragment's actor-id list and `tables[fid]` the
    prefilled `ActorCtx.table_ids` dict (keys are (fid, node_idx)-based,
    matching what the partial build passes to builders)."""
    next_actor = actor_id_base
    next_table = table_id_base
    actors: dict[int, list[int]] = {}
    tables: dict[int, dict] = {}
    for fid in graph.topo_order():
        f = graph.fragments[fid]
        actors[fid] = list(range(next_actor, next_actor + f.parallelism))
        next_actor += f.parallelism
        tab: dict = {}
        for idx, n in enumerate(fragment_node_order(f)):
            for k in _state_table_keys(n.kind, n.args, (fid, idx)):
                tab[k] = next_table
                next_table += 1
        tables[fid] = tab
    return actors, tables, next_actor, next_table


def infer_fragment_schemas(graph: StreamGraph,
                           on_node=None) -> dict[int, Schema]:
    """Planner-level output schema of EVERY fragment without building a
    single executor — what a compute node needs to wire exchange
    receivers for fragments built on OTHER nodes. Mirrors each
    executor's own schema computation; kinds without a rule refuse
    cluster deploy loudly instead of guessing. `on_node(node, input_
    schemas)` is a per-node hook (the cluster deploy's supported-plan
    checks ride it)."""
    out: dict[int, Schema] = {}

    def node_schema(n, fid) -> Schema:
        if isinstance(n, Exchange):
            return out[n.upstream]
        ins = [node_schema(i, fid) for i in n.inputs]
        if on_node is not None:
            on_node(n, ins)
        k, a = n.kind, n.args
        if k == "nexmark_source":
            conn = a.get("connector", "nexmark")
            if conn == "jsonl":
                from ..connectors.file_source import parse_columns
                return parse_columns(a["columns"])
            if conn == "tpch":
                from ..connectors.tpch import TPCH_SCHEMAS
                return TPCH_SCHEMAS[a["table"]]
            from ..connectors.nexmark import (AUCTION_SCHEMA, BID_SCHEMA,
                                              PERSON_SCHEMA)
            return {"bid": BID_SCHEMA, "person": PERSON_SCHEMA,
                    "auction": AUCTION_SCHEMA}[a["table"]]
        if k == "project":
            names = a.get("names") or [f"expr{i}"
                                       for i in range(len(a["exprs"]))]
            return Schema(tuple(SchemaField(nm, e.ret_type)
                                for nm, e in zip(names, a["exprs"])))
        if k in ("filter", "no_op", "dedup", "group_top_n",
                 "retract_top_n", "materialize", "sink", "dynamic_filter"):
            return ins[0]
        if k == "row_id_gen":
            return Schema(tuple(ins[0])
                          + (SchemaField("_row_id", DataType.SERIAL),))
        if k == "hop_window":
            full = list(ins[0]) + [
                SchemaField("window_start", DataType.TIMESTAMP),
                SchemaField("window_end", DataType.TIMESTAMP)]
            oi = a.get("output_indices")
            idx = tuple(oi) if oi is not None else tuple(range(len(full)))
            return Schema(tuple(full[i] for i in idx))
        if k == "hash_agg":
            gk = list(a["group_key_indices"])
            names = list(a.get("group_key_names")
                         or [ins[0][i].name for i in gk])
            return Schema(tuple(
                [SchemaField(nm, ins[0][i].data_type)
                 for nm, i in zip(names, gk)]
                + [SchemaField(f"agg{j}", c.ret_type)
                   for j, c in enumerate(a["agg_calls"])]))
        if k in ("simple_agg", "stateless_simple_agg"):
            return Schema(tuple(SchemaField(f"agg{j}", c.ret_type)
                                for j, c in enumerate(a["agg_calls"])))
        if k in ("hash_join", "sorted_join"):
            fields = tuple(ins[0]) + tuple(ins[1])
            oi = a.get("output_indices")
            if oi is not None:
                fields = tuple(fields[i] for i in oi)
            return Schema(fields)
        if k == "snapshot_join_agg":
            return Schema(tuple(SchemaField(nm, t) for nm, t in
                                zip(a["out_names"], a["out_types"])))
        raise NotImplementedError(
            f"cluster deploy: no schema rule for node kind {k!r}")

    for fid in graph.topo_order():
        out[fid] = node_schema(graph.fragments[fid].root, fid)
    return out


def cluster_remote_edges(graph: StreamGraph, placement: dict):
    """All cross-worker (edge, producer actor, consumer actor) pairs:
    [((up_fid, down_fid, edge_k, u, d), up_worker, down_worker)].
    Deterministic order — both endpoints derive the same pair list."""
    pairs = []
    for fid in graph.topo_order():
        f = graph.fragments[fid]
        for d_fid, k in graph.consumers(fid):
            d = graph.fragments[d_fid]
            for u in range(f.parallelism):
                for di in range(d.parallelism):
                    if f.dispatch == "simple" and f.parallelism > 1 \
                            and u != di:
                        continue          # NoShuffle pairs 1:1
                    uw = placement[fid][u]
                    dw = placement[d_fid][di]
                    if uw != dw:
                        pairs.append(((fid, d_fid, k, u, di), uw, dw))
    return pairs


def build_partial_graph(graph: StreamGraph, env: BuildEnv,
                        placement: dict, my_worker: int,
                        actors: dict, tables: dict,
                        schemas: dict[int, Schema],
                        remote_ins: dict, remote_outs: dict) -> Deployment:
    """Compute-node side of `LocalStreamManager::build_actors`: build and
    spawn ONLY the actors `placement` assigns to `my_worker`, with the
    pre-derived global ids (`assign_graph_ids`) and with cross-worker
    exchange legs resolved to the DCN endpoints the caller prepared
    (`remote_ins[(up,down,k,u,d)]` = recv()-able channel from a remote
    producer; `remote_outs[...]` = connected RemoteOutput to a remote
    consumer). Local legs use ordinary bounded channels exactly like
    `build_graph`."""
    env.pending_source_queues = []
    dep = Deployment(coord=env.coord)
    channels: dict[tuple[int, int, int], dict] = {}
    order = graph.topo_order()
    consumers = {fid: graph.consumers(fid) for fid in order}

    # local-local channel matrix entries only (sparse dict by (u, d));
    # replay buffers on every local leg, trimmed by meta's `committed`
    # push — a worker-local frontier edge replays into a rebuilt
    # consumer exactly like the single-process path
    replay = getattr(env, "partial_recovery", True)
    for fid in order:
        f = graph.fragments[fid]
        for d_fid, k in consumers[fid]:
            d = graph.fragments[d_fid]
            mat: dict = {}
            for u in range(f.parallelism):
                for di in range(d.parallelism):
                    if placement[fid][u] == my_worker \
                            and placement[d_fid][di] == my_worker:
                        ch = Channel(env.channel_capacity)
                        if replay:
                            ch.enable_replay()
                            dep.replay_channels.append(ch)
                        mat[(u, di)] = ch
            channels[(fid, d_fid, k)] = mat
    reg = getattr(env.coord, "register_replay_channels", None)
    if reg is not None and dep.replay_channels:
        reg(dep.replay_channels)

    def edge_chan(up_fid, fid, k, u, di):
        """Channel-like the consumer (fid actor di, local) reads for
        producer actor u of up_fid — a local Channel or a remote leg."""
        if placement[up_fid][u] == my_worker:
            return channels[(up_fid, fid, k)][(u, di)]
        return remote_ins[(up_fid, fid, k, u, di)]

    for fid in order:
        f = graph.fragments[fid]
        dep.roots[fid] = []
        frag_tables = tables[fid]
        for idx in range(f.parallelism):
            if placement[fid][idx] != my_worker:
                continue
            bitmaps = (shard_vnode_bitmaps(f.parallelism)
                       if f.parallelism > 1 else [None])
            actor_id = actors[fid][idx]
            ctx = ActorCtx(env=env, fragment=f, actor_id=actor_id,
                           actor_idx=idx, vnode_bitmap=bitmaps[idx],
                           table_ids=frag_tables)
            edge_seen: dict[int, int] = {}
            node_idx = {id(n): i
                        for i, n in enumerate(fragment_node_order(f))}

            def build_node(n):
                if isinstance(n, Exchange):
                    k = edge_seen.get(n.upstream, 0)
                    edge_seen[n.upstream] = k + 1
                    up = graph.fragments[n.upstream]
                    sch = schemas[n.upstream]
                    stop_on = (lambda b, aid=ctx.actor_id: b.is_stop(aid))
                    co = env.chunk_coalesce_max
                    if up.dispatch == "simple" and up.parallelism > 1:
                        return ChannelInput(
                            edge_chan(n.upstream, fid, k, idx, idx), sch,
                            stop_on=stop_on, coalesce_max=co)
                    chans = [edge_chan(n.upstream, fid, k, u, idx)
                             for u in range(up.parallelism)]
                    if len(chans) == 1:
                        return ChannelInput(chans[0], sch, stop_on=stop_on,
                                            coalesce_max=co)
                    return MergeExecutor(chans, sch, stop_on=stop_on,
                                         coalesce_max=co)
                inputs = [build_node(i) for i in n.inputs]
                return BUILDERS[n.kind](dict(n.args), inputs, ctx,
                                        (fid, node_idx[id(n)]))

            q_before = len(env.pending_source_queues)
            root = build_node(f.root)
            dep.roots[fid].append(root)
            _register_memory(dep, env, root, actor_id)
            _register_mesh(dep, env, root, actor_id, fid=fid)
            dispatcher = _cluster_dispatcher(graph, f, consumers[fid],
                                             channels, placement,
                                             my_worker, remote_outs, idx)
            env.coord.register_actor(actor_id)
            actor = Actor(actor_id, root, dispatcher, env.coord)
            dep.actors.append(actor)
            env.coord.stats.register(env.memory_scope or "flow",
                                     actor, root)
            dep.actor_fragment[actor_id] = fid
            dep.frag_actor_ids.setdefault(fid, []).append(actor_id)
            dep.actor_source_queues[actor_id] = list(
                env.pending_source_queues[q_before:])
            dep.actor_root[actor_id] = root
    dep.source_queues = list(env.pending_source_queues)
    # worker rebuild support (cluster partial recovery): the channel
    # dict rides with the deployment so a closure rebuild can reuse the
    # surviving legs and replace the dead ones
    dep.rebuild_info = {"graph": graph, "env": env, "channels": channels,
                        "consumers": consumers}
    return dep


def build_closure_actors(graph, env, dep, new_placement, my_worker,
                         actors, tables, schemas, closure,
                         in_leg, out_leg) -> list[Actor]:
    """Per-worker partial recovery, compute-node side: build the
    CLOSURE actors assigned to `my_worker` under the NEW placement —
    the dead worker's re-placed actors plus this worker's in-place
    rebuilds — with the ORIGINAL global ids and table maps (the shared
    vnode-partitioned state re-binds at the committed view exactly like
    `rebuild_fragment`). Edge legs resolve through the caller's
    resolvers, which route each edge per its recovery disposition
    (reused surviving channel, rewound remote leg, or a fresh pair
    between two rebuilt actors):

        in_leg(up_fid, fid, k, u, di)  -> recv()-able input leg
        out_leg(fid, d_fid, k, u, di)  -> awaitable send target

    Returns the new Actor list; the caller tears the old incarnations
    down first and spawns these after arming replay."""
    new_actors: list[Actor] = []
    for fid in graph.topo_order():
        f = graph.fragments[fid]
        for idx in sorted(closure.get(fid, ())):
            if new_placement[fid][idx] != my_worker:
                continue
            bitmaps = (shard_vnode_bitmaps(f.parallelism)
                       if f.parallelism > 1 else [None])
            actor_id = actors[fid][idx]
            ctx = ActorCtx(env=env, fragment=f, actor_id=actor_id,
                           actor_idx=idx, vnode_bitmap=bitmaps[idx],
                           table_ids=tables[fid])
            edge_seen: dict[int, int] = {}
            node_idx = {id(n): i
                        for i, n in enumerate(fragment_node_order(f))}

            def build_node(n):
                if isinstance(n, Exchange):
                    k = edge_seen.get(n.upstream, 0)
                    edge_seen[n.upstream] = k + 1
                    up = graph.fragments[n.upstream]
                    sch = schemas[n.upstream]
                    stop_on = (lambda b, aid=ctx.actor_id: b.is_stop(aid))
                    co = env.chunk_coalesce_max
                    if up.dispatch == "simple" and up.parallelism > 1:
                        return ChannelInput(
                            in_leg(n.upstream, fid, k, idx, idx), sch,
                            stop_on=stop_on, coalesce_max=co,
                            actor_id=ctx.actor_id)
                    chans = [in_leg(n.upstream, fid, k, u, idx)
                             for u in range(up.parallelism)]
                    if len(chans) == 1:
                        return ChannelInput(chans[0], sch,
                                            stop_on=stop_on,
                                            coalesce_max=co,
                                            actor_id=ctx.actor_id)
                    return MergeExecutor(chans, sch, stop_on=stop_on,
                                         coalesce_max=co)
                inputs = [build_node(i) for i in n.inputs]
                return BUILDERS[n.kind](dict(n.args), inputs, ctx,
                                        (fid, node_idx[id(n)]))

            q_before = len(env.pending_source_queues)
            root = build_node(f.root)
            dep.roots.setdefault(fid, []).append(root)
            _register_memory(dep, env, root, actor_id)
            _register_mesh(dep, env, root, actor_id, fid=fid)
            cons = graph.consumers(fid)
            dispatcher = None
            if cons:
                per_consumer = []
                for d_fid, k in cons:
                    d = graph.fragments[d_fid]
                    if f.dispatch == "hash":
                        if d.parallelism == 1:
                            per_consumer.append(SimpleDispatcher(
                                out_leg(fid, d_fid, k, idx, 0)))
                        else:
                            per_consumer.append(HashDispatcher(
                                [out_leg(fid, d_fid, k, idx, di)
                                 for di in range(d.parallelism)],
                                f.dist_key_indices,
                                vnode_to_shard(d.parallelism)))
                    elif f.dispatch == "broadcast":
                        per_consumer.append(BroadcastDispatcher(
                            [out_leg(fid, d_fid, k, idx, di)
                             for di in range(d.parallelism)]))
                    else:
                        per_consumer.append(SimpleDispatcher(
                            out_leg(fid, d_fid, k, idx, idx)))
                dispatcher = (per_consumer[0] if len(per_consumer) == 1
                              else FanoutDispatcher(per_consumer))
            env.coord.register_actor(actor_id)
            actor = Actor(actor_id, root, dispatcher, env.coord)
            env.coord.stats.register(env.memory_scope or "flow",
                                     actor, root)
            dep.actor_fragment[actor_id] = fid
            dep.frag_actor_ids.setdefault(fid, []).append(actor_id)
            new_queues = list(env.pending_source_queues[q_before:])
            dep.actor_source_queues[actor_id] = new_queues
            dep.source_queues.extend(new_queues)
            dep.actor_root[actor_id] = root
            new_actors.append(actor)
    return new_actors


def _cluster_dispatcher(graph, f, cons, channels, placement, my_worker,
                        remote_outs, idx):
    """Output dispatcher for LOCAL actor `idx` of fragment `f`: per
    consumer-actor targets are local channels or connected RemoteOutputs
    (both are awaitable `send(msg)` sinks, so the dispatchers are
    agnostic)."""
    if not cons:
        return None
    per_consumer = []
    for d_fid, k in cons:
        d = graph.fragments[d_fid]

        def target(di):
            if placement[d_fid][di] == my_worker:
                return channels[(f.fid, d_fid, k)][(idx, di)]
            return remote_outs[(f.fid, d_fid, k, idx, di)]

        if f.dispatch == "hash":
            if d.parallelism == 1:
                # same singleton-consumer simplification as
                # _dispatcher_for: one output = no routing needed
                per_consumer.append(SimpleDispatcher(target(0)))
            else:
                outs = [target(di) for di in range(d.parallelism)]
                per_consumer.append(HashDispatcher(
                    outs, f.dist_key_indices,
                    vnode_to_shard(d.parallelism)))
        elif f.dispatch == "broadcast":
            per_consumer.append(BroadcastDispatcher(
                [target(di) for di in range(d.parallelism)]))
        else:
            assert d.parallelism == f.parallelism, \
                "simple dispatch is 1:1 (NoShuffle)"
            per_consumer.append(SimpleDispatcher(target(idx)))
    return (per_consumer[0] if len(per_consumer) == 1
            else FanoutDispatcher(per_consumer))

"""Backfill (StreamScan/Chain) — bring a new MV up over an existing MV.

Reference: src/stream/src/executor/backfill/no_shuffle_backfill.rs — the
executor that makes `CREATE MATERIALIZED VIEW ... FROM <mv>` possible:
scan the upstream MV's table in pk order (the snapshot side) while the
upstream's LIVE changelog streams in, reconciling the two with a progress
pointer:

  * at every barrier, read the next snapshot batch of rows with
    pk > current_pos and emit them as Inserts, advancing current_pos;
  * live chunks pass through ONLY for rows at-or-before current_pos
    (their base row is already downstream); rows ahead of it are dropped —
    a later snapshot batch will read their post-change image;
  * when the scan is exhausted the executor flips to pass-through.

Epoch consistency: the upstream actor runs AHEAD of this executor (tap
channels buffer), so an unbounded snapshot read could see upstream epochs
this executor's barrier hasn't reached — a row would be emitted via the
snapshot AND forwarded live (double apply). Snapshot reads are therefore
bounded to staged epochs <= barrier.epoch.prev (exactly the epochs the
upstream sealed before forwarding this barrier), the analogue of the
reference reading the upstream table at precisely the barrier epoch.

Progress (vnode, pk, finished) persists to a state table at each barrier
and recovers on restart, so a mid-backfill crash resumes where it left
off (backfill_state_store in the reference).

Watermarks are suppressed until the backfill finishes: a watermark only
covers the live stream, and downstream state cleaning driven by it could
purge rows the snapshot side has yet to deliver.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import StreamChunk
from ..common.types import DataType, Field, Schema
from ..common.vnode import VNODE_COUNT, compute_vnodes
from ..state.state_table import StateTable
from ..state.storage_table import StorageTable
from .executor import Executor
from .message import Barrier, BarrierKind, Watermark
from ..ops.jit_state import jit_state


def backfill_progress_schema(mv_schema: Schema,
                             pk_indices: Sequence[int]) -> Schema:
    fields = [Field("slot", DataType.INT64), Field("finished", DataType.INT64),
              Field("vnode", DataType.INT64), Field("has_pk", DataType.INT64)]
    for j, i in enumerate(pk_indices):
        fields.append(Field(f"pk{j}", mv_schema[i].data_type))
    return Schema(tuple(fields))


class BackfillExecutor(Executor):
    def __init__(self, upstream: Executor, storage: StorageTable,
                 state_table: Optional[StateTable] = None,
                 batch_rows: int = 65536, chunk_capacity: int = 8192):
        self.input = upstream                 # live changelog tap
        self.storage = storage
        self.schema = storage.schema
        self.pk_indices = tuple(storage.pk_indices)
        self.state_table = state_table
        self.batch_rows = batch_rows
        self.chunk_capacity = chunk_capacity
        self.identity = f"Backfill(table={storage.table_id})"
        self._dist_idx = tuple(storage._layout.dist_key_indices)
        # progress
        self.finished = False
        self.vnode = 0                        # vnodes < this are complete
        self.last_pk: Optional[tuple] = None  # within self.vnode
        self._filter = jit_state(self._filter_impl, name="backfill_filter")
        self.snapshot_rows_total = 0

    # ------------------------------------------------------------ filtering
    def _filter_impl(self, chunk: StreamChunk, cur_vnode, has_pk, pk_vals):
        """Keep rows already covered by the snapshot scan:
        vnode < cur  OR  (vnode == cur AND has_pk AND pk <= last_pk)."""
        vn = compute_vnodes([chunk.columns[i].data for i in self._dist_idx])
        vn = vn.astype(jnp.int64)
        passed = vn < cur_vnode
        le = jnp.ones(chunk.capacity, dtype=bool)
        for i, v in zip(reversed(self.pk_indices), reversed(pk_vals)):
            c = chunk.columns[i].data
            le = (c < v) | ((c == v) & le)
        passed = passed | ((vn == cur_vnode) & has_pk & le)
        return chunk.mask(passed)

    def _filter_chunk(self, chunk: StreamChunk) -> StreamChunk:
        pk_vals = tuple(
            jnp.asarray(self.last_pk[j] if self.last_pk is not None else 0,
                        dtype=self.schema[i].data_type.jnp_dtype)
            for j, i in enumerate(self.pk_indices))
        return self._filter(chunk, jnp.int64(self.vnode),
                            jnp.bool_(self.last_pk is not None), pk_vals)

    # ------------------------------------------------------------- snapshot
    def _snapshot_batch(self, max_epoch: int) -> list[StreamChunk]:
        """Read up to batch_rows rows after the current position; advance
        the position; flip finished when the scan is exhausted."""
        rows: list[tuple] = []
        budget = self.batch_rows
        while budget > 0 and self.vnode < VNODE_COUNT:
            got, exhausted = self.storage.scan_vnode_after(
                self.vnode, self.last_pk, budget, max_epoch=max_epoch)
            rows.extend(got)
            budget -= len(got)
            if exhausted:
                self.vnode += 1
                self.last_pk = None
            else:
                self.last_pk = tuple(got[-1][i] for i in self.pk_indices)
        if self.vnode >= VNODE_COUNT:
            self.finished = True
        self.snapshot_rows_total += len(rows)
        from ..state.storage_table import rows_to_columns
        out = []
        for ofs in range(0, len(rows), self.chunk_capacity):
            part = rows[ofs:ofs + self.chunk_capacity]
            arrays, valids = rows_to_columns(self.schema, part)
            out.append(StreamChunk.from_numpy(
                self.schema, arrays, capacity=self.chunk_capacity,
                valids=[None if v.all() else v for v in valids]))
        return out

    # ------------------------------------------------------------ progress
    def _persist(self, barrier: Barrier) -> None:
        if self.state_table is None:
            return
        pk = (tuple(self.last_pk) if self.last_pk is not None
              else tuple(0 for _ in self.pk_indices))
        row = (0, int(self.finished), self.vnode,
               int(self.last_pk is not None)) + pk
        self.state_table.write_chunk_rows([(0, row)])
        self.state_table.commit(barrier.epoch.curr)

    def _recover(self) -> None:
        if self.state_table is None:
            return
        row = self.state_table.get_row((0,))
        if row is None:
            return
        _, finished, vnode, has_pk, *pk = row
        self.finished = bool(finished)
        self.vnode = int(vnode)
        self.last_pk = tuple(pk) if has_pk else None

    # --------------------------------------------------------------- stream
    async def execute(self):
        first = True
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                if self.finished:
                    yield msg
                else:
                    yield self._filter_chunk(msg)
            elif isinstance(msg, Barrier):
                if first or msg.kind is BarrierKind.INITIAL:
                    first = False
                    if self.state_table is not None:
                        self.state_table.init_epoch(msg.epoch.curr)
                        self._recover()
                    yield msg
                    continue
                if not self.finished:
                    for chunk in self._snapshot_batch(msg.epoch.prev):
                        yield chunk
                self._persist(msg)
                yield msg
            else:
                wm: Watermark = msg
                if self.finished:
                    yield wm

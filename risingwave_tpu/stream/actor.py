"""Actor — the schedulable unit driving one executor chain.

Reference: src/stream/src/executor/actor.rs:138-247 — an infinite loop pulling
the chain's final stream, fanning out through the dispatcher, reporting every
barrier to the local barrier manager (`collect`), exiting on a Stop mutation.
Here actors are asyncio tasks; device work inside executors runs async to the
host loop (XLA dispatch is non-blocking until results are fetched).

Observability (stream/monitor.py): when the coordinator's StreamingStats
attaches an `ActorObs` (metric_level >= info), the loop times every poll
of the chain and splits each barrier interval into apply (chunk compute +
dispatch), persist (the barrier-yielding poll — the chain's flush/commit
work), and align (input-channel waits reported by the exchange inputs +
the epoch fence). The split rides to the EpochTracer at collect time, so
`\trace` answers "who held epoch N and doing what". At metric_level=off
`self.obs` is None and the loop is the uninstrumented one.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional, Protocol

from ..common.chunk import StreamChunk
from ..utils.faults import FAULTS, FaultInjected
from .exchange import Dispatcher
from .executor import Executor
from .message import Barrier
from .monitor import dispatcher_fanout


class BarrierCollector(Protocol):
    def collect(self, actor_id: int, barrier: Barrier) -> None: ...


class Actor:
    def __init__(self, actor_id: int, consumer: Executor,
                 dispatcher: Optional[Dispatcher],
                 collector: Optional[BarrierCollector]):
        self.actor_id = actor_id
        self.consumer = consumer
        self.dispatcher = dispatcher
        self.collector = collector
        self.rows_processed = 0
        # per-chain epoch fence (plan/build._fuse_mesh_chains): a HOLLOW
        # producer actor dispatches no device programs of its own — its
        # stages run inside the downstream fused program, whose actor's
        # fence covers the whole chain — so its barrier path skips the
        # token gather + block
        self.fence_exempt = False
        # per-actor instrument bundle (stream/monitor.py ActorObs);
        # attached/removed by the coordinator's StreamingStats
        self.obs = None

    async def run(self) -> None:
        try:
            await self._run_inner()
        except BaseException as e:
            # report the death so barrier collection fails fast instead of
            # hanging the coordinator (reference: collection failure =>
            # global recovery, barrier/recovery.rs:332)
            failed = getattr(self.collector, "actor_failed", None)
            if failed is not None:
                failed(self.actor_id, e)
            raise

    async def _run_inner(self) -> None:
        last_token = None
        it = self.consumer.execute().__aiter__()
        mono = time.monotonic_ns
        while True:
            obs = self.obs
            if obs is not None:
                t_poll = mono()
                w0 = obs.input_wait_ns
            try:
                msg = await it.__anext__()
            except StopAsyncIteration:
                return
            if obs is not self.obs:
                # re-instrumented while parked in the poll (SET
                # metric_level): restart the span at the switch point so
                # this very message already reports under the new level
                obs = self.obs
                if obs is not None:
                    t_poll = mono()
                    w0 = obs.input_wait_ns
            if isinstance(msg, StreamChunk):
                if msg.columns:
                    last_token = msg.columns[0].data
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(msg)
                if obs is not None:
                    # poll span minus the channel-recv wait accrued inside
                    # it = actual chunk compute + dispatch time
                    waited = obs.input_wait_ns - w0
                    obs.apply_ns += max(0, mono() - t_poll - waited)
                    obs.note_chunk_out(msg,
                                       dispatcher_fanout(self.dispatcher))
            elif isinstance(msg, Barrier):
                if FAULTS.active and FAULTS.hit(
                        "actor_crash", actor=self.actor_id,
                        epoch=msg.epoch.curr) is not None:
                    # before the dispatch: downstream never sees this
                    # barrier, exactly like a mid-interval executor death
                    raise FaultInjected(
                        f"injected actor_crash at actor {self.actor_id} "
                        f"epoch {msg.epoch.curr}")
                barrier = msg.with_passed(self.actor_id)
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(barrier)
                if obs is not None:
                    # the barrier-yielding poll is the chain's barrier
                    # work: every executor's flush/persist/commit runs
                    # inside it before the barrier emerges
                    waited = obs.input_wait_ns - w0
                    obs.persist_ns += max(0, mono() - t_poll - waited)
                # Epoch fence: the barrier is only reported collected once
                # every device program of the epoch has actually executed
                # (the chain dispatches asynchronously) — the last chunk
                # covers per-chunk programs; executor fence tokens cover
                # barrier-time programs (flush/evict/purge) dispatched
                # after it. block_until_ready moves no data — on a
                # tunneled TPU that distinction is critical, a d2h
                # transfer here would permanently degrade dispatch.
                # Blocking runs in a worker thread so other actors keep
                # draining.
                from .executor import gather_fence_tokens
                if self.fence_exempt:
                    tokens = []
                else:
                    tokens = ([last_token]
                              if last_token is not None else [])
                    tokens.extend(gather_fence_tokens(self.consumer))
                t_fence = mono() if obs is not None else 0
                for tok in tokens:
                    if hasattr(tok, "block_until_ready"):
                        await asyncio.to_thread(tok.block_until_ready)
                last_token = None
                if obs is not None:
                    obs.fence_ns += mono() - t_fence
                    phases = obs.on_barrier()
                    ph = getattr(self.collector, "collect_phases", None)
                    if ph is not None:
                        ph(self.actor_id, barrier, phases)
                if self.collector is not None:
                    self.collector.collect(self.actor_id, barrier)
                if barrier.is_stop(self.actor_id):
                    return
            else:
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(msg)
                if obs is not None:
                    waited = obs.input_wait_ns - w0
                    obs.apply_ns += max(0, mono() - t_poll - waited)

    def spawn(self) -> asyncio.Task:
        return asyncio.create_task(self.run(), name=f"actor-{self.actor_id}")

"""Actor — the schedulable unit driving one executor chain.

Reference: src/stream/src/executor/actor.rs:138-247 — an infinite loop pulling
the chain's final stream, fanning out through the dispatcher, reporting every
barrier to the local barrier manager (`collect`), exiting on a Stop mutation.
Here actors are asyncio tasks; device work inside executors runs async to the
host loop (XLA dispatch is non-blocking until results are fetched).
"""

from __future__ import annotations

import asyncio
from typing import Optional, Protocol

from ..common.chunk import StreamChunk
from .exchange import Dispatcher
from .executor import Executor
from .message import Barrier


class BarrierCollector(Protocol):
    def collect(self, actor_id: int, barrier: Barrier) -> None: ...


class Actor:
    def __init__(self, actor_id: int, consumer: Executor,
                 dispatcher: Optional[Dispatcher],
                 collector: Optional[BarrierCollector]):
        self.actor_id = actor_id
        self.consumer = consumer
        self.dispatcher = dispatcher
        self.collector = collector
        self.rows_processed = 0

    async def run(self) -> None:
        try:
            await self._run_inner()
        except BaseException as e:
            # report the death so barrier collection fails fast instead of
            # hanging the coordinator (reference: collection failure =>
            # global recovery, barrier/recovery.rs:332)
            failed = getattr(self.collector, "actor_failed", None)
            if failed is not None:
                failed(self.actor_id, e)
            raise

    async def _run_inner(self) -> None:
        import asyncio as _asyncio
        last_token = None
        async for msg in self.consumer.execute():
            if isinstance(msg, StreamChunk):
                if msg.columns:
                    last_token = msg.columns[0].data
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(msg)
            elif isinstance(msg, Barrier):
                barrier = msg.with_passed(self.actor_id)
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(barrier)
                # Epoch fence: the barrier is only reported collected once
                # every device program of the epoch has actually executed
                # (the chain dispatches asynchronously) — the last chunk
                # covers per-chunk programs; executor fence tokens cover
                # barrier-time programs (flush/evict/purge) dispatched
                # after it. block_until_ready moves no data — on a
                # tunneled TPU that distinction is critical, a d2h
                # transfer here would permanently degrade dispatch.
                # Blocking runs in a worker thread so other actors keep
                # draining.
                from .executor import gather_fence_tokens
                tokens = [last_token] if last_token is not None else []
                tokens.extend(gather_fence_tokens(self.consumer))
                for tok in tokens:
                    if hasattr(tok, "block_until_ready"):
                        await _asyncio.to_thread(tok.block_until_ready)
                last_token = None
                if self.collector is not None:
                    self.collector.collect(self.actor_id, barrier)
                if barrier.is_stop(self.actor_id):
                    return
            else:
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(msg)

    def spawn(self) -> asyncio.Task:
        return asyncio.create_task(self.run(), name=f"actor-{self.actor_id}")

"""Actor — the schedulable unit driving one executor chain.

Reference: src/stream/src/executor/actor.rs:138-247 — an infinite loop pulling
the chain's final stream, fanning out through the dispatcher, reporting every
barrier to the local barrier manager (`collect`), exiting on a Stop mutation.
Here actors are asyncio tasks; device work inside executors runs async to the
host loop (XLA dispatch is non-blocking until results are fetched).
"""

from __future__ import annotations

import asyncio
from typing import Optional, Protocol

from ..common.chunk import StreamChunk
from .exchange import Dispatcher
from .executor import Executor
from .message import Barrier


class BarrierCollector(Protocol):
    def collect(self, actor_id: int, barrier: Barrier) -> None: ...


class Actor:
    def __init__(self, actor_id: int, consumer: Executor,
                 dispatcher: Optional[Dispatcher],
                 collector: Optional[BarrierCollector]):
        self.actor_id = actor_id
        self.consumer = consumer
        self.dispatcher = dispatcher
        self.collector = collector
        self.rows_processed = 0

    async def run(self) -> None:
        async for msg in self.consumer.execute():
            if isinstance(msg, StreamChunk):
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(msg)
            elif isinstance(msg, Barrier):
                barrier = msg.with_passed(self.actor_id)
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(barrier)
                if self.collector is not None:
                    self.collector.collect(self.actor_id, barrier)
                if barrier.is_stop(self.actor_id):
                    return
            else:
                if self.dispatcher is not None:
                    await self.dispatcher.dispatch(msg)

    def spawn(self) -> asyncio.Task:
        return asyncio.create_task(self.run(), name=f"actor-{self.actor_id}")

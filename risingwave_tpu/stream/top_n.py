"""TopN / GroupTopN executors (append-only) with device-resident state.

Reference: src/stream/src/executor/top_n/ — `TopNCache` keeps the rows in
[0, offset+limit) per group, materialized in a state table, emitting
changelog rows as entries enter/leave the window
(top_n_cache.rs, group_top_n.rs, top_n_appendonly.rs).

TPU re-design: per-group state is a dense sorted buffer in HBM —
  keys_sorted [C, K]  (K = offset + limit; asc or desc)
  valid       [C, K]  explicit cell validity (no in-band sentinel: a real
                      row whose order value equals iinfo.max must survive)
  payload     [C, K]  per output column
Group lookup reuses the open-addressing HashTable (ungrouped TopN is the
C=1 degenerate case, no table). Applying a chunk is ONE jitted step:
  1. slot assignment for each row's group key;
  2. in-chunk top-K per group: lexsort rows by (slot, sort_key), rank
     within the slot run, keep rank < K, scatter into cand[C, K];
  3. merge: lexsort(concat(state, cand), keys=(order, ~valid), axis=1)
     [:, :K] — invalid cells sort last, payload columns ride along via
     take_along_axis.
At each barrier a second jitted step diffs the previous emitted window
against the new one POSITIONALLY and lays out Delete/Insert rows for dirty
groups (a positional diff may retract+reinsert a shifted row — a correct,
slightly redundant changelog; the reference emits minimal diffs). The SAME
diff chunk is what gets persisted: deletes tombstone rows that left a
window, so committed state stays bounded by the live windows.

Append-only only: deletions would need refill-from-below (the reference
fetches from the state table); that retractable variant is future work.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column, StreamChunk, OP_DELETE, OP_INSERT, op_sign
from ..ops.hash_table import (HashTable, lookup_or_insert,
                              stable_lexsort, stable_lexsort_rows)
from ..ops.jit_state import jit_state
from ..state.state_table import StateTable
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier, Watermark


class GroupTopNExecutor(StatefulUnaryExecutor):
    """Append-only GroupTopN. Output schema == input schema (the reference
    emits input rows; rank is not a column unless the plan projects it).

    group_key_indices=() gives ungrouped TopN (single window, capacity 1).
    order_col: the sort column (int-comparable dtypes); descending=False
    emits the smallest `limit` rows per group after skipping `offset`."""

    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 order_col: int, limit: int, offset: int = 0,
                 descending: bool = False,
                 capacity: int = 1 << 12,
                 state_table: Optional[StateTable] = None,
                 watchdog_interval: Optional[int] = 1):
        self.input = input
        self.group_key_indices = tuple(group_key_indices)
        self.grouped = bool(self.group_key_indices)
        self.order_col = order_col
        self.limit = limit
        self.offset = offset
        self.K = offset + limit
        self.descending = descending
        self.schema = input.schema
        self.pk_indices = input.pk_indices
        self.capacity = capacity if self.grouped else 1
        self.identity = (f"GroupTopN(keys={self.group_key_indices}, "
                         f"order={order_col}, limit={limit}, offset={offset})")
        in_schema = input.schema
        self._key_dtypes = tuple(
            in_schema[i].data_type.jnp_dtype for i in self.group_key_indices)
        self._col_dtypes = tuple(f.data_type.jnp_dtype for f in in_schema)
        self._order_dtype = in_schema[order_col].data_type.jnp_dtype
        C, K = self.capacity, self.K
        self.table = (HashTable.empty(C, self._key_dtypes)
                      if self.grouped else None)
        self.keys_sorted = jnp.zeros((C, K), dtype=self._order_dtype)
        self.valid = jnp.zeros((C, K), dtype=bool)
        self.payload = tuple(
            jnp.zeros((C, K), dtype=dt) for dt in self._col_dtypes)
        self.dirty = jnp.zeros(C, dtype=bool)
        self.prev_keys = jnp.zeros((C, K), dtype=self._order_dtype)
        self.prev_valid = jnp.zeros((C, K), dtype=bool)
        self.prev_payload = tuple(
            jnp.zeros((C, K), dtype=dt) for dt in self._col_dtypes)
        # Donate only state that is never aliased: the group table, the
        # dirty bitmap, and the error accumulator. keys_sorted / valid /
        # payload must NOT be donated — flush() re-binds them as prev_*
        # (the diff base), so the same arrays stay live across the next
        # apply. In _flush the OLD prev_* (args 4-6) are consumed and
        # replaced, so those donate.
        self._apply = jit_state(self._apply_impl, donate_argnums=(0, 4, 5),
                                name="top_n_apply")
        self._flush = jit_state(self._flush_impl, donate_argnums=(4, 5, 6),
                                name="top_n_flush")
        self._errs_dev = jnp.zeros((), dtype=jnp.int32)
        self._init_stateful(state_table, watchdog_interval)

    def fence_tokens(self) -> list:
        return [self.valid] + super().fence_tokens()

    # --------------------------------------------------------- chunk step
    def _apply_impl(self, table, keys_sorted, valid, payload, dirty,
                    errs, chunk: StreamChunk):
        N = chunk.capacity
        K = self.K
        C = self.capacity
        active = chunk.vis & (op_sign(chunk.ops) > 0)   # append-only
        n_viol = jnp.sum((chunk.vis & (op_sign(chunk.ops) < 0))
                         .astype(jnp.int32))
        if self.grouped:
            key_cols = [chunk.columns[i].data
                        for i in self.group_key_indices]
            table, slots, n_un = lookup_or_insert(table, key_cols, active)
            ok = slots >= 0
            seg = jnp.where(ok, slots, C)
        else:
            n_un = jnp.int32(0)
            ok = active
            seg = jnp.where(active, 0, C).astype(jnp.int32)

        order_vals = chunk.columns[self.order_col].data
        # descending: bitwise-not is monotone-decreasing and, unlike unary
        # minus, cannot overflow at iinfo.min
        rank_key = (jnp.invert(order_vals) if self.descending
                    else order_vals)
        # in-chunk rank within group; inactive rows sort last via ~ok key
        row_ids = jnp.arange(N, dtype=jnp.int32)
        order = stable_lexsort((row_ids, rank_key, seg))
        sseg = seg[order]
        new_run = jnp.concatenate([jnp.array([True]), sseg[1:] != sseg[:-1]])
        pos = jnp.arange(N, dtype=jnp.int32)
        run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
        rank = pos - run_start
        keep = (sseg < C) & (rank < K)
        tgt_row = jnp.where(keep, sseg, C)
        tgt_col = jnp.minimum(rank, K - 1)

        cand_keys = jnp.zeros((C + 1, K), dtype=self._order_dtype)
        cand_keys = cand_keys.at[tgt_row, tgt_col].set(
            order_vals[order].astype(self._order_dtype), mode="drop")
        cand_valid = jnp.zeros((C + 1, K), dtype=bool)
        cand_valid = cand_valid.at[tgt_row, tgt_col].set(True, mode="drop")

        merged_keys = jnp.concatenate([keys_sorted, cand_keys[:C]], axis=1)
        merged_valid = jnp.concatenate([valid, cand_valid[:C]], axis=1)
        mk = jnp.invert(merged_keys) if self.descending else merged_keys
        # lexsort axis=1: primary = invalid-last, secondary = order key
        sort_idx = stable_lexsort_rows((mk, ~merged_valid))[:, :K]
        new_sorted = jnp.take_along_axis(merged_keys, sort_idx, axis=1)
        new_valid = jnp.take_along_axis(merged_valid, sort_idx, axis=1)
        new_payload = []
        for j, (p, dt) in enumerate(zip(payload, self._col_dtypes)):
            col = chunk.columns[j].data
            cand_p = jnp.zeros((C + 1, K), dtype=dt)
            cand_p = cand_p.at[tgt_row, tgt_col].set(
                col[order].astype(dt), mode="drop")
            merged_p = jnp.concatenate([p, cand_p[:C]], axis=1)
            new_payload.append(
                jnp.take_along_axis(merged_p, sort_idx, axis=1))
        adds = jax.ops.segment_sum(keep.astype(jnp.int32), tgt_row, C + 1)[:C]
        touched = adds > 0
        changed = touched & jnp.any(
            (new_sorted != keys_sorted) | (new_valid != valid), axis=1)
        return (table, new_sorted, new_valid, tuple(new_payload),
                dirty | changed, errs + n_un + n_viol)

    # ------------------------------------------------------- barrier diff
    def _flush_impl(self, keys_sorted, valid, payload, dirty,
                    prev_keys, prev_valid, prev_payload):
        """Positional diff of window [offset, K) between prev and current.
        Layout: per group, K delete rows then K insert rows (delete before
        insert keeps downstream MV conflict handling trivial)."""
        C, K = keys_sorted.shape
        win = jnp.arange(K)[None, :] >= self.offset
        in_new = win & valid
        in_prev = win & prev_valid
        same = (valid == prev_valid) & (
            ~valid | (keys_sorted == prev_keys))
        for p, pp in zip(payload, prev_payload):
            same = same & (~valid | ~prev_valid | (p == pp))
        emit_del = dirty[:, None] & in_prev & ~(in_new & same)
        emit_ins = dirty[:, None] & in_new & ~(in_prev & same)
        out_vis = jnp.concatenate([emit_del, emit_ins], axis=1).reshape(-1)
        ops_row = jnp.concatenate(
            [jnp.full((C, K), OP_DELETE, dtype=jnp.int8),
             jnp.full((C, K), OP_INSERT, dtype=jnp.int8)],
            axis=1).reshape(-1)
        out_cols = [jnp.concatenate([pp, p], axis=1).reshape(-1)
                    for p, pp in zip(payload, prev_payload)]
        return out_cols, ops_row, out_vis

    # -------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> None:
        (self.table, self.keys_sorted, self.valid, self.payload,
         self.dirty, self._errs_dev) = self._apply(
            self.table, self.keys_sorted, self.valid, self.payload,
            self.dirty, self._errs_dev, chunk)
        return None

    def check_watchdog(self) -> None:
        n = int(np.asarray(self._errs_dev))
        if n:
            raise RuntimeError(
                f"group-topn overflow or append-only violation ({n} rows, "
                f"capacity {self.capacity})")

    def flush(self) -> StreamChunk:
        cols, ops, vis = self._flush(
            self.keys_sorted, self.valid, self.payload, self.dirty,
            self.prev_keys, self.prev_valid, self.prev_payload)
        self.prev_keys = self.keys_sorted
        self.prev_valid = self.valid
        self.prev_payload = self.payload
        self.dirty = jnp.zeros(self.capacity, dtype=bool)
        return StreamChunk(
            tuple(Column(c) for c in cols), ops, vis, self.schema)

    def persist(self, barrier: Barrier,
                flushed: Optional[StreamChunk]) -> None:
        """Persist the window CHANGELOG: inserts for rows that entered,
        deletes (tombstones) for rows that left — committed state stays
        bounded by the live windows (hash_agg's evict-delete persist path
        has the same role)."""
        if self.state_table is None:
            return
        if flushed is not None:
            self.state_table.write_chunk_rows(flushed.to_rows())
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        rows = [row for _, row in self.state_table.iter_all()]
        if not rows:
            return
        arrays = [np.asarray([r[j] for r in rows])
                  for j in range(len(self._col_dtypes))]
        cap = max(64, 1 << int(np.ceil(np.log2(len(rows) + 1))))
        n = len(rows)
        vis = np.arange(cap) < n
        chunk = StreamChunk(
            tuple(Column(jnp.asarray(np.resize(a, cap))) for a in arrays),
            jnp.full(cap, OP_INSERT, dtype=jnp.int8),
            jnp.asarray(vis), self.schema)
        self.on_chunk(chunk)
        # recovered windows were already emitted before the crash
        self.prev_keys = self.keys_sorted
        self.prev_valid = self.valid
        self.prev_payload = self.payload
        self.dirty = jnp.zeros(self.capacity, dtype=bool)
        self._applied_since_flush = False

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return wm if wm.col_idx in self.group_key_indices else None


def top_n(input: Executor, order_col: int, limit: int, offset: int = 0,
          descending: bool = False, **kw) -> GroupTopNExecutor:
    """Ungrouped TopN (reference top_n_appendonly.rs) — the C=1 case."""
    return GroupTopNExecutor(input, (), order_col, limit, offset=offset,
                             descending=descending, **kw)

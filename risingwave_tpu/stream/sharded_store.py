"""Vnode-sharded dense sorted-row store — the shared mesh plumbing behind
`ShardedTopNExecutor` and `ShardedOverWindowExecutor`.

Both executors keep their FULL input in the dense sorted store
(sorted_store.py) and diff a derived set at each barrier. Sharding that
layout over the vnode mesh axis is identical for both — and identical in
shape to sharded_agg.py, the pattern this module mirrors:

* state arrays go global [S*C] with per-shard [C] views under shard_map
  (`capacity` becomes PER SHARD); the live count and error counters go
  per-shard ([S] / [S*2] int32, mesh-sharded);
* the FUSED plane routes each chunk's rows to their owner shard with
  `mesh_ingest_chunk` (one all_to_all over ICI — no host hop) keyed on
  the executor's ROUTING KEY (group/partition axis; the stream key for
  a global top-N), then applies `sorted_store_apply` per shard; chunks
  buffered within a barrier interval batch into one `lax.scan` inside
  the same program — one fused dispatch per interval;
* hollow producer stages (project / hop_window preludes installed by
  plan/build._fuse_mesh_chains) trace INSIDE the fused program, before
  the shuffle;
* shuffle overflow / store overflow / delete-miss accumulate on device
  and FAIL-STOP at the barrier watchdog fetch (one packed d2h);
* `MeshIngestLog` retains the uncommitted ingest suffix as the
  mesh-plane replay point; `preload_replay` re-feeds it after a
  scope=mesh recovery;
* durable persist/seal/recovery run unchanged through the sharded
  layout: epoch chunks write through to the state table at the barrier,
  and recovery partitions durable rows by the same vnode routing the
  apply path uses, rebuilding each shard's local store.

Per-shard capacity is STATIC at runtime (growth would need a global
re-layout — overflow fail-stops and recovery re-sizes from the worst
shard), matching the sharded agg's contract.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common.chunk import StreamChunk
from ..common.vnode import compute_vnodes
from ..ops.jit_state import jit_state
from ..parallel.exchange import mesh_ingest_chunk, shuffle_cap_out
from ..parallel.mesh import VNODE_AXIS, shard_map, vnode_to_shard
from .sharded_agg import MeshIngestLog
from .sorted_join import _HSENTINEL
from .sorted_store import sorted_store_apply


class ShardedSortedStoreMixin:
    """Mesh plumbing over a (khash, cols, valids, n) sorted store plus a
    same-capacity secondary set. Subclasses (which also inherit the
    single-device executor) must provide:

      route_key_indices   columns the shuffle routes on
      _SECONDARY          (hash, cols, valids) secondary attr names
      _SEC_COUNT          the secondary's live-count attr name
      _flush_local(...)   per-shard flush body (parent's _flush_impl or
                          a mesh-aware variant), called INSIDE shard_map
      _overflow_what      human label for the fail-stop messages

    and call `_init_sharded(...)` AFTER the parent constructor."""

    _SEC_COUNT = ""
    _overflow_what = "sharded sorted store"

    # --------------------------------------------------------------- init
    def _init_sharded(self, mesh, mesh_shuffle: bool,
                      mesh_shuffle_slack: int, mesh_shuffle_adaptive: bool,
                      watchdog_interval: Optional[int]) -> None:
        self.mesh = mesh
        self.n_shards = mesh.shape[VNODE_AXIS]
        self._routing = jnp.asarray(vnode_to_shard(self.n_shards))
        self.mesh_shuffle = bool(mesh_shuffle)
        self.mesh_shuffle_slack = int(mesh_shuffle_slack)
        if self.mesh_shuffle_slack and watchdog_interval is None:
            raise ValueError(
                "mesh_shuffle_slack > 0 needs the barrier watchdog fetch "
                "(watchdog_interval=1): shuffle drops would otherwise go "
                "unchecked and a checkpoint could commit with rows "
                "missing; transfer-free pipelines must use slack 0 "
                "(zero-drop sizing)")
        self.mesh_shuffle_adaptive = (bool(mesh_shuffle_adaptive)
                                      and self.mesh_shuffle_slack == 0
                                      and watchdog_interval is not None)
        self._cap_hint: Optional[int] = None
        self._fill_ewma = 0.0
        self._fill_peak = 0
        self._fill_obs = 0
        self._mesh_preludes: tuple = ()
        self.mesh_chain: Optional[str] = None
        self._replay_preload: list = []
        self.mesh_shuffle_applies = 0
        self._pending_chunks: list = []
        self._batch_max = 8
        self._occ_known = 0
        self.ingest_log = MeshIngestLog()
        self._alloc_sharded_store()
        self._build_sharded_programs()

    def _sharding(self):
        return NamedSharding(self.mesh, P(VNODE_AXIS))

    def _store_schema(self):
        """Schema of the rows the dense store holds (and the state table
        persists) — the executor's input row layout."""
        return self.schema

    def _alloc_sharded_store(self) -> None:
        """Replace the parent's single-device [C] arrays with global
        [S*C] mesh-sharded ones; counts become per-shard [S] lanes."""
        S, C = self.n_shards, self.capacity
        sharding = self._sharding()

        def put(x):
            return jax.device_put(x, sharding)

        dts = tuple(f.data_type.jnp_dtype for f in self._store_schema())
        self.khash = put(jnp.full(S * C, _HSENTINEL, dtype=jnp.int64))
        self.cols = tuple(put(jnp.zeros(S * C, dtype=dt)) for dt in dts)
        self.valids = tuple(put(jnp.zeros(S * C, dtype=bool)) for _ in dts)
        self.n = put(jnp.zeros(S, dtype=jnp.int32))
        self._alloc_sharded_secondary()
        # per-shard error/overflow accumulators ([row_ovf, del_miss] per
        # shard) + the shuffle watchdog lanes, all mesh-sharded
        self._errs_dev = put(jnp.zeros(S * 2, dtype=jnp.int32))
        self._dropped_dev = put(jnp.zeros(S, dtype=jnp.int32))
        self._send_occ_dev = put(jnp.zeros(S, dtype=jnp.int32))

    def _alloc_sharded_secondary(self) -> None:
        S, C = self.n_shards, self.capacity
        sharding = self._sharding()

        def put(x):
            return jax.device_put(x, sharding)

        h, c, v = self._SECONDARY
        sec_dts = tuple(x.dtype for x in getattr(self, c))
        setattr(self, h, put(jnp.full(S * C, _HSENTINEL, dtype=jnp.int64)))
        setattr(self, c, tuple(put(jnp.zeros(S * C, dtype=dt))
                               for dt in sec_dts))
        setattr(self, v, tuple(put(jnp.zeros(S * C, dtype=bool))
                               for _ in sec_dts))
        setattr(self, self._SEC_COUNT, put(jnp.zeros(S, dtype=jnp.int32)))

    def _build_sharded_programs(self) -> None:
        """(Re)wrap the step impls in shard_map — called at init and
        after a recovery re-size (the programs close over capacity)."""
        shard, repl = P(VNODE_AXIS), P()
        mesh_kw = dict(mesh=self.mesh)
        name = type(self).__name__

        def apply_sharded(khash, cols, valids, n, errs, chunk):
            # replicated-mask fallback: every shard sees the whole chunk
            # and masks it down to the rows it owns
            my = jax.lax.axis_index(VNODE_AXIS)
            key_cols = [chunk.columns[i].data
                        for i in self.route_key_indices]
            vn = compute_vnodes(key_cols)
            mine = chunk.vis & (self._routing[vn] == my)
            local = StreamChunk(chunk.columns, chunk.ops, mine,
                                chunk.schema)
            kh, c, v, n2, e2 = sorted_store_apply(
                khash, cols, valids, n[0], errs, local,
                pk_idx=self.pk_indices, capacity=self.capacity)
            return kh, c, v, n2[None], e2

        self._apply = jit_state(shard_map(
            apply_sharded, in_specs=(shard,) * 5 + (repl,),
            out_specs=(shard,) * 5, **mesh_kw),
            donate_argnums=(0, 1, 2, 3, 4), name=f"{name}_apply")

        def flush_sharded(khash, cols, valids, n, sh, sc, sv, sn):
            nh, nc, nv, n2, oc, ops, vis = self._flush_local(
                khash, cols, valids, n[0], sh, sc, sv, sn[0])
            return nh, nc, nv, n2[None], oc, ops, vis

        self._flush = jit_state(shard_map(
            flush_sharded, in_specs=(shard,) * 8,
            out_specs=(shard,) * 7, **mesh_kw),
            donate_argnums=(4, 5, 6, 7), name=f"{name}_flush")

        def watchdog_sharded(errs, n, dr, so):
            e = jax.lax.psum(errs, VNODE_AXIS)            # [2]
            mx = jax.lax.pmax(n[0], VNODE_AXIS)
            td = jax.lax.psum(dr[0], VNODE_AXIS)
            mf = jax.lax.pmax(so[0], VNODE_AXIS)
            return jnp.concatenate(
                [e, jnp.stack([mx, td, mf])]).astype(jnp.int32)[None]

        self._watchdog_pack = jit_state(shard_map(
            watchdog_sharded, in_specs=(shard,) * 4, out_specs=shard,
            **mesh_kw), name=f"{name}_watchdog_pack")

        # per-chunk fused programs keyed by the adaptive cap hint; scans
        # keyed (k, hint) — cleared here so a re-size retraces
        self._fused_applies: dict = {}
        self._fused_scans: dict = {}

    # ------------------------------------------------ fused mesh shuffle
    def set_mesh_preludes(self, fns, chain: Optional[str] = None) -> None:
        """Install hollow producer-stage impls (root-to-source reversed)
        to run INSIDE the fused program, upstream of the shuffle."""
        assert self.mesh_shuffle_applies == 0, \
            "mesh preludes must install before the first fused dispatch"
        self._mesh_preludes = tuple(fns)
        self.mesh_chain = chain

    def _prelude_host(self, chunk: StreamChunk) -> StreamChunk:
        for fn in self._mesh_preludes:
            chunk = fn(chunk)
        return chunk

    def _count_host_hop(self, n: int = 1) -> None:
        if self.mesh_chain is not None:
            from .monitor import mesh_host_round_trip
            mesh_host_round_trip(self.mesh_chain, n)

    def _trace_cap(self, local_rows: int) -> int:
        if not self.mesh_shuffle_adaptive or self._cap_hint is None:
            return shuffle_cap_out(local_rows, self.n_shards,
                                   self.mesh_shuffle_slack)
        return min(local_rows, max(64, self._cap_hint))

    def _fused_step(self, khash, cols, valids, n, errs, dropped, chunk):
        """Preludes + in-mesh shuffle + sorted-store apply for ONE chunk,
        inside shard_map (per-shard views, scalar n/dropped)."""
        for fn in self._mesh_preludes:
            chunk = fn(chunk)
        cap = self._trace_cap(chunk.capacity)
        local, n_drop, fill = mesh_ingest_chunk(
            chunk, self.route_key_indices, self._routing, VNODE_AXIS,
            self.n_shards, cap)
        kh, c, v, n2, e2 = sorted_store_apply(
            khash, cols, valids, n, errs, local,
            pk_idx=self.pk_indices, capacity=self.capacity)
        return kh, c, v, n2, e2, (dropped + n_drop).astype(dropped.dtype), \
            fill

    def _get_fused_apply(self):
        prog = self._fused_applies.get(self._cap_hint)
        if prog is not None:
            return prog
        shard = P(VNODE_AXIS)

        def apply_fused(khash, cols, valids, n, errs, dropped, sendocc,
                        chunk):
            kh, c, v, n2, e2, dr, fill = self._fused_step(
                khash, cols, valids, n[0], errs, dropped[0], chunk)
            so = jnp.maximum(sendocc[0], fill)
            return kh, c, v, n2[None], e2, dr[None], so[None]

        prog = jit_state(shard_map(
            apply_fused, mesh=self.mesh, in_specs=(shard,) * 8,
            out_specs=(shard,) * 7),
            donate_argnums=(0, 1, 2, 3, 4, 5, 6),
            name=f"{type(self).__name__}_apply_fused")
        self._fused_applies[self._cap_hint] = prog
        return prog

    def _make_fused_scan(self, k: int):
        """One barrier interval's k identically-shaped chunks in ONE
        device dispatch: lax.scan over the stacked batch inside
        shard_map, each step shuffling then applying."""
        shard = P(VNODE_AXIS)

        def scan_body(khash, cols, valids, n, errs, dropped, sendocc,
                      *chunks):
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *chunks)

            def step(carry, chunk):
                kh, c, v, nn, e, dr, so = carry
                kh, c, v, n2, e2, dr2, fill = self._fused_step(
                    kh, c, v, nn, e, dr, chunk)
                return (kh, c, v, n2.astype(nn.dtype), e2, dr2,
                        jnp.maximum(so, fill)), ()

            (kh, c, v, nn, e, dr, so), _ = jax.lax.scan(
                step, (khash, cols, valids, n[0], errs, dropped[0],
                       sendocc[0]), stacked)
            return kh, c, v, nn[None], e, dr[None], so[None]

        return jit_state(shard_map(
            scan_body, mesh=self.mesh,
            in_specs=(shard,) * 7 + (shard,) * k,
            out_specs=(shard,) * 7),
            donate_argnums=(0, 1, 2, 3, 4, 5, 6),
            name=f"{type(self).__name__}_apply_fused_scan{k}")

    def _fused_eligible(self, chunk: StreamChunk) -> bool:
        return self.mesh_shuffle and chunk.capacity % self.n_shards == 0

    def _apply_chunk_raw(self, chunk: StreamChunk) -> None:
        if self._fused_eligible(chunk):
            (self.khash, self.cols, self.valids, self.n, self._errs_dev,
             self._dropped_dev, self._send_occ_dev) = \
                self._get_fused_apply()(
                    self.khash, self.cols, self.valids, self.n,
                    self._errs_dev, self._dropped_dev,
                    self._send_occ_dev, chunk)
            self.mesh_shuffle_applies += 1
        else:
            # per-chunk host-plane fallback: hollowed producer stages run
            # eagerly and the crossing counts against the chain
            if self._mesh_preludes:
                chunk = self._prelude_host(chunk)
            self._count_host_hop()
            (self.khash, self.cols, self.valids, self.n,
             self._errs_dev) = self._apply(
                self.khash, self.cols, self.valids, self.n,
                self._errs_dev, chunk)
        self._applied_since_flush = True

    def _drain_pending(self) -> None:
        p = self._pending_chunks
        if not p:
            return
        self._pending_chunks = []
        # replay point: retain the interval's ingest BEFORE the fused
        # program consumes it (references only). With preludes installed
        # the RAW source chunk is the replay point — re-running the fused
        # program re-runs the hollowed producer stages too.
        for ch in p:
            self.ingest_log.note(ch)
        uniform = len({(c.capacity, len(c.columns),
                        tuple(col.valid is not None for col in c.columns))
                       for c in p}) == 1
        if len(p) == 1 or not self._fused_eligible(p[0]) or not uniform:
            for ch in p:
                self._apply_chunk_raw(ch)
            return
        k = 1 << (len(p) - 1).bit_length()
        if k > len(p):
            last = p[-1]
            filler = StreamChunk(last.columns, last.ops,
                                 jnp.zeros(last.capacity, dtype=bool),
                                 last.schema)
            p = p + [filler] * (k - len(p))
        scan = self._fused_scans.get((k, self._cap_hint))
        if scan is None:
            scan = self._make_fused_scan(k)
            self._fused_scans[(k, self._cap_hint)] = scan
        (self.khash, self.cols, self.valids, self.n, self._errs_dev,
         self._dropped_dev, self._send_occ_dev) = scan(
            self.khash, self.cols, self.valids, self.n, self._errs_dev,
            self._dropped_dev, self._send_occ_dev, *p)
        self.mesh_shuffle_applies += 1
        self._applied_since_flush = True

    def preload_replay(self, chunks) -> None:
        """Channel-free mesh replay: the crashed executor's uncommitted
        ingest suffix, staged here and installed into the pending queue
        by recover_state at the INITIAL barrier."""
        self._replay_preload = list(chunks)

    # -------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> None:
        if self.state_table is not None:
            self._epoch_chunks.append(chunk)
        self._pending_chunks.append(chunk)
        if len(self._pending_chunks) >= self._batch_max:
            self._drain_pending()
        return None

    def flush(self):
        self._drain_pending()
        h, c, v = self._SECONDARY
        sec = (getattr(self, h), getattr(self, c), getattr(self, v),
               getattr(self, self._SEC_COUNT))
        (nh, nc, nv, nn, out_cols, ops, vis) = self._flush(
            self.khash, self.cols, self.valids, self.n, *sec)
        setattr(self, h, nh)
        setattr(self, c, nc)
        setattr(self, v, nv)
        setattr(self, self._SEC_COUNT, nn)
        return StreamChunk(out_cols, ops, vis, self.schema)

    def check_watchdog(self) -> None:
        # the drain must run BEFORE the fetch so this interval's shuffle
        # drops / store overflow fail-stop the SAME epoch
        self._drain_pending()
        vals = np.asarray(self._watchdog_pack(
            self._errs_dev, self.n, self._dropped_dev,
            self._send_occ_dev))[0]
        n_ovf, n_miss, max_n, n_drop, fill = (int(vals[0]), int(vals[1]),
                                              int(vals[2]), int(vals[3]),
                                              int(vals[4]))
        self._note_send_fill(fill)
        self._send_occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), self._sharding())
        if n_drop:
            from ..utils.metrics import MESH_SHUFFLE_DROPPED
            MESH_SHUFFLE_DROPPED.inc(n_drop)
            raise RuntimeError(
                f"mesh shuffle overflow: {n_drop} rows dropped en route "
                f"to their owner shard (per-pair send capacity sized by "
                f"mesh_shuffle_slack={self.mesh_shuffle_slack}; 0 = "
                f"zero-drop sizing)")
        if n_ovf:
            raise RuntimeError(
                f"{self._overflow_what} overflow ({n_ovf} rows dropped; "
                f"per-shard capacity {self.capacity})")
        if n_miss:
            raise RuntimeError(
                f"{self._overflow_what}: {n_miss} deletes matched no row")
        self._occ_known = max_n

    def _note_send_fill(self, fill: int) -> None:
        """Adaptive shuffle slack — identical policy to the sharded agg
        (asymmetric EWMA + all-time peak floor, 2x pow2 cap hint after
        3 observations)."""
        if not self.mesh_shuffle_adaptive:
            return
        if fill > self._fill_ewma:
            self._fill_ewma = float(fill)
        else:
            self._fill_ewma = 0.8 * self._fill_ewma + 0.2 * fill
        self._fill_peak = max(self._fill_peak, fill)
        self._fill_obs += 1
        if self._fill_obs < 3:
            return
        worst = max(self._fill_ewma, float(self._fill_peak), 1.0)
        self._cap_hint = 1 << (int(2 * worst) - 1).bit_length()

    def persist(self, barrier, flushed) -> None:
        # stamp the interval's replay point with the epoch this barrier
        # seals; the coordinator drops it when that epoch commits
        self.ingest_log.seal(barrier.epoch.prev)
        if self.state_table is None:
            return
        for c in self._epoch_chunks:
            # raw (pre-prelude) chunks are the replay point, but the
            # state table persists EXECUTOR-SCHEMA rows: run the hollow
            # producer stages host-side before writing through
            if self._mesh_preludes:
                c = self._prelude_host(c)
            vis = np.asarray(c.vis)
            if vis.any():
                self.state_table.write_chunk_columns(
                    np.asarray(c.ops), [np.asarray(col.data)
                                        for col in c.columns], vis)
        self._epoch_chunks = []
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        """Durable rebuild through the sharded layout: partition rows by
        the vnode routing, rebuild each shard's local store, concatenate
        along the mesh axis, then seed the diff baseline with one
        discarded sharded flush (same rationale as the parents')."""
        preload = getattr(self, "_replay_preload", None)
        if preload:
            self._pending_chunks = list(preload) + self._pending_chunks
            self._replay_preload = []
            # the template only flushes epochs that saw input: mark the
            # preloaded suffix as pending work so the NEXT barrier drains
            # and re-emits it even if no fresh chunks arrive
            self._applied_since_flush = True
        if self.state_table is None:
            return
        rows = [r for _, r in self.state_table.iter_all()]
        if not rows:
            return
        from ..common.vnode import compute_vnodes_numpy
        from ..state.storage_table import rows_to_columns
        schema = self._store_schema()
        # NULL routing cells carry data=0 on device (rows_to_columns
        # convention) — mirror that here so rebuild lands rows on the
        # same shard the live apply path routed them to
        route_cols = [np.asarray([0 if r[j] is None else r[j]
                                  for r in rows], dtype=np.int64)
                      for j in self.route_key_indices]
        shard_of = np.asarray(self._routing)[
            compute_vnodes_numpy(route_cols)]
        by_shard = [[] for _ in range(self.n_shards)]
        for r, sh in zip(rows, shard_of):
            by_shard[int(sh)].append(r)
        worst = max(len(b) for b in by_shard)
        need = 1 << max(self.capacity.bit_length() - 1,
                        (int(worst / 0.7)).bit_length())
        if need != self.capacity:
            self.capacity = need
            self._build_sharded_programs()
        C = self.capacity
        dts = tuple(f.data_type.jnp_dtype for f in schema)
        local_apply = jit_state(
            partial(sorted_store_apply, pk_idx=self.pk_indices,
                    capacity=C),
            donate_argnums=(0, 1, 2, 3, 4),
            name=f"{type(self).__name__}_recover_apply")
        locals_ = []
        for part_rows in by_shard:
            kh = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
            cs = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
            vs = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
            nn = jnp.int32(0)
            errs = jnp.zeros(2, dtype=jnp.int32)
            cap = 1 << max(6, max(len(part_rows) - 1, 0).bit_length())
            for ofs in range(0, len(part_rows), cap):
                part = part_rows[ofs:ofs + cap]
                arrays, valids = rows_to_columns(schema, part)
                ch = StreamChunk.from_numpy(
                    schema, arrays, capacity=cap,
                    valids=[None if v.all() else v for v in valids])
                kh, cs, vs, nn, errs = local_apply(kh, cs, vs, nn, errs,
                                                   ch)
            locals_.append((kh, cs, vs, nn[None], errs))
        sharding = self._sharding()

        def concat(*xs):
            return jax.device_put(jnp.concatenate(xs), sharding)

        (self.khash, self.cols, self.valids, self.n,
         self._errs_dev) = jax.tree_util.tree_map(concat, *locals_)
        self._alloc_sharded_secondary()
        self._occ_known = worst
        h, c, v = self._SECONDARY
        sec = (getattr(self, h), getattr(self, c), getattr(self, v),
               getattr(self, self._SEC_COUNT))
        nh, nc, nv, nn, _c, _o, _v = self._flush(
            self.khash, self.cols, self.valids, self.n, *sec)
        setattr(self, h, nh)
        setattr(self, c, nc)
        setattr(self, v, nv)
        setattr(self, self._SEC_COUNT, nn)

    # ------------------------------------------------- HBM memory manager
    @property
    def mem_shards(self) -> int:
        return self.n_shards

    def state_shard_bytes(self) -> int:
        return self.state_bytes() // self.n_shards

    def memory_enable_lru(self) -> None:
        pass

    def memory_evict(self, target_bytes: int, epoch: int) -> int:
        return 0

"""Sorted-merge streaming join — dense sorted state, no chains, no loops.

Semantics match HashJoinExecutor (the reference's two-sided streaming
equi-join, src/stream/src/executor/hash_join.rs:478 with the multimap state
of managed_state/join/mod.rs:238-268): a chunk from one side probes the
OTHER side's stored rows and emits joined changelog rows, then updates its
OWN store (update pairs degrade to Delete/Insert, NULL keys never match).

TPU re-design — why not the chained hash multimap of hash_join.py:
  * The chain walk is a `lax.while_loop` whose trip count is the longest
    key chain: hot keys turn one chunk into hundreds of tiny dependent
    kernel launches.
  * Slots are reclaimed only by a barrier-time rebuild, so the row store
    must hold A WHOLE EPOCH of inserts on top of the live set. That makes
    throughput = row_capacity x barrier_rate — the measured q7/q8 ceiling.

Here each side's state is a *dense, sorted* struct-of-arrays: rows
[0, n) sorted ascending by a 63-bit hash of the join key (exact key
equality re-checked on every candidate, so hash collisions only cost a
wasted compare — they can never produce a wrong match). Everything is
sort / searchsorted / cumsum / gather — static shapes, zero
data-dependent control flow:

  probe   lo/hi = searchsorted(other.khash, h) — each chunk row's matches
          are a CONTIGUOUS RANGE. Ranges are expanded into a fixed match
          buffer [M] with cumsum offsets + one locating searchsorted
          (no loop, unlike the chain walk).
  evict   rows with clean-col < watermark are dropped DURING the same
          merge program that inserts new rows — per chunk, not per
          barrier. State capacity therefore bounds the LIVE set only;
          epoch churn is unlimited. This is what lifts the q7/q8 cap.
  insert  incoming rows are sorted by hash and merged into the kept rows
          with two searchsorteds (stable: state rows stay before new rows
          of equal hash) + scatters — O(C + N) bandwidth, no table sort.
  delete  a retraction finds its victim row via its own side's range +
          exact (key, pk) compare; one victim per retraction (within-chunk
          insert/delete runs on the same pk are netted first, exactly like
          hash_join.py's pk-run resolution).

`append_only=(left, right)` statically removes the retraction machinery
from a side's program — the common windowed-join case compiles to the
probe + merge path alone.

Outer joins (join_type left/right/full) follow the reference's degree
design (managed_state/join/mod.rs:252-261): every stored row carries its
count of condition-passing matches on the other side. A chunk's probe
scatter-adds signed deltas into the OTHER side's degree column; rows whose
degree transitions 0 -> >0 retract their NULL-padded output row, and
> 0 -> 0 (re-)emit it — computed per chunk as NET transitions (transient
flips within one chunk cancel, the Delete/Insert degradation the reference
applies when pairs can't stay adjacent). Unmatched rows on an outer side
emit their NULL-padded row inline at insert/delete time, including
NULL-key rows (which can never match). The non-equi condition therefore
evaluates INSIDE the jitted apply.

Durability (state_tables): the dense sorted layout has no stable slot a
dirty-bit could follow (merge-inserts shift every row), so persistence is
a barrier-time SNAPSHOT DIFF instead of hash_join.py's per-slot dirty
mask: the executor keeps the device state as of the last flush and one
jitted program aligns current-vs-snapshot rows by a 63-bit row hash, then
verifies candidate pairs with an EXACT all-column compare — a hash
collision can only cause a redundant delete+insert of identical rows,
never a missed change. Changed rows compact into [deletes][inserts]
buffers, are written columnar to the per-side StateTable, and committed
at every barrier (reference: state_table.rs:1036 commits everything at
every checkpoint). Degrees are NOT persisted: recovery replays the stored
rows through the normal probe path (right side first into an empty mesh,
then left probing right), which rebuilds both sides' degree columns and
the condition evaluation for free — a TPU-first simplification of the
reference's degree tables (managed_state/join/mod.rs:252).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, StreamChunk, OP_DELETE, OP_INSERT, op_sign,
)
from ..common.types import Field, Schema
from ..memory.accounting import pytree_bytes
from ..memory.spill import HostSpill
from ..ops.hash_table import pack_rows, stable_lexsort
from ..ops.jit_state import jit_state
from .align import LEFT, RIGHT, barrier_align
from .executor import Executor
from .message import Barrier, BarrierKind, Watermark

# Padding value for khash beyond the live prefix: int64 max keeps
# searchsorted ranges inside [0, n) (a real 63-bit hash equals it with
# probability ~2^-63, and even then the exact-key compare rejects the row).
_HSENTINEL = jnp.iinfo(jnp.int64).max
# "No watermark yet" eviction threshold — below any real event time.
NO_WATERMARK = -(1 << 62)


def key_hash(key_cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """63-bit nonnegative hash of the composite key (splitmix64 chain)."""
    h = jnp.full(key_cols[0].shape[0], 0x243F6A8885A308D3, dtype=jnp.uint64)
    for c in key_cols:
        x = h ^ (c.astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15))
        x = x + jnp.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        h = x ^ (x >> jnp.uint64(31))
    return (h >> jnp.uint64(1)).astype(jnp.int64)


@jax.tree_util.register_pytree_node_class
@dataclass
class SortedSideState:
    """One side's store: dense prefix [0, n), ascending by khash."""

    khash: jnp.ndarray                 # int64 [C], sentinel beyond n
    cols: tuple[jnp.ndarray, ...]      # per input column [C]
    valids: tuple[jnp.ndarray, ...]    # per input column bool [C]
    degree: jnp.ndarray                # int32 [C] — matches on other side
    n: jnp.ndarray                     # int32 scalar — live rows

    def tree_flatten(self):
        return ((self.khash, self.cols, self.valids, self.degree,
                 self.n), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        kh, cols, valids, degree, n = children
        return cls(kh, tuple(cols), tuple(valids), degree, n)

    @property
    def capacity(self) -> int:
        return self.khash.shape[0]


def _empty_sorted_side(capacity: int, col_dtypes: Sequence) -> SortedSideState:
    return SortedSideState(
        khash=jnp.full(capacity, _HSENTINEL, dtype=jnp.int64),
        cols=tuple(jnp.zeros(capacity, dtype=dt) for dt in col_dtypes),
        valids=tuple(jnp.zeros(capacity, dtype=bool) for _ in col_dtypes),
        degree=jnp.zeros(capacity, dtype=jnp.int32),
        n=jnp.int32(0),
    )


def grow_sorted_arrays(khash, cols, valids, new_capacity: int):
    """Reallocate a sorted dense store at a larger capacity (live prefix
    unchanged, padding = hash sentinel / zeros). Device-side concat —
    subsequent programs re-jit at the new static shape (reference role:
    src/common/src/estimate_size/ + cache growth; here growth is the
    memory-pressure response instead of fail-stop)."""
    pad = new_capacity - khash.shape[0]
    assert pad > 0
    kh = jnp.concatenate([khash, jnp.full(pad, _HSENTINEL,
                                          dtype=khash.dtype)])
    cols2 = tuple(jnp.concatenate([c, jnp.zeros(pad, dtype=c.dtype)])
                  for c in cols)
    valids2 = tuple(jnp.concatenate([v, jnp.zeros(pad, dtype=bool)])
                    for v in valids)
    return kh, cols2, valids2


def _count_le(sorted_arr: jnp.ndarray, dead_cum: jnp.ndarray,
              vals: jnp.ndarray, side: str) -> jnp.ndarray:
    """Count of LIVE entries of `sorted_arr` </<= vals, where `dead_cum`
    is the inclusive prefix-sum of the dead mask over the same array."""
    idx = jnp.searchsorted(sorted_arr, vals, side=side)
    dead_before = jnp.where(idx > 0, dead_cum[jnp.clip(idx - 1, 0)], 0)
    return (idx - dead_before).astype(jnp.int32)


class SortedJoinExecutor(Executor):
    """Inner equi-join over sorted dense state. Drop-in for
    HashJoinExecutor (same constructor surface minus state_tables)."""

    def __init__(self, left: Executor, right: Executor,
                 left_key_indices: Sequence[int],
                 right_key_indices: Sequence[int],
                 left_pk_indices: Sequence[int],
                 right_pk_indices: Sequence[int],
                 capacity: int = 1 << 17,
                 match_factor: int = 2,
                 match_factors: Optional[tuple] = None,
                 condition=None,
                 join_type: str = "inner",
                 output_indices: Optional[Sequence[int]] = None,
                 append_only: tuple[bool, bool] = (False, False),
                 clean_watermark_cols: tuple[Optional[int], Optional[int]] = (None, None),
                 clean_specs: Optional[tuple] = None,
                 state_tables: Optional[tuple] = None,
                 temporal: bool = False,
                 watchdog_interval: Optional[int] = 1):
        self.inputs = (left, right)
        self.key_indices = (tuple(left_key_indices), tuple(right_key_indices))
        self.pk_indices_side = (tuple(left_pk_indices), tuple(right_pk_indices))
        assert len(self.key_indices[0]) == len(self.key_indices[1])
        lt, rt = left.schema, right.schema
        for li, ri in zip(*self.key_indices):
            assert lt[li].data_type.np_dtype == rt[ri].data_type.np_dtype, \
                f"join key dtype mismatch {lt[li]} vs {rt[ri]}"
            assert np.issubdtype(lt[li].data_type.np_dtype, np.integer), \
                "sorted join keys must be integer-typed (ints/dict/timestamps)"
        self._col_dtypes = (
            tuple(f.data_type.jnp_dtype for f in lt),
            tuple(f.data_type.jnp_dtype for f in rt),
        )
        full_fields = [Field(f"l_{f.name}" if f.name in {g.name for g in rt} else f.name,
                             f.data_type, f.scale) for f in lt]
        full_fields += [Field(f"r_{f.name}" if f.name in {g.name for g in lt} else f.name,
                              f.data_type, f.scale) for f in rt]
        self.output_indices = (tuple(output_indices) if output_indices is not None
                               else tuple(range(len(full_fields))))
        self.schema = Schema(tuple(full_fields[i] for i in self.output_indices))
        out_pk_full = (tuple(self.pk_indices_side[0])
                       + tuple(len(lt) + i for i in self.pk_indices_side[1]))
        # the output stream key is only valid if EVERY stream-key column
        # survives the projection — a partial key is not unique, and a
        # keyed downstream consumer would mis-address retractions
        # (ADVICE r3 #4); advertise no key rather than a wrong one
        if all(i in self.output_indices for i in out_pk_full):
            self.pk_indices = tuple(self.output_indices.index(i)
                                    for i in out_pk_full)
        else:
            self.pk_indices = ()
        self.capacity = [capacity, capacity]
        self.match_factor = match_factor
        # per-side probe buffers: side s's matches are bounded by 1 per
        # row when the OTHER side's rows are unique per join key (its
        # stream key is covered by its equi keys) — the planner passes
        # (2, 64)-style asymmetric factors so a wide chunk probing a
        # unique side doesn't allocate a match_factor-times-wider buffer
        self.match_factors = (tuple(match_factors) if match_factors
                              else (match_factor, match_factor))
        self.condition = condition
        assert join_type in ("inner", "left", "right", "full")
        # Cleaning specs generalize clean_watermark_cols (which maps to
        # ("own", col)) — the reference's planner derives the same three
        # shapes from watermark inference:
        #   ("own", col)                evict below THIS side's watermark
        #                               on col (caller asserts safety)
        #   ("pair", col, kpos)         col is equi-key kpos; evict below
        #                               min of BOTH sides' key watermarks
        #                               (windowed joins — safe even when
        #                               one side lags)
        #   ("band", col, other_col, d[, cap_col])
        #                               residual condition bounds col >
        #                               other.other_col + d; evict below
        #                               other side's watermark + d
        #                               (interval joins). cap_col: for a
        #                               retracting side, additionally cap
        #                               the bound at OWN watermark on
        #                               cap_col — retractions below it
        #                               can no longer arrive
        if clean_specs is None:
            clean_specs = tuple(
                None if c is None else ("own", c)
                for c in clean_watermark_cols)
        self.clean_specs = tuple(clean_specs)
        # Watermark eviction drops rows WITHOUT probing, so it cannot
        # maintain the other side's degree column; combining state
        # cleaning with outer semantics would silently corrupt NULL-row
        # accounting (an evicted row's matches keep degree>0 forever).
        # The reference has the same tension (TTL cleaning is documented
        # as inconsistency-introducing for outer joins); fail loudly.
        if join_type != "inner":
            assert self.clean_specs == (None, None), \
                "outer joins do not support watermark state cleaning"
        self.join_type = join_type
        # Temporal join (reference: temporal_join.rs — FOR SYSTEM_TIME AS
        # OF PROCTIME()): the right side is a TABLE snapshot; its updates
        # maintain state but emit NOTHING (no retroactive fixes of
        # earlier outputs), so only left arrivals produce rows. Left
        # probes read the right side's state as of processing time.
        if temporal:
            assert join_type in ("inner", "left"),                 "temporal joins are inner or left"
        self.temporal = temporal
        # side s "preserves" its unmatched rows (emits NULL-padded output)
        self._outer = (join_type in ("left", "full"),
                       join_type in ("right", "full"))
        self.append_only = tuple(append_only)
        # the column each side's evict programs compare against
        self.clean_cols = tuple(None if sp is None else sp[1]
                                for sp in self.clean_specs)
        self._pending_clean: list[int] = [NO_WATERMARK, NO_WATERMARK]
        # per-side col -> latest watermark value (feeds clean-spec bounds)
        self._wms: list[dict[int, int]] = [{}, {}]
        self.identity = (f"SortedJoin(l={self.key_indices[0]}, "
                         f"r={self.key_indices[1]})")
        self.state_tables = tuple(state_tables) if state_tables else (None, None)
        self.sides = [self._empty(s) for s in (LEFT, RIGHT)]
        # device snapshot as of the last durable flush (diff base)
        self._snap = [self.sides[LEFT], self.sides[RIGHT]]
        self._flush_dirty = [False, False]
        # Donation: ONLY the error accumulator (arg 2). The side states
        # must NOT be donated here, unlike hash_join: `_snap` keeps the
        # last-persisted side as the durable diff base by ALIASING the
        # live arrays (`self._snap[s] = self.sides[s]` in _persist), so
        # the buffers an apply consumes are still live as the snapshot.
        self._apply = jit_state(self._apply_impl,
                                static_argnames=("side", "match_factor"),
                                donate_argnums=(2,),
                                name="sorted_join_apply")
        self._evict = jit_state(self._evict_impl, static_argnames=("side",),
                                name="sorted_join_evict")
        self._diff = jit_state(self._diff_impl, name="sorted_join_diff")
        if watchdog_interval not in (None, 1):
            raise ValueError("watchdog_interval must be 1 or None")
        self.watchdog_interval = watchdog_interval
        self.rebuilds = 0
        # device error accumulator [match_overflow, del_miss, row_overflow];
        # fetched once per barrier (hash_join.py:546 rationale)
        self._errs_dev = jnp.zeros(3, dtype=jnp.int32)
        zero = jnp.zeros((), dtype=jnp.int32)
        self._n_dev = [zero, zero]
        self._dirty = [False, False]
        self._watchdog_pack = jit_state(
            lambda errs, nl, nr: jnp.concatenate([errs, jnp.stack([nl, nr])]),
            name="sorted_join_watchdog_pack")
        self._key_wms: list[dict[int, int]] = [{}, {}]
        self._emitted_key_wm: dict[int, int] = {}
        # watermark value a side's state is already clean to (skip
        # repeated idle-evicts while the watermark holds still)
        self._cleaned_to = [NO_WATERMARK, NO_WATERMARK]
        # ---- HBM memory manager hooks (memory/manager.py): the dense
        # sorted stores have fixed capacity, so the pressure response is
        # occupancy-driven SPILL — ahead of the overflow cliff, the
        # OLDEST rows (by the state-cleaning column, the coldness axis of
        # a windowed join) move to host; a chunk whose key touches a
        # spilled window reloads it through the normal apply path (the
        # recovery-replay shape) before probing. Inner joins only —
        # eviction cannot maintain outer-join degrees, same restriction
        # as watermark cleaning.
        self._mem_on = False
        self._spill = [HostSpill(), HostSpill()]
        self.mem_evicted_bytes = 0
        self.mem_reload_count = 0
        self._mem_cc_range_prog = jit_state(
            self._mem_cc_range_impl, static_argnames=("side",),
            name="sorted_join_mem_range")
        self._mem_pack_prog = jit_state(
            self._mem_pack_impl, static_argnames=("side",),
            name="sorted_join_mem_pack")
        self._mem_kh_cut_prog = jit_state(
            self._mem_kh_cut_impl, static_argnames=("frac_num",),
            name="sorted_join_mem_kh_cut")

    def fence_tokens(self) -> list:
        return [s.n for s in self.sides] + super().fence_tokens()

    def _empty(self, side: int) -> SortedSideState:
        return _empty_sorted_side(self.capacity[side], self._col_dtypes[side])

    # ------------------------------------------------------------- apply
    def _apply_impl(self, own: SortedSideState, other: SortedSideState,
                    errs: jnp.ndarray, chunk: StreamChunk, wm_own, side: int,
                    match_factor: Optional[int] = None):
        """Probe `other`, emit matches (+ outer-join NULL rows and degree
        transitions), evict+update `own` in one program.

        Returns (own', other_degree', out_cols, out_ops, out_vis, errs',
        n_own). Output rows are laid out in up to three segments:
        [0, M)       inner matches
        [M, 2M)      other-side NULL-row transitions   (outer only)
        [2M, 2M+N)   own-side unmatched NULL rows      (own outer only)
        """
        key_idx = self.key_indices[side]
        pk_idx = self.pk_indices_side[side]
        N = chunk.capacity
        C = own.capacity
        Co = other.capacity
        M = (match_factor or self.match_factor) * N
        append_only = self.append_only[side]

        key_cols = [chunk.columns[i].data for i in key_idx]
        key_valid = jnp.ones(N, dtype=bool)
        for i in key_idx:
            key_valid &= chunk.columns[i].valid_mask()
        active = chunk.vis & key_valid               # NULL keys never join
        signs = op_sign(chunk.ops)
        row_ids = jnp.arange(N, dtype=jnp.int32)
        h = key_hash(key_cols)

        # ---- within-chunk pk-run netting (hash_join.py:272 semantics) ----
        if append_only:
            is_ins = active
            is_del = jnp.zeros(N, dtype=bool)
        else:
            sort_keys = [row_ids]
            for p in pk_idx:
                sort_keys.append(chunk.columns[p].data)
            sort_keys.append(~active)
            order = stable_lexsort(tuple(sort_keys))
            s_act = active[order]
            same = s_act[1:] & s_act[:-1]
            for p in pk_idx:
                d = chunk.columns[p].data[order]
                same = same & (d[1:] == d[:-1])
            run_start = jnp.concatenate([jnp.array([True]), ~same])
            run_end = jnp.concatenate([~same, jnp.array([True])])
            s_signs = signs[order]
            eff_del_s = run_start & (s_signs < 0) & s_act
            eff_ins_s = run_end & (s_signs > 0) & s_act
            is_del = jnp.zeros(N, dtype=bool).at[order].set(eff_del_s)
            is_ins = jnp.zeros(N, dtype=bool).at[order].set(eff_ins_s)

        # ---- probe the other side: contiguous hash ranges ----
        lo = jnp.searchsorted(other.khash, h, side="left").astype(jnp.int32)
        hi = jnp.searchsorted(other.khash, h, side="right").astype(jnp.int32)
        # int64 offsets: a hot-key chunk's total candidate-match count can
        # exceed 2^31 (120k-row key run probed by a 20k-row chunk); an int32
        # cumsum would wrap negative and silently drop every match while
        # the overflow counter read zero
        lens = jnp.where(active, (hi - lo).astype(jnp.int64), 0)
        offs = jnp.cumsum(lens)
        total = offs[N - 1]
        j = jnp.arange(M, dtype=jnp.int64)
        src = jnp.searchsorted(offs, j, side="right").astype(jnp.int32)
        srcc = jnp.clip(src, 0, N - 1)
        prev = jnp.where(srcc > 0, offs[jnp.clip(srcc - 1, 0)], 0)
        pos = jnp.clip(lo[srcc] + (j - prev), 0, Co - 1).astype(jnp.int32)
        emit = (j < jnp.minimum(total, M)) & (pos < other.n)
        # exact key equality (hash collisions rejected here)
        for kc, oi in zip(key_cols, self.key_indices[1 - side]):
            emit &= other.cols[oi][pos] == kc[srcc].astype(other.cols[oi].dtype)
        n_match_overflow = jnp.maximum(total - M, 0)

        # ---- match-segment assembly: own row (from chunk) ++ other row ----
        own_cols = [Column(jnp.take(c.data, srcc, axis=0),
                           jnp.take(c.valid_mask(), srcc, axis=0))
                    for c in chunk.columns]
        oth_cols = [Column(r[pos], v[pos])
                    for r, v in zip(other.cols, other.valids)]
        cols = own_cols + oth_cols if side == LEFT else oth_cols + own_cols
        if self.condition is not None:
            pred = self.condition.eval(cols)
            emit &= pred.data.astype(bool) & pred.valid_mask()
        ops_out = jnp.where(jnp.take(signs, srcc) > 0,
                            OP_INSERT, OP_DELETE).astype(jnp.int8)

        outer_own = self._outer[side]
        outer_other = self._outer[1 - side]
        any_outer = outer_own or outer_other
        # condition-passing matches per chunk row (stored as the inserted
        # row's initial degree; zero => own NULL-row emission when outer)
        if any_outer:
            match_cnt = jax.ops.segment_sum(
                emit.astype(jnp.int32), srcc, num_segments=N)
        else:
            match_cnt = None

        if outer_other or outer_own:
            # signed degree delta onto the OTHER side's rows
            d_sign = jnp.where(emit, jnp.take(signs, srcc), 0)
            other_degree = other.degree.at[
                jnp.where(emit, pos, Co)].add(d_sign, mode="drop")
        else:
            other_degree = other.degree

        if outer_other:
            # NET degree transitions on the other side -> NULL-row flips
            touched = jnp.zeros(Co, dtype=bool).at[
                jnp.where(emit, pos, Co)].set(True, mode="drop")
            o_live = jnp.arange(Co, dtype=jnp.int32) < other.n
            was0 = other.degree == 0
            now0 = other_degree == 0
            t_del = touched & o_live & was0 & ~now0   # retract NULL row
            t_ins = touched & o_live & ~was0 & now0   # re-emit NULL row
            t_any = t_del | t_ins
            trank = jnp.cumsum(t_any.astype(jnp.int32)) - 1
            # positions of transition rows compacted into a [M] buffer
            tsel = jnp.zeros(M, dtype=jnp.int32).at[
                jnp.where(t_any & (trank < M), trank, M)].set(
                jnp.arange(Co, dtype=jnp.int32), mode="drop")
            n_trans = jnp.sum(t_any.astype(jnp.int32))
            t_vis = jnp.arange(M, dtype=jnp.int32) < jnp.minimum(n_trans, M)
            t_ops = jnp.where(t_del[tsel], OP_DELETE, OP_INSERT).astype(
                jnp.int8)
            n_match_overflow = n_match_overflow + jnp.maximum(n_trans - M, 0)
        else:
            tsel = t_vis = t_ops = None

        if outer_own:
            # own rows with no condition-passing match (incl. NULL keys)
            zerom = (active & (match_cnt == 0)) | (chunk.vis & ~key_valid)
            z_ops = jnp.where(signs > 0, OP_INSERT, OP_DELETE).astype(
                jnp.int8)
        else:
            zerom = z_ops = None

        if any_outer:
            # full output: [M matches][M transitions][N own-unmatched]
            def seg_col(match_c: Column, oth_row=None, oth_valid=None,
                        own_chunk_col=None, own_side_seg=True):
                parts_d = [match_c.data]
                parts_v = [match_c.valid_mask()]
                if outer_other:
                    if own_side_seg:       # own-side columns: NULL padding
                        parts_d.append(jnp.zeros(M, dtype=match_c.data.dtype))
                        parts_v.append(jnp.zeros(M, dtype=bool))
                    else:                  # other-side columns: real values
                        parts_d.append(oth_row[tsel])
                        parts_v.append(oth_valid[tsel])
                if outer_own:
                    if own_side_seg:       # own columns: chunk values
                        parts_d.append(own_chunk_col.data)
                        parts_v.append(own_chunk_col.valid_mask())
                    else:                  # other columns: NULL padding
                        parts_d.append(jnp.zeros(N, dtype=match_c.data.dtype))
                        parts_v.append(jnp.zeros(N, dtype=bool))
                return Column(jnp.concatenate(parts_d),
                              jnp.concatenate(parts_v))

            own_full = [seg_col(mc, own_chunk_col=cc, own_side_seg=True)
                        for mc, cc in zip(own_cols, chunk.columns)]
            oth_full = [seg_col(mc, oth_row=r, oth_valid=v,
                                own_side_seg=False)
                        for mc, r, v in zip(oth_cols, other.cols,
                                            other.valids)]
            cols = (own_full + oth_full if side == LEFT
                    else oth_full + own_full)
            ops_parts = [ops_out]
            vis_parts = [emit]
            if outer_other:
                ops_parts.append(t_ops)
                vis_parts.append(t_vis)
            if outer_own:
                ops_parts.append(z_ops)
                vis_parts.append(zerom)
            ops_out = jnp.concatenate(ops_parts)
            emit = jnp.concatenate(vis_parts)

        # ---- own-side update: evict + delete + merge-insert ----
        live = jnp.arange(C, dtype=jnp.int32) < own.n
        if self.clean_cols[side] is not None:
            cc = self.clean_cols[side]
            keep = live & ~(own.cols[cc] < wm_own)
        else:
            keep = live

        if not append_only:
            dlo = jnp.searchsorted(own.khash, h, side="left").astype(jnp.int32)
            dhi = jnp.searchsorted(own.khash, h, side="right").astype(jnp.int32)
            dlens = jnp.where(is_del, (dhi - dlo).astype(jnp.int64), 0)
            doffs = jnp.cumsum(dlens)
            dtot = doffs[N - 1]
            dsrc = jnp.searchsorted(doffs, j, side="right").astype(jnp.int32)
            dsrcc = jnp.clip(dsrc, 0, N - 1)
            dprev = jnp.where(dsrcc > 0, doffs[jnp.clip(dsrcc - 1, 0)], 0)
            dpos = jnp.clip(dlo[dsrcc] + (j - dprev), 0,
                            C - 1).astype(jnp.int32)
            cand = (j < jnp.minimum(dtot, M)) & keep[dpos]
            for kc, ki in zip(key_cols, key_idx):
                cand &= own.cols[ki][dpos] == kc[dsrcc].astype(own.cols[ki].dtype)
            for p in pk_idx:
                cand &= (own.cols[p][dpos]
                         == chunk.columns[p].data[dsrcc].astype(own.cols[p].dtype))
            # one victim per retraction: the lowest matching state pos
            victim = jnp.full(N, C, dtype=jnp.int32).at[
                jnp.where(cand, dsrcc, N)].min(dpos, mode="drop")
            found = victim < C
            keep = keep.at[jnp.where(found, victim, C)].set(False, mode="drop")
            n_del_miss = jnp.sum((is_del & ~found).astype(jnp.int32))
        else:
            n_del_miss = jnp.int32(0)

        # merge: kept state rows + new rows, both in hash order
        ins_h = jnp.where(is_ins, h, _HSENTINEL)
        iorder = jnp.argsort(ins_h, stable=True)          # new rows first
        nh = ins_h[iorder]                                 # [N] sorted
        n_new = jnp.sum(is_ins.astype(jnp.int32))
        dead_cum = jnp.cumsum((~keep).astype(jnp.int32))
        kept_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
        n_kept = kept_rank[C - 1] + 1
        # state row t -> kept_rank + (# new rows with hash < khash[t])
        new_lt = jnp.searchsorted(nh, own.khash, side="left").astype(jnp.int32)
        pos_t = kept_rank + new_lt
        # new row r -> r + (# kept state rows with hash <= nh[r])
        kept_le = _count_le(own.khash, dead_cum, nh, side="right")
        rr = jnp.arange(N, dtype=jnp.int32)
        pos_r = rr + kept_le
        new_ok = rr < n_new
        n_after = n_kept + n_new
        n_row_overflow = jnp.maximum(n_after - C, 0)
        n_after = jnp.minimum(n_after, C)

        tgt_t = jnp.where(keep & (pos_t < C), pos_t, C)
        tgt_r = jnp.where(new_ok & (pos_r < C), pos_r, C)
        new_khash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        new_khash = new_khash.at[tgt_t].set(own.khash, mode="drop")
        new_khash = new_khash.at[tgt_r].set(nh, mode="drop")
        out_cols = []
        out_valids = []
        for ci, (sc, sv) in enumerate(zip(own.cols, own.valids)):
            col = chunk.columns[ci]
            c2 = jnp.zeros(C, dtype=sc.dtype).at[tgt_t].set(sc, mode="drop")
            c2 = c2.at[tgt_r].set(col.data[iorder].astype(sc.dtype), mode="drop")
            v2 = jnp.zeros(C, dtype=bool).at[tgt_t].set(sv, mode="drop")
            v2 = v2.at[tgt_r].set(col.valid_mask()[iorder], mode="drop")
            out_cols.append(c2)
            out_valids.append(v2)
        degree = jnp.zeros(C, dtype=jnp.int32).at[tgt_t].set(
            own.degree, mode="drop")
        if any_outer:
            degree = degree.at[tgt_r].set(match_cnt[iorder], mode="drop")
        own2 = SortedSideState(new_khash, tuple(out_cols), tuple(out_valids),
                               degree, n_after.astype(jnp.int32))
        errs = errs + jnp.stack(
            [n_match_overflow, n_del_miss, n_row_overflow]).astype(jnp.int32)
        return own2, other_degree, tuple(cols), ops_out, emit, errs, own2.n

    # ------------------------------------------------------------- evict
    def _evict_impl(self, own: SortedSideState, wm, kh, side: int):
        """Barrier-time eviction: rows below the side's watermark bound
        (idle cleaning — the apply path evicts inline) and/or rows whose
        key hash falls under `kh` (memory spill's fallback axis when the
        time axis cannot discriminate; pass -1 to disable — key hashes
        are nonnegative 63-bit)."""
        C = own.capacity
        cc = self.clean_cols[side]
        live = jnp.arange(C, dtype=jnp.int32) < own.n
        drop = own.khash < kh
        if cc is not None:
            drop = drop | (own.cols[cc] < wm)
        keep = live & ~drop
        rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, rank, C)
        kh = jnp.full(C, _HSENTINEL, dtype=jnp.int64).at[tgt].set(
            own.khash, mode="drop")
        cols = tuple(jnp.zeros(C, dtype=c.dtype).at[tgt].set(c, mode="drop")
                     for c in own.cols)
        valids = tuple(jnp.zeros(C, dtype=bool).at[tgt].set(v, mode="drop")
                       for v in own.valids)
        degree = jnp.zeros(C, dtype=jnp.int32).at[tgt].set(own.degree,
                                                           mode="drop")
        n2 = jnp.sum(keep.astype(jnp.int32))
        return SortedSideState(kh, cols, valids, degree, n2)

    # ------------------------------------------------------- persistence
    @staticmethod
    def _row_lanes(st: SortedSideState) -> list[jnp.ndarray]:
        """Row identity/content lanes for diffing: khash ++ data (invalid
        lanes canonical 0, floats bitcast) ++ valid bits."""
        lanes = [st.khash]
        for c, v in zip(st.cols, st.valids):
            x = (jax.lax.bitcast_convert_type(c, jnp.int64)
                 if jnp.issubdtype(c.dtype, jnp.floating)
                 else c.astype(jnp.int64))
            lanes.append(jnp.where(v, x, 0))
        lanes.extend(v.astype(jnp.int64) for v in st.valids)
        return lanes

    def _diff_impl(self, cur: SortedSideState, snap: SortedSideState):
        """Snapshot diff: rows in `cur` not in `snap` (inserts) and rows
        in `snap` not in `cur` (deletes), matched by row hash + exact
        compare. Returns compacted (del_cols, n_del, ins_cols, n_ins);
        only the first n entries of each buffer are meaningful."""
        def rowhash(st):
            lanes = self._row_lanes(st)
            live = jnp.arange(st.capacity, dtype=jnp.int32) < st.n
            return jnp.where(live, key_hash(lanes), _HSENTINEL), live

        rh_c, live_c = rowhash(cur)
        rh_s, live_s = rowhash(snap)
        order_c = jnp.argsort(rh_c)
        order_s = jnp.argsort(rh_s)
        lanes_c = self._row_lanes(cur)
        lanes_s = self._row_lanes(snap)

        def unmatched(rh_a, live_a, lanes_a, rh_b_sorted, order_b, lanes_b,
                      cap_b):
            pos = jnp.clip(jnp.searchsorted(rh_b_sorted, rh_a), 0, cap_b - 1)
            cand = order_b[pos]
            eq = rh_b_sorted[pos] == rh_a
            for la, lb in zip(lanes_a, lanes_b):
                eq &= la == lb[cand]
            return live_a & ~eq

        ins_mask = unmatched(rh_c, live_c, lanes_c, rh_s[order_s], order_s,
                             lanes_s, snap.capacity)
        del_mask = unmatched(rh_s, live_s, lanes_s, rh_c[order_c], order_c,
                             lanes_c, cur.capacity)

        def compact(mask, cols):
            cap = mask.shape[0]
            rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
            sel = jnp.zeros(cap, dtype=jnp.int32).at[
                jnp.where(mask, rank, cap)].set(
                jnp.arange(cap, dtype=jnp.int32), mode="drop")
            return tuple(c[sel] for c in cols), jnp.sum(mask.astype(jnp.int32))

        del_cols, n_del = compact(del_mask, snap.cols)
        ins_cols, n_ins = compact(ins_mask, cur.cols)
        return del_cols, n_del, ins_cols, n_ins

    def _persist(self, barrier: Barrier) -> None:
        for s in (LEFT, RIGHT):
            st = self.state_tables[s]
            if st is None:
                continue
            if self._flush_dirty[s]:
                self._persist_diff_write(st, self.sides[s], self._snap[s])
                self._snap[s] = self.sides[s]
                self._flush_dirty[s] = False
            st.commit(barrier.epoch.curr)

    def _persist_diff_write(self, st, cur: SortedSideState,
                            snap: SortedSideState) -> None:
        """Diff one (current, snapshot) state pair and write the changed
        rows (the sharded subclass calls this per shard slice).

        d2h discipline: the tunneled TPU charges ~0.15-0.3s PER FETCH
        CALL regardless of size (measured; bandwidth is fine), so the
        whole diff ships in TWO calls — one for the two counts, one for
        every changed row packed into a single int64 buffer (floats
        bitcast). A naive per-column fetch cost 5-9s per barrier."""
        from ..utils.d2h import fetch_prefix_groups
        del_cols, n_del, ins_cols, n_ins = self._diff(cur, snap)
        counts = np.asarray(jnp.stack([n_del, n_ins]))
        nd, ni = int(counts[0]), int(counts[1])
        if not nd and not ni:
            return
        dels, inss = fetch_prefix_groups(
            [(list(del_cols), nd), (list(ins_cols), ni)])
        # deletes strictly before inserts: an updated row (same pk,
        # new values) diffs as delete(old)+insert(new) on one key
        if nd:
            st.write_chunk_columns(
                np.full(nd, OP_DELETE, dtype=np.int8),
                dels, np.ones(nd, dtype=bool))
        if ni:
            st.write_chunk_columns(
                np.full(ni, OP_INSERT, dtype=np.int8),
                inss, np.ones(ni, dtype=bool))

    def _recover_reset(self, s: int, rows: list) -> None:
        """Size a side for recovery and reset it to empty (the sharded
        subclass sizes by the WORST shard's row count instead)."""
        n = len(rows)
        while n > 0.7 * self.capacity[s]:
            self.capacity[s] *= 2
        self.sides[s] = self._empty(s)

    def recover(self) -> None:
        """Rebuild device state from the per-side StateTables.

        Replays RIGHT rows first (LEFT is empty, so nothing matches), then
        LEFT rows, whose probe of the restored RIGHT rebuilds the degree
        columns on BOTH sides (match_cnt for left inserts, scatter-adds
        for right rows) including the non-equi condition — so degrees need
        no durable table of their own. Replay outputs are discarded."""
        # spilled rows are in the durable tables too (eviction re-points
        # the diff base instead of deleting); recovery rebuilds them
        # resident and the host spill is dropped
        for sp in self._spill:
            sp.clear()
        if all(st is None for st in self.state_tables):
            return
        rows_by_side: list[list] = []
        for s in (LEFT, RIGHT):
            st = self.state_tables[s]
            rows_by_side.append(
                [] if st is None else [r for _, r in st.iter_all()])
        for s in (LEFT, RIGHT):
            self._recover_reset(s, rows_by_side[s])
        batch = 1 << 12
        # generous match buffer: a replay batch probes the FULL restored
        # other side; overflow here would silently corrupt degrees, and
        # the barrier watchdog fail-stops on the counter if it ever trips
        mf = max(self.match_factor, 64)
        # flag read by the sharded dispatch: replay rows are already in
        # join-input schema, so chain preludes (raw-chunk transforms)
        # and the mesh ingest log must not see them
        self._state_replay = True
        try:
            for s in (RIGHT, LEFT):
                rows = rows_by_side[s]
                sch = self.inputs[s].schema
                for i in range(0, len(rows), batch):
                    part = rows[i:i + batch]
                    arrays = [np.asarray([r[k] for r in part],
                                         dtype=f.data_type.np_dtype)
                              for k, f in enumerate(sch)]
                    cap = 1 << max(1, (len(part) - 1).bit_length())
                    out = self._apply(
                        self.sides[s], self.sides[1 - s], self._errs_dev,
                        StreamChunk.from_numpy(sch, arrays, capacity=cap),
                        jnp.int64(NO_WATERMARK), side=s, match_factor=mf)
                    self.sides[s] = out[0]
                    o = self.sides[1 - s]
                    self.sides[1 - s] = SortedSideState(
                        o.khash, o.cols, o.valids, out[1], o.n)
                    self._errs_dev = out[5]
                    self._n_dev[s] = out[6]
        finally:
            self._state_replay = False
        self._snap = [self.sides[LEFT], self.sides[RIGHT]]

    # ------------------------------------------------- HBM memory manager
    def state_bytes(self) -> int:
        return pytree_bytes(self.sides)

    @property
    def mem_spilled_rows(self) -> int:
        return self._spill[LEFT].rows + self._spill[RIGHT].rows

    def memory_enable_lru(self) -> None:
        self._mem_on = True

    def _mem_local_slices(self, s: int) -> list:
        """Local side-state views the spill programs run over (the
        sharded subclass returns one slice per shard)."""
        return [self.sides[s]]

    def _mem_live_ns(self) -> list:
        vals = np.asarray(jnp.stack([self.sides[LEFT].n,
                                     self.sides[RIGHT].n]))
        return [int(vals[0]), int(vals[1])]

    def _mem_cc_range_impl(self, side_state: SortedSideState, side: int):
        cc = self.clean_cols[side]
        C = side_state.capacity
        live = jnp.arange(C, dtype=jnp.int32) < side_state.n
        v = side_state.cols[cc].astype(jnp.int64)
        big = jnp.iinfo(jnp.int64).max
        lo = jnp.min(jnp.where(live, v, big))
        hi = jnp.max(jnp.where(live, v, -big))
        return lo, hi

    def _mem_pack_impl(self, side_state: SortedSideState, cc_thresh,
                       kh_thresh, side: int):
        cc = self.clean_cols[side]
        C = side_state.capacity
        live = jnp.arange(C, dtype=jnp.int32) < side_state.n
        mask = side_state.khash < kh_thresh
        if cc is not None:
            mask = mask | (side_state.cols[cc] < cc_thresh)
        return pack_rows(live & mask, list(side_state.cols)
                         + list(side_state.valids))

    def _mem_kh_cut_impl(self, side_state: SortedSideState, frac_num: int):
        """Key-hash value at the frac_num/4 quantile of the live prefix
        (the store is SORTED by khash, so a quantile is one gather)."""
        idx = jnp.clip(side_state.n * frac_num // 4 - 1, 0,
                       side_state.capacity - 1)
        return jnp.where(side_state.n > 0, side_state.khash[idx],
                         jnp.int64(-1))

    def _mem_cc_range(self, s: int) -> tuple[int, int]:
        parts = [self._mem_cc_range_prog(sl, side=s)
                 for sl in self._mem_local_slices(s)]
        arr = np.asarray(jnp.stack([x for p in parts for x in p]))
        return int(arr[0::2].min()), int(arr[1::2].max())

    def memory_maintain(self, epoch: int) -> None:
        """Barrier-time manager tick: sides past 60% occupancy spill cold
        rows to host ahead of the overflow cliff, so a tight fixed
        capacity degrades to host traffic instead of fail-stop +
        recovery-resize. Coldness axis: the state-cleaning (event-time)
        column when its live range discriminates — oldest windows first;
        otherwise (one hot window owns the shard) a key-hash prefix, so
        the spill is uniform over keys and reloads stay key-targeted."""
        if not self._mem_on or self.join_type != "inner":
            return
        ns = None
        for s in (LEFT, RIGHT):
            if ns is None:
                ns = self._mem_live_ns()
            if ns[s] <= 0.6 * self.capacity[s]:
                continue
            cc_t, kh_t = NO_WATERMARK, -1
            if self.clean_cols[s] is not None:
                lo, hi = self._mem_cc_range(s)
                if hi > lo:
                    cc_t = min(hi, lo + max(1, (hi - lo) // 2))
            if cc_t == NO_WATERMARK:
                # hash-prefix fallback: keep only the newest quarter of
                # capacity's worth so one interval's burst still fits
                vals = np.asarray(jnp.stack(
                    [self._mem_kh_cut_prog(sl, 3)
                     for sl in self._mem_local_slices(s)]))
                kh_t = int(np.median(vals))
                if kh_t <= 0:
                    continue
            self._mem_spill_below(s, cc_t, kh_t)

    def _mem_spill_below(self, s: int, cc_thresh: int,
                         kh_thresh: int) -> int:
        """Pack + fetch the rows under the thresholds, park them in the
        host spill, drop them on device. The durable table KEEPS them
        (the snapshot diff base is re-pointed past the eviction), which is
        what makes crash recovery rebuild them for free."""
        from ..utils.d2h import fetch_prefix_groups
        nc = len(self._col_dtypes[s])
        t_dev = jnp.int64(cc_thresh)
        kh_dev = jnp.int64(kh_thresh)
        packs = [self._mem_pack_prog(sl, t_dev, kh_dev, side=s)
                 for sl in self._mem_local_slices(s)]
        counts = np.asarray(jnp.stack([p[1] for p in packs]))
        total = int(counts.sum())
        if total == 0:
            return 0
        groups = [(list(p[0]), int(c))
                  for p, c in zip(packs, counts) if int(c)]
        for host in fetch_prefix_groups(groups):
            for r in range(host[0].shape[0]):
                vals = tuple(host[c][r].item() for c in range(nc))
                valids = tuple(bool(host[nc + c][r]) for c in range(nc))
                key = tuple(vals[i] for i in self.key_indices[s])
                self._spill[s].add(key, (vals, valids))
        self.sides[s] = self._evict(self.sides[s], t_dev, kh_dev, side=s)
        # the eviction must NOT become durable deletes: re-point the diff
        # base so the next persist diff skips it (the rows stay in the
        # table for recovery; reloads re-insert them as idempotent
        # upserts)
        self._snap[s] = self.sides[s]
        from ..utils.metrics import HBM_EVICTIONS
        HBM_EVICTIONS.inc()
        return total

    def _mem_check_reload(self, side: int, chunk: StreamChunk) -> None:
        """Read-through before a chunk applies: its keys can probe the
        other side and retract on its own, so spilled keys on EITHER side
        reload first (one packed fetch of the chunk's key columns, paid
        only while spilled state exists)."""
        from ..utils.d2h import fetch_columns
        key_idx = self.key_indices[side]
        host = fetch_columns(
            [chunk.columns[i].data for i in key_idx] + [chunk.vis])
        idx = np.flatnonzero(host[-1].astype(bool))
        keys, seen = [], set()
        for vals in zip(*(c[idx] for c in host[:-1])):
            k = tuple(v.item() for v in vals)
            if k not in seen:
                seen.add(k)
                keys.append(k)
        for t in (side, 1 - side):
            touched = self._spill[t].take_touched(keys)
            if touched:
                self._mem_reload_rows(
                    t, [rw for rows in touched.values() for rw in rows])
                self.mem_reload_count += len(touched)
                from ..utils.metrics import HBM_RELOADS
                HBM_RELOADS.inc(len(touched))

    def _mem_reload_rows(self, t: int, entries: list) -> None:
        """Replay spilled rows through the normal apply path — the exact
        recovery-replay shape — and DISCARD the emitted matches (they
        were already emitted when the rows first arrived; inner join, so
        no degree side effects)."""
        if not entries:
            return
        sch = self.inputs[t].schema
        mf = max(self.match_factors[t], 64)
        batch = 1 << 12
        for i in range(0, len(entries), batch):
            part = entries[i:i + batch]
            cap = 1 << max(1, (len(part) - 1).bit_length())
            cols = []
            for c, f in enumerate(sch):
                data = np.zeros(cap, dtype=f.data_type.np_dtype)
                valid = np.zeros(cap, dtype=bool)
                for r, (vals, valids) in enumerate(part):
                    data[r] = vals[c]
                    valid[r] = valids[c]
                cols.append(Column(jnp.asarray(data), jnp.asarray(valid)))
            ch = StreamChunk(tuple(cols),
                             jnp.full(cap, OP_INSERT, dtype=jnp.int8),
                             jnp.asarray(np.arange(cap) < len(part)), sch)
            out = self._apply(self.sides[t], self.sides[1 - t],
                              self._errs_dev, ch,
                              jnp.int64(self._pending_clean[t]), side=t,
                              match_factor=mf)
            self.sides[t] = out[0]
            o = self.sides[1 - t]
            self.sides[1 - t] = SortedSideState(o.khash, o.cols, o.valids,
                                                out[1], o.n)
            self._errs_dev = out[5]
            self._n_dev[t] = out[6]
        self._dirty[t] = True
        self._flush_dirty[t] = True

    def _mem_clean_spilled(self, s: int) -> None:
        """Watermark cleaning of evicted ranges: spilled rows below the
        side's eviction bound can never match again — drop them and write
        their durable tombstones."""
        wm = self._pending_clean[s]
        col = self.clean_cols[s]
        if col is None or wm == NO_WATERMARK or not self._spill[s]:
            return
        dead: list = []
        for k in list(self._spill[s].keys()):
            rows = self._spill[s].pop(k)
            for vals, valids in rows:
                if vals[col] < wm:
                    dead.append(vals)
                else:
                    self._spill[s].add(k, (vals, valids))
        if dead and self.state_tables[s] is not None:
            self.state_tables[s].write_chunk_rows(
                [(int(OP_DELETE), vals) for vals in dead])

    # ---------------------------------------------------------- cleaning
    def _recompute_pending(self) -> None:
        """Re-derive each side's eviction bound from the latest observed
        watermarks per its clean spec (monotone: watermarks only grow)."""
        for t in (LEFT, RIGHT):
            spec = self.clean_specs[t]
            if spec is None:
                continue
            kind = spec[0]
            if kind == "own":
                v = self._wms[t].get(spec[1])
            elif kind == "pair":
                kpos = spec[2]
                a = self._wms[t].get(self.key_indices[t][kpos])
                b = self._wms[1 - t].get(self.key_indices[1 - t][kpos])
                v = None if a is None or b is None else min(a, b)
            elif kind == "band":
                o = self._wms[1 - t].get(spec[2])
                v = None if o is None else o + spec[3]
                if len(spec) > 4 and spec[4] is not None:
                    own = self._wms[t].get(spec[4])
                    v = None if own is None or v is None else min(v, own)
            else:
                raise ValueError(f"unknown clean spec {spec!r}")
            if v is not None and v > self._pending_clean[t]:
                self._pending_clean[t] = v

    def _maybe_grow(self) -> None:
        """Double a side's capacity at 0.7 occupancy (memory-pressure
        growth instead of fail-stop; needs the watchdog's barrier fetch
        for the live count — transfer-free mode keeps fixed capacity,
        the same contract as hash_join's rebuild gating)."""
        known = getattr(self, "_n_known", None)
        if known is None:
            return
        for s in (LEFT, RIGHT):
            if known[s] <= 0.7 * self.capacity[s]:
                continue
            new_c = self.capacity[s] * 2
            for attr, st in (("sides", self.sides), ("_snap", self._snap)):
                side = st[s]
                if side is None or side.capacity >= new_c:
                    continue
                kh, cols, valids = grow_sorted_arrays(
                    side.khash, side.cols, side.valids, new_c)
                deg = jnp.concatenate([
                    side.degree,
                    jnp.zeros(new_c - side.capacity, dtype=jnp.int32)])
                st[s] = SortedSideState(kh, cols, valids, deg, side.n)
            self.capacity[s] = new_c
            self.rebuilds += 1

    # --------------------------------------------------------- watchdog
    def _check_watchdog(self) -> None:
        vals = np.asarray(self._watchdog_pack(
            self._errs_dev, self._n_dev[LEFT], self._n_dev[RIGHT]))
        n_mo, n_miss, n_ro = (int(x) for x in vals[:3])
        self._n_known = [int(vals[3]), int(vals[4])]
        if n_mo:
            raise RuntimeError(
                f"sorted-join match-buffer overflow ({n_mo} matches "
                f"dropped; raise match_factor)")
        if n_ro:
            raise RuntimeError(
                f"sorted-join state overflow ({n_ro} rows dropped; "
                f"capacity {self.capacity})")
        if n_miss:
            raise RuntimeError(
                f"sorted-join changelog inconsistency: {n_miss} deletes "
                f"matched no stored row")

    # ----------------------------------------------------------- stream
    async def execute(self):
        first = True
        async for kind, s, msg in barrier_align(*self.inputs):
            if kind == "chunk":
                if self._spill[LEFT] or self._spill[RIGHT]:
                    self._mem_check_reload(s, msg)
                wm = jnp.int64(self._pending_clean[s])
                self._cleaned_to[s] = self._pending_clean[s]
                (self.sides[s], oth_degree, cols, ops, vis, self._errs_dev,
                 self._n_dev[s]) = self._apply(
                    self.sides[s], self.sides[1 - s], self._errs_dev, msg,
                    wm, side=s, match_factor=self.match_factors[s])
                o = self.sides[1 - s]
                self.sides[1 - s] = SortedSideState(
                    o.khash, o.cols, o.valids, oth_degree, o.n)
                self._dirty[s] = True
                self._flush_dirty[s] = True
                if self.temporal and s == RIGHT:
                    continue        # table-side updates emit nothing
                yield StreamChunk(
                    tuple(cols[i] for i in self.output_indices), ops, vis,
                    self.schema)
            elif kind == "barrier":
                barrier: Barrier = msg
                if first or barrier.kind is BarrierKind.INITIAL:
                    first = False
                    for st in self.state_tables:
                        if st is not None:
                            st.init_epoch(barrier.epoch.curr)
                    self.recover()
                    yield barrier
                    continue
                stopping = barrier.mutation is not None and barrier.is_stop_any()
                dirty_any = any(self._dirty)
                # idle sides still clean by watermark at barriers
                for s2 in (LEFT, RIGHT):
                    if (self.clean_cols[s2] is not None
                            and self._pending_clean[s2] != NO_WATERMARK
                            and self._pending_clean[s2] != self._cleaned_to[s2]
                            and not self._dirty[s2]):
                        self.sides[s2] = self._evict(
                            self.sides[s2],
                            jnp.int64(self._pending_clean[s2]),
                            jnp.int64(-1), side=s2)
                        self._cleaned_to[s2] = self._pending_clean[s2]
                        self._flush_dirty[s2] = True
                    self._mem_clean_spilled(s2)
                    self._dirty[s2] = False
                # watchdog BEFORE the durable commit: errors fail-stop
                # this epoch's checkpoint (hash_join.py contract)
                if self.watchdog_interval and (stopping or dirty_any):
                    self._check_watchdog()
                    self._maybe_grow()
                self._persist(barrier)
                yield barrier
            else:
                wm: Watermark = msg
                self._wms[s][wm.col_idx] = wm.val
                self._recompute_pending()
                if wm.col_idx in self.key_indices[s]:
                    kpos = self.key_indices[s].index(wm.col_idx)
                    self._key_wms[s][kpos] = wm.val
                    other_wm = self._key_wms[1 - s].get(kpos)
                    if other_wm is not None:
                        val = min(wm.val, other_wm)
                        if self._emitted_key_wm.get(kpos) != val:
                            self._emitted_key_wm[kpos] = val
                            n_left = len(self.inputs[LEFT].schema)
                            for full_idx in (self.key_indices[LEFT][kpos],
                                             n_left + self.key_indices[RIGHT][kpos]):
                                if full_idx in self.output_indices:
                                    yield Watermark(
                                        self.output_indices.index(full_idx),
                                        wm.data_type, val)

"""Sink executor — changelog egress with EXACTLY-ONCE epoch delivery.

Reference: src/connector/src/sink/ (trait Sink + 12 connectors; mod.rs),
the sink executor (stream/src/executor/sink.rs), and the log-store
decoupling (src/stream/src/common/log_store_impl/) that makes delivery
exactly-once.

Delivery semantics: at each checkpoint barrier the executor APPENDS the
epoch's changelog to a durable per-sink log (logstore/log.py
`SinkChangelog`) staged at the sealed epoch — the entry commits
atomically WITH the Hummock checkpoint, riding the exact
seal/upload_sealed/commit_sealed path the rest of the epoch's state
takes. A background delivery task (`SinkDelivery`, woken at every
checkpoint commit) reads the COMMITTED log and writes each entry to the
target AFTER the commit point, tagged with a dense log-store sequence
number; the delivery cursor persists in sink state with the next
checkpoint and the log truncates below it. Crash anywhere:

  * before the commit — the staged entry dies with the epoch; recovery
    recomputes and re-mints the SAME sequence number (the counter
    restarts from the committed prefix), so the target never sees an
    uncommitted epoch at all;
  * between commit and delivery — the committed log survives; the fresh
    delivery task resumes after the durable cursor and delivers it
    (deliver-after-commit alone would DROP it — recovery does not
    replay committed epochs; the log is what replays them);
  * between delivery and the cursor checkpoint — the entry is
    re-delivered once, and the target dedupes on the STABLE sequence
    number (`committed_seq()`), which — unlike the wall-clock epoch ids
    the old direct path compared — survives restarts.

Net: every committed epoch reaches the target exactly once. The legacy
direct path (deliver at the barrier, before the commit: at-least-once
with per-epoch atomicity) remains for the blackhole bench egress, for
`WITH (exactly_once = 0)`, and for cluster-deployed sinks (v1: a worker
cannot observe meta's commit point; cluster sinks stay at-least-once,
rejected loudly if `exactly_once = 1` is requested).

Targets:
  * BlackholeSink   — counts rows (the reference's blackhole connector,
                      the benchmark egress)
  * FileSink        — newline-delimited JSON, one record per delivered
                      log entry with seq + epoch embedded; reopening
                      recovers `committed_seq()` from the file (torn
                      trailing lines from a mid-write crash are ignored)
  * CallbackSink    — hands (seq, epoch, rows) to a Python callable
                      (embedding/integration egress); pass
                      `committed_seq_fn` for cross-restart dedupe when
                      the callable records sequence numbers durably

Delivery contract: `write(seq, epoch, rows)` with rows = list of
(op, values) in changelog order, called once per committed log entry,
ascending sequence numbers; `committed_seq()` returns the last sequence
the target saw (0 = none) and is how re-deliveries inside the crash
window are skipped."""

from __future__ import annotations

import json
import os
from typing import Callable, Optional

from ..common.chunk import StreamChunk, OP_INSERT, OP_UPDATE_INSERT
from ..common.types import GLOBAL_DICT, DataType
from .executor import Executor
from .message import Barrier, BarrierKind


class SinkTarget:
    def write(self, seq: int, epoch: int, rows: list) -> None:
        raise NotImplementedError

    def committed_seq(self) -> int:
        return 0


class BlackholeSink(SinkTarget):
    def __init__(self):
        self.rows_written = 0
        self.epochs = 0

    def write(self, seq: int, epoch: int, rows: list) -> None:
        self.rows_written += len(rows)
        self.epochs += 1


class CallbackSink(SinkTarget):
    def __init__(self, fn: Callable[[int, int, list], None],
                 committed_seq_fn: Optional[Callable[[], int]] = None):
        self.fn = fn
        self._committed_seq_fn = committed_seq_fn
        self._committed = 0

    def write(self, seq: int, epoch: int, rows: list) -> None:
        self.fn(seq, epoch, rows)
        self._committed = seq

    def committed_seq(self) -> int:
        if self._committed_seq_fn is not None:
            return max(self._committed, int(self._committed_seq_fn()))
        return self._committed


class ArrowCallbackSink(SinkTarget):
    """Delivers each log entry as a pyarrow RecordBatch (ops as an extra
    int8 'op' column) — the Arrow egress ramp (arrow_impl.rs role)."""

    def __init__(self, fn: Callable, schema):
        import pyarrow as pa
        from ..common.arrow import arrow_schema
        self.fn = fn
        self.schema = schema
        self._asch = arrow_schema(schema)
        self._out_schema = self._asch.append(pa.field("op", pa.int8()))
        self._committed = 0

    def write(self, seq: int, epoch: int, rows: list) -> None:
        import pyarrow as pa
        cols = list(zip(*[vals for _, vals in rows])) if rows else [
            [] for _ in self.schema]
        arrays = []
        for f, af, vals in zip(self.schema, self._asch, cols):
            if f.data_type is DataType.VARCHAR:
                arrays.append(pa.array(
                    [None if v is None else GLOBAL_DICT.decode(int(v))
                     for v in vals], type=pa.string()).dictionary_encode())
            else:
                arrays.append(pa.array(list(vals), type=af.type))
        arrays.append(pa.array([op for op, _ in rows], type=pa.int8()))
        batch = pa.RecordBatch.from_arrays(arrays,
                                           schema=self._out_schema)
        self.fn(epoch, batch)
        self._committed = seq

    def committed_seq(self) -> int:
        return self._committed


class FileSink(SinkTarget):
    """JSONL with per-entry records:
    {"seq": S, "epoch": E, "rows": [[op, [...]], ...]}. The append-only
    file doubles as the target-side dedupe state: reopening reads the
    max delivered seq and `committed_seq()` makes crash-window
    re-deliveries no-ops. A torn trailing line (crash mid-append) fails
    to parse and is ignored — its entry re-delivers whole."""

    def __init__(self, path: str, schema=None):
        self.path = path
        self.schema = schema
        self._committed = 0
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue          # torn trailing line
                    self._committed = max(self._committed,
                                          rec.get("seq", 0))

    def _decode(self, values) -> list:
        if self.schema is None:
            return list(values)
        return [GLOBAL_DICT.decode(v)
                if f.data_type is DataType.VARCHAR and v is not None else v
                for v, f in zip(values, self.schema)]

    def write(self, seq: int, epoch: int, rows: list) -> None:
        rec = {"seq": seq, "epoch": epoch,
               "rows": [[op, self._decode(vals)] for op, vals in rows]}
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._committed = seq

    def committed_seq(self) -> int:
        return self._committed


class DeviceBlackholeSinkExecutor(Executor):
    """Benchmark/terminal sink that consumes the changelog WITHOUT host
    readback: chunks stay device arrays; a tiny on-device reduction of
    the last column is kept so callers can block_until_ready() for
    drain syncs. The reduction is a FRESH buffer on purpose: holding the
    raw column would pin whatever buffer the producer emitted, and
    executors that emit views of their device state (the fused q17
    snapshot executor emits diff rows sliced from dense stores it
    DONATES back to the next barrier's program) would leave this
    executor holding a deleted array — the bench teardown's
    "Array has been deleted" note (BENCH q17, pre-existing at seed)."""

    def __init__(self, input: Executor):
        self.input = input
        self.schema = input.schema
        self.pk_indices = getattr(input, "pk_indices", ())
        self.identity = "DeviceBlackholeSink"
        self.last = None

    async def execute(self):
        import jax.numpy as jnp
        from ..common.chunk import StreamChunk
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk) and msg.columns:
                self.last = jnp.sum(msg.columns[-1].data)
            yield msg


class SinkExecutor(Executor):
    """Terminal executor: buffers the epoch's changelog on the host and,
    at each checkpoint barrier, either appends it to the durable
    delivery log (`log` set — the exactly-once path; a background
    `SinkDelivery` owned by the coordinator's LogStoreHub writes it to
    the target after the commit) or delivers directly to the target
    (legacy at-least-once path). Rows leave the system here, so the d2h
    transfer is inherent — it happens at barrier cadence, not per
    chunk."""

    def __init__(self, input: Executor, target: SinkTarget,
                 force_append_only: bool = False,
                 log=None, hub=None, name: Optional[str] = None):
        self.input = input
        self.schema = input.schema
        self.pk_indices = input.pk_indices
        self.target = target
        self.force_append_only = force_append_only
        self.log = log                    # logstore SinkChangelog or None
        self.hub = hub                    # coordinator LogStoreHub
        self.name = name or f"Sink({type(target).__name__})"
        self.identity = f"Sink({type(target).__name__})"
        self._buf: list[StreamChunk] = []
        self._delivery = None
        self.rows_delivered = 0           # legacy-path counter
        self.rows_logged = 0              # log-path counter
        # legacy direct path: wall-clock epochs delivered this
        # incarnation (the old committed_epoch contract's residue —
        # cross-restart dedupe on this path is content-blind, which is
        # exactly why the log path exists)
        self._direct_delivered = 0

    def _epoch_rows(self) -> list:
        rows: list = []
        for chunk in self._buf:
            for op, vals in chunk.to_rows():
                if self.force_append_only:
                    if op in (OP_INSERT, OP_UPDATE_INSERT):
                        rows.append((OP_INSERT, vals))
                else:
                    rows.append((op, vals))
        self._buf = []
        return rows

    def _drain_direct(self, epoch: int) -> None:
        """Legacy path: deliver at the barrier, before the commit
        (at-least-once with per-epoch atomicity)."""
        rows = self._epoch_rows()
        if epoch <= self._direct_delivered:
            return                      # replayed epoch this incarnation
        self.target.write(0, epoch, rows)
        self._direct_delivered = epoch
        self.rows_delivered += len(rows)

    def _append_log(self, epoch: int) -> None:
        """Exactly-once path: stage the epoch's entry + the delivery
        cursor + truncation into the log AT the sealed epoch — all of
        it commits atomically with this checkpoint."""
        rows = self._epoch_rows()
        if rows:
            self.log.append(epoch, rows)
            self.rows_logged += len(rows)
        if self._delivery is not None:
            self.log.persist_cursor(epoch, self._delivery.delivered_seq)

    async def execute(self):
        first = True
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                self._buf.append(msg)
                yield msg
            elif isinstance(msg, Barrier):
                if first or msg.kind is BarrierKind.INITIAL:
                    first = False
                    self._buf = []
                    if self.log is not None and self.hub is not None \
                            and self._delivery is None:
                        self._delivery = self.hub.register_sink(
                            self.name, self.log, self.target)
                    yield msg
                    continue
                if msg.kind is BarrierKind.CHECKPOINT:
                    # the epoch SEALED by this barrier is epoch.prev
                    if self.log is not None:
                        self._append_log(msg.epoch.prev)
                    else:
                        self._drain_direct(msg.epoch.prev)
                yield msg
            else:
                yield msg

"""Sink executor — changelog egress with AT-LEAST-ONCE epoch delivery.

Reference: src/connector/src/sink/ (trait Sink + 12 connectors; mod.rs)
and the sink executor (stream/src/executor/sink.rs).

Delivery semantics (ADVICE r3 #1, documented honestly): each epoch's rows
deliver ATOMICALLY at its checkpoint barrier, ascending, and a restart
never hands the target a half-epoch — but delivery happens when the
barrier REACHES the sink, before the coordinator has durably committed
the epoch, and post-crash replays mint fresh (wall-clock) epoch ids. The
`committed_epoch()` dedupe therefore cannot match replayed rows, and the
crash window delivers twice: at-least-once with per-epoch atomicity.
Exactly-once requires the reference's log-store decoupling (persist the
epoch batch in sink state committed WITH the checkpoint, deliver from
the log after commit, target-side sequence dedupe) — not yet built.
Delivering only after commit is NOT an alternative: a crash between
commit and delivery would silently DROP the epoch (at-most-once), since
recovery does not replay committed epochs.

Targets here:
  * BlackholeSink   — counts rows (the reference's blackhole connector,
                      the benchmark egress)
  * FileSink        — newline-delimited JSON, one record per epoch with
                      the epoch id embedded; re-delivery after recovery
                      dedupes by epoch (append-only file = the log)
  * CallbackSink    — hands (epoch, rows) to a Python callable
                      (embedding/integration egress)

Delivery contract: `write(epoch, rows)` with rows = list of (op, values)
in changelog order, called once per epoch at its CHECKPOINT barrier,
ascending epochs; `committed_epoch()` lets the executor skip epochs the
target already saw WITHIN one incarnation (cross-restart dedupe limited
as described above)."""

from __future__ import annotations

import json
import os
from typing import Callable, Optional

from ..common.chunk import StreamChunk, OP_DELETE, OP_INSERT, OP_UPDATE_INSERT
from ..common.types import GLOBAL_DICT, DataType
from .executor import Executor
from .message import Barrier, BarrierKind, Watermark


class SinkTarget:
    def write(self, epoch: int, rows: list) -> None:
        raise NotImplementedError

    def committed_epoch(self) -> int:
        return 0


class BlackholeSink(SinkTarget):
    def __init__(self):
        self.rows_written = 0
        self.epochs = 0

    def write(self, epoch: int, rows: list) -> None:
        self.rows_written += len(rows)
        self.epochs += 1


class CallbackSink(SinkTarget):
    def __init__(self, fn: Callable[[int, list], None]):
        self.fn = fn

    def write(self, epoch: int, rows: list) -> None:
        self.fn(epoch, rows)


class ArrowCallbackSink(SinkTarget):
    """Delivers each epoch as a pyarrow RecordBatch (ops as an extra
    int8 'op' column) — the Arrow egress ramp (arrow_impl.rs role)."""

    def __init__(self, fn: Callable, schema):
        import pyarrow as pa
        from ..common.arrow import arrow_schema
        self.fn = fn
        self.schema = schema
        self._asch = arrow_schema(schema)
        self._out_schema = self._asch.append(pa.field("op", pa.int8()))
        self._committed = 0

    def write(self, epoch: int, rows: list) -> None:
        import pyarrow as pa
        cols = list(zip(*[vals for _, vals in rows])) if rows else [
            [] for _ in self.schema]
        arrays = []
        for f, af, vals in zip(self.schema, self._asch, cols):
            if f.data_type is DataType.VARCHAR:
                arrays.append(pa.array(
                    [None if v is None else GLOBAL_DICT.decode(int(v))
                     for v in vals], type=pa.string()).dictionary_encode())
            else:
                arrays.append(pa.array(list(vals), type=af.type))
        arrays.append(pa.array([op for op, _ in rows], type=pa.int8()))
        batch = pa.RecordBatch.from_arrays(arrays,
                                           schema=self._out_schema)
        self.fn(epoch, batch)
        self._committed = epoch

    def committed_epoch(self) -> int:
        return self._committed


class FileSink(SinkTarget):
    """JSONL with per-epoch records: {"epoch": E, "rows": [[op, [...]], ...]}.
    The append-only file doubles as the delivery log: recovery reads the
    last epoch and skips SAME-ID re-deliveries (see module docstring for
    why crash-window rows can still appear twice under fresh epoch ids)."""

    def __init__(self, path: str, schema=None):
        self.path = path
        self.schema = schema
        self._committed = 0
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    if line.strip():
                        self._committed = max(
                            self._committed, json.loads(line)["epoch"])

    def _decode(self, values) -> list:
        if self.schema is None:
            return list(values)
        return [GLOBAL_DICT.decode(v)
                if f.data_type is DataType.VARCHAR and v is not None else v
                for v, f in zip(values, self.schema)]

    def write(self, epoch: int, rows: list) -> None:
        rec = {"epoch": epoch,
               "rows": [[op, self._decode(vals)] for op, vals in rows]}
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._committed = epoch

    def committed_epoch(self) -> int:
        return self._committed


class DeviceBlackholeSinkExecutor(Executor):
    """Benchmark/terminal sink that consumes the changelog WITHOUT host
    readback: chunks stay device arrays, only a reference to the last
    column is kept so callers can block_until_ready() for drain syncs.
    The reference's blackhole sink serves the same role in its benches;
    on a tunneled TPU this is also the only sink that cannot poison
    dispatch with d2h fetches."""

    def __init__(self, input: Executor):
        self.input = input
        self.schema = input.schema
        self.pk_indices = getattr(input, "pk_indices", ())
        self.identity = "DeviceBlackholeSink"
        self.last = None

    async def execute(self):
        from ..common.chunk import StreamChunk
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk) and msg.columns:
                self.last = msg.columns[-1].data
            yield msg


class SinkExecutor(Executor):
    """Terminal executor: buffers the epoch's changelog on the host and
    delivers it at the barrier (rows leave the system here, so the d2h
    transfer is inherent — it happens at barrier cadence, not per chunk)."""

    def __init__(self, input: Executor, target: SinkTarget,
                 force_append_only: bool = False):
        self.input = input
        self.schema = input.schema
        self.pk_indices = input.pk_indices
        self.target = target
        self.force_append_only = force_append_only
        self.identity = f"Sink({type(target).__name__})"
        self._buf: list[StreamChunk] = []
        self.rows_delivered = 0

    def _drain(self, epoch: int) -> None:
        rows: list = []
        for chunk in self._buf:
            for op, vals in chunk.to_rows():
                if self.force_append_only:
                    if op in (OP_INSERT, OP_UPDATE_INSERT):
                        rows.append((OP_INSERT, vals))
                else:
                    rows.append((op, vals))
        self._buf = []
        if epoch <= self.target.committed_epoch():
            return                      # replayed epoch: already delivered
        self.target.write(epoch, rows)
        self.rows_delivered += len(rows)

    async def execute(self):
        first = True
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                self._buf.append(msg)
                yield msg
            elif isinstance(msg, Barrier):
                if first or msg.kind is BarrierKind.INITIAL:
                    first = False
                    self._buf = []
                    yield msg
                    continue
                if msg.kind is BarrierKind.CHECKPOINT:
                    # the epoch SEALED by this barrier is epoch.prev
                    self._drain(msg.epoch.prev)
                yield msg
            else:
                yield msg

"""Exchange: channels, dispatchers, merge — the intra-host communication
backend.

Reference: dispatch at src/stream/src/executor/dispatch.rs (Hash/Broadcast/
Simple/RoundRobin), fan-in alignment at merge.rs:109,267-342, bounded permit
channels at exchange/permit.rs. In the TPU design the *mesh-internal* shuffle
is an XLA all_to_all (parallel/exchange.py); these host channels connect
actors within a process and stand where the reference's permit channels +
gRPC exchange stood (between fragments, and host<->host over DCN).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    ChunkCoalescer, StreamChunk, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT,
)
from ..common.vnode import VNODE_COUNT, compute_vnodes
from .executor import Executor
from .message import Barrier, BarrierKind, Message, Watermark
from ..ops.jit_state import jit_state
from ..utils.faults import FAULTS, FaultInjected


class Channel:
    """Bounded mpsc channel (permit.rs analogue).

    `obs` (stream/monitor.py ChannelObs, attached at metric_level=debug)
    adds queue-depth and blocked-put (backpressure) accounting labelled
    by the RECEIVING actor: a full queue means the receiver is the
    bottleneck. `send_obs` (a counter labelled by the SENDING actor,
    attached when the sender's chain instruments) charges the same
    parked seconds to the actor that actually paid them — without it,
    "who is losing time to backpressure" and "who is causing it" were
    conflated under one receiver-side label.

    Replay buffering (per-fragment recovery, plan/build.py): with
    `enable_replay()` every sent message is ALSO appended to an ordered
    buffer tagged with a per-channel sequence number. The barrier
    coordinator trims the buffer at every checkpoint COMMIT — it drops
    everything up to and including the barrier that sealed the committed
    epoch — so the buffer always holds exactly the not-yet-durable
    suffix of the stream (bounded by the checkpoint in-flight window).
    When the consuming fragment is rebuilt from the committed epoch,
    `begin_replay()` re-delivers the whole buffer to the NEW consumer
    (prefixed by a synthetic INITIAL barrier standing for the committed
    point, so the rebuilt executors init/recover BEFORE any replayed
    chunk); live queue entries the dead consumer never drained are
    recognized by sequence number and skipped as duplicates, and a
    producer parked on the full queue is unblocked by the new consumer's
    normal draining. The producer never rewinds — its device state and
    its emitted stream are untouched, which is the whole point."""

    def __init__(self, capacity: int = 16):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=capacity)
        self.obs = None
        self.send_obs = None
        # replay machinery (None/off for plain channels — the hot path
        # below stays the pre-recovery one)
        self._buf = None                  # deque[(seq, msg)] | None
        self._seq = 0
        self._base_barrier = None         # last trimmed (committed) barrier
        self._replay = None               # deque to deliver before queue
        self._last_seq = 0                # max seq ever delivered
        self._stale_ceiling = None        # drop dead-epoch barriers below
        self._skip_refs = None            # chunk ids preloaded downstream

    # ------------------------------------------------------------ replay
    def enable_replay(self) -> None:
        if self._buf is None:
            self._buf = deque()

    @property
    def replay_enabled(self) -> bool:
        return self._buf is not None

    def trim_replay(self, committed_epoch: int) -> None:
        """Drop buffered messages covered by the committed checkpoint:
        everything up to and including the LAST barrier whose
        `epoch.prev <= committed_epoch` (that barrier sealed the epoch;
        all earlier messages are reflected in durable state). The
        dropped barrier is remembered as the replay base — the epoch a
        rebuilt consumer resumes from."""
        buf = self._buf
        if not buf:
            return
        cut, base = -1, None
        for i, (_seq, m) in enumerate(buf):
            if isinstance(m, Barrier) and m.epoch.prev <= committed_epoch:
                cut, base = i, m
        for _ in range(cut + 1):
            buf.popleft()
        if base is not None:
            self._base_barrier = base

    def reset_for_rebuild(self) -> None:
        """Reset an INTRA-CONE edge: both endpoints of this channel are
        being rebuilt (downstream-cone recovery), so everything in flight
        — queued undrained messages, the buffered uncommitted suffix,
        the sequence counters — belongs to dead incarnations. The
        rebuilt producer re-derives the suffix from ITS replayed inputs
        and re-emits it here as fresh messages (starting with the
        synthetic INITIAL barrier it received from the cone's inbound
        frontier), so the rebuilt consumer must see an empty stream, not
        the aborted interval's leftovers."""
        while not self.queue.empty():
            self.queue.get_nowait()
        if self._buf is not None:
            self._buf = deque()
        self._seq = 0
        self._last_seq = 0
        self._replay = None
        self._base_barrier = None
        self._stale_ceiling = None
        self._skip_refs = None

    def begin_replay(self, stale_ceiling: Optional[int] = None,
                     skip_refs: Optional[set] = None) -> int:
        """Arm re-delivery of the buffered suffix to the next consumer.
        Prepends a synthetic INITIAL barrier at the committed point (the
        rebuilt chain's executors init their state tables and reload
        durable state at their first barrier — which must precede every
        replayed chunk). Returns the number of messages to replay.

        `stale_ceiling` (cluster worker recovery): barriers of the
        DROPPED epochs — committed < epoch.curr <= ceiling — are
        filtered out of the replay AND the live stream. In-process cone
        recovery replays them on every leg (all legs saw the same
        stream, so merges align); in the cluster radius a rebuilt
        SOURCE joins straight at the live stream, so a frontier leg
        replaying dead barriers would leave its merge peer one barrier
        short forever. A producer that was parked mid-epoch may even
        dispatch a dead barrier AFTER the rebuild — the ceiling filter
        catches that too.

        `skip_refs` (channel-free mesh replay): object identities of
        chunks the rebuilt consumer already holds — preloaded straight
        from the crashed executor's MeshIngestLog into its pending queue
        — so re-delivering them here would double-apply. The replay
        buffer holds the SAME objects by reference, so identity matching
        is exact; barriers and watermarks still replay for epoch
        alignment. Consumed on match (each ref skips once)."""
        assert self._buf is not None, "replay not enabled on this channel"
        self._stale_ceiling = stale_ceiling
        self._skip_refs = set(skip_refs) if skip_refs else None
        items = deque(self._buf)
        base = self._base_barrier
        if base is not None:
            items.appendleft((None, Barrier(
                base.epoch, BarrierKind.INITIAL, None, (),
                base.inject_time_ns)))
        self._replay = items
        return len(items)

    def _is_stale(self, msg) -> bool:
        c = getattr(self, "_stale_ceiling", None)
        return (c is not None and isinstance(msg, Barrier)
                and msg.kind is not BarrierKind.INITIAL
                and msg.epoch.curr <= c)

    async def send(self, msg: Message) -> None:
        item = msg
        if self._buf is not None:
            self._seq += 1
            item = (self._seq, msg)
            # buffer BEFORE the (possibly blocking) queue put: a sender
            # parked on a full queue at rebuild time already has its
            # message in the buffer, so replay covers it and the queued
            # copy dedupes by seq when it finally lands
            self._buf.append(item)
        obs = self.obs
        send_obs = self.send_obs
        if obs is None and send_obs is None:
            await self.queue.put(item)
            return
        if self.queue.full():
            t0 = time.monotonic()
            await self.queue.put(item)
            dt = time.monotonic() - t0
            if obs is not None:
                obs.blocked_put.inc(dt)
            if send_obs is not None:
                send_obs.inc(dt)
        else:
            self.queue.put_nowait(item)
        if obs is not None:
            obs.depth.set(float(self.queue.qsize()))

    async def recv(self) -> Message:
        while self._replay:
            seq, msg = self._replay.popleft()
            if seq is not None and seq > self._last_seq:
                self._last_seq = seq
            if self._is_stale(msg):
                continue
            skips = getattr(self, "_skip_refs", None)
            if skips and id(msg) in skips:
                skips.discard(id(msg))  # consumer preloaded this chunk
                continue
            return msg
        if self._buf is None:
            msg = await self.queue.get()
            if self.obs is not None:
                self.obs.depth.set(float(self.queue.qsize()))
            return msg
        while True:
            seq, msg = await self.queue.get()
            if self.obs is not None:
                self.obs.depth.set(float(self.queue.qsize()))
            if seq <= self._last_seq:
                continue            # duplicate of a replayed message
            self._last_seq = seq
            if self._is_stale(msg):
                continue            # a dead epoch's barrier, late
            return msg


# ------------------------------------------------------------- dispatchers

class Dispatcher:
    async def dispatch(self, msg: Message) -> None:
        raise NotImplementedError


class TapDispatcher(Dispatcher):
    """Runtime-extendable fanout for MV roots: a downstream `CREATE
    MATERIALIZED VIEW ... FROM <mv>` attaches a channel here while the
    deployment is LIVE (the reference's Add-mutation installs new
    dispatchers the same way, dispatch.rs AddOutput). Attach/detach must
    happen between barriers (the session holds the coordinator's rounds
    lock), so every consumer sees a barrier-aligned prefix.

    A Stop barrier covering ALL of a channel's consumer actors removes
    that channel right after delivering the barrier (the reference drops
    dispatcher outputs at the DropActors barrier) — without this, the
    upstream actor keeps pushing post-stop chunks into a channel nobody
    drains and deadlocks on its bounded capacity."""

    def __init__(self):
        self.channels: list = []          # (Channel, consumer actor ids)

    def add(self, channel, consumer_actor_ids=frozenset()) -> None:
        self.channels.append((channel, frozenset(consumer_actor_ids)))

    def remove(self, channel) -> None:
        self.channels = [(c, ids) for c, ids in self.channels
                         if c is not channel]

    def set_consumers(self, channel, consumer_actor_ids) -> None:
        self.channels = [
            (c, frozenset(consumer_actor_ids) if c is channel else ids)
            for c, ids in self.channels]

    async def dispatch(self, msg: Message) -> None:
        from .message import StopMutation
        for ch, ids in list(self.channels):
            await ch.send(msg)
            if (isinstance(msg, Barrier) and ids
                    and isinstance(msg.mutation, StopMutation)
                    and ids <= msg.mutation.actor_ids):
                self.remove(ch)


class SimpleDispatcher(Dispatcher):
    def __init__(self, output: Channel):
        self.output = output

    async def dispatch(self, msg: Message) -> None:
        await self.output.send(msg)


class BroadcastDispatcher(Dispatcher):
    def __init__(self, outputs: Sequence[Channel]):
        self.outputs = list(outputs)

    async def dispatch(self, msg: Message) -> None:
        for o in self.outputs:
            await o.send(msg)


class HashDispatcher(Dispatcher):
    """vnode-routed fan-out (dispatch.rs:679,737-790): vnode per row from the
    dist-key columns, visibility per output = (vnode_to_output[vnode] == o).
    Update pairs whose halves land on different outputs degrade to
    Delete/Insert (op fixup, :751-790). Chunks keep full capacity — each
    output sees the same arrays with a different mask (zero-copy fan-out)."""

    def __init__(self, outputs: Sequence[Channel], dist_key_indices: Sequence[int],
                 vnode_to_output: np.ndarray):
        assert len(vnode_to_output) == VNODE_COUNT
        self.outputs = list(outputs)
        self.dist_key_indices = tuple(dist_key_indices)
        # the mapping is PASSED to the jitted program, never closed over:
        # a captured device array costs ~3ms per invocation on a tunneled
        # TPU (re-validated constant buffer), an argument ~30us
        self.vnode_to_output = jnp.asarray(vnode_to_output, dtype=jnp.int32)
        # NO donation: route outputs are zero-copy views of the input
        # chunk, which other consumers may still hold
        self._route = jit_state(self._route_impl,
                                name="hash_dispatch_route")

    def _route_impl(self, chunk: StreamChunk, vnode_to_output):
        keys = [chunk.columns[i].data for i in self.dist_key_indices]
        vnodes = compute_vnodes(keys)
        out_idx = jnp.take(vnode_to_output, vnodes)
        results = []
        ops = chunk.ops
        is_ud = ops == OP_UPDATE_DELETE
        is_ui = ops == OP_UPDATE_INSERT
        partner_prev = jnp.roll(out_idx, 1)   # UI's partner UD output
        partner_next = jnp.roll(out_idx, -1)  # UD's partner UI output
        pair_split = (is_ui & (out_idx != partner_prev)) | (is_ud & (out_idx != partner_next))
        fixed_ops = jnp.where(pair_split & is_ui, OP_INSERT, ops)
        fixed_ops = jnp.where(pair_split & is_ud, OP_DELETE, fixed_ops).astype(ops.dtype)
        for o in range(len(self.outputs)):
            vis = chunk.vis & (out_idx == o)
            results.append(StreamChunk(chunk.columns, fixed_ops, vis, chunk.schema))
        return tuple(results)

    async def dispatch(self, msg: Message) -> None:
        if isinstance(msg, StreamChunk):
            for o, ch in zip(self.outputs,
                             self._route(msg, self.vnode_to_output)):
                await o.send(ch)
        else:
            for o in self.outputs:
                await o.send(msg)


# ------------------------------------------------------------------ merge

class ChannelInput(Executor):
    """Executor adapter over a channel (ReceiverExecutor, receiver.rs).

    `stop_on(barrier) -> bool` decides which Stop barrier ends the
    stream. Deployment builders pass the owning actor's predicate
    (`b.is_stop(actor_id)`): a shared coordinator's stop mutation may
    target OTHER deployments' actors (MV-on-MV taps route every barrier
    through everyone), and self-terminating on a foreign stop silently
    killed the chain. Default (None) keeps the standalone/test behavior:
    any Stop ends the stream."""

    def __init__(self, channel: Channel, schema, stop_on=None,
                 coalesce_max: int = 0, actor_id=None):
        self.channel = channel
        self.schema = schema
        self.stop_on = stop_on
        # coalesce_max > 0: pack runs of consecutive chunks up to that
        # total capacity into one chunk (flushed before any barrier/
        # watermark, so cross-message ordering is the uncoalesced one)
        self.coalescer = (ChunkCoalescer(coalesce_max) if coalesce_max
                          else None)
        self.identity = "ChannelInput"
        # owning actor id (fault-point context: poison_chunk/channel_stall
        # rules filter on the CONSUMING actor)
        self.actor_id = actor_id
        # owning actor's ActorObs (stream/monitor.py): recv waits are the
        # align component of the interval phase split
        self.obs = None

    async def execute(self):
        from .message import StopMutation
        co = self.coalescer
        while True:
            obs = self.obs
            if obs is None:
                msg = await self.channel.recv()
            else:
                t0 = time.monotonic_ns()
                msg = await self.channel.recv()
                obs.add_input_wait(time.monotonic_ns() - t0)
                if isinstance(msg, StreamChunk):
                    obs.note_chunk_in()
            if FAULTS.active and isinstance(msg, StreamChunk):
                if FAULTS.hit("poison_chunk",
                              actor=self.actor_id) is not None:
                    raise FaultInjected(
                        f"injected poison_chunk at consumer actor "
                        f"{self.actor_id}")
                stall = FAULTS.hit("channel_stall", actor=self.actor_id)
                if stall is not None:
                    await asyncio.sleep(stall.get("ms", 100) / 1e3)
            if co is not None:
                if isinstance(msg, StreamChunk):
                    for out in co.push(msg):
                        yield out
                    continue
                for out in co.flush():
                    yield out
            yield msg
            if isinstance(msg, Barrier)                     and isinstance(msg.mutation, StopMutation):
                if self.stop_on is None or self.stop_on(msg):
                    return


class MergeExecutor(Executor):
    """Fan-in with barrier alignment (merge.rs:267-342): an upstream that
    yields a barrier is blocked until every upstream yields that barrier,
    then ONE barrier is emitted. Watermarks are min-combined per column."""

    def __init__(self, channels: Sequence[Channel], schema, stop_on=None,
                 coalesce_max: int = 0):
        self.channels = list(channels)
        self.schema = schema
        self.stop_on = stop_on            # see ChannelInput.stop_on
        # fan-in is where small-chunk runs concentrate (N upstream actors
        # interleave inside one barrier interval): one coalescer packs the
        # combined stream, flushed before any barrier/watermark emission
        self.coalescer = (ChunkCoalescer(coalesce_max) if coalesce_max
                          else None)
        self.identity = f"Merge({len(self.channels)})"
        # owning actor's ActorObs: time parked in asyncio.wait covers
        # both upstream starvation AND barrier alignment (channels that
        # already delivered their barrier are held out of the wait set)
        self.obs = None

    async def execute(self):
        n = len(self.channels)
        co = self.coalescer
        getters: dict[int, asyncio.Task] = {
            i: asyncio.create_task(c.recv()) for i, c in enumerate(self.channels)}
        pending_barrier: dict[int, Barrier] = {}
        watermarks: dict[int, dict[int, Watermark]] = {i: {} for i in range(n)}
        emitted_wm: dict[int, object] = {}
        try:
            while True:
                waiting = [t for i, t in getters.items() if i not in pending_barrier]
                if not waiting:
                    barrier = next(iter(pending_barrier.values()))
                    from .message import StopMutation
                    stop = (isinstance(barrier.mutation, StopMutation)
                            and (self.stop_on is None
                                 or self.stop_on(barrier)))
                    if co is not None:
                        for out in co.flush():
                            yield out
                    yield barrier
                    pending_barrier.clear()
                    if stop:
                        return
                    for i, c in enumerate(self.channels):
                        getters[i] = asyncio.create_task(c.recv())
                    continue
                obs = self.obs
                if obs is None:
                    done, _ = await asyncio.wait(
                        waiting, return_when=asyncio.FIRST_COMPLETED)
                else:
                    t0 = time.monotonic_ns()
                    done, _ = await asyncio.wait(
                        waiting, return_when=asyncio.FIRST_COMPLETED)
                    obs.add_input_wait(time.monotonic_ns() - t0)
                # fixed channel order, not set order: asyncio.wait's
                # `done` is a set whose iteration follows task object
                # addresses — with several upstreams ready in one pass
                # the merge interleaving would depend on process memory
                # layout (same fix as stream/align.py barrier_align)
                for i in sorted(getters):
                    t = getters[i]
                    if t not in done or i in pending_barrier:
                        continue
                    msg = t.result()
                    if obs is not None and isinstance(msg, StreamChunk):
                        obs.note_chunk_in()
                    if isinstance(msg, Barrier):
                        pending_barrier[i] = msg
                    elif isinstance(msg, Watermark):
                        watermarks[i][msg.col_idx] = msg
                        wm = self._combined_watermark(msg.col_idx, watermarks)
                        if wm is not None and emitted_wm.get(msg.col_idx) != wm.val:
                            emitted_wm[msg.col_idx] = wm.val
                            if co is not None:
                                for out in co.flush():
                                    yield out
                            yield wm
                        getters[i] = asyncio.create_task(self.channels[i].recv())
                    else:
                        if co is not None:
                            for out in co.push(msg):
                                yield out
                        else:
                            yield msg
                        getters[i] = asyncio.create_task(self.channels[i].recv())
        finally:
            for t in getters.values():
                t.cancel()

    def _combined_watermark(self, col_idx: int, watermarks) -> Optional[Watermark]:
        vals = [w[col_idx] for w in watermarks.values() if col_idx in w]
        if len(vals) < len(self.channels):
            return None
        return min(vals, key=lambda w: w.val)

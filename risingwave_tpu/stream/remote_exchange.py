"""Remote exchange — the cross-host (DCN) tier of the communication
backend.

Reference: src/stream/src/executor/exchange/input.rs:103-120
(RemoteInput), src/compute/src/rpc/service/exchange_service.rs:78
(GetStream) and proto/task_service.proto:103-113 — gRPC streams with
permit-based (credit) backpressure between compute nodes. Mesh-internal
shuffles ride ICI as XLA collectives (parallel/exchange.py); THIS module
carries fragment edges that cross process/host boundaries.

TPU-first wire design: chunks serialize as Arrow IPC record batches
(common/arrow.py — fixed-width columns move as whole buffers, VARCHAR as
dictionary indices against each side's GLOBAL_DICT with the dictionary
shipped in-band), ops ride as an extra int8 column, and only VISIBLE
rows travel. Barriers/watermarks are small JSON frames. Flow control is
credit-based exactly like permit.rs: the receiver grants chunk credits
as its bounded queue drains; the sender awaits credits before writing,
so a slow consumer backpressures through TCP instead of ballooning.

Frame format: 1-byte type ('C' chunk | 'B' barrier | 'W' watermark |
'K' credit grant) + 4-byte big-endian length + payload.
"""

from __future__ import annotations

import asyncio
import io
import json
import struct
from typing import Optional

import numpy as np

from ..common.chunk import StreamChunk
from ..common.types import Schema
from .executor import Executor
from .message import (
    Barrier, BarrierKind, PauseMutation, ResumeMutation, StopMutation,
    ThrottleMutation, Watermark,
)
from ..common.epoch import EpochPair


def _ser_mutation(m) -> Optional[dict]:
    if m is None:
        return None
    if isinstance(m, StopMutation):
        return {"type": "stop", "actor_ids": sorted(m.actor_ids)}
    if isinstance(m, PauseMutation):
        return {"type": "pause"}
    if isinstance(m, ResumeMutation):
        return {"type": "resume"}
    if isinstance(m, ThrottleMutation):
        return {"type": "throttle", "limits": [list(x) for x in m.limits]}
    raise ValueError(f"unserializable mutation {m!r}")


def _de_mutation(d):
    if d is None:
        return None
    t = d["type"]
    if t == "stop":
        return StopMutation(frozenset(d["actor_ids"]))
    if t == "pause":
        return PauseMutation()
    if t == "resume":
        return ResumeMutation()
    if t == "throttle":
        return ThrottleMutation(tuple(tuple(x) for x in d["limits"]))
    raise ValueError(t)


def _chunk_payload(chunk: StreamChunk) -> bytes:
    import pyarrow as pa
    from ..common.arrow import chunk_to_arrow
    batch = chunk_to_arrow(chunk)
    ops = np.asarray(chunk.ops)[np.asarray(chunk.vis)]
    batch = batch.append_column("__op", pa.array(ops, type=pa.int8()))
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue()


def _payload_chunk(payload: bytes, schema: Schema,
                   capacity: int) -> StreamChunk:
    import pyarrow as pa
    from ..common.arrow import batch_to_chunk
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        table = r.read_all()
    batch = (table.combine_chunks().to_batches()[0]
             if table.num_rows else
             pa.RecordBatch.from_pylist([], schema=table.schema))
    ops = np.asarray(batch.column("__op"), dtype=np.int8)
    data = batch.drop_columns(["__op"])
    cap = max(capacity, 1 << max(0, (batch.num_rows - 1).bit_length()))
    chunk = batch_to_chunk(data, schema, capacity=cap)
    full_ops = np.zeros(cap, dtype=np.int8)
    full_ops[:len(ops)] = ops
    import jax.numpy as jnp
    return StreamChunk(chunk.columns, jnp.asarray(full_ops), chunk.vis,
                       schema)


async def _write_frame(writer, tag: bytes, payload: bytes) -> None:
    writer.write(tag + struct.pack("!I", len(payload)) + payload)
    await writer.drain()


async def _read_frame(reader):
    hdr = await reader.readexactly(5)
    ln = struct.unpack("!I", hdr[1:])[0]
    return hdr[:1], await reader.readexactly(ln)


class RemoteOutput:
    """Sender half (dispatch target, Channel-compatible `send`)."""

    def __init__(self, host: str, port: int, credits: int = 0):
        # credits start at ZERO: the receiver's initial grant (its queue
        # depth) is the ONLY source of permits, exactly like permit.rs
        self.host = host
        self.port = port
        self._credits = credits          # chunk permits in hand
        self._credit_evt = asyncio.Event()
        self._reader = self._writer = None
        self._credit_task = None
        self._dead = False

    async def connect(self) -> "RemoteOutput":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._credit_task = asyncio.create_task(self._credit_loop())
        return self

    async def _credit_loop(self) -> None:
        try:
            while True:
                tag, payload = await _read_frame(self._reader)
                if tag == b"K":
                    self._credits += struct.unpack("!I", payload)[0]
                    self._credit_evt.set()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                OSError):
            pass
        finally:
            # a sender parked on the credit wait must FAIL, not hang
            # forever, once the receiver is gone (recovery teardown
            # otherwise deadlocks: receiver waits for this socket to
            # close while we wait for its credits)
            self._dead = True
            self._credit_evt.set()

    async def send(self, msg) -> None:
        if self._dead:
            raise ConnectionResetError("remote receiver is gone")
        if isinstance(msg, StreamChunk):
            while self._credits <= 0:     # permit-based backpressure
                if self._dead:
                    raise ConnectionResetError("remote receiver is gone")
                self._credit_evt.clear()
                await self._credit_evt.wait()
            self._credits -= 1
            await _write_frame(self._writer, b"C", _chunk_payload(msg))
        elif isinstance(msg, Barrier):
            await _write_frame(self._writer, b"B", json.dumps({
                "curr": msg.epoch.curr, "prev": msg.epoch.prev,
                "kind": msg.kind.value,
                "mutation": _ser_mutation(msg.mutation)}).encode())
        elif isinstance(msg, Watermark):
            await _write_frame(self._writer, b"W", json.dumps({
                "col_idx": msg.col_idx, "dtype": msg.data_type.name,
                "val": int(msg.val)}).encode())
        else:
            raise ValueError(f"unsendable message {type(msg)}")

    async def close(self) -> None:
        if self._credit_task:
            self._credit_task.cancel()
        if self._writer:
            self._writer.close()


class RemoteInput(Executor):
    """Receiver half: a TCP server feeding this executor's stream
    (exchange_service.rs GetStream). Grants credits as the consumer
    drains — the bounded in-flight window IS the backpressure."""

    def __init__(self, schema: Schema, host: str = "127.0.0.1",
                 port: int = 0, capacity: int = 1024,
                 queue_depth: int = 16, stop_on=None):
        self.schema = schema
        self.pk_indices = ()
        self.host = host
        self.port = port
        self.capacity = capacity
        self.queue_depth = queue_depth
        self.stop_on = stop_on
        self.identity = "RemoteInput"
        self._queue: asyncio.Queue = asyncio.Queue()
        self._server = None
        self._conn_writer = None

    async def start(self) -> "RemoteInput":
        async def handle(reader, writer):
            if self._conn_writer is not None:
                # one producer per input (fan-in uses one RemoteInput per
                # upstream edge) — a second connection would steal the
                # credit channel and deadlock the first sender
                writer.close()
                return
            self._conn_writer = writer
            # initial credit window
            await _write_frame(writer, b"K",
                               struct.pack("!I", self.queue_depth))
            try:
                while True:
                    tag, payload = await _read_frame(reader)
                    await self._queue.put((tag, payload))
            except (asyncio.IncompleteReadError, ConnectionResetError):
                await self._queue.put((b"X", b""))

        self._server = await asyncio.start_server(handle, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        # close the live connection FIRST: wait_closed() (3.12+) waits
        # for connection handlers, and ours is blocked reading a socket
        # whose peer may itself be blocked on our credits — the
        # recovery-teardown circular wait (round 5)
        if self._conn_writer is not None:
            try:
                self._conn_writer.close()
            except Exception:  # noqa: BLE001
                pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def recv(self):
        """Channel-compatible receive — the cluster partial build
        (plan/build.py build_partial_graph) wires a RemoteInput as ONE
        LEG of a ChannelInput/MergeExecutor, next to local channels.
        Identical decode to execute(); a vanished peer raises (the
        actor's failure report is the cluster's failure detector)."""
        from ..common.types import DataType
        while True:
            tag, payload = await self._queue.get()
            if tag == b"X":
                raise ConnectionResetError(
                    "remote exchange producer went away")
            if tag == b"C":
                chunk = _payload_chunk(payload, self.schema, self.capacity)
                if self._conn_writer is not None:
                    try:
                        await _write_frame(self._conn_writer, b"K",
                                           struct.pack("!I", 1))
                    except (ConnectionResetError, BrokenPipeError, OSError):
                        self._conn_writer = None
                return chunk
            if tag == b"B":
                d = json.loads(payload)
                return Barrier(EpochPair(d["curr"], d["prev"]),
                               BarrierKind(d["kind"]),
                               mutation=_de_mutation(d["mutation"]))
            if tag == b"W":
                d = json.loads(payload)
                return Watermark(d["col_idx"], DataType[d["dtype"]],
                                 d["val"])

    async def execute(self):
        from ..common.types import DataType
        while True:
            tag, payload = await self._queue.get()
            if tag == b"X":
                return
            if tag == b"C":
                chunk = _payload_chunk(payload, self.schema,
                                       self.capacity)
                yield chunk
                # grant the credit back once the chunk is in the pipeline
                # (the peer may already be gone after its stop barrier)
                if self._conn_writer is not None:
                    try:
                        await _write_frame(self._conn_writer, b"K",
                                           struct.pack("!I", 1))
                    except (ConnectionResetError, BrokenPipeError, OSError):
                        self._conn_writer = None
            elif tag == b"B":
                d = json.loads(payload)
                b = Barrier(EpochPair(d["curr"], d["prev"]),
                            BarrierKind(d["kind"]),
                            mutation=_de_mutation(d["mutation"]))
                yield b
                if isinstance(b.mutation, StopMutation) and (
                        self.stop_on is None or self.stop_on(b)):
                    return
            elif tag == b"W":
                d = json.loads(payload)
                yield Watermark(d["col_idx"], DataType[d["dtype"]],
                                d["val"])

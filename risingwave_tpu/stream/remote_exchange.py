"""Remote exchange — the cross-host (DCN) tier of the communication
backend.

Reference: src/stream/src/executor/exchange/input.rs:103-120
(RemoteInput), src/compute/src/rpc/service/exchange_service.rs:78
(GetStream) and proto/task_service.proto:103-113 — gRPC streams with
permit-based (credit) backpressure between compute nodes. Mesh-internal
shuffles ride ICI as XLA collectives (parallel/exchange.py); THIS module
carries fragment edges that cross process/host boundaries.

TPU-first wire design: chunks serialize as Arrow IPC record batches
(common/arrow.py — fixed-width columns move as whole buffers, VARCHAR as
dictionary indices against each side's GLOBAL_DICT with the dictionary
shipped in-band), ops ride as an extra int8 column, and only VISIBLE
rows travel. Barriers/watermarks are small JSON frames. Flow control is
credit-based exactly like permit.rs: the receiver grants chunk credits
as its bounded queue drains; the sender awaits credits before writing,
so a slow consumer backpressures through TCP instead of ballooning.

Frame format: 1-byte type ('C' chunk | 'B' barrier | 'W' watermark |
'K' credit grant) + 4-byte big-endian length + payload.
"""

from __future__ import annotations

import asyncio
import io
import json
import struct
from collections import deque
from typing import Optional

import numpy as np

from ..common.chunk import StreamChunk
from ..common.types import Schema
from .executor import Executor
from .message import (
    Barrier, BarrierKind, PauseMutation, ResumeMutation, StopMutation,
    ThrottleMutation, Watermark,
)
from ..common.epoch import EpochPair


def _ser_mutation(m) -> Optional[dict]:
    if m is None:
        return None
    if isinstance(m, StopMutation):
        return {"type": "stop", "actor_ids": sorted(m.actor_ids)}
    if isinstance(m, PauseMutation):
        return {"type": "pause"}
    if isinstance(m, ResumeMutation):
        return {"type": "resume"}
    if isinstance(m, ThrottleMutation):
        return {"type": "throttle", "limits": [list(x) for x in m.limits]}
    raise ValueError(f"unserializable mutation {m!r}")


def _de_mutation(d):
    if d is None:
        return None
    t = d["type"]
    if t == "stop":
        return StopMutation(frozenset(d["actor_ids"]))
    if t == "pause":
        return PauseMutation()
    if t == "resume":
        return ResumeMutation()
    if t == "throttle":
        return ThrottleMutation(tuple(tuple(x) for x in d["limits"]))
    raise ValueError(t)


def _chunk_payload(chunk: StreamChunk) -> bytes:
    import pyarrow as pa
    from ..common.arrow import chunk_to_arrow
    batch = chunk_to_arrow(chunk)
    ops = np.asarray(chunk.ops)[np.asarray(chunk.vis)]
    batch = batch.append_column("__op", pa.array(ops, type=pa.int8()))
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue()


def _payload_chunk(payload: bytes, schema: Schema,
                   capacity: int) -> StreamChunk:
    import pyarrow as pa
    from ..common.arrow import batch_to_chunk
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        table = r.read_all()
    batch = (table.combine_chunks().to_batches()[0]
             if table.num_rows else
             pa.RecordBatch.from_pylist([], schema=table.schema))
    ops = np.asarray(batch.column("__op"), dtype=np.int8)
    data = batch.drop_columns(["__op"])
    cap = max(capacity, 1 << max(0, (batch.num_rows - 1).bit_length()))
    chunk = batch_to_chunk(data, schema, capacity=cap)
    full_ops = np.zeros(cap, dtype=np.int8)
    full_ops[:len(ops)] = ops
    import jax.numpy as jnp
    return StreamChunk(chunk.columns, jnp.asarray(full_ops), chunk.vis,
                       schema)


# this process's cluster worker id (set by cluster/compute_node.py at
# hello) — fault-rule context so `dcn_drop:worker=N` severs exactly one
# node's leg even though the spec arms every process
WORKER_ID = None


async def _write_frame(writer, tag: bytes, payload: bytes) -> None:
    writer.write(tag + struct.pack("!I", len(payload)) + payload)
    await writer.drain()


async def _read_frame(reader):
    hdr = await reader.readexactly(5)
    ln = struct.unpack("!I", hdr[1:])[0]
    return hdr[:1], await reader.readexactly(ln)


class RemoteOutput:
    """Sender half (dispatch target, Channel-compatible `send`).

    Replay buffering (per-worker partial recovery, cluster/): with
    `enable_replay()` every sent message is ALSO retained in an ordered
    buffer, trimmed by meta's `committed` notification to exactly the
    not-yet-durable suffix — the DCN twin of the in-process Channel's
    replay buffer. A vanished receiver then PARKS sends (instead of
    killing the producer actor): `rewind_replay()` re-establishes the
    leg — to the same receiver (rebuilt in place), the same endpoint
    after a severed socket, or a fresh RemoteInput server where the
    consumer was re-placed — and re-feeds a synthetic-INITIAL 'R' frame
    plus the buffered suffix before live sends resume. Without replay
    (the legacy remote-fragment tier), a dead receiver still fails the
    sender fast."""

    def __init__(self, host: str, port: int, credits: int = 0,
                 replay: bool = False):
        # credits start at ZERO: the receiver's initial grant (its queue
        # depth) is the ONLY source of permits, exactly like permit.rs
        self.host = host
        self.port = port
        self._credits = credits          # chunk permits in hand
        self._credit_evt = asyncio.Event()
        self._reader = self._writer = None
        self._credit_task = None
        self._dead = False
        # ---- replay machinery (None/off for legacy senders) ----
        self._buf = deque() if replay else None    # (seq, msg)
        self._seq = 0
        self._sent_through = 0      # highest seq written to the socket
        self._base_barrier = None   # last trimmed (committed) barrier
        # live sends park while a rewind streams the suffix — an
        # interleaved frame would reach the rebuilt consumer ahead of
        # older suffix messages (order corruption)
        self._rewinding = False

    # ------------------------------------------------------------ replay
    def enable_replay(self) -> None:
        if self._buf is None:
            self._buf = deque()

    @property
    def replay_enabled(self) -> bool:
        return self._buf is not None

    def trim_replay(self, committed_epoch: int) -> None:
        """Same trim rule as the in-process Channel: drop everything up
        to and including the LAST barrier whose epoch.prev is covered
        by the committed checkpoint, remembering it as the replay
        base."""
        buf = self._buf
        if not buf:
            return
        cut, base = -1, None
        for i, (_seq, m) in enumerate(buf):
            if isinstance(m, Barrier) and m.epoch.prev <= committed_epoch:
                cut, base = i, m
        for _ in range(cut + 1):
            buf.popleft()
        if base is not None:
            self._base_barrier = base

    async def connect(self) -> "RemoteOutput":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._credit_task = asyncio.create_task(self._credit_loop())
        return self

    async def _credit_loop(self) -> None:
        try:
            while True:
                tag, payload = await _read_frame(self._reader)
                if tag == b"K":
                    self._credits += struct.unpack("!I", payload)[0]
                    self._credit_evt.set()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                OSError):
            pass
        except asyncio.CancelledError:
            return        # rewind replaces the loop without killing the leg
        finally:
            # a sender parked on the credit wait must WAKE once the
            # receiver is gone: legacy senders fail fast (recovery
            # teardown otherwise deadlocks — receiver waits for this
            # socket to close while we wait for its credits); replay
            # senders park until rewind_replay re-establishes the leg
            self._dead = True
            self._credit_evt.set()

    async def _write_msg(self, msg) -> None:
        if isinstance(msg, StreamChunk):
            while self._credits <= 0:     # permit-based backpressure
                if self._dead:
                    raise ConnectionResetError("remote receiver is gone")
                self._credit_evt.clear()
                await self._credit_evt.wait()
            self._credits -= 1
            await _write_frame(self._writer, b"C", _chunk_payload(msg))
        elif isinstance(msg, Barrier):
            await _write_frame(self._writer, b"B", json.dumps({
                "curr": msg.epoch.curr, "prev": msg.epoch.prev,
                "kind": msg.kind.value,
                "mutation": _ser_mutation(msg.mutation)}).encode())
        elif isinstance(msg, Watermark):
            await _write_frame(self._writer, b"W", json.dumps({
                "col_idx": msg.col_idx, "dtype": msg.data_type.name,
                "val": int(msg.val)}).encode())
        else:
            raise ValueError(f"unsendable message {type(msg)}")

    async def send(self, msg) -> None:
        from ..utils.faults import FAULTS
        seq = None
        if self._buf is not None:
            self._seq += 1
            seq = self._seq
            # buffer BEFORE the (possibly failing) write: a message
            # parked behind a dead socket is already covered by the
            # next rewind's replay
            self._buf.append((seq, msg))
            if FAULTS.active and FAULTS.hit(
                    "dcn_drop", port=self.port,
                    worker=WORKER_ID) is not None:
                # sever this leg mid-epoch: the write path below sees a
                # closed socket, parks, and waits for the recovery
                # rewind — exactly a mid-flight DCN cable pull
                try:
                    self._writer.close()
                except Exception:  # noqa: BLE001
                    pass
        while True:
            if self._dead or self._rewinding:
                if self._buf is None:
                    raise ConnectionResetError("remote receiver is gone")
                # replay mode: park until rewind_replay re-establishes
                # the leg (recovery teardown cancels parked sends)
                self._credit_evt.clear()
                await self._credit_evt.wait()
                continue
            if seq is not None and seq <= self._sent_through:
                return        # a rewind already wrote this message
            try:
                await self._write_msg(msg)
                if seq is not None:
                    self._sent_through = seq
                return
            except (ConnectionResetError, BrokenPipeError, OSError):
                self._dead = True
                if self._buf is None:
                    raise

    async def rewind_replay(self, host=None, port=None) -> int:
        """Per-worker partial recovery: re-feed the uncommitted suffix
        to a REBUILT consumer. With host/port the leg reconnects (the
        consumer was re-placed onto a fresh RemoteInput server —
        possibly loopback); without, a dead socket reconnects to the
        SAME endpoint (severed leg, consumer rebuilt in place behind
        its surviving server) and a live socket is reused in-band. The
        'R' frame carries the committed base barrier (the consumer
        synthesizes the INITIAL from it and discards everything queued
        before it), then the buffered suffix follows, then `send`
        resumes live. Returns the number of replayed messages."""
        assert self._buf is not None, "replay not enabled on this leg"
        self._rewinding = True      # live sends park until the suffix
        try:                        # has streamed in order
            if self._credit_task is not None:
                self._credit_task.cancel()
            if host is not None or self._dead:
                try:
                    self._writer.close()
                except Exception:  # noqa: BLE001
                    pass
                if host is not None:
                    self.host, self.port = host, port
                self._reader, self._writer = await asyncio.open_connection(
                    self.host, self.port)
            self._credits = 0
            self._dead = False
            self._credit_task = asyncio.create_task(self._credit_loop())
            base = self._base_barrier
            await _write_frame(self._writer, b"R", json.dumps(
                {"curr": base.epoch.curr, "prev": base.epoch.prev,
                 "inject_ns": base.inject_time_ns}
                if base is not None else {}).encode())
            n = 0
            for seq, msg in list(self._buf):
                await self._write_msg(msg)
                self._sent_through = max(self._sent_through, seq)
                n += 1
            return n
        finally:
            self._rewinding = False
            # wake any send parked across the rewind: either its
            # message was covered by the replay, or the leg is live
            # again and it writes in order behind the suffix
            self._credit_evt.set()

    async def close(self) -> None:
        if self._credit_task:
            self._credit_task.cancel()
        if self._writer:
            self._writer.close()


class RemoteInput(Executor):
    """Receiver half: a TCP server feeding this executor's stream
    (exchange_service.rs GetStream). Grants credits as the consumer
    drains — the bounded in-flight window IS the backpressure."""

    def __init__(self, schema: Schema, host: str = "127.0.0.1",
                 port: int = 0, capacity: int = 1024,
                 queue_depth: int = 16, stop_on=None):
        self.schema = schema
        self.pk_indices = ()
        self.host = host
        self.port = port
        self.capacity = capacity
        self.queue_depth = queue_depth
        self.stop_on = stop_on
        self.identity = "RemoteInput"
        self._queue: asyncio.Queue = asyncio.Queue()
        self._server = None
        self._conn_writer = None
        # per-worker partial recovery: a rebuilt consumer reading a
        # SURVIVING server arms this flag — everything queued before
        # the producer's 'R' rewind frame belongs to the dead
        # incarnation and is discarded at recv
        self._await_rewind = False
        # barriers of the DROPPED epochs (committed < curr <= ceiling)
        # are filtered: a rebuilt source peer joins the live stream
        # directly, so replaying dead barriers on this leg would leave
        # merges misaligned forever (see Channel.begin_replay)
        self.stale_ceiling = None

    def expect_rewind(self, stale_ceiling=None) -> None:
        self._await_rewind = True
        if stale_ceiling is not None:
            self.stale_ceiling = stale_ceiling

    async def start(self) -> "RemoteInput":
        async def handle(reader, writer):
            if self._conn_writer is not None:
                # one producer per input (fan-in uses one RemoteInput per
                # upstream edge) — a second LIVE connection would steal
                # the credit channel and deadlock the first sender; a
                # dead producer's slot frees below so a rewound or
                # re-placed producer can re-attach
                writer.close()
                return
            self._conn_writer = writer
            # initial credit window
            await _write_frame(writer, b"K",
                               struct.pack("!I", self.queue_depth))
            try:
                while True:
                    tag, payload = await _read_frame(reader)
                    if tag == b"R":
                        # rewind: grant a fresh window HERE (the read
                        # loop), so the producer's replayed chunks flow
                        # before the rebuilt consumer even spawns
                        await _write_frame(
                            writer, b"K",
                            struct.pack("!I", self.queue_depth))
                    await self._queue.put((tag, payload))
            except (asyncio.IncompleteReadError, ConnectionResetError,
                    OSError):
                await self._queue.put((b"X", b""))
            finally:
                if self._conn_writer is writer:
                    self._conn_writer = None

        self._server = await asyncio.start_server(handle, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        # close the live connection FIRST: wait_closed() (3.12+) waits
        # for connection handlers, and ours is blocked reading a socket
        # whose peer may itself be blocked on our credits — the
        # recovery-teardown circular wait (round 5)
        if self._conn_writer is not None:
            try:
                self._conn_writer.close()
            except Exception:  # noqa: BLE001
                pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def recv(self):
        """Channel-compatible receive — the cluster partial build
        (plan/build.py build_partial_graph) wires a RemoteInput as ONE
        LEG of a ChannelInput/MergeExecutor, next to local channels.
        Identical decode to execute(); a vanished peer raises (the
        actor's failure report is the cluster's failure detector)."""
        from ..common.types import DataType
        while True:
            tag, payload = await self._queue.get()
            if self._await_rewind and tag != b"R":
                # rebuilt consumer on a surviving server: everything
                # queued before the producer's rewind frame belongs to
                # the dead incarnation (incl. its X disconnect marker)
                continue
            if tag == b"R":
                self._await_rewind = False
                d = json.loads(payload)
                if not d:
                    continue    # no committed base: the suffix is whole
                return Barrier(EpochPair(d["curr"], d["prev"]),
                               BarrierKind.INITIAL, None, (),
                               d.get("inject_ns", 0))
            if tag == b"X":
                raise ConnectionResetError(
                    "remote exchange producer went away")
            if tag == b"C":
                chunk = _payload_chunk(payload, self.schema, self.capacity)
                if self._conn_writer is not None:
                    try:
                        await _write_frame(self._conn_writer, b"K",
                                           struct.pack("!I", 1))
                    except (ConnectionResetError, BrokenPipeError, OSError):
                        self._conn_writer = None
                return chunk
            if tag == b"B":
                d = json.loads(payload)
                if self.stale_ceiling is not None \
                        and d["curr"] <= self.stale_ceiling \
                        and BarrierKind(d["kind"]) \
                        is not BarrierKind.INITIAL:
                    # a dead epoch's barrier (see above) — but never
                    # the INITIAL a rebuilt producer propagates at the
                    # committed base (it necessarily sits below the
                    # ceiling, and the consumer's chain initializes on
                    # it before any recomputed chunk)
                    continue
                return Barrier(EpochPair(d["curr"], d["prev"]),
                               BarrierKind(d["kind"]),
                               mutation=_de_mutation(d["mutation"]))
            if tag == b"W":
                d = json.loads(payload)
                return Watermark(d["col_idx"], DataType[d["dtype"]],
                                 d["val"])

    async def execute(self):
        from ..common.types import DataType
        while True:
            tag, payload = await self._queue.get()
            if tag == b"R":
                continue      # rewinds are a recv()-path (cluster) affair
            if tag == b"X":
                return
            if tag == b"C":
                chunk = _payload_chunk(payload, self.schema,
                                       self.capacity)
                yield chunk
                # grant the credit back once the chunk is in the pipeline
                # (the peer may already be gone after its stop barrier)
                if self._conn_writer is not None:
                    try:
                        await _write_frame(self._conn_writer, b"K",
                                           struct.pack("!I", 1))
                    except (ConnectionResetError, BrokenPipeError, OSError):
                        self._conn_writer = None
            elif tag == b"B":
                d = json.loads(payload)
                b = Barrier(EpochPair(d["curr"], d["prev"]),
                            BarrierKind(d["kind"]),
                            mutation=_de_mutation(d["mutation"]))
                yield b
                if isinstance(b.mutation, StopMutation) and (
                        self.stop_on is None or self.stop_on(b)):
                    return
            elif tag == b"W":
                d = json.loads(payload)
                yield Watermark(d["col_idx"], DataType[d["dtype"]],
                                d["val"])

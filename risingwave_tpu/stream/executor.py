"""Executor base — async message-stream transforms.

Reference: the `Executor` trait (src/stream/src/executor/mod.rs:157-216):
an executor is a single-consumer stream of Message{Chunk,Barrier,Watermark}
with a schema and identity; executors wrap their inputs, barriers flow
through every executor in order. Here an executor is an async generator
(`execute()`); the device work inside stateful executors is a pure jitted
step function — the async host layer never holds the GIL against XLA.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional, Sequence

from ..common.chunk import StreamChunk
from ..common.types import Schema
from .message import Barrier, Message, Watermark


class Executor:
    schema: Schema
    identity: str = "Executor"
    pk_indices: tuple[int, ...] = ()

    def execute(self) -> AsyncIterator[Message]:
        raise NotImplementedError

    def fence_tokens(self) -> list:
        """Device arrays the epoch fence must wait on at a barrier.

        Per-chunk programs are covered by the last chunk flowing to the
        actor, but stateful executors dispatch MORE device work while
        handling the barrier itself (flush/evict/purge/persist views) after
        yielding their last chunk; the actor blocks on these tokens (no
        data transfer) before reporting the barrier collected, so an epoch
        is only 'collected' once all its device programs have executed.
        Default: delegate to `input`(s); stateful executors add their
        current state root."""
        toks: list = []
        inp = getattr(self, "input", None)
        if inp is not None:
            toks.extend(inp.fence_tokens())
        for i in getattr(self, "inputs", ()) or ():
            toks.extend(i.fence_tokens())
        return toks

    def __repr__(self):
        return self.identity


def gather_fence_tokens(node) -> list:
    """Duck-typed fence-token walk for arbitrary chain heads (sinks and
    test harness wrappers often wrap an Executor without subclassing)."""
    ft = getattr(node, "fence_tokens", None)
    if callable(ft):
        return ft()
    toks: list = []
    inp = getattr(node, "input", None)
    if inp is not None:
        toks.extend(gather_fence_tokens(inp))
    for i in getattr(node, "inputs", ()) or ():
        toks.extend(gather_fence_tokens(i))
    return toks


class StatelessUnaryExecutor(Executor):
    """Common shape: map chunks, forward barriers/watermarks."""

    def __init__(self, input: Executor):
        self.input = input
        self.schema = input.schema
        self.pk_indices = input.pk_indices

    def map_chunk(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        raise NotImplementedError

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return wm

    def on_barrier(self, barrier: Barrier) -> None:
        pass

    async def execute(self):
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                out = self.map_chunk(msg)
                if out is not None:
                    yield out
            elif isinstance(msg, Barrier):
                self.on_barrier(msg)
                yield msg
            else:
                wm = self.map_watermark(msg)
                if wm is not None:
                    yield wm

"""Executor base — async message-stream transforms.

Reference: the `Executor` trait (src/stream/src/executor/mod.rs:157-216):
an executor is a single-consumer stream of Message{Chunk,Barrier,Watermark}
with a schema and identity; executors wrap their inputs, barriers flow
through every executor in order. Here an executor is an async generator
(`execute()`); the device work inside stateful executors is a pure jitted
step function — the async host layer never holds the GIL against XLA.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional, Sequence

from ..common.chunk import StreamChunk
from ..common.types import Schema
from .message import Barrier, BarrierKind, Message, Watermark


class Executor:
    schema: Schema
    identity: str = "Executor"
    pk_indices: tuple[int, ...] = ()

    def execute(self) -> AsyncIterator[Message]:
        raise NotImplementedError

    def fence_tokens(self) -> list:
        """Device arrays the epoch fence must wait on at a barrier.

        Per-chunk programs are covered by the last chunk flowing to the
        actor, but stateful executors dispatch MORE device work while
        handling the barrier itself (flush/evict/purge/persist views) after
        yielding their last chunk; the actor blocks on these tokens (no
        data transfer) before reporting the barrier collected, so an epoch
        is only 'collected' once all its device programs have executed.
        Default: delegate to `input`(s); stateful executors add their
        current state root."""
        toks: list = []
        inp = getattr(self, "input", None)
        if inp is not None:
            toks.extend(inp.fence_tokens())
        for i in getattr(self, "inputs", ()) or ():
            toks.extend(i.fence_tokens())
        return toks

    def __repr__(self):
        return self.identity


def gather_fence_tokens(node) -> list:
    """Duck-typed fence-token walk for arbitrary chain heads (sinks and
    test harness wrappers often wrap an Executor without subclassing)."""
    ft = getattr(node, "fence_tokens", None)
    if callable(ft):
        return ft()
    toks: list = []
    inp = getattr(node, "input", None)
    if inp is not None:
        toks.extend(gather_fence_tokens(inp))
    for i in getattr(node, "inputs", ()) or ():
        toks.extend(gather_fence_tokens(i))
    return toks


class StatefulUnaryExecutor(Executor):
    """Template for single-input stateful executors — holds the barrier
    protocol invariants in ONE place (reference: every stateful executor
    repeats this sequence; here hash_agg-style control flow is shared):

      first/INITIAL barrier  -> init_epoch + recover, no flush
      data chunk             -> on_chunk (device dispatch, no transfers)
      barrier                -> watchdog fail-stop BEFORE the checkpoint
                                commits, then flush -> persist -> emit

    Subclasses implement the hooks; `watchdog_interval` must be 1 (check
    every barrier) or None (transfer-free mode, no d2h fetch ever — see
    HashAggExecutor for why that mode exists on tunneled TPUs)."""

    state_table = None

    def _init_stateful(self, state_table, watchdog_interval) -> None:
        if watchdog_interval not in (None, 1):
            raise ValueError(
                "watchdog_interval must be 1 (check before every checkpoint "
                "commit) or None (transfer-free mode): any lag would let a "
                "checkpoint commit unverified state")
        self.state_table = state_table
        self.watchdog_interval = watchdog_interval
        self._applied_since_flush = False

    # ------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        """Apply a chunk; return an output chunk to emit now (or None)."""
        raise NotImplementedError

    def check_watchdog(self) -> None:
        """Fetch device error counters; raise to fail-stop pre-commit."""

    def flush(self) -> Optional[StreamChunk]:
        """Barrier-time changelog emission (None = nothing to emit)."""
        return None

    def persist(self, barrier: Barrier, flushed: Optional[StreamChunk]) -> None:
        """Write state rows + commit the state table at this barrier."""
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        """Rebuild device state from the state table (INITIAL barrier)."""

    def on_clean_barrier(self, barrier: Barrier) -> None:
        """Post-persist barrier work (eviction/purge/rebuild)."""

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return wm

    # ---------------------------------------------------------- template
    async def execute(self):
        first = True
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                out = self.on_chunk(msg)
                self._applied_since_flush = True
                if out is not None:
                    yield out
            elif isinstance(msg, Barrier):
                if first or msg.kind is BarrierKind.INITIAL:
                    first = False
                    if self.state_table is not None:
                        self.state_table.init_epoch(msg.epoch.curr)
                        self.recover_state(msg.epoch.curr)
                    yield msg
                    continue
                stopping = msg.mutation is not None and msg.is_stop_any()
                if self.watchdog_interval and (
                        stopping or self._applied_since_flush):
                    self.check_watchdog()
                flushed = None
                if self._applied_since_flush:
                    self._applied_since_flush = False
                    flushed = self.flush()
                self.persist(msg, flushed)
                self.on_clean_barrier(msg)
                if flushed is not None:
                    yield flushed
                yield msg
            else:
                out = self.map_watermark(msg)
                if out is None:
                    continue
                for w in (out if isinstance(out, list) else [out]):
                    yield w


class StatelessUnaryExecutor(Executor):
    """Common shape: map chunks, forward barriers/watermarks."""

    def __init__(self, input: Executor):
        self.input = input
        self.schema = input.schema
        self.pk_indices = input.pk_indices

    def map_chunk(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        raise NotImplementedError

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return wm

    def on_barrier(self, barrier: Barrier) -> None:
        pass

    async def execute(self):
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                out = self.map_chunk(msg)
                if out is not None:
                    yield out
            elif isinstance(msg, Barrier):
                self.on_barrier(msg)
                yield msg
            else:
                wm = self.map_watermark(msg)
                if wm is None:
                    continue
                for w in (wm if isinstance(wm, list) else [wm]):
                    yield w

"""SimpleAgg / StatelessSimpleAgg — global (ungrouped) aggregation.

Reference: src/stream/src/executor/simple_agg.rs (singleton fragment holding
one global agg group, emitting a changelog row pair at each barrier) and
stateless_simple_agg.rs (per-chunk partial aggregates BEFORE the exchange —
the classic two-phase agg split; partials are combined downstream by a
SimpleAgg).

TPU re-design: the group state is one scalar per agg call; applying a chunk
is a single jitted segment-reduction with every visible row in segment 0.
StatelessSimpleAgg emits one partial row per chunk, which is exactly what
the mesh path psum-combines across shards (SURVEY §2.3 singleton analogue).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, StreamChunk, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
    op_sign,
)
from ..common.types import Field, Schema
from ..expr.agg import AggCall, AggKind
from ..ops.jit_state import jit_state
from ..state.state_table import StateTable
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier, Watermark


class StatelessSimpleAggExecutor(Executor):
    """Emits one Insert row of chunk-local partial aggregates per chunk.
    Stateless: no barrier work, no state table (reference
    stateless_simple_agg.rs — partials feed a downstream SimpleAgg)."""

    def __init__(self, input: Executor, agg_calls: Sequence[AggCall]):
        self.input = input
        self.agg_calls = tuple(agg_calls)
        self.specs = tuple(c.spec() for c in agg_calls)
        self.schema = Schema(tuple(
            Field(f"agg{j}", c.ret_type) for j, c in enumerate(agg_calls)))
        self.pk_indices = ()
        self.identity = "StatelessSimpleAgg"
        self._step = jit_state(self._step_impl,
                               name="stateless_simple_agg_step")

    def _step_impl(self, chunk: StreamChunk):
        signs = jnp.where(chunk.vis, op_sign(chunk.ops), 0)
        seg = jnp.zeros(chunk.capacity, dtype=jnp.int32)
        outs = []
        for spec, call in zip(self.specs, self.agg_calls):
            if call.arg is None:
                values = jnp.zeros(chunk.capacity, dtype=spec.state_dtype)
                row_signs = signs
            else:
                col = chunk.columns[call.arg]
                values = col.data
                row_signs = jnp.where(col.valid_mask(), signs, 0)
            part = spec.partial(values, row_signs, seg, 1)
            outs.append(spec.emit(part))
        return tuple(outs)

    async def execute(self):
        ops = jnp.asarray(np.asarray([OP_INSERT], dtype=np.int8))
        vis = jnp.ones(1, dtype=bool)
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                outs = self._step(msg)
                yield StreamChunk(
                    tuple(Column(o) for o in outs), ops, vis, self.schema)
            else:
                yield msg


class SimpleAggExecutor(StatefulUnaryExecutor):
    """Global agg group in a singleton fragment; emits the UD/UI changelog
    pair at each barrier (Insert on first emission), like the reference's
    AggGroup::build_change."""

    def __init__(self, input: Executor, agg_calls: Sequence[AggCall],
                 state_table: Optional[StateTable] = None,
                 combine_partials: bool = False):
        self.input = input
        self.agg_calls = tuple(agg_calls)
        self.specs = tuple(c.spec() for c in agg_calls)
        for c in agg_calls:
            if c.kind in (AggKind.MIN, AggKind.MAX) and not c.append_only:
                raise NotImplementedError(
                    "retractable min/max needs materialized-input state")
        # combine_partials: input rows are partial STATES from an upstream
        # StatelessSimpleAgg (two-phase agg); combine instead of re-reduce.
        self.combine_partials = combine_partials
        if combine_partials and any(c.arg is None for c in agg_calls):
            raise ValueError(
                "combine_partials reads partial values from input columns; "
                "every agg call needs an arg (count partials are summed)")
        self.schema = Schema(tuple(
            Field(f"agg{j}", c.ret_type) for j, c in enumerate(agg_calls)))
        self.pk_indices = ()
        self.identity = "SimpleAgg"
        self.states = tuple(s.init_state(()) for s in self.specs)
        self.row_count = jnp.zeros((), dtype=jnp.int64)
        self._emitted = False
        self._prev_emit: Optional[tuple] = None
        # states + row_count are threaded scalars, re-bound in on_chunk
        self._apply = jit_state(self._apply_impl, donate_argnums=(0, 1),
                                name="simple_agg_apply")
        self._init_stateful(state_table, 1)

    def fence_tokens(self) -> list:
        return [self.row_count] + super().fence_tokens()

    def _apply_impl(self, states, row_count, chunk: StreamChunk):
        signs = jnp.where(chunk.vis, op_sign(chunk.ops), 0)
        seg = jnp.zeros(chunk.capacity, dtype=jnp.int32)
        new_states = []
        for j, (spec, call) in enumerate(zip(self.specs, self.agg_calls)):
            if call.arg is None:
                values = jnp.zeros(chunk.capacity, dtype=spec.state_dtype)
                row_signs = signs
            else:
                col = chunk.columns[call.arg]
                values = col.data
                row_signs = jnp.where(col.valid_mask(), signs, 0)
            if self.combine_partials and call.kind is AggKind.COUNT:
                # partial rows carry COUNTS in the arg column: combining
                # means summing them, not counting rows
                v = values.astype(spec.state_dtype) * row_signs.astype(
                    spec.state_dtype)
                part = jnp.sum(v)
            else:
                part = spec.partial(values, row_signs, seg, 1)[0]
            new_states.append(spec.combine(states[j], part))
        rc = row_count + jnp.sum(signs.astype(jnp.int64))
        return tuple(new_states), rc

    # -------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> None:
        self.states, self.row_count = self._apply(
            self.states, self.row_count, chunk)
        self._dirty_persist = True
        return None

    def flush(self) -> Optional[StreamChunk]:
        cur = tuple(
            np.asarray(spec.emit(st))
            for spec, st in zip(self.specs, self.states))
        prev = self._prev_emit
        existed = self._emitted
        self._prev_emit = cur
        self._emitted = True
        if existed and prev is not None and all(
                (a == b).all() for a, b in zip(prev, cur)):
            return None  # NoChange (reference agg_group.rs:71)
        rows_ops = []
        if existed:
            rows_ops.append((OP_UPDATE_DELETE, prev))
            rows_ops.append((OP_UPDATE_INSERT, cur))
        else:
            rows_ops.append((OP_INSERT, cur))
        cap = 2
        ops = np.full(cap, OP_INSERT, dtype=np.int8)
        vis = np.zeros(cap, dtype=bool)
        cols = [np.zeros(cap, dtype=np.asarray(c).dtype) for c in cur]
        for i, (op, vals) in enumerate(rows_ops):
            ops[i] = op
            vis[i] = True
            for j, v in enumerate(vals):
                cols[j][i] = v
        return StreamChunk(
            tuple(Column(jnp.asarray(c)) for c in cols),
            jnp.asarray(ops), jnp.asarray(vis), self.schema)

    def persist(self, barrier: Barrier, flushed) -> None:
        if self.state_table is None:
            return
        if getattr(self, "_dirty_persist", False):
            self._dirty_persist = False
            # .item() preserves the state dtype (int() would truncate
            # floats and overflow on +-inf min/max identities)
            row = tuple(np.asarray(s).item() for s in self.states) + (
                int(np.asarray(self.row_count)),)
            self.state_table.write_chunk_rows([(int(OP_INSERT), (0,) + row)])
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        row = self.state_table.get_row((0,))
        if row is None:
            return
        vals = row[1:]
        self.states = tuple(
            jnp.asarray(v, dtype=s.state_dtype)
            for v, s in zip(vals[:-1], self.specs))
        self.row_count = jnp.asarray(vals[-1], dtype=jnp.int64)
        # recovered state was flushed before the crash: seed prev_emit so
        # recovery does not re-emit an Insert for an already-emitted group
        self._prev_emit = tuple(
            np.asarray(spec.emit(st))
            for spec, st in zip(self.specs, self.states))
        self._emitted = True

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return None  # no group keys to carry watermarks

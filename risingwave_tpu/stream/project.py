"""Project / Filter executors — the stateless jit targets.

Reference: src/stream/src/executor/project.rs and filter.rs (~400 LoC each).
Both are pure chunk->chunk maps; each compiles once (fixed chunk capacity =
static shapes) and all expressions in the tree fuse into a single XLA
computation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import (
    Column, StreamChunk, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
)
from ..common.types import Field, Schema
from ..expr.ir import Expr
from .executor import Executor, StatelessUnaryExecutor
from .message import Watermark
from ..ops.jit_state import jit_state


class ProjectExecutor(StatelessUnaryExecutor):
    # Mesh-chain fusion (plan/build._fuse_mesh_chains): a hollow project
    # passes chunks through UNTOUCHED — its _step_impl runs instead as a
    # prelude INSIDE the downstream sharded executor's fused shard_map
    # program (zero host hops). Watermark mapping stays host-side active:
    # watermarks are control metadata in output coordinates either way.
    mesh_hollow = False
    mesh_chain_hop: Optional[str] = None  # chain label when registered un-hollowed

    def mesh_prelude_fn(self):
        """Pure chunk->chunk map safe to run per-SHARD inside shard_map.

        Project qualifies: row-wise, no cross-row structure. (Filter does
        NOT — its UD/UI pair fixup reads the neighbouring row via roll,
        which breaks when an update pair straddles a shard-slice edge.)"""
        return self._step_impl

    def __init__(self, input: Executor, exprs: Sequence[Expr],
                 names: Optional[Sequence[str]] = None,
                 watermark_mapping: Optional[dict[int, int]] = None,
                 watermark_transforms: Optional[dict] = None):
        super().__init__(input)
        self.exprs = tuple(exprs)
        names = names or [f"expr{i}" for i in range(len(exprs))]
        self.schema = Schema(tuple(Field(n, e.ret_type) for n, e in zip(names, exprs)))
        # input col idx -> output col idx for watermark passthrough (the
        # reference derives this from InputRef-only exprs; here explicit)
        self.watermark_mapping = watermark_mapping or {
            e.index: i for i, e in enumerate(self.exprs)
            if type(e).__name__ == "InputRef"
        }
        # input col idx -> (output col idx, host fn) for watermarks through
        # MONOTONE non-decreasing expressions (reference: Watermark::
        # transform_with_expr, e.g. tumble_end) — the caller asserts
        # monotonicity by providing the transform
        self.watermark_transforms = dict(watermark_transforms or {})
        self.identity = f"Project({', '.join(map(repr, self.exprs))})"
        self._step = jit_state(self._step_impl, name="project_step")

    def _step_impl(self, chunk: StreamChunk) -> StreamChunk:
        cols = tuple(e.eval(chunk.columns) for e in self.exprs)
        return StreamChunk(cols, chunk.ops, chunk.vis, self.schema)

    def map_chunk(self, chunk):
        if self.mesh_hollow:
            return chunk            # prelude runs fused downstream
        if self.mesh_chain_hop is not None:
            from .monitor import mesh_host_round_trip
            mesh_host_round_trip(self.mesh_chain_hop)
        return self._step(chunk)

    def map_watermark(self, wm: Watermark):
        tf = self.watermark_transforms.get(wm.col_idx)
        if tf is not None:
            # one input watermark may fan out to several monotone outputs
            # (tumble: event time -> window_start AND window_end)
            tfs = tf if isinstance(tf, list) else [tf]
            return [Watermark(out_idx, self.schema[out_idx].data_type,
                              fn(wm.val))
                    for out_idx, fn in tfs]
        out = self.watermark_mapping.get(wm.col_idx)
        return wm.with_idx(out) if out is not None else None


class FilterExecutor(StatelessUnaryExecutor):
    """Filter with changelog op fixup (reference filter.rs:simplified_ops):
    an Update pair whose old row passes but new doesn't becomes a Delete;
    new-passes-only becomes an Insert. Fully vectorized over the pair
    structure (UpdateDelete at i, UpdateInsert at i+1)."""

    def __init__(self, input: Executor, predicate: Expr):
        super().__init__(input)
        self.predicate = predicate
        self.identity = f"Filter({predicate!r})"
        self._step = jit_state(self._step_impl, name="filter_step")

    def _step_impl(self, chunk: StreamChunk) -> StreamChunk:
        pred = self.predicate.eval(chunk.columns)
        cond = pred.data & pred.valid_mask()  # NULL = filtered out
        ops = chunk.ops
        is_ud = ops == OP_UPDATE_DELETE
        is_ui = ops == OP_UPDATE_INSERT
        # cond of the pair partner
        cond_prev = jnp.roll(cond, 1)   # for UI rows: partner UD at i-1
        cond_next = jnp.roll(cond, -1)  # for UD rows: partner UI at i+1
        new_ops = jnp.where(is_ui & cond & ~cond_prev, OP_INSERT, ops)
        new_ops = jnp.where(is_ud & cond & ~cond_next, OP_DELETE, new_ops).astype(ops.dtype)
        return StreamChunk(chunk.columns, new_ops, chunk.vis & cond, chunk.schema)

    def map_chunk(self, chunk):
        out = self._step(chunk)
        return out

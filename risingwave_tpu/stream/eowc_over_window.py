"""EMIT ON WINDOW CLOSE over-window (append-only final rows).

Reference: src/stream/src/executor/over_window/eowc.rs — rows buffer
until the partition's ORDER column passes the watermark; then their
window-function values are FINAL (frames end at CURRENT ROW and later
rows sort strictly after the frontier), so each row emits exactly once,
append-only, with no retraction machinery downstream.

TPU re-design: subclass of the general over-window executor — the same
dense sorted store and one-pass segmented window compute — with the
changelog DIFF replaced by a RIPENESS GATE: at each barrier the full
store recomputes (O(n) vectorized, the store is capacity-bound) and
rows whose order value moved inside (emitted_frontier, watermark] emit
as inserts. The emission frontier is durable (its own one-row state
table) so recovery neither re-emits nor drops.

v1 scope: `lead` is refused (a row's lead needs FUTURE rows, which an
unbounded EOWC stream cannot finalize), and the store keeps full
history (unbounded-frame sums need every predecessor; the reference
instead carries per-partition accumulators — a later optimization).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column, StreamChunk, OP_INSERT
from ..common.types import Schema
from .executor import Executor
from .general_over_window import GeneralOverWindowExecutor, WindowSpec
from .message import Barrier, Watermark
from .sorted_join import NO_WATERMARK
from ..ops.jit_state import jit_state


class EowcOverWindowExecutor(GeneralOverWindowExecutor):
    def __init__(self, input: Executor,
                 partition_by: Sequence[int],
                 order_specs: Sequence[tuple],
                 windows: Sequence[WindowSpec],
                 capacity: int = 1 << 14,
                 state_table=None,
                 frontier_table=None,
                 pk_indices: Optional[Sequence[int]] = None,
                 watchdog_interval: Optional[int] = 1):
        assert all(w.kind != "lead" for w in windows), \
            "EMIT ON WINDOW CLOSE cannot finalize lead()"
        assert order_specs and not order_specs[0][1], \
            "EOWC needs the watermarked ORDER BY column ascending"
        super().__init__(input, partition_by, order_specs, windows,
                         capacity=capacity, state_table=state_table,
                         pk_indices=pk_indices,
                         watchdog_interval=watchdog_interval)
        self.identity = "Eowc" + self.identity
        self.eowc_col = order_specs[0][0]
        self.frontier_table = frontier_table
        self._wm_pending = NO_WATERMARK
        self._emitted_to = NO_WATERMARK
        self._flush_eowc = jit_state(self._flush_eowc_impl,
                                     name="eowc_over_window_flush")

    # ------------------------------------------------------------- flush
    def _flush_eowc_impl(self, khash, cols, valids, n, lo, hi):
        C = self.capacity
        live = jnp.arange(C, dtype=jnp.int32) < n
        order, wouts, wvalids = self._compute_windows(cols, valids, live)
        s_cols = [c[order] for c in cols]
        s_valids = [v[order] for v in valids]
        out_fields = tuple(self.schema)[self.in_width:]
        full_cols = s_cols + [
            o.astype(f.data_type.jnp_dtype)
            for o, f in zip(wouts, out_fields)]
        full_valids = s_valids + list(wvalids)
        oval = cols[self.eowc_col][order]
        ripe = live[order] & (oval > lo) & (oval <= hi)
        out = tuple(Column(c, v)
                    for c, v in zip(full_cols, full_valids))
        ops = jnp.full(C, OP_INSERT, dtype=jnp.int8)
        return out, ops, ripe

    def flush(self) -> Optional[StreamChunk]:
        if self._wm_pending <= self._emitted_to:
            return None
        out, ops, vis = self._flush_eowc(
            self.khash, self.cols, self.valids, self.n,
            jnp.int64(self._emitted_to), jnp.int64(self._wm_pending))
        self._emitted_to = self._wm_pending
        return StreamChunk(out, ops, vis, self.schema)

    # ----------------------------------------------------------- durable
    def persist(self, barrier: Barrier, flushed) -> None:
        super().persist(barrier, flushed)
        if self.frontier_table is not None:
            self.frontier_table.write_chunk_rows(
                [(int(OP_INSERT), (0, int(self._emitted_to)))])
            self.frontier_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        if self.frontier_table is not None:
            self.frontier_table.init_epoch(epoch)
            row = self.frontier_table.get_row((0,))
            if row is not None:
                self._emitted_to = int(row[1])
                self._wm_pending = max(self._wm_pending, self._emitted_to)
        # parent loads the input rows; its diff-baseline seeding runs a
        # general flush — harmless here (em_* is unused by EOWC)
        super().recover_state(epoch)

    # --------------------------------------------------------- watermark
    def map_watermark(self, wm: Watermark):
        if wm.col_idx == self.eowc_col:
            if wm.val > self._wm_pending:
                self._wm_pending = wm.val
                # a watermark alone ripens buffered rows: force the
                # barrier flush even with no data this epoch
                self._applied_since_flush = True
            # the order column survives at the same output position;
            # emitted rows never precede the forwarded frontier
            return Watermark(wm.col_idx, wm.data_type, wm.val)
        return None

"""Now + DynamicFilter executors.

Reference: src/stream/src/executor/now.rs (a barrier-driven one-row
changelog of the epoch timestamp) and dynamic_filter.rs (filter a stream
against a CHANGING scalar — the right side is a one-row stream such as a
global max or NOW(); when the scalar moves, rows crossing the boundary
emit inserts/deletes).

TPU re-design of DynamicFilter: the reference range-scans its
column-ordered state for the crossed interval. Here the left rows live
in the dense sorted row store (pk-hash order) and the barrier flush
recomputes `col OP rhs` over ALL rows, emitting the hash-membership DIFF
against the previously-passing set — O(C) vectorized per barrier, no
range index, and retractions/updates of left rows fall out of the same
diff (the identical pattern the retractable TopN/OverWindow use).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, StreamChunk, OP_DELETE, OP_INSERT, OP_UPDATE_INSERT,
)
from ..common.types import DataType, Field, Schema
from ..ops.jit_state import jit_state
from .executor import Executor
from .align import LEFT, RIGHT, barrier_align
from .message import Barrier, BarrierKind, Watermark
from .sorted_join import _HSENTINEL, key_hash
from .sorted_store import GrowableSortedStore, sorted_store_apply


class NowExecutor(Executor):
    """One-row changelog of the epoch's physical timestamp, updated at
    every barrier (now.rs): UpdateDelete(old) + UpdateInsert(new)."""

    def __init__(self, barrier_queue, name: str = "now"):
        self.barrier_queue = barrier_queue
        self.schema = Schema((Field(name, DataType.TIMESTAMP),))
        self.pk_indices = ()
        self.identity = "Now"
        self._last: Optional[int] = None

    @staticmethod
    def _epoch_us(epoch: int) -> int:
        return (epoch >> 16) * 1000          # physical ms -> us

    def _chunk(self, rows) -> StreamChunk:
        ops = np.asarray([op for op, _ in rows], dtype=np.int8)
        vals = np.asarray([v for _, v in rows], dtype=np.int64)
        return StreamChunk.from_numpy(self.schema, [vals], ops=ops,
                                      capacity=4)

    async def execute(self):
        while True:
            barrier: Barrier = await self.barrier_queue.get()
            ts = self._epoch_us(barrier.epoch.curr)
            if self._last is None:
                yield self._chunk([(OP_INSERT, ts)])
                self._last = ts
            elif ts > self._last:
                yield self._chunk([(OP_DELETE, self._last),
                                   (OP_INSERT, ts)])
                self._last = ts
            yield barrier
            if barrier.is_stop_any():
                return


class DynamicFilterExecutor(GrowableSortedStore, Executor):
    """left WHERE left[key_col] OP right_scalar, right_scalar changing."""

    _SECONDARY = ("em_hash", "em_cols", "em_valids")

    def __init__(self, left: Executor, right: Executor, key_col: int,
                 op: str = "greater_than",
                 capacity: int = 1 << 14,
                 pk_indices: Optional[Sequence[int]] = None,
                 watchdog_interval: Optional[int] = 1):
        assert op in ("greater_than", "greater_than_or_equal",
                      "less_than", "less_than_or_equal")
        self.inputs = (left, right)
        self.schema = left.schema
        self.pk_indices = tuple(
            pk_indices if pk_indices is not None
            else (left.pk_indices or range(len(left.schema))))
        self.key_col = key_col
        self.op = op
        self.capacity = capacity
        self.identity = f"DynamicFilter(${key_col} {op} <rhs>)"
        C = capacity
        dts = tuple(f.data_type.jnp_dtype for f in left.schema)
        self.khash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.cols = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
        self.valids = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
        self.n = jnp.int32(0)
        self.em_hash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.em_cols = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
        self.em_valids = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
        self.em_n = jnp.int32(0)
        self._errs_dev = jnp.zeros(2, dtype=jnp.int32)
        # store pytree + errs threaded (em_* is a fresh gather): donate;
        # _flush consumes/replaces the em_* previous-emission set
        self._apply = jit_state(
            partial(sorted_store_apply, pk_idx=self.pk_indices,
                    capacity=self.capacity),
            donate_argnums=(0, 1, 2, 3, 4), name="dynamic_filter_apply")
        self._flush = jit_state(self._flush_impl,
                                donate_argnums=(4, 5, 6, 7),
                                name="dynamic_filter_flush")
        self._wd_pack = jit_state(
            lambda e, n: jnp.concatenate([e, n[None].astype(jnp.int32)]),
            name="dynamic_filter_wd_pack")
        self._rhs: Optional[int] = None      # host scalar (tiny rhs rows)
        self._dirty = False
        if watchdog_interval not in (None, 1):
            raise ValueError("watchdog_interval must be 1 or None")
        self.watchdog_interval = watchdog_interval

    # ------------------------------------------------------------- flush
    def _flush_impl(self, khash, cols, valids, n, em_hash, em_cols,
                    em_valids, em_n, rhs):
        C = self.capacity
        live = jnp.arange(C, dtype=jnp.int32) < n
        x = cols[self.key_col]
        xv = valids[self.key_col]
        if self.op == "greater_than":
            passing = x > rhs
        elif self.op == "greater_than_or_equal":
            passing = x >= rhs
        elif self.op == "less_than":
            passing = x < rhs
        else:
            passing = x <= rhs
        passing = passing & live & xv

        lanes = []
        for c, v in zip(cols, valids):
            d = (jax.lax.bitcast_convert_type(c, jnp.int64)
                 if jnp.issubdtype(c.dtype, jnp.floating)
                 else c.astype(jnp.int64))
            lanes.append(jnp.where(v, d, 0))
            lanes.append(v.astype(jnp.int64))
        rhash = jnp.where(passing, key_hash(lanes), _HSENTINEL)
        order = jnp.argsort(rhash, stable=True)
        new_hash = rhash[order]
        n_new = jnp.sum(passing.astype(jnp.int32))
        new_cols = tuple(c[order] for c in cols)
        new_valids = tuple(v[order] for v in valids)

        def member(a_hash, a_n, b_hash):
            i = jnp.clip(jnp.searchsorted(b_hash, a_hash), 0, C - 1)
            return (jnp.arange(C) < a_n) & (b_hash[i] == a_hash)

        old_still = member(em_hash, em_n, new_hash)
        emit_del = (jnp.arange(C) < em_n) & ~old_still
        new_was = member(new_hash, n_new, em_hash)
        emit_ins = (jnp.arange(C) < n_new) & ~new_was
        out_cols = tuple(
            Column(jnp.concatenate([ec, nc]), jnp.concatenate([ev, nv]))
            for ec, nc, ev, nv in zip(em_cols, new_cols, em_valids,
                                      new_valids))
        ops = jnp.concatenate([
            jnp.full(C, OP_DELETE, dtype=jnp.int8),
            jnp.full(C, OP_INSERT, dtype=jnp.int8)])
        vis = jnp.concatenate([emit_del, emit_ins])
        return (new_hash, new_cols, new_valids, n_new.astype(jnp.int32),
                out_cols, ops, vis)

    # ----------------------------------------------------------- stream
    async def execute(self):
        first = True
        async for kind, s, msg in barrier_align(*self.inputs):
            if kind == "chunk":
                if s == RIGHT:
                    # one-row dynamic side, applied in changelog order: an
                    # insert sets the scalar, a delete of the CURRENT
                    # value with no replacement clears it (no rhs row =>
                    # the condition has no value and nothing passes)
                    for op, vals in msg.to_rows():
                        if op in (OP_INSERT, OP_UPDATE_INSERT):
                            self._rhs = vals[0]
                        elif vals[0] == self._rhs:
                            self._rhs = None
                    self._dirty = True
                else:
                    (self.khash, self.cols, self.valids, self.n,
                     self._errs_dev) = self._apply(
                        self.khash, self.cols, self.valids, self.n,
                        self._errs_dev, msg)
                    self._dirty = True
            elif kind == "barrier":
                barrier: Barrier = msg
                if first or barrier.kind is BarrierKind.INITIAL:
                    first = False
                    yield barrier
                    continue
                if self._dirty and self._rhs is None \
                        and int(self.em_n) != 0:
                    # rhs row retracted: the previously-passing set
                    # empties (use a sentinel no row passes)
                    sentinel = (jnp.iinfo(jnp.int64).max
                                if self.op.startswith("greater")
                                else jnp.iinfo(jnp.int64).min)
                    (self.em_hash, self.em_cols, self.em_valids,
                     self.em_n, out_cols, ops, vis) = self._flush(
                        self.khash, self.cols, self.valids, self.n,
                        self.em_hash, self.em_cols, self.em_valids,
                        self.em_n, jnp.int64(sentinel))
                    self._dirty = False
                    yield StreamChunk(out_cols, ops, vis, self.schema)
                if self._dirty and self._rhs is not None:
                    (self.em_hash, self.em_cols, self.em_valids,
                     self.em_n, out_cols, ops, vis) = self._flush(
                        self.khash, self.cols, self.valids, self.n,
                        self.em_hash, self.em_cols, self.em_valids,
                        self.em_n, jnp.int64(self._rhs))
                    self._dirty = False
                    yield StreamChunk(out_cols, ops, vis, self.schema)
                if self.watchdog_interval:
                    vals = np.asarray(self._wd_pack(self._errs_dev,
                                                    self.n))
                    if int(vals[0]) or int(vals[1]):
                        raise RuntimeError(
                            f"dynamic filter state errors "
                            f"{vals[:2].tolist()}")
                    self._maybe_grow(int(vals[2]))
                yield barrier
            else:
                wm: Watermark = msg
                if s == LEFT:
                    if wm.col_idx != self.key_col:
                        # ADVICE r4 #4: a dynamic filter must not forward
                        # non-key-column watermarks — ANY threshold
                        # movement (rising for >, falling for <) deletes
                        # rows whose values on those columns sit below an
                        # already-forwarded watermark, violating the
                        # contract downstream (del_miss fail-stop on a
                        # state-cleaned store)
                        continue
                    elif self.op in ("greater_than",
                                     "greater_than_or_equal") \
                            and self._rhs is not None:
                        # the key-column watermark is capped at the rhs:
                        # a rising threshold later DELETES rows in
                        # (old_rhs, new_rhs], which an uncapped watermark
                        # would have let downstream state-clean away
                        # (reference: dynamic filter wm passthrough caps
                        # at the current bound)
                        yield Watermark(wm.col_idx, wm.data_type,
                                        min(wm.val, self._rhs))

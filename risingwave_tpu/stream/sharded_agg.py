"""Vnode-sharded HashAgg — the real executor under shard_map over a mesh.

Reference: a hash-distributed fragment is N parallel actors, each owning a
vnode-bitmap slice of the 256 vnodes, fed by HashDataDispatcher
(proto/stream_plan.proto:834-876, dispatch.rs:679). On a TPU mesh the
dispatcher+merge pair collapses INTO the jitted step: state lives sharded
along the `vnode` mesh axis (global arrays [S*C], each shard seeing a
local [C] table), and each shard masks the replicated input chunk down to
its own vnodes — the "exchange" is a visibility mask on ICI-resident data,
not a data movement. The barrier flush runs per shard and concatenates
along the shard axis into one global changelog chunk.

This is the SAME executor logic as HashAggExecutor — `_apply_impl`,
`_flush_impl`, `_evict_impl`, `_rehash_impl` are inherited unchanged and
wrapped in shard_map; capacities inside are the per-shard local shapes.

v1 scope: device-resident only (no durable state table) and static
capacity (overflow still fail-stops via the device watchdog; the
transfer-free purge path works per shard).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.chunk import StreamChunk
from ..common.vnode import compute_vnodes
from ..expr.agg import AggCall
from ..parallel.mesh import VNODE_AXIS, vnode_to_shard
from .executor import Executor
from .hash_agg import AggState, HashAggExecutor


class ShardedHashAggExecutor(HashAggExecutor):
    """HashAgg over `mesh`: state sharded on the vnode axis, input chunks
    replicated and masked per shard. `capacity` is PER SHARD."""

    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 agg_calls: Sequence[AggCall], mesh: Mesh,
                 capacity: int = 1 << 14,
                 group_key_names: Optional[Sequence[str]] = None,
                 cleaning_watermark_col: Optional[int] = None,
                 watchdog_interval: Optional[int] = 1):
        self.mesh = mesh
        self.n_shards = mesh.shape[VNODE_AXIS]
        self._routing = jnp.asarray(vnode_to_shard(self.n_shards))
        super().__init__(input, group_key_indices, agg_calls,
                         capacity=capacity, state_table=None,
                         group_key_names=group_key_names,
                         cleaning_watermark_col=cleaning_watermark_col,
                         watchdog_interval=watchdog_interval)
        # re-wrap the inherited step impls in shard_map (the parent set up
        # plain jits over the freshly built sharded state)
        mesh_kw = dict(mesh=mesh)
        shard = P(VNODE_AXIS)
        repl = P()

        def apply_sharded(state, overflow, chunk):
            my = jax.lax.axis_index(VNODE_AXIS)
            key_cols = [chunk.columns[i].data
                        for i in self.group_key_indices]
            vn = compute_vnodes(key_cols)
            mine = chunk.vis & (self._routing[vn] == my)
            local = StreamChunk(chunk.columns, chunk.ops, mine,
                                chunk.schema)
            st, ov, occ = self._apply_impl(state, overflow[0], local)
            return st, ov[None], occ[None]

        self._apply = jax.jit(jax.shard_map(
            apply_sharded, in_specs=(shard, shard, repl),
            out_specs=(shard, shard, shard), **mesh_kw))

        def flush_sharded(state):
            st, cols, ops, vis = self._flush_impl(state)
            return st, cols, ops, vis

        self._flush = jax.jit(jax.shard_map(
            flush_sharded, in_specs=(shard,),
            out_specs=(shard, shard, shard, shard), **mesh_kw))

        def evict_sharded(state, wm):
            return self._evict_impl(state, wm)

        self._evict = jax.jit(jax.shard_map(
            evict_sharded, in_specs=(shard, repl), out_specs=shard,
            **mesh_kw))

        def purge_sharded(state):
            return self._rehash_impl(state, self.capacity)

        self._purge = jax.jit(jax.shard_map(
            purge_sharded, in_specs=(shard,), out_specs=shard, **mesh_kw))

        def rehash_same_capacity(state, cap):
            # sharded v1 never grows: only same-capacity purges reach here
            assert cap == self.capacity, "sharded agg capacity is static"
            return self._purge(state)
        self._rehash = rehash_same_capacity

        def watchdog_sharded(ov, occ):
            total_ov = jax.lax.psum(ov[0], VNODE_AXIS)
            max_occ = jax.lax.pmax(occ[0], VNODE_AXIS)
            return jnp.stack([total_ov, max_occ])[None]

        self._watchdog_pack = jax.jit(jax.shard_map(
            watchdog_sharded, in_specs=(shard, shard), out_specs=shard,
            **mesh_kw))

        # per-shard watchdog accumulators replace the parent's scalars
        sharding = NamedSharding(mesh, P(VNODE_AXIS))
        self._overflow_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        self._occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)

    # ------------------------------------------------------------ state
    def _initial_state(self, capacity: int) -> AggState:
        """Global state arrays [S*C] placed sharded along the mesh axis
        (_empty_state itself stays LOCAL — jitted impls build per-shard
        scratch state with it inside shard_map)."""
        S = self.n_shards
        local = self._empty_state(capacity)
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))

        def expand(x):
            g = jnp.tile(x, (S,) + (1,) * (x.ndim - 1)) if x.ndim else x
            return jax.device_put(g, sharding)

        return jax.tree_util.tree_map(expand, local)

    def _maybe_rebuild_at_barrier(self) -> None:
        # static per-shard capacity in v1 (growth would need a global
        # re-layout), but zombie PURGING is mesh-safe: when the watchdog's
        # max-shard occupancy crosses the threshold, rebuild at the same
        # capacity to reclaim watermark-evicted slots — without this,
        # default-watchdog pipelines accumulate zombies until a spurious
        # overflow fail-stop
        if self._occ_known > 0.7 * self.capacity:
            self.state = self._purge(self.state)
            self.rebuilds += 1
            self._occ_known = 0  # refreshed by the next watchdog fetch

    def recover(self, barrier_epoch: int) -> None:
        raise NotImplementedError("sharded agg is device-resident in v1")

    def _check_watchdog(self) -> None:
        vals = np.asarray(self._watchdog_pack(self._overflow_dev,
                                              self._occ_dev))[0]
        n_un = int(vals[0])
        if n_un:
            raise RuntimeError(
                f"sharded hash-agg overflow ({n_un} rows, per-shard "
                f"capacity {self.capacity})")
        self._occ_known = int(vals[1])

"""Vnode-sharded HashAgg — the real executor under shard_map over a mesh.

Reference: a hash-distributed fragment is N parallel actors, each owning a
vnode-bitmap slice of the 256 vnodes, fed by HashDataDispatcher
(proto/stream_plan.proto:834-876, dispatch.rs:679). On a TPU mesh the
dispatcher+merge pair collapses INTO the jitted step: state lives sharded
along the `vnode` mesh axis (global arrays [S*C], each shard seeing a
local [C] table).

Two input planes:

* FUSED MESH SHUFFLE (default, `mesh_shuffle=True`): the whole fragment —
  source-side dispatch, hash exchange, stateful apply — is ONE
  shard_map-ed program per barrier interval. The host chunk is sliced
  CONTIGUOUSLY over the mesh axis (shard s holds rows [s*L, (s+1)*L)),
  each shard vnode-routes its slice to the owner shards with
  `parallel/exchange.mesh_ingest_chunk` (`lax.all_to_all` over ICI — no
  host Channel hop, no replication), and applies its local hash table to
  exactly the rows it owns. Chunks buffered within an interval batch into
  one `lax.scan` inside the same shard_map program, so device dispatches
  per interval scale with neither chunk count nor shard count. Shuffle
  overflow (per-pair capacity from `mesh_shuffle_slack`; 0 = zero-drop
  sizing) accumulates on device and FAIL-STOPS the epoch at the barrier
  watchdog fetch.

* REPLICATED MASK (fallback: `mesh_shuffle=False`, or a chunk whose
  capacity does not divide by the shard count): the input chunk is
  replicated and each shard masks it down to its own vnodes — the
  "exchange" is a visibility mask on ICI-resident data.

The barrier flush runs per shard and concatenates
along the shard axis into one global changelog chunk.

This is the SAME executor logic as HashAggExecutor — `_apply_impl`,
`_flush_impl`, `_evict_impl`, `_rehash_impl` are inherited unchanged and
wrapped in shard_map; capacities inside are the per-shard local shapes.

Durability: fully supported — `_persist` runs a per-shard persist view
(each shard's dirty rows compact to its local prefix) and ships all
shards' prefixes in two packed d2h calls into the state table, and
`recover` rebuilds the sharded device state by routing durable rows
through the same vnode->shard map the apply path masks by. Per-shard
capacity stays static at runtime (growth would need a global re-layout;
recovery may re-size from the worst shard's row count), and the
transfer-free purge path works per shard.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.chunk import StreamChunk
from ..common.vnode import compute_vnodes
from ..expr.agg import AggCall
from ..ops.jit_state import jit_state
from ..parallel.exchange import mesh_ingest_chunk, shuffle_cap_out
from ..parallel.mesh import VNODE_AXIS, shard_map, vnode_to_shard
from .executor import Executor
from .hash_agg import AggState, HashAggExecutor


class MeshIngestLog:
    """Host-side per-interval ingest snapshot of a fused mesh fragment —
    the mesh-plane REPLAY POINT. Every chunk entering the fused
    shard_map program is also retained here BY REFERENCE (device arrays
    are immutable and the ingest path never donates them, so holding
    them moves no data), stamped with the epoch its barrier seals, and
    dropped when that epoch COMMITS — the coordinator trims this log
    through the same pulse that trims the exchange replay buffers
    (plan/build.py registers it next to the fragment's channels). The
    log therefore always holds exactly the uncommitted ingest suffix,
    bounded by `checkpoint_max_inflight`; a mesh fragment failure
    re-runs the fused program from the committed epoch over this
    suffix (delivered back through the armed frontier channels) instead
    of tearing down the deployment. A hard cap backstops executors
    driven without a coordinator (engine-level tests)."""

    HARD_CAP = 8
    replay_enabled = True

    def __init__(self):
        from collections import deque
        self._pending: list = []
        self._log = deque()

    def note(self, item) -> None:
        self._pending.append(item)

    def seal(self, epoch: int) -> None:
        """Stamp the open interval's ingests with the epoch its barrier
        seals (called from the executor's barrier-time persist)."""
        if self._pending:
            self._log.append((epoch, self._pending))
            self._pending = []
            while len(self._log) > self.HARD_CAP:
                self._log.popleft()

    def trim_replay(self, committed_epoch: int) -> None:
        while self._log and self._log[0][0] <= committed_epoch:
            self._log.popleft()

    def entries(self) -> list:
        return list(self._log)

    def chunk_count(self) -> int:
        return sum(len(chunks) for _, chunks in self._log) \
            + len(self._pending)


class ShardedHashAggExecutor(HashAggExecutor):
    """HashAgg over `mesh`: state sharded on the vnode axis, input routed
    to its owner shard by the fused in-mesh shuffle (or replicated and
    masked as the fallback). `capacity` is PER SHARD."""

    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 agg_calls: Sequence[AggCall], mesh: Mesh,
                 capacity: int = 1 << 14,
                 state_table=None,
                 group_key_names: Optional[Sequence[str]] = None,
                 cleaning_watermark_col: Optional[int] = None,
                 watchdog_interval: Optional[int] = 1,
                 mesh_shuffle: bool = True,
                 mesh_shuffle_slack: int = 0,
                 mesh_shuffle_adaptive: bool = True):
        self.mesh = mesh
        self.n_shards = mesh.shape[VNODE_AXIS]
        self._routing = jnp.asarray(vnode_to_shard(self.n_shards))
        self.mesh_shuffle = bool(mesh_shuffle)
        self.mesh_shuffle_slack = int(mesh_shuffle_slack)
        if self.mesh_shuffle_slack and watchdog_interval is None:
            raise ValueError(
                "mesh_shuffle_slack > 0 needs the barrier watchdog fetch "
                "(watchdog_interval=1): shuffle drops would otherwise go "
                "unchecked and a checkpoint could commit with rows "
                "missing; transfer-free pipelines must use slack 0 "
                "(zero-drop sizing)")
        # adaptive shuffle slack (ROADMAP 3c): send-bucket capacity derived
        # from OBSERVED per-destination demand (watchdog-fetched max fill,
        # asymmetric EWMA + peak floor), instead of the manual slack var.
        # Engages only under zero-drop default sizing (manual slack stays
        # an override) and only with the watchdog fetch active — overflow
        # under an adapted cap still fail-stops, recovery replays, and the
        # fresh executor restarts at zero-drop sizing.
        self.mesh_shuffle_adaptive = (bool(mesh_shuffle_adaptive)
                                      and self.mesh_shuffle_slack == 0
                                      and watchdog_interval is not None)
        self._cap_hint: Optional[int] = None
        self._fill_ewma = 0.0
        self._fill_peak = 0
        self._fill_obs = 0
        # mesh-chain fusion (plan/build._fuse_mesh_chains): hollow producer
        # stage impls run INSIDE the fused program, before the shuffle
        self._mesh_preludes: tuple = ()
        self.mesh_chain: Optional[str] = None
        self._replay_preload: list = []
        # fused-plane dispatch count (one per interval batch in steady
        # state): tests and scripts/mesh_profile.py assert the fused
        # exchange actually engaged
        self.mesh_shuffle_applies = 0
        super().__init__(input, group_key_indices, agg_calls,
                         capacity=capacity, state_table=state_table,
                         group_key_names=group_key_names,
                         cleaning_watermark_col=cleaning_watermark_col,
                         watchdog_interval=watchdog_interval)
        # re-wrap the inherited step impls in shard_map (the parent set up
        # plain jits over the freshly built sharded state); donation rules
        # match the parent's — the sharded AggState and the per-shard
        # accumulators are threaded, never aliased. Chunk batching runs
        # through the FUSED shard_map scan (_drain_pending below); the
        # parent's unsharded scan programs are never built here.
        self._use_chunk_batching = self.mesh_shuffle
        mesh_kw = dict(mesh=mesh)
        shard = P(VNODE_AXIS)
        repl = P()

        def apply_sharded(state, overflow, chunk):
            my = jax.lax.axis_index(VNODE_AXIS)
            key_cols = [chunk.columns[i].data
                        for i in self.group_key_indices]
            vn = compute_vnodes(key_cols)
            mine = chunk.vis & (self._routing[vn] == my)
            local = StreamChunk(chunk.columns, chunk.ops, mine,
                                chunk.schema)
            st, ov, occ = self._apply_impl(state, overflow[0], local)
            return st, ov[None], occ[None]

        self._apply = jit_state(shard_map(
            apply_sharded, in_specs=(shard, shard, repl),
            out_specs=(shard, shard, shard), **mesh_kw),
            donate_argnums=(0, 1), name="sharded_agg_apply")

        # ---- fused mesh shuffle: exchange + apply in ONE program ----
        # the chunk enters SHARDED over the row axis (in_spec P(vnode):
        # shard s sees rows [s*L, (s+1)*L)); the in-mesh all_to_all
        # routes rows to their owner shard, then the local hash table
        # applies exactly the owned rows. `dropped` accumulates shuffle
        # overflow per shard; the barrier watchdog fail-stops on it.
        # per-chunk fused programs, keyed by the adaptive cap hint active
        # at trace time (None = zero-drop sizing); scans keyed (k, hint)
        self._fused_applies: dict = {}
        self._fused_scans: dict = {}

        def flush_sharded(state):
            st, cols, ops, vis = self._flush_impl(state)
            return st, cols, ops, vis

        self._flush = jit_state(shard_map(
            flush_sharded, in_specs=(shard,),
            out_specs=(shard, shard, shard, shard), **mesh_kw),
            donate_argnums=(0,), name="sharded_agg_flush")

        def evict_sharded(state, wm):
            return self._evict_impl(state, wm)

        self._evict = jit_state(shard_map(
            evict_sharded, in_specs=(shard, repl), out_specs=shard,
            **mesh_kw), donate_argnums=(0,), name="sharded_agg_evict")

        def purge_sharded(state):
            return self._rehash_impl(state, self.capacity)

        self._purge = jit_state(shard_map(
            purge_sharded, in_specs=(shard,), out_specs=shard, **mesh_kw),
            donate_argnums=(0,), name="sharded_agg_purge")

        def rehash_same_capacity(state, cap):
            # sharded v1 never grows: only same-capacity purges reach here
            assert cap == self.capacity, "sharded agg capacity is static"
            return self._purge(state)
        self._rehash = rehash_same_capacity

        def watchdog_sharded(ov, occ, dr, so):
            total_ov = jax.lax.psum(ov[0], VNODE_AXIS)
            max_occ = jax.lax.pmax(occ[0], VNODE_AXIS)
            total_dr = jax.lax.psum(dr[0], VNODE_AXIS)
            max_fill = jax.lax.pmax(so[0], VNODE_AXIS)
            return jnp.stack([total_ov, max_occ, total_dr, max_fill])[None]

        self._watchdog_pack = jit_state(shard_map(
            watchdog_sharded, in_specs=(shard, shard, shard, shard),
            out_specs=shard,
            **mesh_kw), name="sharded_agg_watchdog_pack")

        def persist_view_sharded(state):
            cols, ops, vis, n_dirty = self._persist_view_impl(state)
            return tuple(cols), ops, vis, n_dirty[None]

        # the parent's eager persist view gathers on sharded arrays
        # (XLA aborts); run it per shard instead — each shard's dirty
        # rows compact to that shard's LOCAL prefix
        self._persist_view_sh = jit_state(shard_map(
            persist_view_sharded, in_specs=(shard,),
            out_specs=(shard, shard, shard, shard), **mesh_kw),
            name="sharded_agg_persist_view")

        # mesh-plane replay point: the uncommitted ingest suffix, held
        # host-side by reference (see MeshIngestLog)
        self.ingest_log = MeshIngestLog()
        # per-shard watchdog accumulators replace the parent's scalars
        sharding = NamedSharding(mesh, P(VNODE_AXIS))
        self._overflow_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        self._occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        self._dropped_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        # max send-bucket DEMAND seen since the last watchdog fetch — the
        # adaptive slack signal (reset to fresh zeros at each fetch)
        self._send_occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)

    # ------------------------------------------------ fused mesh shuffle
    def set_mesh_preludes(self, fns, chain: Optional[str] = None) -> None:
        """Install hollow producer-stage impls (project / hop_window
        `_step_impl`s, root-to-source order reversed so the source-most
        runs first) to execute INSIDE the fused program, upstream of the
        shuffle. Must install before the first fused trace — the compiled
        programs close over the prelude list."""
        assert self.mesh_shuffle_applies == 0, \
            "mesh preludes must install before the first fused dispatch"
        self._mesh_preludes = tuple(fns)
        self.mesh_chain = chain

    def _prelude_host(self, chunk: StreamChunk) -> StreamChunk:
        """Per-chunk host fallback: run the hollowed producer stages
        eagerly so the replicated-mask path sees the transformed schema
        it expects. Counted as host round trips by the caller."""
        for fn in self._mesh_preludes:
            chunk = fn(chunk)
        return chunk

    def _count_host_hop(self, n: int = 1) -> None:
        if self.mesh_chain is not None:
            from .monitor import mesh_host_round_trip
            mesh_host_round_trip(self.mesh_chain, n)

    def _trace_cap(self, local_rows: int) -> int:
        """Per-(src,dst) send capacity at TRACE time: the manual slack
        override wins; otherwise the adaptive hint (2x pow2-quantized
        observed peak demand) once enough barriers have been observed;
        zero-drop sizing until then."""
        if not self.mesh_shuffle_adaptive or self._cap_hint is None:
            return shuffle_cap_out(local_rows, self.n_shards,
                                   self.mesh_shuffle_slack)
        return min(local_rows, max(64, self._cap_hint))

    def _fused_step(self, state, overflow, dropped, chunk):
        """One chunk's preludes + shuffle + apply, INSIDE shard_map
        (per-shard views; `chunk` fields are this shard's local [L] row
        slices). Hollow producer stages run here first — device-resident,
        zero host hops — then the in-mesh all_to_all routes the
        transformed rows to their owner shards. Shapes are static under
        trace, so the per-pair send capacity re-derives per
        chunk-capacity signature (and per adaptive cap hint)."""
        for fn in self._mesh_preludes:
            chunk = fn(chunk)
        cap = self._trace_cap(chunk.capacity)
        local, n_drop, fill = mesh_ingest_chunk(
            chunk, self.group_key_indices, self._routing, VNODE_AXIS,
            self.n_shards, cap)
        st, ov, occ = self._apply_impl(state, overflow, local)
        return (st, ov, (dropped + n_drop).astype(dropped.dtype), occ,
                fill)

    def _get_fused_apply(self):
        prog = self._fused_applies.get(self._cap_hint)
        if prog is not None:
            return prog
        shard = P(VNODE_AXIS)

        def apply_fused(state, overflow, dropped, sendocc, chunk):
            st, ov, dr, occ, fill = self._fused_step(
                state, overflow[0], dropped[0], chunk)
            so = jnp.maximum(sendocc[0], fill)
            return st, ov[None], dr[None], occ[None], so[None]

        prog = jit_state(shard_map(
            apply_fused, mesh=self.mesh,
            in_specs=(shard,) * 5, out_specs=(shard,) * 5),
            donate_argnums=(0, 1, 2, 3), name="sharded_agg_apply_fused")
        self._fused_applies[self._cap_hint] = prog
        return prog

    def _make_fused_scan(self, k: int):
        """k identically-shaped chunks of one barrier interval, applied
        in ONE device dispatch: lax.scan over the stacked batch INSIDE
        the shard_map program, each step shuffling then applying — the
        whole interval's exchange + compute is a single fused program
        regardless of shard count."""
        shard = P(VNODE_AXIS)

        def scan_body(state, overflow, dropped, sendocc, *chunks):
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *chunks)

            def step(carry, chunk):
                st, ov, dr, so = carry
                st, ov2, dr2, occ, fill = self._fused_step(
                    st, ov, dr, chunk)
                return (st, ov2.astype(ov.dtype), dr2,
                        jnp.maximum(so, fill)), occ

            (st, ov, dr, so), occs = jax.lax.scan(
                step, (state, overflow[0], dropped[0], sendocc[0]),
                stacked)
            return st, ov[None], dr[None], occs[-1][None], so[None]

        return jit_state(shard_map(
            scan_body, mesh=self.mesh,
            in_specs=(shard, shard, shard, shard) + (shard,) * k,
            out_specs=(shard, shard, shard, shard, shard)),
            donate_argnums=(0, 1, 2, 3),
            name=f"sharded_agg_apply_fused_scan{k}")

    def _fused_eligible(self, chunk: StreamChunk) -> bool:
        # shard_map row-slices the chunk contiguously over the mesh axis,
        # which needs the capacity to divide evenly; everything else
        # (including every power-of-two capacity >= n_shards) is eligible
        return self.mesh_shuffle and chunk.capacity % self.n_shards == 0

    def _apply_chunk_raw(self, chunk: StreamChunk) -> None:
        if self._fused_eligible(chunk):
            (self.state, self._overflow_dev, self._dropped_dev,
             self._occ_dev, self._send_occ_dev) = self._get_fused_apply()(
                self.state, self._overflow_dev, self._dropped_dev,
                self._send_occ_dev, chunk)
            self.mesh_shuffle_applies += 1
        else:
            # per-chunk host-plane fallback: a chain member couldn't stay
            # fused, so the hollowed producer stages (if any) run here on
            # the host and the crossing is counted against the chain
            if self._mesh_preludes:
                chunk = self._prelude_host(chunk)
            self._count_host_hop()
            self.state, self._overflow_dev, self._occ_dev = self._apply(
                self.state, self._overflow_dev, chunk)
        self._applied_since_flush = True

    def _drain_pending(self) -> None:
        """Interval drain: a multi-chunk run goes through the fused
        shard_map scan (one dispatch); single chunks and ineligible
        capacities fall back to the per-chunk programs. The parent's
        unsharded scan machinery is bypassed entirely — its programs
        would mis-handle the sharded global state."""
        p = self._pending_chunks
        if not p:
            return
        self._pending_chunks = []
        # replay point: retain the interval's ingest BEFORE the fused
        # program consumes it (references only — chunks are never
        # donated on the ingest path). With preludes installed, the RAW
        # source chunk is the replay point — re-running the fused program
        # re-runs the hollowed producer stages too.
        for ch in p:
            self.ingest_log.note(ch)
        # replay preloads bypass _enqueue_chunk's shape splitting, so the
        # scan's jnp.stack needs an explicit uniformity check here
        uniform = len({(c.capacity, len(c.columns),
                        tuple(col.valid is not None for col in c.columns))
                       for c in p}) == 1
        if len(p) == 1 or not self._fused_eligible(p[0]) or not uniform:
            if not self._mesh_preludes:
                # raw-schema chunks under preludes would confuse the
                # spill reload walk; the sharded agg never spills anyway
                self._mem_check_reload(p)
            for ch in p:
                self._apply_chunk_raw(ch)
            return
        # pow2 batch buckets with all-invisible fillers, exactly like the
        # parent's scan path (zero-copy views of the last chunk's arrays)
        k = 1 << (len(p) - 1).bit_length()
        if k > len(p):
            last = p[-1]
            filler = StreamChunk(last.columns, last.ops,
                                 jnp.zeros(last.capacity, dtype=bool),
                                 last.schema)
            p = p + [filler] * (k - len(p))
        if not self._mesh_preludes:
            self._mem_check_reload(p)
        scan = self._fused_scans.get((k, self._cap_hint))
        if scan is None:
            scan = self._make_fused_scan(k)
            self._fused_scans[(k, self._cap_hint)] = scan
        (self.state, self._overflow_dev, self._dropped_dev,
         self._occ_dev, self._send_occ_dev) = scan(
            self.state, self._overflow_dev, self._dropped_dev,
            self._send_occ_dev, *p)
        self.mesh_shuffle_applies += 1
        self._applied_since_flush = True

    def preload_replay(self, chunks) -> None:
        """Channel-free mesh replay (ROADMAP 3d): the uncommitted ingest
        suffix captured from the crashed executor's MeshIngestLog (plus
        its undrained pending chunks) is fed straight into the fused
        program — staged here, installed into the pending queue by
        `recover()` at the INITIAL barrier (AFTER the durable state
        rebuild; the INITIAL's own drain runs before recover, so
        prepending now would apply the suffix to pre-recovery state),
        then re-run as one fused scan at the next barrier and re-noted
        into the fresh log by that drain. The frontier channels skip
        these chunks by identity (Channel.begin_replay skip_refs);
        barriers and watermarks still replay through them for epoch
        alignment."""
        self._replay_preload = list(chunks)

    # ------------------------------------------------------------ state
    def _initial_state(self, capacity: int) -> AggState:
        """Global state arrays [S*C] placed sharded along the mesh axis
        (_empty_state itself stays LOCAL — jitted impls build per-shard
        scratch state with it inside shard_map)."""
        S = self.n_shards
        local = self._empty_state(capacity)
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))

        def expand(x):
            g = jnp.tile(x, (S,) + (1,) * (x.ndim - 1)) if x.ndim else x
            return jax.device_put(g, sharding)

        return jax.tree_util.tree_map(expand, local)

    def _maybe_rebuild_at_barrier(self) -> None:
        # static per-shard capacity in v1 (growth would need a global
        # re-layout), but zombie PURGING is mesh-safe: when the watchdog's
        # max-shard occupancy crosses the threshold, rebuild at the same
        # capacity to reclaim watermark-evicted slots — without this,
        # default-watchdog pipelines accumulate zombies until a spurious
        # overflow fail-stop
        if self._occ_known > 0.7 * self.capacity:
            self.state = self._purge(self.state)
            self.rebuilds += 1
            self._occ_known = 0  # refreshed by the next watchdog fetch

    def _persist(self, barrier) -> None:
        """Durable flush of the SHARDED state: the per-shard persist
        view compacts each shard's dirty rows to its LOCAL prefix; all
        shards' prefixes ship in TWO d2h calls (counts, then one packed
        buffer — same per-call d2h discipline as the parent's). Like the
        parent's, the device views dispatch AT the barrier and the
        blocking fetch + writes + commit defer to the store (drained by
        the background uploader in pipelined mode)."""
        # stamp the interval's replay point with the epoch this barrier
        # seals; the coordinator drops it when that epoch commits
        self.ingest_log.seal(barrier.epoch.prev)
        if self.state_table is None:
            return
        from ..utils.d2h import (fetch_flat, finish_prefix_groups,
                                 prepare_prefix_groups)
        st = self.state_table
        dev = None
        if self._applied_since_flush:
            dev = self._persist_view_sh(self.state)
        dev_evict = n_ev = None
        if (self.cleaning_watermark_key is not None
                and self._pending_clean_wm is not None):
            keys_dev, n_ev = self._evict_keys(self.state,
                                              self._pending_clean_wm)
            dev_evict = list(keys_dev)
        count_parts = []
        if dev is not None:
            count_parts.append(jnp.ravel(dev[3]))      # n_dirty per shard
        if dev_evict is not None:
            count_parts.append(jnp.ravel(n_ev))
        counts_dev = (jnp.concatenate(count_parts) if count_parts
                      else None)
        new_epoch = barrier.epoch.curr
        C, S = self.capacity, self.n_shards
        cell: dict = {}

        def wait_counts():
            return np.asarray(counts_dev) if counts_dev is not None else None

        def cont_prepare(counts):
            groups, i = [], 0
            cell["n_rows_groups"] = 0
            cell["nev"] = 0
            if dev is not None:
                cols, ops, vis, _ = dev
                for sh in range(S):
                    nd = int(counts[i + sh])
                    if not nd:
                        continue
                    lo = sh * C
                    groups.append((
                        [ops[lo:lo + C], vis[lo:lo + C]]
                        + [c[lo:lo + C] for c in cols], nd))
                cell["n_rows_groups"] = len(groups)
                i += S
            if dev_evict is not None:
                cell["nev"] = int(counts[i])
                if cell["nev"]:
                    groups.append((dev_evict, cell["nev"]))
            if groups:
                cell["prep"] = prepare_prefix_groups(groups)

        def wait_flat():
            prep = cell.get("prep")
            return fetch_flat(prep[0]) if prep is not None else None

        def cont_apply(host_flat):
            prep = cell.get("prep")
            if prep is not None:
                outs = finish_prefix_groups(host_flat, prep[1], prep[2])
                for seg in outs[:cell["n_rows_groups"]]:
                    st.write_chunk_columns(seg[0], seg[2:], seg[1])
                if cell["nev"]:
                    self._apply_evict_deletes(outs[-1], cell["nev"])
            st.commit(new_epoch)

        st.store.defer_flush(barrier.epoch.prev,
                             (wait_counts, cont_prepare),
                             (wait_flat, cont_apply),
                             table_id=st.table_id)

    def recover(self, barrier_epoch: int) -> None:
        """Rebuild SHARDED device state: rows partition by
        vnode-of-group-key (the same routing the apply path masks by),
        each shard's slice is built locally with the parent's machinery,
        and the slices concatenate along the mesh axis. The durable
        persist path is the parent's unchanged — its snapshot-diff view
        is shape-agnostic over the global [S*C] arrays."""
        # channel-free mesh replay: install the preloaded ingest suffix
        # now that the durable state rebuild is about to run on pre-crash
        # committed state (the INITIAL barrier's drain already ran, so
        # these apply in one fused scan at the NEXT barrier).
        preload = getattr(self, "_replay_preload", None)
        if preload:
            self._pending_chunks = list(preload) + self._pending_chunks
            self._replay_preload = []
        if self.state_table is None:
            return
        rows = [r for _, r in self.state_table.iter_all()]
        if not rows:
            return
        from ..common.vnode import compute_vnodes_numpy
        nk = len(self.group_key_indices)
        key_cols = [np.asarray([r[j] for r in rows], dtype=np.int64)
                    for j in range(nk)]
        shard_of = np.asarray(self._routing)[compute_vnodes_numpy(key_cols)]
        by_shard = [[] for _ in range(self.n_shards)]
        for r, sh in zip(rows, shard_of):
            by_shard[int(sh)].append(r)
        worst = max(len(b) for b in by_shard)
        need = 1 << max(self.capacity.bit_length() - 1,
                        (int(worst / 0.7)).bit_length())
        self.capacity = max(self.capacity, need)
        locals_ = [self._state_from_rows(b, self.capacity)
                   for b in by_shard]
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))

        def concat(*xs):
            if xs[0].ndim == 0:
                return xs[0]   # replicated scalar (as in _initial_state)
            return jax.device_put(jnp.concatenate(xs), sharding)

        self.state = jax.tree_util.tree_map(concat, *locals_)
        self._occ_known = worst

    # ------------------------------------------------- HBM memory manager
    # Accounting is inherited (pytree_bytes over the global [S*C] arrays
    # is exact), but per-shard capacity is STATIC in v1 — a shrinking
    # rehash would need a global re-layout — so the sharded agg reports
    # bytes and never evicts (ROADMAP open item).
    @property
    def mem_shards(self) -> int:
        """Shard count for the memory manager's per-shard breakdown:
        the global arrays split evenly over the mesh axis, so each
        device holds state_bytes() / n_shards of this executor's HBM."""
        return self.n_shards

    def state_shard_bytes(self) -> int:
        return self.state_bytes() // self.n_shards

    def memory_enable_lru(self) -> None:
        pass

    def memory_evict(self, target_bytes: int, epoch: int) -> int:
        return 0

    def _note_send_fill(self, fill: int) -> None:
        """Adaptive slack observation (barrier-collection cadence): track
        the max per-destination send demand with an ASYMMETRIC EWMA —
        jumps up instantly on a larger fill (overflow safety beats
        smoothing), decays slowly on smaller ones — plus an all-time peak
        floor. The cap hint is 2x the pow2-ceiling of the worst signal
        and only engages after 3 observations, so caps never shrink below
        twice the worst demand ever seen; a workload whose skew suddenly
        doubles past that still fail-stops and replays at zero-drop."""
        if not self.mesh_shuffle_adaptive:
            return
        if fill > self._fill_ewma:
            self._fill_ewma = float(fill)
        else:
            self._fill_ewma = 0.8 * self._fill_ewma + 0.2 * fill
        self._fill_peak = max(self._fill_peak, fill)
        self._fill_obs += 1
        if self._fill_obs < 3:
            return
        worst = max(self._fill_ewma, float(self._fill_peak), 1.0)
        self._cap_hint = 1 << (int(2 * worst) - 1).bit_length()

    def _check_watchdog(self) -> None:
        vals = np.asarray(self._watchdog_pack(self._overflow_dev,
                                              self._occ_dev,
                                              self._dropped_dev,
                                              self._send_occ_dev))[0]
        n_un, occ, n_drop, fill = (int(vals[0]), int(vals[1]),
                                   int(vals[2]), int(vals[3]))
        self._note_send_fill(fill)
        # the pack donated nothing, but the interval's demand signal is
        # consumed: start the next observation window from zero
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))
        self._send_occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        if n_drop:
            # fail-stop BEFORE this epoch's checkpoint commits: a row the
            # shuffle dropped was never applied, so committing would make
            # the loss durable and silent. Recovery replays from the last
            # committed epoch; the slack needs raising (0 = zero-drop).
            from ..utils.metrics import MESH_SHUFFLE_DROPPED
            MESH_SHUFFLE_DROPPED.inc(n_drop)
            raise RuntimeError(
                f"mesh shuffle overflow: {n_drop} rows dropped en route "
                f"to their owner shard (per-pair send capacity sized by "
                f"mesh_shuffle_slack={self.mesh_shuffle_slack}; 0 = "
                f"zero-drop sizing)")
        if n_un:
            raise RuntimeError(
                f"sharded hash-agg overflow ({n_un} rows, per-shard "
                f"capacity {self.capacity})")
        self._occ_known = occ

"""Vnode-sharded HashAgg — the real executor under shard_map over a mesh.

Reference: a hash-distributed fragment is N parallel actors, each owning a
vnode-bitmap slice of the 256 vnodes, fed by HashDataDispatcher
(proto/stream_plan.proto:834-876, dispatch.rs:679). On a TPU mesh the
dispatcher+merge pair collapses INTO the jitted step: state lives sharded
along the `vnode` mesh axis (global arrays [S*C], each shard seeing a
local [C] table), and each shard masks the replicated input chunk down to
its own vnodes — the "exchange" is a visibility mask on ICI-resident data,
not a data movement. The barrier flush runs per shard and concatenates
along the shard axis into one global changelog chunk.

This is the SAME executor logic as HashAggExecutor — `_apply_impl`,
`_flush_impl`, `_evict_impl`, `_rehash_impl` are inherited unchanged and
wrapped in shard_map; capacities inside are the per-shard local shapes.

Durability: fully supported — `_persist` runs a per-shard persist view
(each shard's dirty rows compact to its local prefix) and ships all
shards' prefixes in two packed d2h calls into the state table, and
`recover` rebuilds the sharded device state by routing durable rows
through the same vnode->shard map the apply path masks by. Per-shard
capacity stays static at runtime (growth would need a global re-layout;
recovery may re-size from the worst shard's row count), and the
transfer-free purge path works per shard.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.chunk import StreamChunk
from ..common.vnode import compute_vnodes
from ..expr.agg import AggCall
from ..ops.jit_state import jit_state
from ..parallel.mesh import VNODE_AXIS, shard_map, vnode_to_shard
from .executor import Executor
from .hash_agg import AggState, HashAggExecutor


class ShardedHashAggExecutor(HashAggExecutor):
    """HashAgg over `mesh`: state sharded on the vnode axis, input chunks
    replicated and masked per shard. `capacity` is PER SHARD."""

    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 agg_calls: Sequence[AggCall], mesh: Mesh,
                 capacity: int = 1 << 14,
                 state_table=None,
                 group_key_names: Optional[Sequence[str]] = None,
                 cleaning_watermark_col: Optional[int] = None,
                 watchdog_interval: Optional[int] = 1):
        self.mesh = mesh
        self.n_shards = mesh.shape[VNODE_AXIS]
        self._routing = jnp.asarray(vnode_to_shard(self.n_shards))
        super().__init__(input, group_key_indices, agg_calls,
                         capacity=capacity, state_table=state_table,
                         group_key_names=group_key_names,
                         cleaning_watermark_col=cleaning_watermark_col,
                         watchdog_interval=watchdog_interval)
        # re-wrap the inherited step impls in shard_map (the parent set up
        # plain jits over the freshly built sharded state); donation rules
        # match the parent's — the sharded AggState and the per-shard
        # accumulators are threaded, never aliased. Chunk batching stays
        # off: the scan programs are built over the unsharded impls.
        self._use_chunk_batching = False
        mesh_kw = dict(mesh=mesh)
        shard = P(VNODE_AXIS)
        repl = P()

        def apply_sharded(state, overflow, chunk):
            my = jax.lax.axis_index(VNODE_AXIS)
            key_cols = [chunk.columns[i].data
                        for i in self.group_key_indices]
            vn = compute_vnodes(key_cols)
            mine = chunk.vis & (self._routing[vn] == my)
            local = StreamChunk(chunk.columns, chunk.ops, mine,
                                chunk.schema)
            st, ov, occ = self._apply_impl(state, overflow[0], local)
            return st, ov[None], occ[None]

        self._apply = jit_state(shard_map(
            apply_sharded, in_specs=(shard, shard, repl),
            out_specs=(shard, shard, shard), **mesh_kw),
            donate_argnums=(0, 1), name="sharded_agg_apply")

        def flush_sharded(state):
            st, cols, ops, vis = self._flush_impl(state)
            return st, cols, ops, vis

        self._flush = jit_state(shard_map(
            flush_sharded, in_specs=(shard,),
            out_specs=(shard, shard, shard, shard), **mesh_kw),
            donate_argnums=(0,), name="sharded_agg_flush")

        def evict_sharded(state, wm):
            return self._evict_impl(state, wm)

        self._evict = jit_state(shard_map(
            evict_sharded, in_specs=(shard, repl), out_specs=shard,
            **mesh_kw), donate_argnums=(0,), name="sharded_agg_evict")

        def purge_sharded(state):
            return self._rehash_impl(state, self.capacity)

        self._purge = jit_state(shard_map(
            purge_sharded, in_specs=(shard,), out_specs=shard, **mesh_kw),
            donate_argnums=(0,), name="sharded_agg_purge")

        def rehash_same_capacity(state, cap):
            # sharded v1 never grows: only same-capacity purges reach here
            assert cap == self.capacity, "sharded agg capacity is static"
            return self._purge(state)
        self._rehash = rehash_same_capacity

        def watchdog_sharded(ov, occ):
            total_ov = jax.lax.psum(ov[0], VNODE_AXIS)
            max_occ = jax.lax.pmax(occ[0], VNODE_AXIS)
            return jnp.stack([total_ov, max_occ])[None]

        self._watchdog_pack = jit_state(shard_map(
            watchdog_sharded, in_specs=(shard, shard), out_specs=shard,
            **mesh_kw), name="sharded_agg_watchdog_pack")

        def persist_view_sharded(state):
            cols, ops, vis, n_dirty = self._persist_view_impl(state)
            return tuple(cols), ops, vis, n_dirty[None]

        # the parent's eager persist view gathers on sharded arrays
        # (XLA aborts); run it per shard instead — each shard's dirty
        # rows compact to that shard's LOCAL prefix
        self._persist_view_sh = jit_state(shard_map(
            persist_view_sharded, in_specs=(shard,),
            out_specs=(shard, shard, shard, shard), **mesh_kw),
            name="sharded_agg_persist_view")

        # per-shard watchdog accumulators replace the parent's scalars
        sharding = NamedSharding(mesh, P(VNODE_AXIS))
        self._overflow_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        self._occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)

    # ------------------------------------------------------------ state
    def _initial_state(self, capacity: int) -> AggState:
        """Global state arrays [S*C] placed sharded along the mesh axis
        (_empty_state itself stays LOCAL — jitted impls build per-shard
        scratch state with it inside shard_map)."""
        S = self.n_shards
        local = self._empty_state(capacity)
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))

        def expand(x):
            g = jnp.tile(x, (S,) + (1,) * (x.ndim - 1)) if x.ndim else x
            return jax.device_put(g, sharding)

        return jax.tree_util.tree_map(expand, local)

    def _maybe_rebuild_at_barrier(self) -> None:
        # static per-shard capacity in v1 (growth would need a global
        # re-layout), but zombie PURGING is mesh-safe: when the watchdog's
        # max-shard occupancy crosses the threshold, rebuild at the same
        # capacity to reclaim watermark-evicted slots — without this,
        # default-watchdog pipelines accumulate zombies until a spurious
        # overflow fail-stop
        if self._occ_known > 0.7 * self.capacity:
            self.state = self._purge(self.state)
            self.rebuilds += 1
            self._occ_known = 0  # refreshed by the next watchdog fetch

    def _persist(self, barrier) -> None:
        """Durable flush of the SHARDED state: the per-shard persist
        view compacts each shard's dirty rows to its LOCAL prefix; all
        shards' prefixes ship in TWO d2h calls (counts, then one packed
        buffer — same per-call d2h discipline as the parent's). Like the
        parent's, the device views dispatch AT the barrier and the
        blocking fetch + writes + commit defer to the store (drained by
        the background uploader in pipelined mode)."""
        if self.state_table is None:
            return
        from ..utils.d2h import (fetch_flat, finish_prefix_groups,
                                 prepare_prefix_groups)
        st = self.state_table
        dev = None
        if self._applied_since_flush:
            dev = self._persist_view_sh(self.state)
        dev_evict = n_ev = None
        if (self.cleaning_watermark_key is not None
                and self._pending_clean_wm is not None):
            keys_dev, n_ev = self._evict_keys(self.state,
                                              self._pending_clean_wm)
            dev_evict = list(keys_dev)
        count_parts = []
        if dev is not None:
            count_parts.append(jnp.ravel(dev[3]))      # n_dirty per shard
        if dev_evict is not None:
            count_parts.append(jnp.ravel(n_ev))
        counts_dev = (jnp.concatenate(count_parts) if count_parts
                      else None)
        new_epoch = barrier.epoch.curr
        C, S = self.capacity, self.n_shards
        cell: dict = {}

        def wait_counts():
            return np.asarray(counts_dev) if counts_dev is not None else None

        def cont_prepare(counts):
            groups, i = [], 0
            cell["n_rows_groups"] = 0
            cell["nev"] = 0
            if dev is not None:
                cols, ops, vis, _ = dev
                for sh in range(S):
                    nd = int(counts[i + sh])
                    if not nd:
                        continue
                    lo = sh * C
                    groups.append((
                        [ops[lo:lo + C], vis[lo:lo + C]]
                        + [c[lo:lo + C] for c in cols], nd))
                cell["n_rows_groups"] = len(groups)
                i += S
            if dev_evict is not None:
                cell["nev"] = int(counts[i])
                if cell["nev"]:
                    groups.append((dev_evict, cell["nev"]))
            if groups:
                cell["prep"] = prepare_prefix_groups(groups)

        def wait_flat():
            prep = cell.get("prep")
            return fetch_flat(prep[0]) if prep is not None else None

        def cont_apply(host_flat):
            prep = cell.get("prep")
            if prep is not None:
                outs = finish_prefix_groups(host_flat, prep[1], prep[2])
                for seg in outs[:cell["n_rows_groups"]]:
                    st.write_chunk_columns(seg[0], seg[2:], seg[1])
                if cell["nev"]:
                    self._apply_evict_deletes(outs[-1], cell["nev"])
            st.commit(new_epoch)

        st.store.defer_flush(barrier.epoch.prev,
                             (wait_counts, cont_prepare),
                             (wait_flat, cont_apply))

    def recover(self, barrier_epoch: int) -> None:
        """Rebuild SHARDED device state: rows partition by
        vnode-of-group-key (the same routing the apply path masks by),
        each shard's slice is built locally with the parent's machinery,
        and the slices concatenate along the mesh axis. The durable
        persist path is the parent's unchanged — its snapshot-diff view
        is shape-agnostic over the global [S*C] arrays."""
        if self.state_table is None:
            return
        rows = [r for _, r in self.state_table.iter_all()]
        if not rows:
            return
        from ..common.vnode import compute_vnodes_numpy
        nk = len(self.group_key_indices)
        key_cols = [np.asarray([r[j] for r in rows], dtype=np.int64)
                    for j in range(nk)]
        shard_of = np.asarray(self._routing)[compute_vnodes_numpy(key_cols)]
        by_shard = [[] for _ in range(self.n_shards)]
        for r, sh in zip(rows, shard_of):
            by_shard[int(sh)].append(r)
        worst = max(len(b) for b in by_shard)
        need = 1 << max(self.capacity.bit_length() - 1,
                        (int(worst / 0.7)).bit_length())
        self.capacity = max(self.capacity, need)
        locals_ = [self._state_from_rows(b, self.capacity)
                   for b in by_shard]
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))

        def concat(*xs):
            if xs[0].ndim == 0:
                return xs[0]   # replicated scalar (as in _initial_state)
            return jax.device_put(jnp.concatenate(xs), sharding)

        self.state = jax.tree_util.tree_map(concat, *locals_)
        self._occ_known = worst

    # ------------------------------------------------- HBM memory manager
    # Accounting is inherited (pytree_bytes over the global [S*C] arrays
    # is exact), but per-shard capacity is STATIC in v1 — a shrinking
    # rehash would need a global re-layout — so the sharded agg reports
    # bytes and never evicts (ROADMAP open item).
    def memory_enable_lru(self) -> None:
        pass

    def memory_evict(self, target_bytes: int, epoch: int) -> int:
        return 0

    def _check_watchdog(self) -> None:
        vals = np.asarray(self._watchdog_pack(self._overflow_dev,
                                              self._occ_dev))[0]
        n_un = int(vals[0])
        if n_un:
            raise RuntimeError(
                f"sharded hash-agg overflow ({n_un} rows, per-shard "
                f"capacity {self.capacity})")
        self._occ_known = int(vals[1])

"""Materialize executor — terminal op maintaining the MV's state table.

Reference: src/stream/src/executor/mview/materialize.rs (:52,65,141-183):
applies the changelog to the MV table with a ConflictBehavior, commits at
barriers. The MV table *is* the queryable result (batch side reads it at a
committed snapshot).

Serving hook: when the session registers the MV with the serving layer
(serving/manager.py), `serving_hook` carries the EFFECTIVE changelog —
the post-conflict-resolution upserts/deletes actually applied to the
table — so the per-MV SnapshotCache replays exactly what the storage
sees, and stamps each interval's rows with the sealed epoch at the
barrier.

Changelog log: `changelog_log` (logstore/log.py MvChangelogWriter,
registered alongside the serving hook) receives the SAME effective
rows and stages them into the durable per-MV log under the sealed
epoch at each barrier — the feed changelog subscriptions and serving
replicas tail after the checkpoint commits. While no subscription has
activated the log, the writer drops its buffer at each barrier, so
unsubscribed MVs pay nothing durable.
"""

from __future__ import annotations

import enum
from typing import Optional

from ..common.chunk import (
    StreamChunk, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
)
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, BarrierKind, Watermark


class ConflictBehavior(enum.Enum):
    NO_CHECK = "no_check"            # trust the changelog (MV over stream ops)
    OVERWRITE = "overwrite"          # upsert by pk (tables with pk)
    IGNORE = "ignore_conflict"       # first write wins


class MaterializeExecutor(Executor):
    def __init__(self, input: Executor, table: StateTable,
                 conflict: ConflictBehavior = ConflictBehavior.NO_CHECK):
        self.input = input
        self.schema = input.schema
        self.pk_indices = table.pk_indices
        self.table = table
        self.conflict = conflict
        self.identity = f"Materialize(table={table.table_id})"
        # serving changelog tap (serving/cache.py MvChangelogHook); set by
        # the session when the MV registers with the serving layer
        self.serving_hook = None
        # durable changelog tap (logstore/log.py MvChangelogWriter); set
        # by the session when the MV registers with the log-store hub
        self.changelog_log = None

    async def execute(self):
        first = True
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                self._apply(msg)
                yield msg
            elif isinstance(msg, Barrier):
                # a dataflow created mid-session initializes on its first
                # OBSERVED barrier, which need not be the Initial kind
                # (MV-on-MV actors join a running epoch stream)
                if first or msg.kind is BarrierKind.INITIAL:
                    first = False
                    self.table.init_epoch(msg.epoch.curr)
                else:
                    self.table.commit(msg.epoch.curr)
                if self.serving_hook is not None:
                    # the interval just committed belongs to the epoch
                    # this barrier seals
                    self.serving_hook.on_barrier(msg.epoch.prev)
                if self.changelog_log is not None:
                    # staged at the sealed epoch: the log entry rides
                    # this barrier's checkpoint, committing atomically
                    # with the table state it describes
                    self.changelog_log.on_barrier(msg.epoch.prev)
                yield msg
            else:
                yield msg

    def _apply(self, chunk: StreamChunk) -> None:
        from ..serving.cache import OP_DEL, OP_PUT
        rows = chunk.to_rows()
        hook = self.serving_hook
        clog = self.changelog_log
        if self.conflict is ConflictBehavior.NO_CHECK:
            self.table.write_chunk_rows(rows)
            if hook is not None or clog is not None:
                # NO_CHECK inserts land last-write-wins in the mem-table,
                # i.e. upserts at the storage level — mirror that exactly
                eff = [(OP_PUT if op in (OP_INSERT, OP_UPDATE_INSERT)
                        else OP_DEL, row) for op, row in rows]
                if hook is not None:
                    hook.on_rows(eff)
                if clog is not None:
                    clog.on_rows(eff)
            return
        eff = []
        for op, row in rows:
            if op in (OP_INSERT, OP_UPDATE_INSERT):
                pk = tuple(row[i] for i in self.table.pk_indices)
                existing = self.table.get_row(pk, dist_values=tuple(
                    row[i] for i in self.table.dist_key_indices))
                if existing is not None:
                    if self.conflict is ConflictBehavior.IGNORE:
                        continue
                    self.table.update(existing, row)
                else:
                    self.table.insert(row)
                eff.append((OP_PUT, row))
            else:
                self.table.delete(row)
                eff.append((OP_DEL, row))
        if eff:
            if hook is not None:
                hook.on_rows(eff)
            if clog is not None:
                clog.on_rows(eff)

"""Two-input barrier alignment for joins.

Reference: src/stream/src/executor/barrier_align.rs:34-43 — poll both
upstreams; a side that yields a barrier is blocked until the other yields
the same barrier, then ONE aligned barrier is delivered. Chunks and
watermarks pass through eagerly, tagged with their side, so the consumer
(HashJoin) sees a totally ordered interleaving whose epochs agree.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from .executor import Executor
from .message import Barrier

LEFT = 0
RIGHT = 1


async def barrier_align(left: Executor, right: Executor) -> AsyncIterator[tuple]:
    """Yields ("chunk"|"watermark", side, msg) and ("barrier", None, barrier)."""
    from ..common.chunk import StreamChunk

    streams = [left.execute().__aiter__(), right.execute().__aiter__()]
    tasks: dict[int, asyncio.Task] = {
        s: asyncio.create_task(anext(streams[s])) for s in (LEFT, RIGHT)}
    pending: dict[int, Barrier] = {}
    done: set[int] = set()
    try:
        while len(done) < 2:
            ready = [tasks[s] for s in (LEFT, RIGHT)
                     if s not in pending and s not in done]
            if not ready:
                # both sides parked on a barrier: emit one aligned barrier
                bl, br = pending[LEFT], pending[RIGHT]
                assert bl.epoch.curr == br.epoch.curr, \
                    f"misaligned barriers {bl.epoch} vs {br.epoch}"
                yield ("barrier", None, bl)
                pending.clear()
                for s in (LEFT, RIGHT):
                    if s not in done:
                        tasks[s] = asyncio.create_task(anext(streams[s]))
                continue
            finished, _ = await asyncio.wait(
                ready, return_when=asyncio.FIRST_COMPLETED)
            # Process sides in FIXED order, not `for t in finished:` —
            # asyncio.wait returns a SET, whose iteration order follows
            # the task objects' addresses. When both sides are ready in
            # the same pass (synchronous upstreams), that made the
            # left/right interleaving depend on process memory layout:
            # unrelated code-size changes flipped join emission
            # interleavings run-to-run (found via the memory_profile
            # gate flapping). Deterministic alignment also makes
            # recovery REPLAY content-deterministic, which the log
            # store's re-minted sequence numbers lean on (logstore/).
            for s in (LEFT, RIGHT):
                t = tasks[s]
                if t not in finished or s in done or s in pending:
                    continue
                try:
                    msg = t.result()
                except StopAsyncIteration:
                    done.add(s)
                    # treat an exhausted side as aligned (its stop barrier
                    # was already delivered)
                    if s in pending:
                        del pending[s]
                    continue
                if isinstance(msg, Barrier):
                    pending[s] = msg
                elif isinstance(msg, StreamChunk):
                    yield ("chunk", s, msg)
                    tasks[s] = asyncio.create_task(anext(streams[s]))
                else:
                    yield ("watermark", s, msg)
                    tasks[s] = asyncio.create_task(anext(streams[s]))
            # one side exhausted while the other still holds a barrier: the
            # barrier can never align; deliver it (stop barriers end streams)
            if done and pending and len(done) + len(pending) == 2:
                b = next(iter(pending.values()))
                yield ("barrier", None, b)
                pending.clear()
                for s in (LEFT, RIGHT):
                    if s not in done:
                        tasks[s] = asyncio.create_task(anext(streams[s]))
    finally:
        for t in tasks.values():
            t.cancel()

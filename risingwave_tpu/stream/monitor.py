"""Streaming stats — the per-actor observability plane.

Reference: `StreamingMetrics` (src/stream/src/executor/monitor/
streaming_stats.rs, ~150 labelled Prometheus series) gated by a
`MetricLevel` knob (common/src/config.rs `MetricLevel`): per-actor and
per-executor series are Debug-level so production clusters can turn the
label cardinality (and collection cost) off without losing the headline
totals. This module is that subsystem for the TPU port:

  * `MetricLevel` — off | info | debug (SET metric_level ...);
  * `ActorObs` — one bundle of instruments per actor: row/chunk counts,
    busy vs. align-wait seconds, dispatch fanout, plus the interval
    phase split (apply / persist / align) the EpochTrace shows;
  * `ChannelObs` — queue depth + blocked-put (backpressure) seconds on
    every exchange channel feeding an actor;
  * `StreamingStats` — the per-coordinator registrar: `build_graph`
    registers every actor chain through it (the same walk the
    MemoryManager uses), `Deployment.stop` unregisters, and
    `SET metric_level` re-instruments live actors in place.

Cost discipline (tunneled-TPU rules): per-chunk row counts accumulate
as LAZY device scalars (`chunk.cardinality()` sums the visibility mask
on device) and are fetched ONCE per actor-barrier, right after the
epoch fence already blocked on the interval's programs — never a
per-chunk d2h. At `off`, actors carry no obs object at all and the hot
loop is the pre-observability one.
"""

from __future__ import annotations

import enum
import time
from typing import Optional

import numpy as np

from ..utils.metrics import GLOBAL_METRICS, MetricsRegistry


class MetricLevel(enum.IntEnum):
    """Collection verbosity (reference common/src/config.rs MetricLevel,
    collapsed to the three tiers the engine distinguishes)."""

    OFF = 0       # no per-actor instrumentation, no phase tracking
    INFO = 1      # phase splits for \trace; no per-actor series (default)
    DEBUG = 2     # full per-actor/per-channel labelled series

    @classmethod
    def parse(cls, v) -> "MetricLevel":
        if isinstance(v, cls):
            return v
        if isinstance(v, int):
            return cls(v)
        s = str(v).strip().lower()
        try:
            return {"off": cls.OFF, "disabled": cls.OFF,
                    "info": cls.INFO, "debug": cls.DEBUG}[s]
        except KeyError:
            raise ValueError(
                f"unknown metric_level {v!r} (expected off|info|debug)")


def dispatcher_channels(d) -> list:
    """The output Channel objects a dispatcher feeds (sender-side
    instrumentation walk; remote/DCN legs are not Channels and are
    skipped — their backpressure is visible on the socket, not here)."""
    from .exchange import Channel
    if d is None:
        return []
    out = []
    outs = getattr(d, "outputs", None)
    if outs is not None:
        out.extend(outs)
    if getattr(d, "output", None) is not None:
        out.append(d.output)
    chans = getattr(d, "channels", None)   # TapDispatcher: (ch, ids) pairs
    if chans is not None:
        out.extend(ch for ch, _ids in chans)
    subs = getattr(d, "dispatchers", None)  # FanoutDispatcher
    if subs is not None:
        for sub in subs:
            out.extend(dispatcher_channels(sub))
    return [c for c in out if isinstance(c, Channel)]


def mesh_host_round_trip(chain: str, n: int = 1) -> None:
    """Count one host-plane crossing inside a registered mesh chain.

    A "round trip" is any per-chunk work a fused chain had to do on the
    host between its source and its sharded consumer: a producer stage
    running un-hollowed, or the sharded executor falling back to the
    per-chunk host-ingest plane.  Steady-state fused intervals must keep
    this at zero — barrier-time control, persist d2h, and the ingest-log
    replay point are sanctioned and never counted here."""
    GLOBAL_METRICS.counter(
        "mesh_host_round_trips_total", chain=str(chain)).inc(n)


def mesh_host_round_trips(chain: Optional[str] = None) -> int:
    """Current total of host-plane crossings, optionally for one chain."""
    snap = GLOBAL_METRICS.snapshot()
    total = 0
    for e in snap.get("mesh_host_round_trips_total", []):
        if chain is None or e["labels"].get("chain") == str(chain):
            total += int(e["value"])
    return total


def dispatcher_fanout(d) -> int:
    """Number of output channels a dispatcher feeds right now (Tap
    fanout is runtime-extendable, so this re-reads on every call)."""
    if d is None:
        return 0
    outs = getattr(d, "outputs", None)
    if outs is not None:
        return len(outs)
    if getattr(d, "output", None) is not None:
        return 1
    chans = getattr(d, "channels", None)   # TapDispatcher: (ch, ids) pairs
    if chans is not None:
        return len(chans)
    subs = getattr(d, "dispatchers", None)  # FanoutDispatcher
    if subs is not None:
        return sum(dispatcher_fanout(x) for x in subs)
    return 1


class ChannelObs:
    """Queue depth + blocked-put accounting for one exchange channel,
    labelled by the RECEIVING actor (backpressure blames the slow
    consumer, which is what an operator wants to see)."""

    __slots__ = ("depth", "blocked_put", "keys")

    def __init__(self, registry: MetricsRegistry, actor_label: str,
                 executor_label: str, input_idx: int):
        labels = dict(actor=actor_label, executor=executor_label,
                      input=str(input_idx))
        self.depth = registry.gauge("stream_exchange_queue_depth", **labels)
        self.blocked_put = registry.counter(
            "stream_exchange_blocked_put_seconds_total", **labels)
        self.keys = [("stream_exchange_queue_depth", labels),
                     ("stream_exchange_blocked_put_seconds_total", labels)]


class ExecutorObs:
    """Per-executor child handle inside a fused chain: attributes the
    actor's row flow and wall time to each executor position (labels
    {actor, executor, pos}; pos 0 = chain root, so the root child's
    row count equals the actor-level total). The hot path only stashes
    the chunk's vis-mask reference — NO device dispatch per chunk (a
    per-executor jnp.sum would multiply dispatch count by chain
    length); `ActorObs.on_barrier` flushes every child after the epoch
    fence already blocked, so the host-side count there syncs nothing
    extra."""

    __slots__ = ("row_count", "busy_seconds", "_vis", "busy_ns",
                 "keys")

    def __init__(self, registry: MetricsRegistry, actor_id: int,
                 executor_label: str, pos: int):
        labels = dict(actor=str(actor_id), executor=executor_label,
                      pos=str(pos))
        self.row_count = registry.counter(
            "stream_actor_row_count", **labels)
        self.busy_seconds = registry.counter(
            "stream_actor_busy_seconds_total", **labels)
        self.keys = [("stream_actor_row_count", labels),
                     ("stream_actor_busy_seconds_total", labels)]
        self._vis = []
        self.busy_ns = 0

    def note_chunk(self, chunk) -> None:
        self._vis.append(chunk.vis)

    def flush(self) -> None:
        if self._vis:
            n = 0
            for v in self._vis:
                n += int(np.asarray(v).sum())
            self.row_count.inc(n)
            self._vis.clear()
        if self.busy_ns:
            self.busy_seconds.inc(self.busy_ns / 1e9)
            self.busy_ns = 0


def _wrap_executor(ex) -> None:
    """Install the per-executor counting passthrough ONCE per executor
    instance. The wrapper consults `ex._exec_obs` per message (None =
    pure passthrough), so `SET metric_level` toggles attribution live
    without touching a generator chain that is already running. Row
    counts stay lazy device scalars; the wall clock charged to a child
    is the time its frame (and everything upstream of it) took to
    produce each item — pos-ordered series therefore nest, and the
    difference between adjacent positions isolates one executor."""
    if getattr(ex, "_exec_obs_wrapped", False):
        return
    inner = ex.execute

    def execute(*a, **k):
        async def _gen():
            t0 = time.monotonic_ns()
            async for item in inner(*a, **k):
                obs = ex._exec_obs
                if obs is not None:
                    obs.busy_ns += time.monotonic_ns() - t0
                    if hasattr(item, "cardinality"):
                        obs.note_chunk(item)
                yield item
                t0 = time.monotonic_ns()
        return _gen()

    ex._exec_obs = None
    ex.execute = execute
    ex._exec_obs_wrapped = True


class ActorObs:
    """Per-actor instrument bundle. Interval cells reset at each
    barrier; the phase split they produce rides into the EpochTrace."""

    __slots__ = (
        "actor_id", "debug", "apply_ns", "persist_ns", "input_wait_ns",
        "fence_ns", "_row_acc", "row_count", "chunks_in", "chunks_out",
        "dispatch", "busy_seconds", "align_seconds", "keys",
        "_occupancy", "registry", "children",
    )

    def __init__(self, registry: MetricsRegistry, actor_id: int,
                 executor_label: str, debug: bool):
        self.registry = registry
        self.actor_id = actor_id
        self.debug = debug
        # interval phase cells (ns), reset at every barrier
        self.apply_ns = 0
        self.persist_ns = 0
        self.input_wait_ns = 0
        self.fence_ns = 0
        self._row_acc = None          # lazy device scalar (sum of chunk
        #                               cardinalities this interval)
        self._occupancy = []          # (executor_label, part, gauge, fn)
        self.children = []            # ExecutorObs, chain-walk order
        self.keys = []
        if debug:
            labels = dict(actor=str(actor_id), executor=executor_label)
            self.row_count = registry.counter(
                "stream_actor_row_count", **labels)
            self.chunks_in = registry.counter(
                "stream_actor_in_chunk_count", **labels)
            self.chunks_out = registry.counter(
                "stream_actor_out_chunk_count", **labels)
            self.dispatch = registry.counter(
                "stream_actor_dispatch_total", **labels)
            self.busy_seconds = registry.counter(
                "stream_actor_busy_seconds_total", **labels)
            self.align_seconds = registry.counter(
                "stream_actor_barrier_align_seconds_total", **labels)
            self.keys = [
                (n, labels) for n in (
                    "stream_actor_row_count", "stream_actor_in_chunk_count",
                    "stream_actor_out_chunk_count",
                    "stream_actor_dispatch_total",
                    "stream_actor_busy_seconds_total",
                    "stream_actor_barrier_align_seconds_total")]
        else:
            self.row_count = self.chunks_in = self.chunks_out = None
            self.dispatch = self.busy_seconds = self.align_seconds = None

    # ------------------------------------------------------ hot-path notes
    def add_input_wait(self, ns: int) -> None:
        """Exchange inputs (ChannelInput/Merge) report channel recv
        waits here — the align component of the phase split."""
        self.input_wait_ns += ns

    def note_chunk_in(self) -> None:
        if self.chunks_in is not None:
            self.chunks_in.inc()

    def note_chunk_out(self, chunk, fanout: int) -> None:
        if self.chunks_out is not None:
            self.chunks_out.inc()
            self.dispatch.inc(fanout)
            # lazy device scalar: no transfer until the barrier flush
            card = chunk.cardinality()
            self._row_acc = (card if self._row_acc is None
                             else self._row_acc + card)

    # --------------------------------------------------------- barrier flush
    def on_barrier(self) -> dict:
        """Close the interval: fetch the accumulated row count (the
        epoch fence already blocked on this interval's programs, so the
        8-byte readback is transfer-only), flush the busy/align
        counters, refresh occupancy gauges, and return the phase split
        for the epoch trace."""
        align_ns = self.input_wait_ns + self.fence_ns
        phases = {"apply_ns": self.apply_ns,
                  "persist_ns": self.persist_ns,
                  "align_ns": align_ns}
        if self.debug:
            if self._row_acc is not None:
                self.row_count.inc(int(np.asarray(self._row_acc)))
            self.busy_seconds.inc((self.apply_ns + self.persist_ns) / 1e9)
            self.align_seconds.inc(align_ns / 1e9)
            for child in self.children:
                child.flush()
            for _label, _part, gauge, fn in self._occupancy:
                try:
                    gauge.set(float(fn()))
                except Exception:
                    pass
        self.apply_ns = self.persist_ns = 0
        self.input_wait_ns = self.fence_ns = 0
        self._row_acc = None
        return phases

    def add_occupancy_gauge(self, executor_label: str, part: str,
                            fn) -> None:
        labels = dict(actor=str(self.actor_id), executor=executor_label,
                      part=part)
        gauge = self.registry.gauge("stream_executor_hash_occupancy",
                                    **labels)
        self._occupancy.append((executor_label, part, gauge, fn))
        self.keys.append(("stream_executor_hash_occupancy", labels))


def _iter_chain(root):
    """Every executor reachable from a fragment root through input(s) —
    the same walk plan/build.py uses for memory registration."""
    seen: set[int] = set()
    stack = [root]
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        yield node
        inp = getattr(node, "input", None)
        if inp is not None:
            stack.append(inp)
        for i in getattr(node, "inputs", ()) or ():
            stack.append(i)


def _occupancy_parts(ex):
    """(part, fn) occupancy fractions for hash-table executors — duck
    typed on the host-known occupancy the growth logic already tracks
    (`_occ_known`), so reading it costs nothing on device."""
    occ = getattr(ex, "_occ_known", None)
    if occ is None:
        return []
    if isinstance(occ, (list, tuple)):
        caps = getattr(ex, "key_capacity", None)
        if not isinstance(caps, (list, tuple)) or len(caps) != len(occ):
            return []
        names = ("left", "right") if len(occ) == 2 else tuple(
            str(i) for i in range(len(occ)))
        return [(names[i],
                 (lambda e=ex, i=i: (e._occ_known[i] /
                                     max(1, e.key_capacity[i]))))
                for i in range(len(occ))]
    cap = getattr(ex, "capacity", None)
    if not isinstance(cap, int) or cap <= 0:
        return []
    return [("all", lambda e=ex: e._occ_known / max(1, e.capacity))]


class StreamingStats:
    """Per-coordinator registrar for actor-level streaming metrics.

    `build_graph` registers every (actor, chain root) pair here right
    where it registers with the MemoryManager; `Deployment.stop`
    unregisters, which REMOVES the actor's series from the registry so
    dead actors don't linger in scrapes. `configure()` re-instruments
    live actors in place, so `SET metric_level` takes effect without a
    redeploy."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else GLOBAL_METRICS
        self.level = MetricLevel.INFO
        # actor_id -> (actor, root, scope)
        self._regs: dict[int, tuple] = {}

    # ------------------------------------------------------------- config
    def configure(self, level) -> None:
        lv = MetricLevel.parse(level)
        if lv == self.level:
            return
        self.level = lv
        for actor_id in list(self._regs):
            actor, root, scope = self._regs[actor_id]
            self._uninstrument(actor, root)
            self._instrument(actor, root, scope)

    # ------------------------------------------------------- registration
    def register(self, scope: str, actor, root) -> None:
        self._regs[actor.actor_id] = (actor, root, scope)
        self._instrument(actor, root, scope)

    def unregister(self, actor_id: int) -> None:
        reg = self._regs.pop(actor_id, None)
        if reg is not None:
            self._uninstrument(reg[0], reg[1])

    def actor_series_count(self) -> int:
        """Per-actor series currently registered (tests / REPL)."""
        return sum(len(a.obs.keys) for a, _r, _s in self._regs.values()
                   if getattr(a, "obs", None) is not None)

    # ----------------------------------------------------- instrumentation
    def _instrument(self, actor, root, scope: str) -> None:
        from .exchange import ChannelInput, MergeExecutor
        if self.level <= MetricLevel.OFF:
            actor.obs = None
            return
        debug = self.level >= MetricLevel.DEBUG
        executor_label = f"{scope}/{getattr(root, 'identity', 'Executor')}"
        obs = ActorObs(self.registry, actor.actor_id, executor_label,
                       debug)
        chan_idx = 0
        for pos, ex in enumerate(_iter_chain(root)):
            # per-executor attribution: wrap execute() once (pure
            # passthrough until a child handle fills the slot); at
            # debug, each chain position gets its own {actor, executor,
            # pos} row/busy series so a hot fused chain names the
            # executor, not just the actor
            _wrap_executor(ex)
            if debug:
                child = ExecutorObs(
                    self.registry, actor.actor_id,
                    f"{scope}/"
                    f"{getattr(ex, 'identity', type(ex).__name__)}", pos)
                ex._exec_obs = child
                obs.children.append(child)
                obs.keys.extend(child.keys)
            else:
                ex._exec_obs = None
        for ex in _iter_chain(root):
            if hasattr(ex, "barrier_queue") and hasattr(ex, "obs"):
                # sources: barrier-queue wait is align (idle) time
                ex.obs = obs
            if isinstance(ex, (ChannelInput, MergeExecutor)):
                ex.obs = obs
                if debug:
                    chans = ([ex.channel] if isinstance(ex, ChannelInput)
                             else list(ex.channels))
                    for ch in chans:
                        ch.obs = ChannelObs(self.registry,
                                            str(actor.actor_id),
                                            ex.identity, chan_idx)
                        obs.keys.extend(ch.obs.keys)
                        chan_idx += 1
            elif debug:
                for part, fn in _occupancy_parts(ex):
                    obs.add_occupancy_gauge(ex.identity, part, fn)
        if debug:
            # sender-side backpressure attribution: seconds THIS actor
            # spends parked on a FULL downstream channel are charged to
            # it (the receiver-labelled blocked_put series keeps naming
            # the culprit; this one names who pays)
            for out_idx, ch in enumerate(
                    dispatcher_channels(actor.dispatcher)):
                labels = dict(actor=str(actor.actor_id),
                              executor=executor_label,
                              output=str(out_idx))
                ch.send_obs = self.registry.counter(
                    "stream_exchange_send_blocked_seconds_total",
                    **labels)
                obs.keys.append(
                    ("stream_exchange_send_blocked_seconds_total",
                     labels))
        actor.obs = obs

    def _uninstrument(self, actor, root) -> None:
        from .exchange import ChannelInput, MergeExecutor
        obs = getattr(actor, "obs", None)
        if obs is not None:
            for name, labels in obs.keys:
                self.registry.remove(name, **labels)
        actor.obs = None
        for ex in _iter_chain(root):
            ex._exec_obs = None       # wrapper stays; slot goes dark
            if hasattr(ex, "barrier_queue") and hasattr(ex, "obs"):
                ex.obs = None
            if isinstance(ex, (ChannelInput, MergeExecutor)):
                ex.obs = None
                chans = ([ex.channel] if isinstance(ex, ChannelInput)
                         else list(ex.channels))
                for ch in chans:
                    ch.obs = None
        for ch in dispatcher_channels(actor.dispatcher):
            ch.send_obs = None

"""Dense sorted row store — the shared state layout for retraction-capable
executors that must hold their FULL input (retractable TopN, general
OverWindow).

Rows live in a dense prefix [0, n) of fixed-capacity arrays sorted by a
63-bit hash of the STREAM KEY (retractions address rows by it), maintained
with the same searchsorted/merge machinery as sorted_join.py's own-side
update: per chunk, one jitted program nets within-chunk pk runs, finds
delete victims by (hash, pk) match, and merge-inserts the survivors —
static shapes, no data-dependent control flow.

Reference analogue: the row-holding state tables behind
top_n_state.rs / over_window's partition cache — re-designed dense for
the TPU instead of per-key BTree ranges.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..common.chunk import StreamChunk, op_sign
from ..ops.hash_table import stable_lexsort
from .sorted_join import _HSENTINEL, _count_le, key_hash


def sorted_store_apply(khash, cols, valids, n, errs, chunk: StreamChunk,
                       pk_idx: tuple, capacity: int):
    """Insert/retract chunk rows into the sorted dense store. Returns
    (khash', cols', valids', n', errs' + [row_overflow, del_miss])."""
    N = chunk.capacity
    C = capacity
    active = chunk.vis
    signs = op_sign(chunk.ops)
    row_ids = jnp.arange(N, dtype=jnp.int32)
    h = key_hash([chunk.columns[i].data for i in pk_idx])

    # within-chunk pk-run netting (sorted_join semantics)
    sort_keys = [row_ids]
    for p in pk_idx:
        sort_keys.append(chunk.columns[p].data)
    sort_keys.append(~active)
    order = stable_lexsort(tuple(sort_keys))
    s_act = active[order]
    same = s_act[1:] & s_act[:-1]
    for p in pk_idx:
        d = chunk.columns[p].data[order]
        same = same & (d[1:] == d[:-1])
    run_start = jnp.concatenate([jnp.array([True]), ~same])
    run_end = jnp.concatenate([~same, jnp.array([True])])
    s_signs = signs[order]
    is_del = jnp.zeros(N, dtype=bool).at[order].set(
        run_start & (s_signs < 0) & s_act)
    is_ins = jnp.zeros(N, dtype=bool).at[order].set(
        run_end & (s_signs > 0) & s_act)

    live = jnp.arange(C, dtype=jnp.int32) < n
    keep = live
    # deletes: exact (hash, pk) match
    dlo = jnp.searchsorted(khash, h, side="left").astype(jnp.int32)
    dhi = jnp.searchsorted(khash, h, side="right").astype(jnp.int32)
    M = 2 * N
    dlens = jnp.where(is_del, (dhi - dlo).astype(jnp.int64), 0)
    doffs = jnp.cumsum(dlens)
    dtot = doffs[N - 1]
    j = jnp.arange(M, dtype=jnp.int64)
    dsrc = jnp.searchsorted(doffs, j, side="right").astype(jnp.int32)
    dsrcc = jnp.clip(dsrc, 0, N - 1)
    dprev = jnp.where(dsrcc > 0, doffs[jnp.clip(dsrcc - 1, 0)], 0)
    dpos = jnp.clip(dlo[dsrcc] + (j - dprev), 0, C - 1).astype(jnp.int32)
    cand = (j < jnp.minimum(dtot, M)) & keep[dpos]
    for p in pk_idx:
        cand &= (cols[p][dpos]
                 == chunk.columns[p].data[dsrcc].astype(cols[p].dtype))
    victim = jnp.full(N, C, dtype=jnp.int32).at[
        jnp.where(cand, dsrcc, N)].min(dpos, mode="drop")
    found = victim < C
    keep = keep.at[jnp.where(found, victim, C)].set(False, mode="drop")
    n_del_miss = jnp.sum((is_del & ~found).astype(jnp.int32))

    # merge inserts (stable, state rows before equal-hash new rows)
    ins_h = jnp.where(is_ins, h, _HSENTINEL)
    iorder = jnp.argsort(ins_h, stable=True)
    nh = ins_h[iorder]
    n_new = jnp.sum(is_ins.astype(jnp.int32))
    dead_cum = jnp.cumsum((~keep).astype(jnp.int32))
    kept_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    n_kept = kept_rank[C - 1] + 1
    new_lt = jnp.searchsorted(nh, khash, side="left").astype(jnp.int32)
    pos_t = kept_rank + new_lt
    kept_le = _count_le(khash, dead_cum, nh, side="right")
    rr = jnp.arange(N, dtype=jnp.int32)
    pos_r = rr + kept_le
    new_ok = rr < n_new
    n_after = n_kept + n_new
    n_row_overflow = jnp.maximum(n_after - C, 0)
    n_after = jnp.minimum(n_after, C)
    tgt_t = jnp.where(keep & (pos_t < C), pos_t, C)
    tgt_r = jnp.where(new_ok & (pos_r < C), pos_r, C)
    kh2 = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
    kh2 = kh2.at[tgt_t].set(khash, mode="drop")
    kh2 = kh2.at[tgt_r].set(nh, mode="drop")
    cols2, valids2 = [], []
    for ci, (sc, sv) in enumerate(zip(cols, valids)):
        col = chunk.columns[ci]
        c2 = jnp.zeros(C, dtype=sc.dtype).at[tgt_t].set(sc, mode="drop")
        c2 = c2.at[tgt_r].set(col.data[iorder].astype(sc.dtype),
                              mode="drop")
        v2 = jnp.zeros(C, dtype=bool).at[tgt_t].set(sv, mode="drop")
        v2 = v2.at[tgt_r].set(col.valid_mask()[iorder], mode="drop")
        cols2.append(c2)
        valids2.append(v2)
    errs = errs + jnp.stack([n_row_overflow, n_del_miss]).astype(jnp.int32)
    return (kh2, tuple(cols2), tuple(valids2),
            n_after.astype(jnp.int32), errs)


def segment_starts(sorted_group_ids: jnp.ndarray):
    """For an array sorted by group id: (new_run mask, run_start positions
    broadcast per element) — the standard segmented-scan primitives."""
    import jax
    C = sorted_group_ids.shape[0]
    new_run = jnp.concatenate([jnp.array([True]),
                               sorted_group_ids[1:] != sorted_group_ids[:-1]])
    pos = jnp.arange(C, dtype=jnp.int32)
    run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
    return new_run, run_start


class GrowableSortedStore:
    """Mixin for executors holding the dense sorted store plus one
    same-capacity secondary (the last-emitted set): doubles both at 0.7
    occupancy instead of fail-stopping, and pre-sizes before a recovery
    replay so state that grew past the constructor capacity recovers.
    Subclasses set _SECONDARY to the (hash, cols, valids) attr names."""

    _SECONDARY: tuple = ()

    def state_bytes(self) -> int:
        """Exact accounted HBM bytes of the primary + secondary stores
        (memory/accounting.py) — registers the executor with the memory
        manager for per-flow accounting. Eviction for the dense sorted
        layout is a ROADMAP open item; growth is the pressure response."""
        from ..memory.accounting import pytree_bytes
        h, c, v = self._SECONDARY
        return pytree_bytes((self.khash, self.cols, self.valids,
                             getattr(self, h), getattr(self, c),
                             getattr(self, v)))

    def _grow_to(self, new_c: int) -> None:
        from functools import partial
        from ..ops.jit_state import jit_state
        from .sorted_join import grow_sorted_arrays
        self.khash, self.cols, self.valids = grow_sorted_arrays(
            self.khash, self.cols, self.valids, new_c)
        h, c, v = self._SECONDARY
        kh2, c2, v2 = grow_sorted_arrays(
            getattr(self, h), getattr(self, c), getattr(self, v), new_c)
        setattr(self, h, kh2)
        setattr(self, c, c2)
        setattr(self, v, v2)
        self.capacity = new_c
        # same donation contract as the constructor-time _apply: the
        # primary store pytree is threaded, the secondary never aliases it
        self._apply = jit_state(
            partial(sorted_store_apply, pk_idx=self.pk_indices,
                    capacity=new_c),
            donate_argnums=(0, 1, 2, 3, 4),
            name=f"{type(self).__name__}_apply")

    def _maybe_grow(self, n_live: int) -> None:
        if n_live > 0.7 * self.capacity:
            self._grow_to(self.capacity * 2)

    def _presize_for(self, n_rows: int) -> None:
        """Before a recovery replay: make room for every persisted row
        (the store may have grown past the constructor capacity before
        the crash)."""
        c = self.capacity
        while n_rows > 0.7 * c:
            c *= 2
        if c != self.capacity:
            self._grow_to(c)

"""Planner-integrated remote fragment placement (VERDICT r4 #6).

Reference: a compute node serving a fragment of another job's graph —
meta ships `StreamNode` protobufs to CNs (proto/stream_plan.proto:730,
stream_manager.rs:253) and fragment edges cross nodes through the
exchange service (exchange_service.rs:78). Here the main process ships
the fragment's Node subtree to a worker process (risingwave_tpu.worker)
over a control socket — the v1 IR wire format is a pickle of the plan
dataclasses between TRUSTED processes of one deployment, standing in
for the reference's protobuf — and the data plane is the existing DCN
tier (stream/remote_exchange.py: Arrow-IPC chunks, barrier frames,
credit backpressure).

Topology per remote fragment (all lazy, set up on first execute()):

    main upstream actors ──channel──> pump ──RemoteOutput──> worker in
    worker: [RemoteInput...] -> fragment executors -> RemoteOutput
    main: RemoteInput -> THIS executor -> normal Actor + dispatcher

Barriers flow through the worker and back, so the main-side Actor
collects each barrier only after the remote fragment processed it —
alignment and pacing work unchanged. v1 constraint: the remote
fragment runs VOLATILE (the planner requires streaming_durability = 0),
so recovery replays sources from offset 0 and the materialize upsert
converges the MV (the reference instead re-binds durable state to the
surviving CN set).
"""

from __future__ import annotations

import asyncio
import json
import pickle
import struct
from typing import Sequence

from ..common.types import Schema
from .executor import Executor
from .message import Barrier
from .remote_exchange import RemoteInput, RemoteOutput


async def _send_blob(writer, blob: bytes) -> None:
    writer.write(struct.pack("!i", len(blob)) + blob)
    await writer.drain()


async def _recv_blob(reader) -> bytes:
    ln = struct.unpack("!i", await reader.readexactly(4))[0]
    return await reader.readexactly(ln)


class RemoteFragmentExecutor(Executor):
    """Main-process stand-in for a fragment running in a worker."""

    def __init__(self, worker_addr: str, node, in_channels: Sequence,
                 in_schemas: Sequence[Schema], out_schema: Schema,
                 pk_indices=(), actor_id: int = 0):
        self.worker_addr = worker_addr
        self.node = node
        self.in_channels = list(in_channels)
        self.in_schemas = list(in_schemas)
        self.schema = out_schema
        self.pk_indices = tuple(pk_indices)
        self.actor_id = actor_id
        self.identity = f"RemoteFragment({worker_addr}, {node.kind})"

    def fence_tokens(self) -> list:
        return []      # device state lives in the worker process

    async def _pump(self, chan, out: RemoteOutput) -> None:
        while True:
            msg = await chan.recv()
            await out.send(msg)
            # only OUR OWN stop ends the pump: a shared coordinator
            # routes other deployments' stop barriers through every
            # pipeline (same contract as the local build's stop_on)
            if isinstance(msg, Barrier) and msg.mutation is not None \
                    and msg.is_stop(self.actor_id):
                return

    async def execute(self):
        host, _, port = self.worker_addr.partition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        # bind all interfaces: the worker may live on another host and
        # connects back to us at the address it sees on the control
        # socket (the DCN tier is cross-host by design)
        rx = await RemoteInput(self.schema, host="0.0.0.0",
                               queue_depth=8).start()
        spec = pickle.dumps({
            "node": self.node,
            "in_schemas": self.in_schemas,
            "out_schema": self.schema,
            "out_port": rx.port,
            "stop_actor_id": self.actor_id,
        })
        await _send_blob(writer, spec)
        reply = json.loads(await _recv_blob(reader))
        outs = []
        for p in reply["input_ports"]:
            outs.append(await RemoteOutput(host, p).connect())
        pumps = [asyncio.create_task(self._pump(c, o))
                 for c, o in zip(self.in_channels, outs)]
        try:
            async for msg in rx.execute():
                yield msg
                if isinstance(msg, Barrier) and msg.mutation is not None \
                        and msg.is_stop(self.actor_id):
                    break
        finally:
            for t in pumps:
                t.cancel()
            for o in outs:
                try:
                    await o.close()
                except Exception:  # noqa: BLE001
                    pass
            await rx.stop()
            writer.close()

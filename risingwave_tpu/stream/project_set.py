"""ProjectSet — table functions in the select list.

Reference: src/stream/src/executor/project_set.rs: each input row
produces 0..k output rows (set-returning functions like
generate_series / unnest), plus ordinary scalar projections and the
`projected_row_id` ordinal column that keeps the output stream keyed.

TPU re-design: the row fan-out is STATIC — with a declared per-row bound
K, the output is an [N*K] lane grid (row i, ordinal j at lane i*K+j)
with visibility j < count(i). No data-dependent shapes; ops replicate to
every lane of their row, so retractions retract the whole set.

Select items:
  ("scalar", expr)                       one value per row
  ("series", start_expr, stop_expr)      generate_series(start, stop):
                                         ordinals start..stop-1, bounded
                                         by max_rows_per_input
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import DataType, Field, Schema
from .executor import StatelessUnaryExecutor
from .message import Watermark
from ..ops.jit_state import jit_state


class ProjectSetExecutor(StatelessUnaryExecutor):
    def __init__(self, input, items: Sequence[tuple],
                 max_rows_per_input: int = 16,
                 names=None):
        super().__init__(input)
        self.items = tuple(items)
        assert any(it[0] == "series" for it in self.items), \
            "ProjectSet without a set-returning item is just Project"
        self.k = max_rows_per_input
        fields = [Field("projected_row_id", DataType.INT64)]
        for j, it in enumerate(self.items):
            name = (names[j] if names else f"p{j}")
            # series values compute in int64 (start + ordinal)
            fields.append(Field(name, it[1].ret_type if it[0] == "scalar"
                                else DataType.INT64))
        self.schema = Schema(tuple(fields))
        self.identity = f"ProjectSet(k={self.k})"
        # rows a series produced beyond the static bound — silently
        # clipping would make the MV wrong with no signal (every bounded
        # structure here fail-stops; see sorted-store overflow counters)
        self._overflow_dev = jnp.zeros((), dtype=jnp.int32)
        self._step = jit_state(self._step_impl, name="project_set_step")

    def _step_impl(self, overflow, chunk: StreamChunk):
        N = chunk.capacity
        K = self.k
        lane = jnp.arange(N * K, dtype=jnp.int64)
        src = (lane // K).astype(jnp.int32)
        ordinal = lane % K
        # per-row output count = max over series items of their lengths
        count = jnp.zeros(N, dtype=jnp.int64)
        series_vals = {}
        for j, it in enumerate(self.items):
            if it[0] != "series":
                continue
            start = it[1].eval(chunk.columns)
            stop = it[2].eval(chunk.columns)
            raw = jnp.clip(stop.data.astype(jnp.int64)
                           - start.data.astype(jnp.int64), 0, None)
            ok = start.valid_mask() & stop.valid_mask() & chunk.vis
            raw = jnp.where(ok, raw, 0)
            overflow = overflow + jnp.sum(
                jnp.maximum(raw - K, 0)).astype(jnp.int32)
            ln = jnp.minimum(raw, K)
            count = jnp.maximum(count, ln)
            series_vals[j] = (start.data.astype(jnp.int64), ln)
        vis = jnp.take(chunk.vis, src) & (ordinal < jnp.take(count, src))
        ops = jnp.take(chunk.ops, src)
        cols = [Column(ordinal)]
        for j, it in enumerate(self.items):
            if it[0] == "scalar":
                c = it[1].eval(chunk.columns)
                cols.append(Column(jnp.take(c.data, src, axis=0),
                                   jnp.take(c.valid_mask(), src, axis=0)))
            else:
                start, ln = series_vals[j]
                val = jnp.take(start, src) + ordinal
                valid = ordinal < jnp.take(ln, src)
                cols.append(Column(val, valid))
        return overflow, StreamChunk(tuple(cols), ops, vis, self.schema)

    def map_chunk(self, chunk):
        self._overflow_dev, out = self._step(self._overflow_dev, chunk)
        return out

    def on_barrier(self, barrier) -> None:
        import numpy as np
        n = int(np.asarray(self._overflow_dev))
        if n:
            raise RuntimeError(
                f"ProjectSet series overflow: {n} rows beyond "
                f"max_rows_per_input={self.k} were dropped")

    def map_watermark(self, wm: Watermark):
        return None      # ordinals break monotonicity; keep it simple

from .message import (
    Barrier, BarrierKind, Watermark, Message,
    StopMutation, PauseMutation, ResumeMutation, ThrottleMutation,
    AddMutation, UpdateMutation,
)
from .executor import Executor, StatelessUnaryExecutor
from .project import ProjectExecutor, FilterExecutor
from .row_id import RowIdGenExecutor
from .materialize import MaterializeExecutor, ConflictBehavior
from .source import SourceExecutor
from .actor import Actor
from .exchange import (
    Channel, SimpleDispatcher, BroadcastDispatcher, HashDispatcher,
    ChannelInput, MergeExecutor, TapDispatcher,
)
from .hash_agg import HashAggExecutor
from .hash_join import HashJoinExecutor
from .sorted_join import SortedJoinExecutor
from .sharded_join import ShardedSortedJoinExecutor
from .backfill import BackfillExecutor
from .sink import (SinkExecutor, BlackholeSink, FileSink, CallbackSink)
from .align import barrier_align
from .hop_window import HopWindowExecutor
from .dedup import AppendOnlyDedupExecutor
from .simple_agg import SimpleAggExecutor, StatelessSimpleAggExecutor
from .top_n import GroupTopNExecutor, top_n
from .retract_top_n import RetractableTopNExecutor
from .sort import SortExecutor
from .over_window import OverWindowExecutor, ROW_NUMBER
from .misc import (
    ExpandExecutor, FlowControlExecutor, NoOpExecutor, UnionExecutor,
    ValuesExecutor, WatermarkFilterExecutor,
)
from .general_over_window import GeneralOverWindowExecutor, WindowSpec  # noqa: E402,F401
from .sharded_top_n import ShardedTopNExecutor  # noqa: E402,F401
from .sharded_over_window import ShardedOverWindowExecutor  # noqa: E402,F401
from .dynamic import DynamicFilterExecutor, NowExecutor  # noqa: E402,F401
from .project_set import ProjectSetExecutor  # noqa: E402,F401

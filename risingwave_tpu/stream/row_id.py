"""RowIdGen executor — appends a `_row_id` Serial column.

Reference: src/stream/src/executor/row_id_gen.rs + common/src/util/row_id.rs —
append-only sources without a pk get vnode-prefixed serial row ids so the MV
has a primary key. Reference layout embeds the barrier epoch's physical
timestamp so ids never collide across restarts (no row-id state table
needed; the reference generator *stalls* when it exhausts a millisecond's
sequence space — here bursts borrow forward instead).

Layout: row_id = instance(8b) << 55 | seq(55b), seq seeded and re-floored
from each barrier's physical epoch ms << 15 (32k rows/ms/instance before
borrowing ahead of the clock). Restart safety has two layers: (1) the
BarrierCoordinator recovers its epoch floor from the store's committed
epoch, so post-restart epochs are strictly greater than any pre-restart
epoch; (2) seq is floored by those epochs. Collisions would need a sustained
>32M rows/s/instance burst racing the clock across a restart gap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.epoch import to_physical
from ..common.types import DataType, Field, Schema
from .executor import Executor, StatelessUnaryExecutor
from .message import Barrier
from ..ops.jit_state import jit_state

_SEQ_PER_MS_BITS = 15


class RowIdGenExecutor(StatelessUnaryExecutor):
    def __init__(self, input: Executor, instance: int = 0, row_id_name: str = "_row_id"):
        super().__init__(input)
        self.instance = instance
        self._next_seq = 0
        self.schema = Schema(input.schema.fields + (Field(row_id_name, DataType.SERIAL),))
        self.pk_indices = (len(self.schema) - 1,)
        self.identity = "RowIdGen"
        self._step = jit_state(self._step_impl, name="row_id_step")

    def on_barrier(self, barrier: Barrier) -> None:
        # epoch physical time floors the sequence => restart-safe ids
        self._next_seq = max(self._next_seq,
                             to_physical(barrier.epoch.curr) << _SEQ_PER_MS_BITS)

    def _step_impl(self, chunk: StreamChunk, base: jnp.ndarray) -> StreamChunk:
        ids = base + jnp.arange(chunk.capacity, dtype=jnp.int64)
        cols = chunk.columns + (Column(ids),)
        return StreamChunk(cols, chunk.ops, chunk.vis, self.schema)

    def map_chunk(self, chunk):
        base = (self.instance << 55) | self._next_seq
        self._next_seq += chunk.capacity
        return self._step(chunk, jnp.int64(base))

"""HashAgg executor — grouped streaming aggregation with device state.

Reference: src/stream/src/executor/hash_agg.rs — groups keyed by `HashKey`
live in a managed cache; chunks are applied group-wise (`apply_chunk`:349);
at each barrier the executor diffs old vs new agg values and emits change
rows (`flush_data`:436), then commits its state tables.

TPU re-design: the group map is a `HashTable` in HBM plus parallel state
arrays [C] (one per agg call) and a row-count array. Applying a chunk is one
jitted step: slot assignment (open addressing) -> segment-reduce partials by
slot -> combine into states, marking touched slots dirty. The barrier flush
is a second jitted step that compacts dirty slots to the front and lays out
UpdateDelete/UpdateInsert pairs (Insert for born groups, Delete for died
ones) exactly like the reference's changelog contract. Zombie slots (groups
at row_count 0) keep their keys so probe chains stay intact; the executor
rebuilds/grows the table when load crosses the threshold.

min/max require append-only input here (the reference's retractable min/max
uses materialized input state, aggregation/minput.rs — that variant lives in
the planner's fallback path, not this executor yet).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, StreamChunk, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT, op_sign,
)
from ..common.types import Field, Schema
from ..expr.agg import AggCall, AggKind
from ..ops.extrema import (
    extrema_emit, extrema_empty, extrema_gather, extrema_mask_keep,
    extrema_underflow, extrema_update,
)
from ..memory.accounting import pytree_bytes
from ..memory.spill import HostSpill
from ..ops.hash_table import (
    BUCKET_SLOTS, HashTable, compact_mask, lookup_or_insert, lru_stamp,
    needs_rebuild,
)
from ..ops.jit_state import jit_state
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, BarrierKind, Watermark


@jax.tree_util.register_pytree_node_class
@dataclass
class AggState:
    """Device state of one HashAgg instance (all arrays share capacity C)."""

    table: HashTable
    agg_states: tuple[jnp.ndarray, ...]   # one [C] per agg call
    row_count: jnp.ndarray                # int64 [C] — group liveness
    dirty: jnp.ndarray                    # bool [C] — touched since flush
    prev_exists: jnp.ndarray              # bool [C] — group was in output
    prev_emit: tuple[jnp.ndarray, ...]    # last emitted value per agg [C]

    def tree_flatten(self):
        return ((self.table, self.agg_states, self.row_count, self.dirty,
                 self.prev_exists, self.prev_emit), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        table, agg_states, row_count, dirty, prev_exists, prev_emit = children
        return cls(table, tuple(agg_states), row_count, dirty,
                   prev_exists, tuple(prev_emit))


class HashAggExecutor(Executor):
    def __init__(self, input: Executor, group_key_indices: Sequence[int],
                 agg_calls: Sequence[AggCall], capacity: int = 1 << 16,
                 state_table: Optional[StateTable] = None,
                 group_key_names: Optional[Sequence[str]] = None,
                 cleaning_watermark_col: Optional[int] = None,
                 watchdog_interval: Optional[int] = 1,
                 minput_k: int = 32):
        self.input = input
        self.group_key_indices = tuple(group_key_indices)
        self.agg_calls = tuple(agg_calls)
        self.specs = tuple(c.spec() for c in agg_calls)
        # retractable MIN/MAX use materialized-input top-K value buffers
        # (reference minput.rs); linear aggs keep one scalar per group
        self.minput_k = minput_k
        self._retractable = tuple(
            c.kind in (AggKind.MIN, AggKind.MAX) and not c.append_only
            for c in agg_calls)
        in_schema = input.schema
        gk_names = list(group_key_names or
                        [in_schema[i].name for i in self.group_key_indices])
        self.schema = Schema(tuple(
            [Field(n, in_schema[i].data_type)
             for n, i in zip(gk_names, self.group_key_indices)]
            + [Field(f"agg{j}", c.ret_type) for j, c in enumerate(agg_calls)]))
        self.pk_indices = tuple(range(len(self.group_key_indices)))
        self.capacity = capacity
        self.state_table = state_table
        # Watermark state cleaning (reference: StateTable::update_watermark
        # state_table.rs:1029 -> Hummock table watermarks): groups whose
        # watermark-column key falls below the watermark can never be touched
        # again, so their state is zeroed on device at the barrier. The slot
        # stays occupied (probe chains intact) until a rebuild purges it.
        # `cleaning_watermark_col` is an INPUT column index and must be one
        # of the group keys.
        self.cleaning_watermark_key: Optional[int] = (
            None if cleaning_watermark_col is None
            else self.group_key_indices.index(cleaning_watermark_col))
        self._pending_clean_wm: Optional[int] = None
        self.identity = f"HashAgg(keys={self.group_key_indices})"
        self._key_dtypes = tuple(
            in_schema[i].data_type.jnp_dtype for i in self.group_key_indices)
        self.state = self._initial_state(capacity)
        # State-threading programs donate the AggState pytree (and the
        # device watchdog accumulator) so XLA updates the table buffers in
        # place: `self.state = self._apply(self.state, ...)` is the only
        # reference, which is the donation contract. Read-only views
        # (_live_zombie, _evict_keys, _persist_view) must NOT donate —
        # the state stays live after them.
        self._apply = jit_state(self._apply_impl, donate_argnums=(0, 1),
                                name="hash_agg_apply")
        self._flush = jit_state(self._flush_impl, donate_argnums=(0,),
                                name="hash_agg_flush")
        self._live_zombie = jit_state(self._live_zombie_impl,
                                      name="hash_agg_live_zombie")
        self._evict = jit_state(self._evict_impl, donate_argnums=(0,),
                                name="hash_agg_evict")
        self._evict_keys = jit_state(self._evict_keys_impl,
                                     name="hash_agg_evict_keys")
        self._rehash = jit_state(self._rehash_impl, static_argnums=1,
                                 donate_argnums=(0,), name="hash_agg_rehash")
        self._persist_view = jit_state(self._persist_view_impl,
                                       name="hash_agg_persist_view")
        # multi-chunk apply: chunks buffered within a barrier interval are
        # applied in ONE dispatch via lax.scan over a stacked batch (the
        # sharded subclass opts out — its programs are shard_map-wrapped)
        self._use_chunk_batching = True
        self._batch_max = 8
        self._pending_chunks: list[StreamChunk] = []
        self._apply_scans: dict[int, object] = {}
        # load/overflow watchdog (see _check_watchdog). watchdog_interval =
        # barriers between watchdog fetches; None disables the fetch
        # ENTIRELY (even at stop) — on a tunneled TPU the FIRST d2h
        # transfer of any kind degrades program dispatch erratically
        # (measured: ~10-300ms per program, sometimes minutes of stall,
        # after one np.asarray of an int32[2]), so latency-critical
        # pipelines must keep the whole process transfer-free. In that
        # mode correctness rests on CPU-backend tests of the same pipeline
        # shapes and on device-side zombie purges keeping occupancy
        # bounded; overflow still accumulates on device for post-hoc
        # inspection.
        if watchdog_interval not in (None, 1):
            raise ValueError(
                "watchdog_interval must be 1 (check before every checkpoint "
                "commit) or None (transfer-free mode): any lag would let a "
                "checkpoint commit state whose overflow counter was never "
                "checked, defeating the fail-stop contract")
        self.watchdog_interval = watchdog_interval
        self.rebuilds = 0
        self._occ_known = 0
        self._applied_since_flush = False
        # ---- HBM memory manager hooks (memory/manager.py) ----
        # LRU hotness is an int64 epoch stamp PER SLOT, advanced at each
        # barrier from the interval's dirty bitmap — one elementwise
        # select per interval, no device->host sync on the data path.
        # Cold slots spill their rows to the host dict; a later touch of
        # a spilled key reloads it at drain time before the chunk
        # applies.
        self._mem_lru_on = False
        self._slot_epoch = None             # int64 [C] device, lazy
        # shrink floor: below ~64 buckets the two-choice overflow
        # probability stops being negligible at moderate load, so
        # eviction never shrinks under this (tests override)
        self._mem_min_capacity = 1024
        self._spill = HostSpill()
        self.mem_evicted_bytes = 0
        self.mem_reload_count = 0
        # keys the reload-LFU guard kept resident through an eviction
        # round (memory/manager.py ReloadGuard, set as self.mem_guard)
        self.mem_guard_protected = 0
        self._lru_stamp = jit_state(self._lru_stamp_impl,
                                    donate_argnums=(1,),
                                    name="hash_agg_lru_stamp")
        self._mem_stats = jit_state(self._mem_stats_impl,
                                    name="hash_agg_mem_stats")
        self._mem_pack = jit_state(self._mem_pack_impl,
                                   name="hash_agg_mem_pack")
        self._mem_rehash = jit_state(self._mem_rehash_impl,
                                     static_argnames=("new_capacity",),
                                     donate_argnums=(0,),
                                     name="hash_agg_mem_rehash")
        self._mem_reloads: dict[int, object] = {}
        self._overflow_dev = jnp.zeros((), dtype=jnp.int32)
        self._occ_dev = jnp.zeros((), dtype=jnp.int32)
        self._watchdog_pack = jit_state(
            lambda ov, occ: jnp.stack([ov, occ]),
            name="hash_agg_watchdog_pack")

    def fence_tokens(self) -> list:
        # the state root depends on every program dispatched this epoch,
        # including barrier-time evict/purge work
        return [self.state.table.keys[0]] + super().fence_tokens()

    # ------------------------------------------------------------ state
    def _initial_state(self, capacity: int) -> AggState:
        """Constructor-time state; sharded variants override this to place
        global arrays over the mesh while _empty_state stays LOCAL (it is
        called inside jitted per-shard impls like _rehash_impl)."""
        return self._empty_state(capacity)

    # ---- per-call state polymorphism: linear scalar vs extrema buffer
    def _call_init_state(self, j: int, capacity: int):
        if self._retractable[j]:
            return extrema_empty(capacity, self.minput_k,
                                 self.specs[j].state_dtype)
        return self.specs[j].init_state((capacity,))

    def _call_emit(self, j: int, st):
        if self._retractable[j]:
            # match the scalar path's ret-type cast (schema dtype contract)
            return extrema_emit(
                st, self.specs[j].init, self.specs[j].state_dtype).astype(
                    self.agg_calls[j].ret_type.jnp_dtype)
        return self.specs[j].emit(st)

    def _empty_state(self, capacity: int) -> AggState:
        table = HashTable.empty(capacity, self._key_dtypes)
        return AggState(
            table=table,
            agg_states=tuple(self._call_init_state(j, capacity)
                             for j in range(len(self.specs))),
            row_count=jnp.zeros(capacity, dtype=jnp.int64),
            dirty=jnp.zeros(capacity, dtype=bool),
            prev_exists=jnp.zeros(capacity, dtype=bool),
            prev_emit=tuple(
                jnp.zeros(capacity, dtype=c.ret_type.jnp_dtype)
                for c in self.agg_calls),
        )

    # ------------------------------------------------------- chunk apply
    def _apply_impl(self, state: AggState, overflow, chunk: StreamChunk):
        key_cols = [chunk.columns[i].data for i in self.group_key_indices]
        table, slots, n_unresolved = lookup_or_insert(
            state.table, key_cols, chunk.vis)
        C = table.capacity
        ok = slots >= 0
        # segment id per row; trash segment C for masked rows
        seg = jnp.where(ok, slots, C)
        signs = jnp.where(ok, op_sign(chunk.ops), 0)
        row_count = state.row_count + jax.ops.segment_sum(
            signs.astype(jnp.int64), seg, C + 1)[:C]
        new_states = []
        n_err = jnp.int32(0)
        for j, (spec, call, st) in enumerate(
                zip(self.specs, self.agg_calls, state.agg_states)):
            if call.arg is None:
                values = jnp.zeros(chunk.capacity, dtype=spec.state_dtype)
                valid_in = jnp.ones(chunk.capacity, dtype=bool)
            else:
                col = chunk.columns[call.arg]
                values = col.data
                # NULL inputs don't contribute (reference strict agg
                # semantics)
                valid_in = col.valid_mask()
            if self._retractable[j]:
                st2, e = extrema_update(
                    st, values.astype(spec.state_dtype), valid_in, signs,
                    seg, C, is_max=(call.kind is AggKind.MAX))
                # lossy + emptied + live rows = unknowable extremum
                e = e + extrema_underflow(st2, row_count)
                n_err = n_err + e
                new_states.append(st2)
            else:
                row_signs = jnp.where(valid_in, signs, 0)
                part = spec.partial(values, row_signs, seg, C + 1)[:C]
                new_states.append(spec.combine(st, part))
        dirty = state.dirty.at[seg].set(True, mode="drop")
        new_state = AggState(table, tuple(new_states), row_count, dirty,
                             state.prev_exists, state.prev_emit)
        # watchdog counters stay ON DEVICE: overflow accumulates across the
        # epoch and occupancy rides along as the latest value; the host
        # fetches both ONCE per barrier. A d2h copy serializes ~10-100ms
        # into the device stream on a tunneled TPU, so per-chunk copies are
        # the difference between wire speed and 100x slower.
        occ = jnp.sum(table.occupied.astype(jnp.int32))
        # keep the accumulator's dtype stable (the segment sums promote to
        # int64): donation can only reuse the input buffer — and lax.scan
        # only accepts the carry — when the dtype round-trips
        overflow = (overflow + n_unresolved + n_err).astype(overflow.dtype)
        return new_state, overflow, occ

    # ---------------------------------------------------------- flush
    def _flush_impl(self, state: AggState):
        """Emit the barrier diff as one chunk of capacity 2*C with
        interleaved UD/UI pairs; returns (state', chunk arrays...).

        Compaction is a cumsum-scatter (O(C) scan), not a sort: dirty slot
        with rank j lands at output positions 2j (old value) / 2j+1 (new)."""
        C = state.table.capacity
        exists_now = state.row_count > 0
        dirty = state.dirty
        rank = jnp.cumsum(dirty.astype(jnp.int32)) - 1   # rank among dirty
        slot_ids = jnp.arange(C, dtype=jnp.int32)
        # scatter: d_slot[j] = slot of j-th dirty entry (garbage past n_dirty)
        d_slot = jnp.zeros(C, dtype=jnp.int32).at[
            jnp.where(dirty, rank, C)].set(slot_ids, mode="drop")
        n_dirty = jnp.sum(dirty.astype(jnp.int32))
        existed = state.prev_exists[d_slot]
        exists = exists_now[d_slot]
        is_dirty = slot_ids < n_dirty

        # no-change skip (reference agg_group.rs:71 build_change -> NoChange):
        # a group that existed before, still exists, and whose emitted outputs
        # are all unchanged produces no changelog rows
        unchanged = existed & exists
        for j, (st, pe) in enumerate(zip(state.agg_states, state.prev_emit)):
            unchanged &= self._call_emit(j, st)[d_slot] == pe[d_slot]

        # output row j at positions 2j (old) and 2j+1 (new)
        vis_old = is_dirty & existed & ~unchanged   # UD or Delete
        vis_new = is_dirty & exists & ~unchanged    # UI or Insert
        ops_old = jnp.where(exists, OP_UPDATE_DELETE, OP_DELETE)
        ops_new = jnp.where(existed, OP_UPDATE_INSERT, OP_INSERT)

        def interleave(a, b):
            return jnp.stack([a, b], axis=1).reshape(2 * C)

        out_ops = interleave(ops_old, ops_new).astype(jnp.int8)
        out_vis = interleave(vis_old, vis_new)
        out_cols = []
        for tk in state.table.keys:
            v = tk[d_slot]
            out_cols.append(interleave(v, v))
        new_emit = []
        for j, (st, pe) in enumerate(zip(state.agg_states, state.prev_emit)):
            cur = self._call_emit(j, st)
            new_emit.append(cur)
            out_cols.append(interleave(pe[d_slot], cur[d_slot]))

        prev_exists = exists_now
        prev_emit = tuple(new_emit)
        state2 = AggState(state.table, state.agg_states, state.row_count,
                          jnp.zeros(C, dtype=bool), prev_exists, prev_emit)
        return state2, tuple(out_cols), out_ops, out_vis

    def _live_zombie_impl(self, state: AggState):
        occ = jnp.sum(state.table.occupied.astype(jnp.int32))
        live = jnp.sum((state.row_count > 0).astype(jnp.int32))
        return occ, live

    def _evict_keys_impl(self, state: AggState, watermark):
        """Compacted group keys of live groups below the cleaning watermark —
        the rows that must be DELETED from the durable state table when the
        device state is zeroed (reference: StateTable::update_watermark ->
        Hummock table-watermark pruning keeps committed state bounded)."""
        j = self.cleaning_watermark_key
        evict = (state.table.occupied & (state.table.keys[j] < watermark)
                 & (state.row_count > 0))
        C = state.table.capacity
        rank = jnp.cumsum(evict.astype(jnp.int32)) - 1
        sel = jnp.zeros(C, dtype=jnp.int32).at[
            jnp.where(evict, rank, C)].set(jnp.arange(C, dtype=jnp.int32),
                                           mode="drop")
        n = jnp.sum(evict.astype(jnp.int32))
        return tuple(tk[sel] for tk in state.table.keys), n

    def _evict_impl(self, state: AggState, watermark) -> AggState:
        """Zero out groups below the state-cleaning watermark. Slots remain
        occupied zombies (chain-safe); rebuilds reclaim them later."""
        j = self.cleaning_watermark_key
        evict = state.table.occupied & (state.table.keys[j] < watermark)
        keep = ~evict
        def zero_call(jj, st):
            if self._retractable[jj]:
                return extrema_mask_keep(st, keep)
            return jnp.where(keep, st, self.specs[jj].init)

        return AggState(
            table=state.table,
            agg_states=tuple(
                zero_call(jj, st)
                for jj, st in enumerate(state.agg_states)),
            row_count=jnp.where(keep, state.row_count, 0),
            dirty=state.dirty & keep,
            prev_exists=state.prev_exists & keep,
            prev_emit=tuple(jnp.where(keep, p, 0) for p in state.prev_emit),
        )

    def _rehash_impl(self, state: AggState, new_capacity: int) -> AggState:
        """Device-side rebuild: re-insert surviving groups into a fresh
        table of `new_capacity` slots. Pure XLA — no host roundtrip; only a
        capacity CHANGE triggers a recompile (distinct static shape)."""
        keep = state.table.occupied & (
            (state.row_count > 0) | (state.dirty & state.prev_exists))
        return self._rehash_keep(state, keep, new_capacity)

    def _rehash_keep(self, state: AggState, keep: jnp.ndarray,
                     new_capacity: int) -> AggState:
        """Shared rebuild body: re-insert exactly the `keep` slots into a
        fresh table (growth/purge keeps all survivors; memory eviction
        additionally drops the cold groups)."""
        fresh = HashTable.empty(new_capacity, self._key_dtypes)
        # compact surviving entries to the front so insertion order is dense
        C = state.table.capacity
        sel, n_keep = compact_mask(keep)
        active = jnp.arange(C) < n_keep
        key_cols = [tk[sel] for tk in state.table.keys]
        table, slots, n_un = lookup_or_insert(fresh, key_cols, active)
        # n_un must be 0 by construction (new_capacity >= live set)
        tgt = jnp.where(active, slots, new_capacity)
        empty = self._empty_state(new_capacity)
        def gather_call(j, os):
            if self._retractable[j]:
                return extrema_gather(os, sel, tgt, new_capacity,
                                      self.minput_k,
                                      self.specs[j].state_dtype)
            return empty.agg_states[j].at[tgt].set(os[sel], mode="drop")

        return AggState(
            table=table,
            agg_states=tuple(
                gather_call(j, os)
                for j, os in enumerate(state.agg_states)),
            row_count=empty.row_count.at[tgt].set(state.row_count[sel], mode="drop"),
            dirty=empty.dirty.at[tgt].set(state.dirty[sel], mode="drop"),
            prev_exists=empty.prev_exists.at[tgt].set(state.prev_exists[sel], mode="drop"),
            prev_emit=tuple(
                ep.at[tgt].set(op[sel], mode="drop")
                for ep, op in zip(empty.prev_emit, state.prev_emit)),
        )

    # --------------------------------------------------------- rebuild
    def _rebuild(self, new_capacity: int) -> int:
        """Purge zombies / grow via the device-side rehash.
        Returns the rebuilt occupancy (one readback — rebuilds are rare)."""
        self.state = self._rehash(self.state, new_capacity)
        self.capacity = new_capacity
        self.rebuilds += 1
        # slot geometry changed: restamp lazily (everything hot, and one
        # interval later the LRU discriminates again)
        self._slot_epoch = None
        occ, _ = self._live_zombie(self.state)
        return int(occ)

    def _check_watchdog(self) -> None:
        """ONE small blocking fetch of the device-accumulated (overflow,
        occupied) pair — called per BARRIER, never per chunk. The counters
        accumulate on device across the epoch; fetching them per chunk
        gates throughput on d2h copy latency (and `copy_to_host_async`
        stalls completion-event delivery for seconds on a tunneled TPU —
        measured, not theoretical — so the fetch is a plain blocking
        np.asarray of two scalars, ~10-90ms once per barrier).

        Overflow fail-stops BEFORE this epoch's checkpoint commits, so a
        chunk the table dropped rows from is never made durable; recovery
        replays from the last committed epoch (SURVEY.md §3.5). Capacity
        provisioning + barrier-time growth make this a last-resort
        watchdog."""
        vals = np.asarray(self._watchdog_pack(self._overflow_dev,
                                              self._occ_dev))
        n_un = int(vals[0])
        if n_un:
            raise RuntimeError(
                f"hash-agg table overflow mid-epoch ({n_un} rows, "
                f"capacity {self.capacity}); recovery must replay the "
                f"epoch with a larger table")
        self._occ_known = int(vals[1])

    def _maybe_rebuild_at_barrier(self) -> None:
        """Barrier-time growth: the table is examined between epochs, when
        occupancy knowledge from the barrier watchdog fetch is safe to act
        on. Crossing the high watermark purges zombies (dead windows/
        groups) or doubles capacity; both re-jit the apply step, which is
        why it never happens mid-epoch."""
        if self._occ_known <= 0.7 * self.capacity:
            return
        occ, live = self._live_zombie(self.state)
        rebuild, cap = needs_rebuild(int(occ), int(live), self.capacity)
        if rebuild:
            self._occ_known = self._rebuild(cap)

    # ------------------------------------------------- HBM memory manager
    def state_bytes(self) -> int:
        """EXACT device-state bytes (memory/accounting.py): static pytree
        shapes, no transfer, no estimate."""
        extra = () if self._slot_epoch is None else (self._slot_epoch,)
        return pytree_bytes((self.state,) + extra)

    @property
    def mem_spilled_rows(self) -> int:
        return self._spill.rows

    def memory_enable_lru(self) -> None:
        self._mem_lru_on = True

    def _lru_stamp_impl(self, dirty, slot_epoch, epoch):
        return lru_stamp(slot_epoch, dirty, epoch)

    def _mem_stamp(self, epoch: int) -> None:
        if self._slot_epoch is None \
                or self._slot_epoch.shape[0] != self.capacity:
            # first stamp / post-rebuild: everything counts as hot now;
            # one interval later untouched slots fall behind again
            self._slot_epoch = jnp.full(self.capacity, epoch,
                                        dtype=jnp.int64)
            return
        self._slot_epoch = self._lru_stamp(self.state.dirty,
                                           self._slot_epoch, epoch)

    def _mem_stats_impl(self, state: AggState, slot_epoch):
        """Per-slot (live, stamp) packed for ONE fetch (eviction only)."""
        live = state.table.occupied & (state.row_count > 0) & ~state.dirty
        return live, slot_epoch

    def _mem_pack_impl(self, state: AggState, slot_epoch, thresh):
        """Compact the to-evict rows (live, clean, stamp <= thresh) to
        the buffer prefix in durable-row layout."""
        evict = (state.table.occupied & (state.row_count > 0)
                 & ~state.dirty & (slot_epoch <= thresh))
        sel, n = compact_mask(evict)
        return tuple(self._durable_cols_at(state, sel)), n

    def _mem_rehash_impl(self, state: AggState, slot_epoch, thresh,
                         new_capacity: int) -> AggState:
        """Rebuild WITHOUT the evicted cold rows — frees their slots and
        (with a smaller new_capacity) the HBM behind them."""
        drop = ((state.row_count > 0) & ~state.dirty
                & (slot_epoch <= thresh))
        keep = (state.table.occupied
                & ((state.row_count > 0) | (state.dirty & state.prev_exists))
                & ~drop)
        return self._rehash_keep(state, keep, new_capacity)

    def _mem_fetch_stats(self, epoch: int):
        """(live mask, stamps, cold stamps asc, this-interval touch count)
        in ONE packed fetch — the eviction decision inputs."""
        from ..utils.d2h import fetch_columns
        live_dev, ep_dev = self._mem_stats(self.state, self._slot_epoch)
        live_np, ep_np = fetch_columns([live_dev, ep_dev])
        live_np = live_np.astype(bool)
        cold = np.sort(ep_np[live_np & (ep_np < epoch)])
        return live_np, ep_np, cold, int((ep_np == epoch).sum())

    def _mem_cap_for(self, n_survive: int, touched_now: int) -> int:
        """Post-eviction capacity: survivors + one more interval of fresh
        keys at a 0.35 target load, so the shrunk table neither re-grows
        immediately nor hits a mid-epoch bucket-overflow fail-stop."""
        c = max(2 * BUCKET_SLOTS, self._mem_min_capacity)
        while n_survive + touched_now > 0.35 * c:
            c *= 2
        return c

    def _mem_do_evict(self, epoch: int, thresh: int,
                      new_cap: int, survivors: int) -> int:
        """Pack + spill slots stamped <= thresh, rehash at new_cap.
        Returns bytes freed (0 for a same-capacity cold purge — the win
        there is distance from the overflow cliff, not bytes)."""
        from ..utils.d2h import fetch_prefix_groups
        guard = getattr(self, "mem_guard", None)
        cols_dev, n_dev = self._mem_pack(self.state, self._slot_epoch,
                                         jnp.int64(thresh))
        n = int(np.asarray(n_dev))
        protected: list = []
        if n:
            host = fetch_prefix_groups([(list(cols_dev), n)])[0]
            nk = len(self.group_key_indices)
            for r in range(n):
                row = tuple(c[r].item() for c in host)
                if guard is not None \
                        and guard.is_protected(id(self), row[:nk]):
                    # reload-LFU guard: reloaded >= 2x within the window
                    # -> exempt from this round, re-insert below
                    protected.append(row)
                else:
                    self._spill.set(row[:nk], row)
        before = self.state_bytes()
        self.state = self._mem_rehash(self.state, self._slot_epoch,
                                      jnp.int64(thresh),
                                      new_capacity=new_cap)
        self.capacity = new_cap
        self._slot_epoch = jnp.full(new_cap, epoch, dtype=jnp.int64)
        self._occ_known = max(0, survivors)
        if protected:
            self._mem_reload_rows(protected)
            self.mem_guard_protected += len(protected)
            guard.note_protected(len(protected))
        freed = max(0, before - self.state_bytes())
        self.mem_evicted_bytes += freed
        return freed

    def memory_evict(self, target_bytes: int, epoch: int) -> int:
        """Budget response: spill the coldest slots to host and SHRINK
        the table. Called by the MemoryManager between epochs (executor
        idle); the packed fetches follow the same per-barrier d2h
        discipline as the persist path. Returns bytes actually freed."""
        if not self._mem_lru_on or self._slot_epoch is None:
            return 0
        live_np, ep_np, cold, touched_now = self._mem_fetch_stats(epoch)
        if cold.size == 0:
            return 0
        total_live = int(live_np.sum())
        bps = max(1, self.state_bytes() // max(1, self.capacity))
        # oldest-first: the smallest evicted count whose shrink covers
        # the target (stamps are whole epochs — the cut is exact)
        removed, thresh = 0, None
        for t in np.unique(cold):
            removed = int((cold <= t).sum())
            thresh = int(t)
            if (self.capacity
                    - self._mem_cap_for(total_live - removed,
                                        touched_now)) * bps \
                    >= target_bytes:
                break
        new_cap = self._mem_cap_for(total_live - removed, touched_now)
        if thresh is None or new_cap >= self.capacity:
            return 0               # shrink impossible — hot set owns it
        return self._mem_do_evict(epoch, thresh, new_cap,
                                  total_live - removed)

    def memory_maintain(self, epoch: int) -> None:
        """Steady-state LRU tick: once eviction is on, cold slots spill
        BEFORE occupancy reaches the growth threshold — eviction is the
        plan, capacity resize the fallback. Evicts the oldest stamps
        until occupancy (plus one interval of headroom) sits at the 0.35
        target; a same-capacity purge still counts (it buys distance
        from the overflow cliff)."""
        if not self._mem_lru_on or self._slot_epoch is None:
            return
        if self._occ_known <= 0.55 * self.capacity:
            return
        live_np, ep_np, cold, touched_now = self._mem_fetch_stats(epoch)
        if cold.size == 0:
            return
        total_live = int(live_np.sum())
        need = total_live + touched_now - int(0.35 * self.capacity)
        removed, thresh = 0, None
        for t in np.unique(cold):
            removed = int((cold <= t).sum())
            thresh = int(t)
            if removed >= need:
                break
        new_cap = min(self.capacity,
                      self._mem_cap_for(total_live - removed,
                                        touched_now))
        self._mem_do_evict(epoch, thresh, new_cap, total_live - removed)

    def _mem_check_reload(self, chunks: list) -> None:
        """Read-through miss handling: before a drain applies, reload any
        spilled key the chunks touch (one packed fetch of the chunks' key
        columns — only paid while spilled state exists)."""
        if not self._spill:
            return
        from ..utils.d2h import fetch_columns
        nk = len(self.group_key_indices)
        arrays = []
        for ch in chunks:
            arrays.extend(ch.columns[i].data for i in self.group_key_indices)
            arrays.append(ch.vis)
        host = fetch_columns(arrays)
        seen: set = set()
        touched: list = []
        for ci in range(len(chunks)):
            part = host[ci * (nk + 1):(ci + 1) * (nk + 1)]
            vis = part[-1].astype(bool)
            idx = np.flatnonzero(vis)
            for vals in zip(*(c[idx] for c in part[:nk])):
                k = tuple(v.item() for v in vals)
                if k in seen:
                    continue
                seen.add(k)
                if k in self._spill:
                    touched.append(k)
        if not touched:
            return
        guard = getattr(self, "mem_guard", None)
        if guard is not None:
            guard.note(id(self), touched)
        rows = [row for k in touched for row in self._spill.pop(k)]
        self._mem_reload_rows(rows)
        self.mem_reload_count += len(touched)
        from ..utils.metrics import HBM_RELOADS
        HBM_RELOADS.inc(len(touched))

    def _mem_reload_rows(self, rows: list) -> None:
        """Scatter spilled durable-layout rows back into live state (the
        same row format recovery replays — read-through rides the replay
        machinery). Keys insert via lookup_or_insert; unresolved inserts
        accumulate into the overflow watchdog (fail-stop -> recovery
        rebuilds larger), but the host pre-grows when occupancy is known
        to crowd."""
        if not rows:
            return
        n = len(rows)
        if self._occ_known + n > 0.7 * self.capacity:
            cap = self.capacity
            while self._occ_known + n > 0.7 * cap:
                cap *= 2
            self._occ_known = self._rebuild(cap)
        B = 1 << max(0, (n - 1).bit_length())
        pad = rows + [rows[0]] * (B - n)
        active = jnp.asarray(np.arange(B) < n)
        nk = len(self.group_key_indices)
        key_cols = tuple(
            jnp.asarray(np.asarray([r[j] for r in pad],
                                   dtype=np.dtype(self._key_dtypes[j])))
            for j in range(nk))
        call_cols = []
        off = nk
        for j, spec in enumerate(self.specs):
            if self._retractable[j]:
                K = self.minput_k
                vals = jnp.asarray(np.asarray(
                    [[r[off + k] for k in range(K)] for r in pad]),
                    dtype=spec.state_dtype)
                cnts = jnp.asarray(np.asarray(
                    [[r[off + K + k] for k in range(K)] for r in pad],
                    dtype=np.int32))
                lossy = jnp.asarray(np.asarray(
                    [bool(r[off + 2 * K]) for r in pad]))
                call_cols.append((vals, cnts, lossy))
                off += 2 * K + 1
            else:
                call_cols.append(jnp.asarray(
                    np.asarray([r[off] for r in pad])).astype(
                        spec.state_dtype))
                off += 1
        row_count = jnp.asarray(np.asarray([r[off] for r in pad],
                                           dtype=np.int64))
        reload = self._mem_reloads.get(B)
        if reload is None:
            reload = jit_state(self._mem_reload_impl, donate_argnums=(0, 1),
                               name=f"hash_agg_mem_reload{B}")
            self._mem_reloads[B] = reload
        self.state, self._overflow_dev = reload(
            self.state, self._overflow_dev, key_cols, tuple(call_cols),
            row_count, active)
        self._applied_since_flush = True
        self._occ_known += n

    def _mem_reload_impl(self, state: AggState, overflow, key_cols,
                         call_cols, row_count, active):
        table, slots, n_un = lookup_or_insert(state.table, key_cols, active)
        C = table.capacity
        ok = active & (slots >= 0)
        tgt = jnp.where(ok, slots, C)
        agg_states, prev_emit = [], []
        for j in range(len(self.specs)):
            cs = call_cols[j]
            if self._retractable[j]:
                vals_b, cnts_b, lossy_b = cs
                e_vals, e_cnts, e_lossy = state.agg_states[j]
                agg_states.append((
                    e_vals.at[tgt].set(vals_b, mode="drop"),
                    e_cnts.at[tgt].set(cnts_b, mode="drop"),
                    e_lossy.at[tgt].set(lossy_b, mode="drop")))
            else:
                agg_states.append(state.agg_states[j].at[tgt].set(
                    cs.astype(state.agg_states[j].dtype), mode="drop"))
            prev_emit.append(state.prev_emit[j].at[tgt].set(
                self._call_emit(j, cs), mode="drop"))
        # dirty=True: re-persists the rows (idempotent upsert), keeps the
        # LRU stamp hot, and the flush's no-change skip still emits no
        # changelog because prev_emit matches
        return AggState(
            table=table,
            agg_states=tuple(agg_states),
            row_count=state.row_count.at[tgt].set(row_count, mode="drop"),
            dirty=state.dirty.at[tgt].set(True, mode="drop"),
            prev_exists=state.prev_exists.at[tgt].set(True, mode="drop"),
            prev_emit=tuple(prev_emit),
        ), (overflow + n_un).astype(overflow.dtype)

    def _clean_spilled(self, wm) -> None:
        """Watermark state cleaning of EVICTED ranges: spilled keys below
        the cleaning watermark leave the spill dict and (when durable)
        the state table, in step with the device-side zeroing."""
        if not self._spill or self.cleaning_watermark_key is None:
            return
        j = self.cleaning_watermark_key
        dead = self._spill.purge(lambda k, rows: k[j] < wm)
        if dead and self.state_table is not None:
            keys_np = [
                np.asarray([k[i] for k, _ in dead],
                           dtype=np.dtype(self._key_dtypes[i]))
                for i in range(len(self.group_key_indices))]
            self._apply_evict_deletes(keys_np, len(dead))

    # ------------------------------------------------------- persistence
    def _persist(self, barrier: Barrier) -> None:
        """Overlap-friendly durable flush: the packed persist/evict views
        are DISPATCHED here (device work queues behind the epoch's applies,
        into fresh non-donated buffers), and the blocking work hands off
        to the store as a staged deferred flush — inline by default,
        drained by the barrier coordinator's background uploader in
        pipelined mode, so the stream resumes as soon as the dispatch is
        queued. Stage waits are PURE (np.asarray of dispatched buffers,
        thread-safe); the count-dependent prefix slicing/packing happens
        in the stage continuations, which always run on the event loop.

        d2h discipline (tunneled TPU charges ~0.15-0.3s PER FETCH CALL
        regardless of size): dirty rows are compacted to the buffer
        prefix, and the whole payload — ops, vis, every column (floats
        bitcast), evict keys — ships in TWO calls (counts, then one
        packed buffer)."""
        if self.state_table is None:
            return
        from ..utils.d2h import (fetch_flat, finish_prefix_groups,
                                 prepare_prefix_groups)
        st = self.state_table
        dev_rows = n_dirty = None
        if self._applied_since_flush:
            cols, ops, vis, n_dirty = self._flush_persist_view()
            dev_rows = [ops, vis] + list(cols)
        dev_evict = n_ev = None
        if (self.cleaning_watermark_key is not None
                and self._pending_clean_wm is not None):
            # evicted groups leave the durable table in the SAME epoch their
            # device state is zeroed, so committed state stays bounded and
            # recovery never resurrects dead windows (mem-table is a dict:
            # these tombstones override any insert staged above)
            keys_dev, n_ev = self._evict_keys(self.state,
                                              self._pending_clean_wm)
            dev_evict = list(keys_dev)
        count_parts = [jnp.ravel(x) for x in (n_dirty, n_ev)
                       if x is not None]
        counts_dev = (jnp.concatenate(count_parts) if count_parts
                      else None)
        new_epoch = barrier.epoch.curr
        cell: dict = {}

        def wait_counts():
            return np.asarray(counts_dev) if counts_dev is not None else None

        def cont_prepare(counts):
            groups, i = [], 0
            cell["nd"] = cell["nev"] = 0
            if dev_rows is not None:
                cell["nd"] = int(counts[i])
                i += 1
                if cell["nd"]:
                    groups.append((dev_rows, cell["nd"]))
            if dev_evict is not None:
                cell["nev"] = int(counts[i])
                i += 1
                if cell["nev"]:
                    groups.append((dev_evict, cell["nev"]))
            if groups:
                cell["prep"] = prepare_prefix_groups(groups)

        def wait_flat():
            prep = cell.get("prep")
            return fetch_flat(prep[0]) if prep is not None else None

        def cont_apply(host_flat):
            prep = cell.get("prep")
            if prep is not None:
                outs = finish_prefix_groups(host_flat, prep[1], prep[2])
                oi = 0
                if cell["nd"]:
                    host = outs[oi]
                    oi += 1
                    st.write_chunk_columns(host[0], host[2:], host[1])
                if cell["nev"]:
                    self._apply_evict_deletes(outs[oi], cell["nev"])
            st.commit(new_epoch)

        st.store.defer_flush(barrier.epoch.prev,
                             (wait_counts, cont_prepare),
                             (wait_flat, cont_apply),
                             table_id=st.table_id)

    def _apply_evict_deletes(self, keys_np, n: int) -> None:
        width = sum(self._call_persist_width(j)
                    for j in range(len(self.specs))) + 1
        pad = (0,) * width                  # non-pk columns unused by delete
        rows = [(int(OP_DELETE), tuple(k[r].item() for k in keys_np) + pad)
                for r in range(n)]
        self.state_table.write_chunk_rows(rows)

    def _flush_persist_view(self):
        """The state rows that changed this epoch (computed pre-flush)."""
        return self._persist_view(self.state)

    def _persist_view_impl(self, st: AggState):
        # persisted row = keys ++ raw agg states ++ row_count; same
        # cumsum-compaction as the flush step. Pure in `st` so the
        # sharded subclass can run it per shard under shard_map.
        C = st.table.capacity
        exists_now = st.row_count > 0
        d_slot, n_dirty = compact_mask(st.dirty)
        is_dirty = jnp.arange(C, dtype=jnp.int32) < n_dirty
        exists = exists_now[d_slot]
        existed = st.prev_exists[d_slot]
        vis = is_dirty & (exists | existed)
        ops = jnp.where(exists, OP_INSERT, OP_DELETE).astype(jnp.int8)
        cols = self._durable_cols_at(st, d_slot)
        return cols, ops, vis, n_dirty

    def _durable_cols_at(self, st: AggState, sel: jnp.ndarray) -> list:
        """Durable-row column layout (keys ++ raw agg states ++
        row_count) gathered at `sel` — shared by the persist view and the
        memory-eviction spill pack, so spilled rows and persisted rows
        are byte-for-byte the same format."""
        cols = [tk[sel] for tk in st.table.keys]
        for j, ags in enumerate(st.agg_states):
            if self._retractable[j]:
                vals, cnts, lossy = ags
                for k in range(self.minput_k):
                    cols.append(vals[sel, k])
                for k in range(self.minput_k):
                    cols.append(cnts[sel, k].astype(jnp.int64))
                cols.append(lossy[sel].astype(jnp.int64))
            else:
                cols.append(ags[sel])
        cols.append(st.row_count[sel])
        return cols

    def _call_persist_width(self, j: int) -> int:
        """Columns one agg call contributes to the durable state row."""
        return (2 * self.minput_k + 1) if self._retractable[j] else 1

    def recover(self, barrier_epoch: int) -> None:
        """Rebuild device state from the state table (recovery path)."""
        # spilled rows are in the durable table too (eviction never
        # deletes them), so recovery rebuilds EVERYTHING resident and the
        # host spill is simply dropped
        self._spill.clear()
        if self.state_table is None:
            return
        rows = [r for _, r in self.state_table.iter_all()]
        if not rows:
            return
        # Runtime capacity growth is not persisted; size the recovery table
        # from the actual persisted row count so a post-growth crash can
        # always be recovered (ADVICE r1: a hard assert at the constructor
        # capacity made such recovery permanently fail).
        need = 1 << max(self.capacity.bit_length() - 1,
                        (int(len(rows) / 0.7)).bit_length())
        self.capacity = max(self.capacity, need)
        self.state = self._state_from_rows(rows, self.capacity)
        self._occ_known = len(rows)

    def _state_from_rows(self, rows: list, capacity: int) -> AggState:
        """One LOCAL AggState of `capacity` holding exactly `rows` (the
        durable-row layout of _flush_persist_view). The sharded subclass
        calls this per shard and concatenates along the mesh axis."""
        if not rows:
            return self._empty_state(capacity)
        nk = len(self.group_key_indices)
        key_cols = [
            jnp.asarray(np.asarray([r[j] for r in rows],
                                   dtype=np.dtype(self._key_dtypes[j])))
            for j in range(nk)]
        active = jnp.ones(len(rows), dtype=bool)
        table, slots, n_un = lookup_or_insert(
            HashTable.empty(capacity, self._key_dtypes), key_cols, active)
        assert int(n_un) == 0
        st = self._empty_state(capacity)
        agg_states = []
        off = nk
        for j, spec in enumerate(self.specs):
            if self._retractable[j]:
                K = self.minput_k
                e_vals, e_cnts, e_lossy = st.agg_states[j]
                vals = np.asarray([[r[off + k] for k in range(K)]
                                   for r in rows])
                cnts = np.asarray([[r[off + K + k] for k in range(K)]
                                   for r in rows], dtype=np.int32)
                lossy = np.asarray([bool(r[off + 2 * K]) for r in rows])
                agg_states.append((
                    e_vals.at[slots].set(
                        jnp.asarray(vals, dtype=spec.state_dtype)),
                    e_cnts.at[slots].set(jnp.asarray(cnts)),
                    e_lossy.at[slots].set(jnp.asarray(lossy)),
                ))
                off += 2 * K + 1
            else:
                vals = jnp.asarray(np.asarray([r[off] for r in rows]))
                agg_states.append(st.agg_states[j].at[slots].set(
                    vals.astype(st.agg_states[j].dtype)))
                off += 1
        counts = jnp.asarray(np.asarray([r[off] for r in rows],
                                        dtype=np.int64))
        emits = tuple(
            st.prev_emit[j].at[slots].set(
                self._call_emit(j, agg_states[j])[slots])
            for j in range(len(self.specs)))
        return AggState(
            table=table,
            agg_states=tuple(agg_states),
            row_count=st.row_count.at[slots].set(counts),
            dirty=jnp.zeros(capacity, dtype=bool),
            prev_exists=st.prev_exists.at[slots].set(True),
            prev_emit=emits,
        )

    # ---------------------------------------------------- multi-chunk apply
    def _apply_chunk_now(self, chunk: StreamChunk) -> None:
        self._mem_check_reload([chunk])
        self._apply_chunk_raw(chunk)

    def _apply_chunk_raw(self, chunk: StreamChunk) -> None:
        self.state, self._overflow_dev, self._occ_dev = self._apply(
            self.state, self._overflow_dev, chunk)
        self._applied_since_flush = True

    def _enqueue_chunk(self, chunk: StreamChunk) -> None:
        """Buffer a chunk for the batched scan apply. Output only happens
        at the barrier flush, so deferring applies to the interval end is
        observationally identical to per-chunk applies — minus k-1
        dispatches per k-chunk interval."""
        if not self._use_chunk_batching:
            self._apply_chunk_now(chunk)
            return
        p = self._pending_chunks
        if p and (p[-1].capacity != chunk.capacity
                  or jax.tree_util.tree_structure(p[-1])
                  != jax.tree_util.tree_structure(chunk)):
            # only identically-shaped chunks stack; mixed runs split
            self._drain_pending()
        self._pending_chunks.append(chunk)
        if len(self._pending_chunks) >= self._batch_max:
            self._drain_pending()

    def _drain_pending(self) -> None:
        p = self._pending_chunks
        if not p:
            return
        self._pending_chunks = []
        if len(p) == 1:
            self._apply_chunk_now(p[0])
            return
        self._mem_check_reload(p)
        # bucket the batch length to a power of two so the scan program
        # set stays tiny; filler chunks are all-invisible views of the
        # last chunk's arrays (zero-copy) and contribute nothing
        k = 1 << (len(p) - 1).bit_length()
        if k > len(p):
            last = p[-1]
            filler = StreamChunk(last.columns, last.ops,
                                 jnp.zeros(last.capacity, dtype=bool),
                                 last.schema)
            p = p + [filler] * (k - len(p))
        scan = self._apply_scans.get(k)
        if scan is None:
            scan = self._make_apply_scan(k)
            self._apply_scans[k] = scan
        self.state, self._overflow_dev, self._occ_dev = scan(
            self.state, self._overflow_dev, *p)
        self._applied_since_flush = True

    def _make_apply_scan(self, k: int):
        def scan_impl(state, overflow, *chunks):
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *chunks)

            def step(carry, chunk):
                st, ov = carry
                st, ov2, occ = self._apply_impl(st, ov, chunk)
                # the overflow counter promotes to int64 through the
                # segment sums; scan needs a dtype-stable carry
                return (st, ov2.astype(ov.dtype)), occ

            (st, ov), occs = jax.lax.scan(step, (state, overflow), stacked)
            return st, ov, occs[-1]

        return jit_state(scan_impl, donate_argnums=(0, 1),
                         name=f"hash_agg_apply_scan{k}")

    # ----------------------------------------------------------- stream
    async def execute(self):
        first = True
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                self._enqueue_chunk(msg)
            elif isinstance(msg, Barrier):
                self._drain_pending()
                if first or msg.kind is BarrierKind.INITIAL:
                    first = False
                    if self.state_table is not None:
                        self.state_table.init_epoch(msg.epoch.curr)
                        self.recover(msg.epoch.curr)
                    yield msg
                    continue
                stopping = msg.mutation is not None and msg.is_stop_any()
                # watchdog_interval=None => NO fetch ever (not even at
                # stop): on the tunneled TPU the first d2h transfer stalls
                # erratically (measured seconds to minutes after a long
                # run). Correctness in that mode rests on CPU-backend tests
                # of the same pipeline shapes + device-side zombie purges
                # below keeping occupancy bounded.
                if self.watchdog_interval and (
                        stopping or self._applied_since_flush):
                    self._check_watchdog()
                # LRU epoch stamp BEFORE the flush resets dirty (one
                # segment_max per interval; no-op while eviction is off)
                if self._mem_lru_on and self._applied_since_flush:
                    self._mem_stamp(msg.epoch.curr)
                self._persist(msg)
                flushed = self._applied_since_flush
                if flushed:
                    self._applied_since_flush = False
                    self.state, cols, ops, vis = self._flush(self.state)
                    yield StreamChunk(
                        tuple(Column(c) for c in cols), ops, vis, self.schema)
                if (self.cleaning_watermark_key is not None
                        and self._pending_clean_wm is not None):
                    self._clean_spilled(self._pending_clean_wm)
                    self.state = self._evict(self.state, self._pending_clean_wm)
                    self._pending_clean_wm = None
                    flushed = True
                    if self.watchdog_interval is None:
                        # transfer-free mode: evicted groups leave zombie
                        # slots, and without occupancy readbacks the host
                        # can never trigger a purge — so purge ON DEVICE
                        # with a same-capacity rehash (compiles once, no
                        # host roundtrip) to keep occupancy == live set.
                        self.state = self._rehash(self.state, self.capacity)
                if flushed:
                    self._maybe_rebuild_at_barrier()
                yield msg
            else:
                # watermarks on group-key columns pass through re-indexed;
                # others are consumed (reference: watermark inference)
                wm: Watermark = msg
                if wm.col_idx in self.group_key_indices:
                    pos = self.group_key_indices.index(wm.col_idx)
                    if pos == self.cleaning_watermark_key:
                        self._pending_clean_wm = wm.val
                    yield wm.with_idx(pos)

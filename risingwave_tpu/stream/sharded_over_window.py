"""Mesh-sharded over-window — PARTITION BY windows ON the mesh plane.

`GeneralOverWindowExecutor`'s dense sorted store and emitted-set diff,
sharded over the vnode mesh axis. Rows route on the PARTITION BY key,
so every window partition lives whole on one shard and the parent's
sort-and-recompute flush — partition segmentation, rank family, frame
aggregates, lag/lead gathers — runs per shard unchanged: window frames
never cross partitions, so they never cross shards either.

An EMPTY partition_by (one global partition) cannot shard this way and
stays on the single-device executor (the binder only lowers to this
executor when a partition axis exists); all the mesh plumbing — fused
per-interval shuffle+apply scan, watchdog fail-stop, replay log,
durable recovery partitioned by the same routing — comes from
sharded_store.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..common.types import Schema
from .executor import Executor
from .general_over_window import GeneralOverWindowExecutor, WindowSpec
from .sharded_store import ShardedSortedStoreMixin

__all__ = ["ShardedOverWindowExecutor", "WindowSpec"]


class ShardedOverWindowExecutor(ShardedSortedStoreMixin,
                                GeneralOverWindowExecutor):

    _SEC_COUNT = "em_n"
    _overflow_what = "sharded over-window store"

    def __init__(self, input: Executor,
                 partition_by: Sequence[int],
                 order_specs: Sequence[tuple],
                 windows: Sequence[WindowSpec],
                 capacity: int = 1 << 11,
                 state_table=None,
                 pk_indices: Optional[Sequence[int]] = None,
                 watchdog_interval: Optional[int] = 1,
                 *, mesh, mesh_shuffle: bool = True,
                 mesh_shuffle_slack: int = 0,
                 mesh_shuffle_adaptive: bool = True):
        if not partition_by:
            raise ValueError(
                "ShardedOverWindowExecutor shards along the partition "
                "axis; an OVER () window with no PARTITION BY has "
                "nothing to shard on — use GeneralOverWindowExecutor")
        super().__init__(input, partition_by, order_specs, windows,
                         capacity, state_table, pk_indices,
                         watchdog_interval)
        self.route_key_indices = self.partition_by
        self._init_sharded(mesh, mesh_shuffle, mesh_shuffle_slack,
                           mesh_shuffle_adaptive, watchdog_interval)
        self.identity = (f"ShardedOverWindow[S={self.n_shards}]"
                         f"(p={self.partition_by}, o={self.order_specs}, "
                         f"f={[w.kind for w in self.windows]})")

    def _store_schema(self):
        # the dense store (and the state table) hold INPUT rows; the
        # executor schema appends the computed window columns
        return Schema(tuple(self.schema)[:self.in_width])

    def _flush_local(self, khash, cols, valids, n, em_hash, em_cols,
                     em_valids, em_n):
        # partitions are co-located: the parent's full sort-and-diff is
        # exact on each shard's slice
        return self._flush_impl(khash, cols, valids, n, em_hash, em_cols,
                                em_valids, em_n)

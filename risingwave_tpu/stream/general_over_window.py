"""General (retraction-capable) OverWindow — window functions over a
changing input.

Reference: src/stream/src/executor/over_window/general.rs (~1100 LoC):
per-partition BTree caches, delta application, affected-range recompute,
changelog emission. The append-only fast path lives in over_window.py.

TPU re-design: the FULL input lives in the dense sorted row store
(sorted_store.py — shared with retractable TopN). At each barrier, ONE
program lexsorts live rows by (partition hash, order keys, row key),
computes every window function with segmented scans (cumsum/cummax over
partition runs — no per-partition loops), and emits the DIFF against the
previously-emitted (row ++ outputs) set by hash membership: rows whose
outputs changed produce Delete(old)/Insert(new) pairs, inserted/deleted
rows fall out of the same diff. Affected-partition tracking is
unnecessary — the full recompute is a handful of O(C) vectorized passes,
which on TPU is cheaper than managing per-partition deltas.

Window functions (WindowSpec.kind):
  row_number          1-based position within partition by order keys
  rank                ties (equal order keys) share a rank
  sum / count / avg   over UNBOUNDED PRECEDING..CURRENT ROW, or a
                      bounded frame of `preceding` rows (ROWS BETWEEN n
                      PRECEDING AND CURRENT ROW) via prefix-sum
                      differences
All functions evaluate per the ROW order; retractions upstream shift
later rows' values and the diff re-emits exactly those rows.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column, StreamChunk, OP_DELETE, OP_INSERT
from ..common.types import DataType, Field, Schema
from ..ops.hash_table import stable_lexsort
from ..ops.jit_state import jit_state
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier, Watermark
from .sorted_join import _HSENTINEL, key_hash
from .sorted_store import (GrowableSortedStore, segment_starts,
                           sorted_store_apply)


@dataclass(frozen=True)
class WindowSpec:
    """One window function call (reference: WindowFuncCall)."""

    kind: str         # row_number|rank|dense_rank|sum|count|avg|
    #                     lag|lead|first_value
    arg: Optional[int] = None       # input column (None for rank family)
    preceding: Optional[int] = None  # None = UNBOUNDED PRECEDING
    name: str = ""
    offset: int = 1                 # lag/lead row offset

    def ret_type(self, in_schema: Schema) -> DataType:
        if self.kind in ("row_number", "rank", "dense_rank", "count"):
            return DataType.INT64
        if self.kind == "avg":
            return DataType.FLOAT64
        if self.kind in ("lag", "lead", "first_value"):
            # row values pass through UNCHANGED (no promotion)
            return in_schema[self.arg].data_type
        at = in_schema[self.arg].data_type
        # sum promotes: a narrow-int running sum would silently wrap when
        # cast back (the streaming agg path promotes the same way)
        if at in (DataType.FLOAT64, DataType.FLOAT32):
            return DataType.FLOAT64
        return DataType.INT64


class GeneralOverWindowExecutor(GrowableSortedStore,
                                StatefulUnaryExecutor):
    def __init__(self, input: Executor,
                 partition_by: Sequence[int],
                 order_specs: Sequence[tuple],     # [(col, desc)]
                 windows: Sequence[WindowSpec],
                 capacity: int = 1 << 14,
                 state_table=None,
                 pk_indices: Optional[Sequence[int]] = None,
                 watchdog_interval: Optional[int] = 1):
        self.input = input
        in_schema = input.schema
        self.partition_by = tuple(partition_by)
        self.order_specs = tuple((int(c), bool(d)) for c, d in order_specs)
        self.windows = tuple(windows)
        for w in self.windows:
            assert w.kind in ("row_number", "rank", "dense_rank", "sum",
                              "count", "avg", "lag", "lead",
                              "first_value"), w
            if w.preceding is not None:
                assert w.kind in ("sum", "count", "avg"), \
                    "bounded frames support sum/count/avg"
            if w.kind in ("lag", "lead"):
                assert w.offset >= 1, "lag/lead offset must be >= 1"
        self.schema = Schema(tuple(in_schema) + tuple(
            Field(w.name or f"w{j}", w.ret_type(in_schema))
            for j, w in enumerate(self.windows)))
        self.in_width = len(in_schema)
        self.pk_indices = tuple(
            pk_indices if pk_indices is not None
            else (input.pk_indices or range(len(in_schema))))
        self.capacity = capacity
        self.identity = (f"GeneralOverWindow(p={self.partition_by}, "
                         f"o={self.order_specs}, "
                         f"f={[w.kind for w in self.windows]})")
        C = capacity
        dts = tuple(f.data_type.jnp_dtype for f in in_schema)
        self.khash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.cols = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
        self.valids = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
        self.n = jnp.int32(0)
        # previously-emitted (input ++ outputs) set for the barrier diff
        out_dts = tuple(f.data_type.jnp_dtype for f in self.schema)
        self.em_hash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.em_cols = tuple(jnp.zeros(C, dtype=dt) for dt in out_dts)
        self.em_valids = tuple(jnp.zeros(C, dtype=bool) for _ in out_dts)
        self.em_n = jnp.int32(0)
        self._errs_dev = jnp.zeros(2, dtype=jnp.int32)
        # store pytree + errs threaded (em_* is a fresh gather): donate;
        # _flush consumes/replaces the em_* previous-emission set
        self._apply = jit_state(
            partial(sorted_store_apply, pk_idx=self.pk_indices,
                    capacity=self.capacity),
            donate_argnums=(0, 1, 2, 3, 4),
            name="general_over_window_apply")
        # ONE d2h fetch per barrier: errs and the live count ride together
        self._wd_pack = jit_state(
            lambda e, n: jnp.concatenate([e, n[None].astype(jnp.int32)]),
            name="general_over_window_wd_pack")
        self._flush = jit_state(self._flush_impl,
                                donate_argnums=(4, 5, 6, 7),
                                name="general_over_window_flush")
        self._epoch_chunks: list[StreamChunk] = []
        self._init_stateful(state_table, watchdog_interval)

    # ------------------------------------------------------------- flush
    def _compute_windows(self, cols, valids, live):
        """-> (out data cols, out valid cols) for the window functions,
        aligned with the (partition, order)-sorted row order."""
        C = self.capacity
        ghash = (key_hash([cols[i] for i in self.partition_by])
                 if self.partition_by else jnp.zeros(C, dtype=jnp.int64))
        gkey = jnp.where(live, ghash, jnp.iinfo(jnp.int64).max)
        okeys = []
        for c, desc in reversed(self.order_specs):
            oval = cols[c]
            if jnp.issubdtype(oval.dtype, jnp.floating):
                okeys.append(-oval if desc else oval)
            else:
                okeys.append(~oval if desc else oval)
        # tiebreak on store position (the store is khash-sorted, so this
        # is deterministic row identity)
        order = stable_lexsort(tuple(
            [jnp.arange(C, dtype=jnp.int32)] + okeys + [gkey]))
        s_live = live[order]
        new_run, run_start = segment_starts(gkey[order])
        pos = jnp.arange(C, dtype=jnp.int32)
        idx_in_part = pos - run_start

        # tie runs: a new tie starts when the partition OR any order key
        # changes
        tie_new = new_run
        for c, _ in self.order_specs:
            sv = cols[c][order]
            tie_new = tie_new | jnp.concatenate(
                [jnp.array([True]), sv[1:] != sv[:-1]])
        tie_start = jax.lax.cummax(jnp.where(tie_new, pos, 0))
        # per-row partition END (for lead): run starts of the REVERSED
        # sorted keys are reversed run ends
        _, rev_start = segment_starts(gkey[order][::-1])
        run_end = (C - 1) - rev_start[::-1]

        outs, out_valids = [], []
        for w in self.windows:
            if w.kind == "row_number":
                outs.append((idx_in_part + 1).astype(jnp.int64))
                out_valids.append(s_live)
                continue
            if w.kind == "rank":
                outs.append((tie_start - run_start + 1).astype(jnp.int64))
                out_valids.append(s_live)
                continue
            if w.kind == "dense_rank":
                dcs = jnp.cumsum(tie_new.astype(jnp.int64))
                outs.append(dcs - dcs[run_start] + 1)
                out_valids.append(s_live)
                continue
            if w.kind in ("lag", "lead", "first_value"):
                raw = cols[w.arg][order]
                rawv = valids[w.arg][order]
                if w.kind == "first_value":
                    src = run_start
                    in_part = jnp.ones(C, dtype=bool)
                elif w.kind == "lag":
                    src = pos - w.offset
                    in_part = src >= run_start
                else:
                    src = pos + w.offset
                    in_part = src <= run_end
                srcc = jnp.clip(src, 0, C - 1)
                outs.append(raw[srcc])
                out_valids.append(s_live & in_part & rawv[srcc])
                continue
            av = cols[w.arg][order]
            avalid = valids[w.arg][order] & s_live
            if w.kind == "count":
                x = avalid.astype(jnp.int64)
            elif jnp.issubdtype(av.dtype, jnp.floating) or w.kind == "avg":
                x = jnp.where(avalid, av.astype(jnp.float64), 0.0)
            else:
                x = jnp.where(avalid, av.astype(jnp.int64), 0)
            cs = jnp.cumsum(x)
            base = cs[run_start] - x[run_start]     # exclusive @ part start
            seg = cs - base                          # inclusive within part
            if w.preceding is not None:
                # frame [j - preceding, j]: subtract the prefix ending
                # before the frame (clamped to the partition start)
                lo = pos - (w.preceding + 1)
                in_part = lo >= run_start
                lo_c = jnp.clip(lo, 0, C - 1)
                seg = seg - jnp.where(in_part, seg[lo_c], 0)
            if w.kind in ("avg", "sum"):
                cnt = jnp.cumsum(avalid.astype(jnp.int64))
                cbase = cnt[run_start] - avalid[run_start].astype(jnp.int64)
                cseg = cnt - cbase
                if w.preceding is not None:
                    lo = pos - (w.preceding + 1)
                    in_part = lo >= run_start
                    lo_c = jnp.clip(lo, 0, C - 1)
                    cseg = cseg - jnp.where(in_part, cnt[lo_c] - cbase, 0)
                if w.kind == "avg":
                    outs.append(seg / jnp.maximum(cseg, 1))
                else:
                    # sum over an all-NULL frame is NULL, not 0
                    # (ADVICE r4 #1 — count alone stays always-valid)
                    outs.append(seg)
                out_valids.append(s_live & (cseg > 0))
            else:
                outs.append(seg)
                out_valids.append(s_live)
        return order, outs, out_valids

    def _flush_impl(self, khash, cols, valids, n, em_hash, em_cols,
                    em_valids, em_n):
        C = self.capacity
        live = jnp.arange(C, dtype=jnp.int32) < n
        order, wouts, wvalids = self._compute_windows(cols, valids, live)
        s_cols = [c[order] for c in cols]
        s_valids = [v[order] for v in valids]
        out_fields = tuple(self.schema)[self.in_width:]
        full_cols = s_cols + [
            o.astype(f.data_type.jnp_dtype)
            for o, f in zip(wouts, out_fields)]
        full_valids = s_valids + list(wvalids)
        s_live = live[order]

        # identity for the diff: hash over ALL columns (floats bitcast)
        lanes = []
        for c, v in zip(full_cols, full_valids):
            x = (jax.lax.bitcast_convert_type(
                     c.astype(jnp.float64), jnp.int64)
                 if jnp.issubdtype(c.dtype, jnp.floating)
                 else c.astype(jnp.int64))
            lanes.append(jnp.where(v, x, 0))
            lanes.append(v.astype(jnp.int64))
        rhash = jnp.where(s_live, key_hash(lanes), _HSENTINEL)
        rorder = jnp.argsort(rhash, stable=True)
        new_hash = rhash[rorder]
        n_new = jnp.sum(s_live.astype(jnp.int32))
        new_cols = tuple(c[rorder] for c in full_cols)
        new_valids = tuple(v[rorder] for v in full_valids)

        def lanes_of(cols_, valids_):
            out = []
            for c, v in zip(cols_, valids_):
                # f32 upcasts before the bitcast (a 32->64 bitcast is a
                # bit-width error at trace time)
                x = (jax.lax.bitcast_convert_type(
                         c.astype(jnp.float64), jnp.int64)
                     if jnp.issubdtype(c.dtype, jnp.floating)
                     else c.astype(jnp.int64))
                out.append(jnp.where(v, x, 0))
                out.append(v.astype(jnp.int64))
            return out

        new_lanes = lanes_of(new_cols, new_valids)
        em_lanes = lanes_of(em_cols, em_valids)

        def member(a_hash, a_n, a_lanes, b_hash, b_lanes):
            # hash probe + EXACT all-lane compare (ADVICE r4 #2): a
            # collision can only cause a redundant delete+insert of an
            # identical row, never a suppressed changelog emission
            i = jnp.clip(jnp.searchsorted(b_hash, a_hash), 0, C - 1)
            same = b_hash[i] == a_hash
            for la, lb in zip(a_lanes, b_lanes):
                same = same & (lb[i] == la)
            return (jnp.arange(C) < a_n) & same

        old_still = member(em_hash, em_n, em_lanes, new_hash, new_lanes)
        emit_del = (jnp.arange(C) < em_n) & ~old_still
        new_was = member(new_hash, n_new, new_lanes, em_hash, em_lanes)
        emit_ins = (jnp.arange(C) < n_new) & ~new_was

        out_cols = tuple(
            Column(jnp.concatenate([ec, nc]), jnp.concatenate([ev, nv]))
            for ec, nc, ev, nv in zip(em_cols, new_cols, em_valids,
                                      new_valids))
        ops = jnp.concatenate([
            jnp.full(C, OP_DELETE, dtype=jnp.int8),
            jnp.full(C, OP_INSERT, dtype=jnp.int8)])
        vis = jnp.concatenate([emit_del, emit_ins])
        return (new_hash, new_cols, new_valids, n_new.astype(jnp.int32),
                out_cols, ops, vis)

    # -------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> None:
        (self.khash, self.cols, self.valids, self.n,
         self._errs_dev) = self._apply(self.khash, self.cols, self.valids,
                                       self.n, self._errs_dev, chunk)
        if self.state_table is not None:
            self._epoch_chunks.append(chunk)
        return None

    def flush(self) -> Optional[StreamChunk]:
        (self.em_hash, self.em_cols, self.em_valids, self.em_n,
         out_cols, ops, vis) = self._flush(
            self.khash, self.cols, self.valids, self.n,
            self.em_hash, self.em_cols, self.em_valids, self.em_n)
        return StreamChunk(out_cols, ops, vis, self.schema)

    def persist(self, barrier: Barrier, flushed) -> None:
        if self.state_table is None:
            return
        for c in self._epoch_chunks:
            vis = np.asarray(c.vis)
            if vis.any():
                self.state_table.write_chunk_columns(
                    np.asarray(c.ops), [np.asarray(col.data)
                                        for col in c.columns], vis)
        self._epoch_chunks = []
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        rows = [r for _, r in self.state_table.iter_all()]
        if not rows:
            return
        self._presize_for(len(rows))
        from ..state.storage_table import rows_to_columns
        in_schema = Schema(tuple(self.schema)[:self.in_width])
        cap = 1 << max(6, (len(rows) - 1).bit_length())
        for ofs in range(0, len(rows), cap):
            part = rows[ofs:ofs + cap]
            arrays, valids = rows_to_columns(in_schema, part)
            c = StreamChunk.from_numpy(
                in_schema, arrays, capacity=cap,
                valids=[None if v.all() else v for v in valids])
            (self.khash, self.cols, self.valids, self.n,
             self._errs_dev) = self._apply(self.khash, self.cols,
                                           self.valids, self.n,
                                           self._errs_dev, c)
        # seed the diff baseline (same rationale as retractable TopN):
        # the downstream materialized exactly these outputs pre-crash
        (self.em_hash, self.em_cols, self.em_valids, self.em_n,
         _c, _o, _v) = self._flush(
            self.khash, self.cols, self.valids, self.n,
            self.em_hash, self.em_cols, self.em_valids, self.em_n)

    _SECONDARY = ("em_hash", "em_cols", "em_valids")

    def check_watchdog(self) -> None:
        vals = np.asarray(self._wd_pack(self._errs_dev, self.n))
        if int(vals[0]):
            raise RuntimeError(
                f"over-window store overflow ({int(vals[0])} rows "
                f"dropped; capacity {self.capacity})")
        if int(vals[1]):
            raise RuntimeError(
                f"over-window: {int(vals[1])} deletes matched no row")
        self._maybe_grow(int(vals[2]))

    def fence_tokens(self) -> list:
        return [self.n, self.em_n] + super().fence_tokens()

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return None      # any row's outputs can change retroactively

"""Source executor.

Reference: src/stream/src/executor/source/source_executor.rs — the stream is
a select over (dedicated barrier channel, connector chunks); barriers always
win, Pause/Resume/Throttle mutations gate the connector side, and the split
offsets are committed to a state table at each checkpoint barrier
(state_table_handler.rs) so recovery reseeks the connector.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Optional, Protocol

from ..common.chunk import StreamChunk
from ..common.types import Schema
from ..state.state_table import StateTable
from .executor import Executor
from .message import Barrier, BarrierKind, ThrottleMutation


class Connector(Protocol):
    schema: Schema
    offset: int

    def next_chunk(self) -> StreamChunk: ...
    def seek(self, offset: int) -> None: ...


class SourceExecutor(Executor):
    def __init__(self, source_id: int, connector: Optional[Connector] = None,
                 barrier_queue: "asyncio.Queue[Barrier]" = None,
                 state_table: Optional[StateTable] = None,
                 rate_limit_rows_per_barrier: Optional[int] = None,
                 emit_watermarks: bool = False,
                 watermark_lag_us: int = 0,
                 max_inflight_chunks: int = 16,
                 splits: Optional[list] = None,
                 name: Optional[str] = None):
        """Single-connector form (connector=...) or split-assigned form
        (splits=[(split_id, connector), ...] — reference: the actor's
        split assignment from SourceManager)."""
        self.source_id = source_id
        # catalog name for labelled per-split series + SHOW sources
        self.source_name = name or f"src{source_id}"
        if splits is None:
            splits = [(0, connector)]
        assert splits and all(c is not None for _, c in splits)
        self.splits = list(splits)
        # (epoch, {split_id: offset}) snapshots taken at each offset
        # commit — the broker retention plane's durable-floor source
        self.offset_history: list[tuple[int, dict]] = []
        self.connector = self.splits[0][1]
        self.schema = self.connector.schema
        self.barrier_queue = barrier_queue
        self.state_table = state_table
        self.rate_limit = rate_limit_rows_per_barrier
        self.identity = f"Source({source_id})"
        self.paused = False
        # Connector-declared watermarks (reference: WATERMARK FOR clause on
        # sources + WatermarkFilterExecutor). The connector computes them on
        # host (no device readback); the source emits after each chunk.
        def has_wm(c):
            # probe through split wrappers: the wrapper defines the
            # method unconditionally, the capability lives on the inner
            return hasattr(getattr(c, "inner", c), "current_watermark")
        self.emit_watermarks = emit_watermarks and all(
            has_wm(c) for _, c in self.splits)
        # watermark lag (reference: WATERMARK FOR ts AS ts - interval):
        # downstream lookback joins/windows need rows to outlive the raw
        # event-time frontier by their window span
        self.watermark_lag_us = watermark_lag_us
        self._last_wm: Optional[int] = None
        # Device-credit flow control (reference: permit-based exchange
        # channels, executor/exchange/permit.rs — bounded records in flight).
        # JAX dispatch is asynchronous: without a bound, the host enqueues
        # device programs far ahead of execution, queue depth explodes, and
        # every downstream consistency signal (telemetry readbacks, barrier
        # collection) lags unboundedly. The TPU runs programs in submission
        # order, so "chunk N's generator output is ready" implies every
        # program enqueued before it (the whole pipeline for chunk N-1) has
        # executed: one token per emitted chunk bounds TOTAL pipeline depth.
        self.max_inflight_chunks = max_inflight_chunks
        self._tokens: deque = deque()
        # reference stream_source_output_rows_counts (streaming_stats.rs:214).
        # Semantics: host-known emitted rows — exact when the connector
        # exposes `last_chunk_rows`, padded chunk capacity otherwise (no
        # per-chunk d2h sync is allowed to count device-visible rows).
        from ..utils.metrics import GLOBAL_METRICS
        self._rows_metric = GLOBAL_METRICS.counter(
            "stream_source_output_rows_counts", source_id=str(source_id))
        # owning actor's ActorObs (stream/monitor.py): time parked on the
        # barrier queue is ALIGN wait (idle between intervals), not
        # barrier-processing work — without this the whole inter-barrier
        # idle time would be misattributed to the persist phase
        self.obs = None

    async def _get_barrier(self):
        obs = self.obs
        if obs is None:
            return await self.barrier_queue.get()
        t0 = time.monotonic_ns()
        b = await self.barrier_queue.get()
        obs.add_input_wait(time.monotonic_ns() - t0)
        return b

    async def _acquire_credit(self) -> None:
        # Block (in a worker thread, keeping the event loop live) rather
        # than poll `is_ready`: on a tunneled TPU, completion events are
        # only delivered promptly when something blocks — passive polling
        # sees them ~100s of ms late, which would gate the whole pipeline
        # to ~4 chunks/s. A blocking wait forces the flush and returns as
        # soon as the oldest in-flight chunk's pipeline has really run.
        while len(self._tokens) >= self.max_inflight_chunks:
            token = self._tokens.popleft()
            await asyncio.to_thread(token.block_until_ready)

    def _recover_offset(self) -> None:
        if self.state_table is None:
            return
        # keyed by SPLIT ID: split ids are stable across rebuilds while
        # actor ids are not (rescale/recovery reallocate them) — a
        # re-assigned split finds its committed offset wherever it lands
        # (reference: state_table_handler.rs keyed by split id)
        for sid, conn in self.splits:
            row = self.state_table.get_row((sid,))
            if row is not None:
                conn.seek(row[1])

    def _commit_offset(self, barrier: Barrier) -> None:
        self._update_split_metrics()
        if self.state_table is None:
            return
        # upsert (split_id, next_offset) per owned split; offsets ride
        # the same epoch commit as operator state => exactly-once resume
        self.state_table.write_chunk_rows(
            [(0, (sid, conn.offset)) for sid, conn in self.splits])
        self.state_table.commit(barrier.epoch.curr)
        # Committed-offset history for the broker retention plane: the
        # rows above are STAGED at barrier.epoch.prev (StateTable.commit
        # writes at the pre-advance epoch), so they are durable once the
        # store's committed epoch reaches it. The retention manager takes
        # the newest snapshot at-or-below the committed epoch — never the
        # live connector offset, which runs ahead of the checkpoint.
        self.offset_history.append(
            (barrier.epoch.prev,
             {sid: int(conn.offset) for sid, conn in self.splits}))
        del self.offset_history[:-16]

    # ------------------------------------------------- split observability
    def _update_split_metrics(self) -> None:
        """Per-split offset/lag gauges, refreshed at barrier cadence
        (host-known values only — lag reads the connector's CACHED
        broker high watermark, never an RPC on the barrier path)."""
        from ..utils.metrics import GLOBAL_METRICS
        for sid, conn in self.splits:
            GLOBAL_METRICS.gauge(
                "source_split_offset", source=self.source_name,
                split=str(sid)).set(float(conn.offset))
            lag = getattr(conn, "lag_rows", None)
            if lag is not None:
                GLOBAL_METRICS.gauge(
                    "source_lag_rows", source=self.source_name,
                    split=str(sid)).set(float(lag()))

    def remove_split_metrics(self) -> None:
        """Deployment teardown: labelled per-split series die with the
        executor (the per-actor streaming-series rule)."""
        from ..utils.metrics import GLOBAL_METRICS
        for sid, _conn in self.splits:
            GLOBAL_METRICS.remove("source_split_offset",
                                  source=self.source_name, split=str(sid))
            GLOBAL_METRICS.remove("source_lag_rows",
                                  source=self.source_name, split=str(sid))

    def split_report(self) -> list[tuple]:
        """SHOW sources rows: (split_id, offset, lag-or-None)."""
        out = []
        for sid, conn in self.splits:
            lag = getattr(conn, "lag_rows", None)
            out.append((sid, conn.offset,
                        lag() if lag is not None else None))
        return out

    def _adopt_splits(self, assigned) -> None:
        """AddSplitsMutation arrival (a barrier): take ownership of
        newly-discovered splits. A split already owned is skipped
        (mutation replay across recovery); a split with a committed
        offset resumes there (a re-assigned split finds its state
        wherever it lands, the `_recover_offset` rule). Offsets for the
        new splits commit from THIS barrier on."""
        for sid, conn in assigned:
            if any(s == sid for s, _ in self.splits):
                continue
            if self.state_table is not None:
                row = self.state_table.get_row((sid,))
                if row is not None:
                    conn.seek(row[1])
            self.splits.append((sid, conn))
            # watermark safety: the frontier is a MIN over owned splits,
            # so a split that cannot report one disables emission rather
            # than silently over-advancing it
            if self.emit_watermarks and not hasattr(
                    getattr(conn, "inner", conn), "current_watermark"):
                self.emit_watermarks = False

    async def execute(self):
        # First message is always the Initial barrier (reference: actors are
        # built, then the Add/Initial barrier arrives before any data).
        barrier = await self._get_barrier()
        if self.state_table is not None:
            self.state_table.init_epoch(barrier.epoch.curr)
        # recover on the FIRST observed barrier whatever its kind: a
        # rescale/MV-on-MV rebuild joins a running epoch stream where the
        # Initial barrier happened long ago
        self._recover_offset()
        # the first barrier can already carry mutations (a split
        # discovered between build and the first injection must not be
        # dropped — the enumerator will never re-announce it)
        self._apply_mutation(barrier)
        yield barrier

        sent_this_interval = 0
        while True:
            if self.paused:
                barrier = await self._get_barrier()
            else:
                try:
                    barrier = self.barrier_queue.get_nowait()
                except asyncio.QueueEmpty:
                    barrier = None
            if barrier is not None:
                self._apply_mutation(barrier)
                self._commit_offset(barrier)
                sent_this_interval = 0
                yield barrier
                if barrier.is_stop(self.source_id):
                    return
                continue
            if self.rate_limit is not None and sent_this_interval >= self.rate_limit:
                # throttled: wait for the next barrier
                barrier = await self._get_barrier()
                self._apply_mutation(barrier)
                self._commit_offset(barrier)
                sent_this_interval = 0
                yield barrier
                if barrier.is_stop(self.source_id):
                    return
                continue
            if all(getattr(c, "exhausted", False)
                   for _, c in self.splits):
                # finite connectors (ArrowSource): nothing to read until
                # something external appends — block on barriers instead
                # of busy-spinning empty chunks through the dataflow
                barrier = await self._get_barrier()
                self._apply_mutation(barrier)
                self._commit_offset(barrier)
                sent_this_interval = 0
                yield barrier
                if barrier.is_stop(self.source_id):
                    return
                continue
            await self._acquire_credit()
            # round-robin across owned splits (reference: the reader
            # stream interleaves its assigned splits), skipping splits
            # with nothing to read — a lagging split must not starve the
            # rest behind empty chunks (all-exhausted was handled above)
            self._rr = getattr(self, "_rr", 0)
            conn = self.splits[self._rr % len(self.splits)][1]
            self._rr += 1
            for _ in range(len(self.splits) - 1):
                if not getattr(conn, "exhausted", False):
                    break
                conn = self.splits[self._rr % len(self.splits)][1]
                self._rr += 1
            chunk = conn.next_chunk()
            self._tokens.append(chunk.columns[0].data)
            # Visible rows come from HOST knowledge only: a d2h sync per
            # chunk is forbidden in the steady state on tunneled TPUs. A
            # connector that tracks its own fill exposes `last_chunk_rows`
            # (generators fill every chunk, so capacity is exact for them);
            # otherwise padded capacity is used, which OVER-counts partial
            # chunks by their padding — the conservative direction for the
            # rate limiter, and documented in the metric name below.
            rows_host = getattr(conn, "last_chunk_rows", None)
            if rows_host is None:
                rows_host = chunk.capacity
            self._rows_metric.inc(rows_host)
            if self.rate_limit is not None:
                sent_this_interval += rows_host
            yield chunk
            if self.emit_watermarks:
                # safe frontier = MIN over owned splits (a lagging split
                # may still hold earlier rows)
                wm = min(c.current_watermark()
                         for _, c in self.splits) - self.watermark_lag_us
                if self._last_wm is None or wm > self._last_wm:
                    self._last_wm = wm
                    from ..common.types import DataType
                    from .message import Watermark
                    yield Watermark(self.splits[0][1].watermark_col,
                                    DataType.TIMESTAMP, wm)
            # let barriers/other actors in
            await asyncio.sleep(0)

    def _apply_mutation(self, barrier: Barrier) -> None:
        if barrier.is_pause():
            self.paused = True
        from .message import AddSplitsMutation, ResumeMutation
        if isinstance(barrier.mutation, ResumeMutation):
            self.paused = False
        if isinstance(barrier.mutation, ThrottleMutation):
            for actor_id, limit in barrier.mutation.limits:
                if actor_id == self.source_id:
                    self.rate_limit = limit
        if isinstance(barrier.mutation, AddSplitsMutation):
            self._adopt_splits(
                barrier.mutation.assignments.get(self.source_id, ()))

"""Retractable (Group)TopN — full-input sorted state, per-barrier diff.

Reference: src/stream/src/executor/top_n/ (top_n_cache.rs): the
retractable path persists ALL input rows so a deleted top row can be
refilled from below; the cache keeps the top-K hot. The append-only
variant lives in top_n.py; THIS executor handles retracting inputs
(e.g. TopN over an aggregation's changelog).

TPU re-design: the whole live input lives in a dense array store sorted
by a 63-bit hash of the ROW KEY (the stream key — retractions address
rows by it), maintained with the same searchsorted/merge machinery as
sorted_join.py's own-side update. Nothing data-dependent per chunk.
At each barrier the flush program:

  1. lexsorts live rows by (group hash, order key, row key) — iterated
     stable argsorts, compile-friendly;
  2. ranks rows within their group runs (cummax over run starts);
  3. selects ranks in [offset, offset+limit) as the NEW top set;
  4. diffs it against the LAST EMITTED top set by full-row hash
     membership (two searchsorteds) and emits Deletes for dropped rows
     and Inserts for new ones — refill-from-below falls out naturally:
     when a top row is retracted, rank promotion pulls the next row in
     and the diff emits it.

v1 scope: device-resident (durable TopN remains the append-only
GroupTopNExecutor; this one serves retracting inputs).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from functools import partial

from ..common.chunk import Column, StreamChunk, OP_DELETE, OP_INSERT
from ..ops.hash_table import stable_lexsort
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier, Watermark
from ..ops.jit_state import jit_state
from .sorted_join import _HSENTINEL, key_hash
from .sorted_store import GrowableSortedStore, sorted_store_apply


class RetractableTopNExecutor(GrowableSortedStore,
                              StatefulUnaryExecutor):
    """Output: the rows whose rank within their group (by order_col,
    direction) falls in [offset, offset+limit), maintained incrementally
    under inserts AND retractions."""

    def __init__(self, input: Executor,
                 group_key_indices: Sequence[int],
                 order_col=None, limit: int = 0, offset: int = 0,
                 descending: bool = False,
                 order_specs: Optional[Sequence[tuple]] = None,
                 capacity: int = 1 << 14,
                 state_table=None,
                 pk_indices: Optional[Sequence[int]] = None,
                 watchdog_interval: Optional[int] = 1):
        self.input = input
        self.schema = input.schema
        self.pk_indices = tuple(
            pk_indices if pk_indices is not None
            else (input.pk_indices or range(len(input.schema))))
        self.group_key_indices = tuple(group_key_indices)
        # order_specs: [(col, descending)] most-significant first
        # (top_n_cache.rs handles arbitrary order keys the same way);
        # (order_col, descending) kept as the single-key shorthand
        if order_specs is None:
            assert order_col is not None
            order_specs = [(order_col, descending)]
        self.order_specs = tuple((int(c), bool(d)) for c, d in order_specs)
        self.limit = limit
        self.offset = offset
        self.capacity = capacity
        self.identity = (f"RetractTopN(g={self.group_key_indices}, "
                         f"by={self.order_specs}, k={limit})")
        C = capacity
        dts = tuple(f.data_type.jnp_dtype for f in input.schema)
        self._col_dtypes = dts
        # dense store sorted by row-key hash
        self.khash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.cols = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
        self.valids = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
        self.n = jnp.int32(0)
        # last emitted top set, as a sorted array of full-row hashes plus
        # the row payloads (for emitting deletes)
        self.top_hash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.top_cols = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
        self.top_valids = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
        self.top_n = jnp.int32(0)
        self._errs_dev = jnp.zeros(2, dtype=jnp.int32)  # [row_ovf, del_miss]
        # the dense store pytree (khash, cols, valids, n) + errs is
        # threaded and aliased nowhere (the emitted top set is a fresh
        # gather): donate. _flush consumes/replaces the top_* triplet.
        self._apply = jit_state(
            partial(sorted_store_apply, pk_idx=self.pk_indices,
                    capacity=self.capacity),
            donate_argnums=(0, 1, 2, 3, 4), name="retract_top_n_apply")
        # ONE d2h fetch per barrier: errs and the live count ride together
        self._wd_pack = jit_state(
            lambda e, n: jnp.concatenate([e, n[None].astype(jnp.int32)]),
            name="retract_top_n_wd_pack")
        self._flush = jit_state(self._flush_impl,
                                donate_argnums=(4, 5, 6, 7),
                                name="retract_top_n_flush")
        # durability: the state table materializes the FULL input row set
        # keyed by the stream key (the reference's TopN state table holds
        # all input rows too, top_n_state.rs); each epoch's buffered
        # chunks apply to it at the barrier, recovery re-inserts them
        self._epoch_chunks: list[StreamChunk] = []
        self._init_stateful(state_table, watchdog_interval)

    # ------------------------------------------------------------- flush
    def _flush_impl(self, khash, cols, valids, n, top_hash, top_cols,
                    top_valids, top_n):
        """Compute the new top set, diff vs the last emitted one."""
        C = self.capacity
        live = jnp.arange(C, dtype=jnp.int32) < n
        ghash = (key_hash([cols[i] for i in self.group_key_indices])
                 if self.group_key_indices
                 else jnp.zeros(C, dtype=jnp.int64))
        # order keys least-significant first for the lexsort; DESC via
        # bitwise complement (overflow-free order reversal on ints)
        okeys = []
        for c, desc in reversed(self.order_specs):
            oval = cols[c]
            if jnp.issubdtype(oval.dtype, jnp.floating):
                okeys.append(-oval if desc else oval)
            else:
                # bitwise complement reverses int order overflow-free
                okeys.append(~oval if desc else oval)
        # sort live rows by (group, order..., row hash); dead rows last
        order = stable_lexsort(tuple(
            [khash] + okeys
            + [jnp.where(live, ghash, jnp.iinfo(jnp.int64).max)]))
        s_g = jnp.where(live, ghash, jnp.iinfo(jnp.int64).max)[order]
        new_run = jnp.concatenate([jnp.array([True]),
                                   s_g[1:] != s_g[:-1]])
        pos = jnp.arange(C, dtype=jnp.int32)
        run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
        rank = pos - run_start
        s_live = live[order]
        in_top = s_live & (rank >= self.offset) & (
            rank < self.offset + self.limit)
        # full-row hash identifies a row across top sets
        s_cols = [c[order] for c in cols]
        rhash = key_hash(s_cols)
        topk = jnp.where(in_top, rhash, _HSENTINEL)
        torder = jnp.argsort(topk, stable=True)
        new_hash = topk[torder]
        n_top = jnp.sum(in_top.astype(jnp.int32))
        new_cols = tuple(c[torder] for c in s_cols)
        new_valids = tuple(v[order][torder] for v in valids)

        # membership diffs via searchsorted (hashes are sorted arrays)
        def member(a_hash, a_n, b_hash):
            i = jnp.searchsorted(b_hash, a_hash)
            i = jnp.clip(i, 0, C - 1)
            return (jnp.arange(C) < a_n) & (b_hash[i] == a_hash)

        old_still = member(top_hash, top_n, new_hash)   # in both
        emit_del = (jnp.arange(C) < top_n) & ~old_still
        new_was = member(new_hash, n_top, top_hash)
        emit_ins = (jnp.arange(C) < n_top) & ~new_was

        out_cols = tuple(
            Column(jnp.concatenate([tc, nc]),
                   jnp.concatenate([tv, nv]))
            for tc, nc, tv, nv in zip(top_cols, new_cols, top_valids,
                                      new_valids))
        ops = jnp.concatenate([
            jnp.full(C, OP_DELETE, dtype=jnp.int8),
            jnp.full(C, OP_INSERT, dtype=jnp.int8)])
        vis = jnp.concatenate([emit_del, emit_ins])
        return (new_hash, new_cols, new_valids, n_top.astype(jnp.int32),
                out_cols, ops, vis)

    # -------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> None:
        (self.khash, self.cols, self.valids, self.n,
         self._errs_dev) = self._apply(self.khash, self.cols, self.valids,
                                       self.n, self._errs_dev, chunk)
        if self.state_table is not None:
            self._epoch_chunks.append(chunk)
        return None

    def persist(self, barrier: Barrier, flushed) -> None:
        if self.state_table is None:
            return
        for c in self._epoch_chunks:
            vis = np.asarray(c.vis)
            if vis.any():
                self.state_table.write_chunk_columns(
                    np.asarray(c.ops), [np.asarray(col.data)
                                        for col in c.columns], vis)
        self._epoch_chunks = []
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        rows = [r for _, r in self.state_table.iter_all()]
        if not rows:
            return
        self._presize_for(len(rows))
        from ..state.storage_table import rows_to_columns
        cap = 1 << max(6, (len(rows) - 1).bit_length())
        for ofs in range(0, len(rows), cap):
            part = rows[ofs:ofs + cap]
            arrays, valids = rows_to_columns(self.schema, part)
            c = StreamChunk.from_numpy(
                self.schema, arrays, capacity=cap,
                valids=[None if v.all() else v for v in valids])
            (self.khash, self.cols, self.valids, self.n,
             self._errs_dev) = self._apply(self.khash, self.cols,
                                           self.valids, self.n,
                                           self._errs_dev, c)
        # Seed the diff BASELINE: the downstream MV materialized exactly
        # the top set of this recovered (checkpoint-consistent) store, so
        # compute it once and DISCARD the output — the next real flush
        # then emits only genuine changes. Without this, rows that left
        # the top set across the rebuild would never receive a Delete
        # (re-emitting inserts is idempotent; omitted deletes are not).
        (self.top_hash, self.top_cols, self.top_valids, self.top_n,
         _c, _o, _v) = self._flush(
            self.khash, self.cols, self.valids, self.n,
            self.top_hash, self.top_cols, self.top_valids, self.top_n)

    def flush(self) -> Optional[StreamChunk]:
        (self.top_hash, self.top_cols, self.top_valids, self.top_n,
         out_cols, ops, vis) = self._flush(
            self.khash, self.cols, self.valids, self.n,
            self.top_hash, self.top_cols, self.top_valids, self.top_n)
        return StreamChunk(out_cols, ops, vis, self.schema)

    _SECONDARY = ("top_hash", "top_cols", "top_valids")

    def check_watchdog(self) -> None:
        vals = np.asarray(self._wd_pack(self._errs_dev, self.n))
        if int(vals[0]):
            raise RuntimeError(
                f"retractable TopN overflow ({int(vals[0])} rows dropped; "
                f"capacity {self.capacity})")
        if int(vals[1]):
            raise RuntimeError(
                f"retractable TopN: {int(vals[1])} deletes matched no row")
        self._maybe_grow(int(vals[2]))

    def fence_tokens(self) -> list:
        return [self.n, self.top_n] + super().fence_tokens()

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return None          # ranks can change; no watermark survives

"""Retractable (Group)TopN — full-input sorted state, per-barrier diff.

Reference: src/stream/src/executor/top_n/ (top_n_cache.rs): the
retractable path persists ALL input rows so a deleted top row can be
refilled from below; the cache keeps the top-K hot. The append-only
variant lives in top_n.py; THIS executor handles retracting inputs
(e.g. TopN over an aggregation's changelog).

TPU re-design: the whole live input lives in a dense array store sorted
by a 63-bit hash of the ROW KEY (the stream key — retractions address
rows by it), maintained with the same searchsorted/merge machinery as
sorted_join.py's own-side update. Nothing data-dependent per chunk.
At each barrier the flush program:

  1. lexsorts live rows by (group hash, order key, row key) — iterated
     stable argsorts, compile-friendly;
  2. ranks rows within their group runs (cummax over run starts);
  3. selects ranks in [offset, offset+limit) as the NEW top set;
  4. diffs it against the LAST EMITTED top set by full-row hash
     membership (two searchsorteds) and emits Deletes for dropped rows
     and Inserts for new ones — refill-from-below falls out naturally:
     when a top row is retracted, rank promotion pulls the next row in
     and the diff emits it.

v1 scope: device-resident (durable TopN remains the append-only
GroupTopNExecutor; this one serves retracting inputs).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column, StreamChunk, OP_DELETE, OP_INSERT, op_sign
from ..ops.hash_table import stable_lexsort
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier, Watermark
from .sorted_join import _HSENTINEL, _count_le, key_hash


class RetractableTopNExecutor(StatefulUnaryExecutor):
    """Output: the rows whose rank within their group (by order_col,
    direction) falls in [offset, offset+limit), maintained incrementally
    under inserts AND retractions."""

    def __init__(self, input: Executor,
                 group_key_indices: Sequence[int],
                 order_col: int, limit: int, offset: int = 0,
                 descending: bool = False,
                 capacity: int = 1 << 14,
                 state_table=None,
                 pk_indices: Optional[Sequence[int]] = None,
                 watchdog_interval: Optional[int] = 1):
        self.input = input
        self.schema = input.schema
        self.pk_indices = tuple(
            pk_indices if pk_indices is not None
            else (input.pk_indices or range(len(input.schema))))
        self.group_key_indices = tuple(group_key_indices)
        self.order_col = order_col
        self.limit = limit
        self.offset = offset
        self.descending = descending
        self.capacity = capacity
        self.identity = (f"RetractTopN(g={self.group_key_indices}, "
                         f"by={order_col}, k={limit})")
        C = capacity
        dts = tuple(f.data_type.jnp_dtype for f in input.schema)
        self._col_dtypes = dts
        # dense store sorted by row-key hash
        self.khash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.cols = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
        self.valids = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
        self.n = jnp.int32(0)
        # last emitted top set, as a sorted array of full-row hashes plus
        # the row payloads (for emitting deletes)
        self.top_hash = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        self.top_cols = tuple(jnp.zeros(C, dtype=dt) for dt in dts)
        self.top_valids = tuple(jnp.zeros(C, dtype=bool) for _ in dts)
        self.top_n = jnp.int32(0)
        self._errs_dev = jnp.zeros(2, dtype=jnp.int32)  # [row_ovf, del_miss]
        self._apply = jax.jit(self._apply_impl)
        self._flush = jax.jit(self._flush_impl)
        # durability: the state table materializes the FULL input row set
        # keyed by the stream key (the reference's TopN state table holds
        # all input rows too, top_n_state.rs); each epoch's buffered
        # chunks apply to it at the barrier, recovery re-inserts them
        self._epoch_chunks: list[StreamChunk] = []
        self._init_stateful(state_table, watchdog_interval)

    # ------------------------------------------------------------- apply
    def _apply_impl(self, khash, cols, valids, n, errs, chunk: StreamChunk):
        """Insert/retract chunk rows into the sorted dense store (the
        own-side update of sorted_join._apply_impl, sans probe)."""
        N = chunk.capacity
        C = self.capacity
        pk_idx = self.pk_indices
        active = chunk.vis
        signs = op_sign(chunk.ops)
        row_ids = jnp.arange(N, dtype=jnp.int32)
        h = key_hash([chunk.columns[i].data for i in pk_idx])

        # within-chunk pk-run netting (sorted_join semantics)
        sort_keys = [row_ids]
        for p in pk_idx:
            sort_keys.append(chunk.columns[p].data)
        sort_keys.append(~active)
        order = stable_lexsort(tuple(sort_keys))
        s_act = active[order]
        same = s_act[1:] & s_act[:-1]
        for p in pk_idx:
            d = chunk.columns[p].data[order]
            same = same & (d[1:] == d[:-1])
        run_start = jnp.concatenate([jnp.array([True]), ~same])
        run_end = jnp.concatenate([~same, jnp.array([True])])
        s_signs = signs[order]
        is_del = jnp.zeros(N, dtype=bool).at[order].set(
            run_start & (s_signs < 0) & s_act)
        is_ins = jnp.zeros(N, dtype=bool).at[order].set(
            run_end & (s_signs > 0) & s_act)

        live = jnp.arange(C, dtype=jnp.int32) < n
        keep = live
        # deletes: exact (hash, pk) match
        dlo = jnp.searchsorted(khash, h, side="left").astype(jnp.int32)
        dhi = jnp.searchsorted(khash, h, side="right").astype(jnp.int32)
        M = 2 * N
        dlens = jnp.where(is_del, (dhi - dlo).astype(jnp.int64), 0)
        doffs = jnp.cumsum(dlens)
        dtot = doffs[N - 1]
        j = jnp.arange(M, dtype=jnp.int64)
        dsrc = jnp.searchsorted(doffs, j, side="right").astype(jnp.int32)
        dsrcc = jnp.clip(dsrc, 0, N - 1)
        dprev = jnp.where(dsrcc > 0, doffs[jnp.clip(dsrcc - 1, 0)], 0)
        dpos = jnp.clip(dlo[dsrcc] + (j - dprev), 0, C - 1).astype(jnp.int32)
        cand = (j < jnp.minimum(dtot, M)) & keep[dpos]
        for p in pk_idx:
            cand &= (cols[p][dpos]
                     == chunk.columns[p].data[dsrcc].astype(cols[p].dtype))
        victim = jnp.full(N, C, dtype=jnp.int32).at[
            jnp.where(cand, dsrcc, N)].min(dpos, mode="drop")
        found = victim < C
        keep = keep.at[jnp.where(found, victim, C)].set(False, mode="drop")
        n_del_miss = jnp.sum((is_del & ~found).astype(jnp.int32))

        # merge inserts (stable, state rows before equal-hash new rows)
        ins_h = jnp.where(is_ins, h, _HSENTINEL)
        iorder = jnp.argsort(ins_h, stable=True)
        nh = ins_h[iorder]
        n_new = jnp.sum(is_ins.astype(jnp.int32))
        dead_cum = jnp.cumsum((~keep).astype(jnp.int32))
        kept_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
        n_kept = kept_rank[C - 1] + 1
        new_lt = jnp.searchsorted(nh, khash, side="left").astype(jnp.int32)
        pos_t = kept_rank + new_lt
        kept_le = _count_le(khash, dead_cum, nh, side="right")
        rr = jnp.arange(N, dtype=jnp.int32)
        pos_r = rr + kept_le
        new_ok = rr < n_new
        n_after = n_kept + n_new
        n_row_overflow = jnp.maximum(n_after - C, 0)
        n_after = jnp.minimum(n_after, C)
        tgt_t = jnp.where(keep & (pos_t < C), pos_t, C)
        tgt_r = jnp.where(new_ok & (pos_r < C), pos_r, C)
        kh2 = jnp.full(C, _HSENTINEL, dtype=jnp.int64)
        kh2 = kh2.at[tgt_t].set(khash, mode="drop")
        kh2 = kh2.at[tgt_r].set(nh, mode="drop")
        cols2, valids2 = [], []
        for ci, (sc, sv) in enumerate(zip(cols, valids)):
            col = chunk.columns[ci]
            c2 = jnp.zeros(C, dtype=sc.dtype).at[tgt_t].set(sc, mode="drop")
            c2 = c2.at[tgt_r].set(col.data[iorder].astype(sc.dtype),
                                  mode="drop")
            v2 = jnp.zeros(C, dtype=bool).at[tgt_t].set(sv, mode="drop")
            v2 = v2.at[tgt_r].set(col.valid_mask()[iorder], mode="drop")
            cols2.append(c2)
            valids2.append(v2)
        errs = errs + jnp.stack([n_row_overflow, n_del_miss]).astype(
            jnp.int32)
        return (kh2, tuple(cols2), tuple(valids2),
                n_after.astype(jnp.int32), errs)

    # ------------------------------------------------------------- flush
    def _flush_impl(self, khash, cols, valids, n, top_hash, top_cols,
                    top_valids, top_n):
        """Compute the new top set, diff vs the last emitted one."""
        C = self.capacity
        live = jnp.arange(C, dtype=jnp.int32) < n
        ghash = (key_hash([cols[i] for i in self.group_key_indices])
                 if self.group_key_indices
                 else jnp.zeros(C, dtype=jnp.int64))
        oval = cols[self.order_col]
        okey = -oval if self.descending else oval
        # sort live rows by (group, order, row hash); dead rows last
        order = stable_lexsort((khash, okey,
                                jnp.where(live, ghash, jnp.iinfo(
                                    jnp.int64).max)))
        s_g = jnp.where(live, ghash, jnp.iinfo(jnp.int64).max)[order]
        new_run = jnp.concatenate([jnp.array([True]),
                                   s_g[1:] != s_g[:-1]])
        pos = jnp.arange(C, dtype=jnp.int32)
        run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
        rank = pos - run_start
        s_live = live[order]
        in_top = s_live & (rank >= self.offset) & (
            rank < self.offset + self.limit)
        # full-row hash identifies a row across top sets
        s_cols = [c[order] for c in cols]
        rhash = key_hash(s_cols)
        topk = jnp.where(in_top, rhash, _HSENTINEL)
        torder = jnp.argsort(topk, stable=True)
        new_hash = topk[torder]
        n_top = jnp.sum(in_top.astype(jnp.int32))
        new_cols = tuple(c[torder] for c in s_cols)
        new_valids = tuple(v[order][torder] for v in valids)

        # membership diffs via searchsorted (hashes are sorted arrays)
        def member(a_hash, a_n, b_hash):
            i = jnp.searchsorted(b_hash, a_hash)
            i = jnp.clip(i, 0, C - 1)
            return (jnp.arange(C) < a_n) & (b_hash[i] == a_hash)

        old_still = member(top_hash, top_n, new_hash)   # in both
        emit_del = (jnp.arange(C) < top_n) & ~old_still
        new_was = member(new_hash, n_top, top_hash)
        emit_ins = (jnp.arange(C) < n_top) & ~new_was

        out_cols = tuple(
            Column(jnp.concatenate([tc, nc]),
                   jnp.concatenate([tv, nv]))
            for tc, nc, tv, nv in zip(top_cols, new_cols, top_valids,
                                      new_valids))
        ops = jnp.concatenate([
            jnp.full(C, OP_DELETE, dtype=jnp.int8),
            jnp.full(C, OP_INSERT, dtype=jnp.int8)])
        vis = jnp.concatenate([emit_del, emit_ins])
        return (new_hash, new_cols, new_valids, n_top.astype(jnp.int32),
                out_cols, ops, vis)

    # -------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> None:
        (self.khash, self.cols, self.valids, self.n,
         self._errs_dev) = self._apply(self.khash, self.cols, self.valids,
                                       self.n, self._errs_dev, chunk)
        if self.state_table is not None:
            self._epoch_chunks.append(chunk)
        return None

    def persist(self, barrier: Barrier, flushed) -> None:
        if self.state_table is None:
            return
        for c in self._epoch_chunks:
            vis = np.asarray(c.vis)
            if vis.any():
                self.state_table.write_chunk_columns(
                    np.asarray(c.ops), [np.asarray(col.data)
                                        for col in c.columns], vis)
        self._epoch_chunks = []
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        rows = [r for _, r in self.state_table.iter_all()]
        if not rows:
            return
        from ..state.storage_table import rows_to_columns
        cap = 1 << max(6, (len(rows) - 1).bit_length())
        for ofs in range(0, len(rows), cap):
            part = rows[ofs:ofs + cap]
            arrays, valids = rows_to_columns(self.schema, part)
            c = StreamChunk.from_numpy(
                self.schema, arrays, capacity=cap,
                valids=[None if v.all() else v for v in valids])
            (self.khash, self.cols, self.valids, self.n,
             self._errs_dev) = self._apply(self.khash, self.cols,
                                           self.valids, self.n,
                                           self._errs_dev, c)
        # Seed the diff BASELINE: the downstream MV materialized exactly
        # the top set of this recovered (checkpoint-consistent) store, so
        # compute it once and DISCARD the output — the next real flush
        # then emits only genuine changes. Without this, rows that left
        # the top set across the rebuild would never receive a Delete
        # (re-emitting inserts is idempotent; omitted deletes are not).
        (self.top_hash, self.top_cols, self.top_valids, self.top_n,
         _c, _o, _v) = self._flush(
            self.khash, self.cols, self.valids, self.n,
            self.top_hash, self.top_cols, self.top_valids, self.top_n)

    def flush(self) -> Optional[StreamChunk]:
        (self.top_hash, self.top_cols, self.top_valids, self.top_n,
         out_cols, ops, vis) = self._flush(
            self.khash, self.cols, self.valids, self.n,
            self.top_hash, self.top_cols, self.top_valids, self.top_n)
        return StreamChunk(out_cols, ops, vis, self.schema)

    def check_watchdog(self) -> None:
        vals = np.asarray(self._errs_dev)
        if int(vals[0]):
            raise RuntimeError(
                f"retractable TopN overflow ({int(vals[0])} rows dropped; "
                f"capacity {self.capacity})")
        if int(vals[1]):
            raise RuntimeError(
                f"retractable TopN: {int(vals[1])} deletes matched no row")

    def fence_tokens(self) -> list:
        return [self.n, self.top_n] + super().fence_tokens()

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return None          # ranks can change; no watermark survives

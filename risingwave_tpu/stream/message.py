"""Stream messages: Chunk | Barrier | Watermark.

Reference: src/stream/src/executor/mod.rs:228-406 (Barrier, Mutation),
:690-762 (Watermark), :765-833 (Message). Barriers carry ALL reconfiguration
(scale, new jobs, pause/resume, throttle) as mutations — configuration changes
ride the data stream so they are totally ordered with data, which is the
property that makes elastic scaling exactly-once. The TPU build keeps that
protocol verbatim on the host control plane; only chunk *processing* moved to
device.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Union

from ..common.chunk import StreamChunk
from ..common.epoch import EpochPair
from ..common.types import DataType


class BarrierKind(enum.Enum):
    INITIAL = "initial"        # first barrier after (re)start; no prev state
    BARRIER = "barrier"        # pace-keeping, no durability
    CHECKPOINT = "checkpoint"  # state must be synced durable at this epoch


# --- mutations (reference Mutation enum, executor/mod.rs:245-280) ---------

@dataclass(frozen=True)
class StopMutation:
    actor_ids: frozenset[int]


@dataclass(frozen=True)
class PauseMutation:
    pass


@dataclass(frozen=True)
class ResumeMutation:
    pass


@dataclass(frozen=True)
class ThrottleMutation:
    # actor id -> rows/sec limit (None lifts the limit)
    limits: tuple[tuple[int, Optional[int]], ...]


@dataclass(frozen=True)
class AddMutation:
    """New downstream actors added (CREATE MV); may pause the sources."""
    added_actor_ids: frozenset[int] = frozenset()
    pause: bool = False


@dataclass(frozen=True)
class UpdateMutation:
    """Reschedule: vnode bitmap changes per actor (elastic scaling)."""
    # actor id -> new vnode bitmap (numpy bool[256] as tuple for hashability)
    vnode_bitmaps: tuple[tuple[int, Any], ...] = ()
    dropped_actors: frozenset[int] = frozenset()


@dataclass(frozen=True)
class AddSplitsMutation:
    """Split discovery (reference: SourceManager split assignment riding
    a barrier, source_manager.rs): newly-discovered source splits reach
    their assigned actors totally ordered with data — the actor adopts
    them at barrier receipt and commits their offsets from the SAME
    barrier on. In-process only (live connector objects ride along;
    cluster deploys reject discovery-managed sources in v1)."""
    # source actor id -> ((split_id, connector), ...)
    assignments: dict = field(default_factory=dict)


Mutation = Union[StopMutation, PauseMutation, ResumeMutation,
                 ThrottleMutation, AddMutation, UpdateMutation,
                 AddSplitsMutation]


@dataclass(frozen=True)
class Barrier:
    epoch: EpochPair
    kind: BarrierKind = BarrierKind.CHECKPOINT
    mutation: Optional[Mutation] = None
    passed_actors: tuple[int, ...] = ()
    # host wall-clock when meta injected it (barrier-latency metric source)
    inject_time_ns: int = 0

    @property
    def is_checkpoint(self) -> bool:
        return self.kind is BarrierKind.CHECKPOINT

    def is_stop(self, actor_id: int) -> bool:
        return isinstance(self.mutation, StopMutation) and actor_id in self.mutation.actor_ids

    def is_stop_any(self) -> bool:
        """True for any Stop mutation regardless of target actor — used by
        executors (which don't know their actor id) for end-of-life work."""
        return isinstance(self.mutation, StopMutation)

    def is_pause(self) -> bool:
        return isinstance(self.mutation, PauseMutation) or (
            isinstance(self.mutation, AddMutation) and self.mutation.pause)

    def with_passed(self, actor_id: int) -> "Barrier":
        return Barrier(self.epoch, self.kind, self.mutation,
                       self.passed_actors + (actor_id,), self.inject_time_ns)


@dataclass(frozen=True)
class Watermark:
    """Monotonic lower bound: no future row has col < val
    (reference executor/mod.rs:690)."""
    col_idx: int
    data_type: DataType
    val: Any

    def with_idx(self, idx: int) -> "Watermark":
        return Watermark(idx, self.data_type, self.val)


Message = Union[StreamChunk, Barrier, Watermark]


def is_chunk(m: Message) -> bool:
    return isinstance(m, StreamChunk)

"""Sort executor (emit-on-window-close) — watermark-driven buffer flush.

Reference: src/stream/src/executor/sort.rs + sort_buffer.rs — rows buffer
in a state table keyed by the sort (event-time) column; when the watermark
advances, all rows with sort_key <= watermark are emitted IN ORDER and
deleted from the buffer. This is the EOWC building block (append-only
output, late rows already filtered by the upstream watermark filter).

TPU re-design: the buffer is a fixed-capacity device row store (columns
[C] + live mask). Appending a chunk is one jitted compaction-scatter; the
watermark flush is a second jitted step that selects ripe rows, sorts them
by the sort key, emits them as an ordered chunk, and compacts the
survivors to the front. Overflow (buffer full) is counted on device and
fail-stopped at the barrier, like every bounded structure here.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column, StreamChunk, OP_INSERT, op_sign
from ..ops.hash_table import stable_lexsort
from ..state.state_table import StateTable
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier, Watermark
from ..ops.jit_state import jit_state


class SortExecutor(StatefulUnaryExecutor):
    """Append-only EOWC sort on an int-comparable column."""

    def __init__(self, input: Executor, sort_col: int,
                 capacity: int = 1 << 14,
                 state_table: Optional[StateTable] = None,
                 watchdog_interval: Optional[int] = 1):
        self.input = input
        self.schema = input.schema
        self.pk_indices = input.pk_indices
        self.sort_col = sort_col
        self.capacity = capacity
        self.identity = f"Sort(col={sort_col}, eowc)"
        self._col_dtypes = tuple(f.data_type.jnp_dtype for f in self.schema)
        C = capacity
        self.rows = tuple(jnp.zeros(C, dtype=dt) for dt in self._col_dtypes)
        self.live = jnp.zeros(C, dtype=bool)
        self._pending_wm: Optional[int] = None
        # buffer arrays + errs are threaded and re-bound at both call
        # sites; nothing aliases them between steps: donate
        self._append = jit_state(self._append_impl,
                                 donate_argnums=(0, 1, 2),
                                 name="sort_append")
        self._flush_ripe = jit_state(self._flush_ripe_impl,
                                     donate_argnums=(0, 1),
                                     name="sort_flush_ripe")
        self._errs_dev = jnp.zeros((), dtype=jnp.int32)
        self._init_stateful(state_table, watchdog_interval)

    def fence_tokens(self) -> list:
        return [self.live] + super().fence_tokens()

    # --------------------------------------------------------------- steps
    def _append_impl(self, rows, live, errs, chunk: StreamChunk):
        C = self.capacity
        act = chunk.vis & (op_sign(chunk.ops) > 0)
        n_viol = jnp.sum((chunk.vis & (op_sign(chunk.ops) < 0))
                         .astype(jnp.int32))
        # free slots compacted: rank free slots and incoming rows
        free_rank = jnp.cumsum((~live).astype(jnp.int32)) - 1
        slot_of_rank = jnp.zeros(C, dtype=jnp.int32).at[
            jnp.where(~live, free_rank, C)].set(
                jnp.arange(C, dtype=jnp.int32), mode="drop")
        in_rank = jnp.cumsum(act.astype(jnp.int32)) - 1
        n_free = jnp.sum((~live).astype(jnp.int32))
        ok = act & (in_rank < n_free)
        n_over = jnp.sum(act.astype(jnp.int32)) - jnp.sum(
            ok.astype(jnp.int32))
        tgt = jnp.where(ok, slot_of_rank[jnp.clip(in_rank, 0, C - 1)], C)
        new_rows = tuple(
            r.at[tgt].set(c.data.astype(r.dtype), mode="drop")
            for r, c in zip(rows, chunk.columns))
        new_live = live.at[tgt].set(True, mode="drop")
        return new_rows, new_live, errs + n_viol + n_over

    def _flush_ripe_impl(self, rows, live, wm):
        """Emit rows with sort_key <= wm in sort order; keep the rest."""
        C = self.capacity
        key = rows[self.sort_col]
        ripe = live & (key <= wm)
        # order ripe rows by key (stable), invalid last
        order = stable_lexsort((jnp.arange(C), key, ~ripe))
        out_cols = tuple(r[order] for r in rows)
        out_vis = ripe[order]
        keep = live & ~ripe
        return out_cols, out_vis, rows, keep

    # --------------------------------------------------------------- hooks
    def map_watermark(self, wm: Watermark):
        if wm.col_idx == self.sort_col:
            self._pending_wm = wm.val
            # a watermark alone ripens buffered rows (e.g. right after
            # recovery): force the barrier flush even with no new chunks
            self._applied_since_flush = True
            return wm
        return None

    def check_watchdog(self) -> None:
        n = int(np.asarray(self._errs_dev))
        if n:
            raise RuntimeError(
                f"sort buffer overflow or append-only violation ({n} "
                f"rows, capacity {self.capacity})")

    def flush(self) -> Optional[StreamChunk]:
        if self._pending_wm is None:
            return None
        wm = self._pending_wm
        self._pending_wm = None
        cols, vis, self.rows, self.live = self._flush_ripe(
            self.rows, self.live, wm)
        ops = jnp.full(self.capacity, OP_INSERT, dtype=jnp.int8)
        return StreamChunk(tuple(Column(c) for c in cols), ops, vis,
                           self.schema)

    def on_chunk(self, chunk: StreamChunk):
        self.rows, self.live, self._errs_dev = self._append(
            self.rows, self.live, self._errs_dev, chunk)
        self._dirty_persist = True
        return None

    def persist(self, barrier: Barrier, flushed) -> None:
        if self.state_table is None:
            return
        if getattr(self, "_dirty_persist", False) or flushed is not None:
            self._dirty_persist = False
            # snapshot the live buffer through the columnar batch path
            # (native codec for all-int64 schemas — same hot path as
            # hash_agg persistence)
            cols = [np.asarray(r) for r in self.rows]
            ops = np.zeros(self.capacity, dtype=np.int8)  # OP_INSERT
            self.state_table.write_chunk_columns(
                ops, cols, np.asarray(self.live))
            if flushed is not None:
                # tombstone rows flushed out this epoch
                del_ops = np.ones(flushed.capacity, dtype=np.int8)
                self.state_table.write_chunk_columns(
                    del_ops, [np.asarray(c.data) for c in flushed.columns],
                    np.asarray(flushed.vis))
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        rows = [row for _, row in self.state_table.iter_all()]
        if not rows:
            return
        cap = max(64, 1 << int(np.ceil(np.log2(len(rows) + 1))))
        n = len(rows)
        vis = np.arange(cap) < n
        arrays = [np.resize(np.asarray([r[j] for r in rows]), cap)
                  for j in range(len(self._col_dtypes))]
        chunk = StreamChunk(
            tuple(Column(jnp.asarray(a)) for a in arrays),
            jnp.full(cap, OP_INSERT, dtype=jnp.int8),
            jnp.asarray(vis), self.schema)
        self.on_chunk(chunk)
        self._applied_since_flush = False

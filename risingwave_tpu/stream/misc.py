"""Small stream executors: Values, Union, Expand, NoOp, FlowControl,
WatermarkFilter.

Reference: src/stream/src/executor/{values.rs, union.rs, expand.rs,
no_op.rs, flow_control.rs, watermark_filter.rs}.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column, StreamChunk, OP_INSERT
from ..common.types import DataType, Schema
from ..state.state_table import StateTable
from .exchange import Channel, MergeExecutor
from .executor import Executor, StatelessUnaryExecutor
from .message import Barrier, BarrierKind, Watermark
from ..ops.jit_state import jit_state


class ValuesExecutor(Executor):
    """Emit a fixed set of rows once, after the Initial barrier
    (reference values.rs — the VALUES clause of a streaming insert)."""

    def __init__(self, schema: Schema, rows: Sequence[tuple],
                 barrier_queue: "asyncio.Queue[Barrier]"):
        self.schema = schema
        self.rows = list(rows)
        self.barrier_queue = barrier_queue
        self.identity = f"Values({len(self.rows)} rows)"
        self.pk_indices = ()

    async def execute(self):
        barrier = await self.barrier_queue.get()
        yield barrier
        if self.rows:
            cols = [np.asarray([r[j] for r in self.rows],
                               dtype=f.data_type.np_dtype)
                    for j, f in enumerate(self.schema)]
            yield StreamChunk.from_numpy(self.schema, cols)
        while True:
            barrier = await self.barrier_queue.get()
            yield barrier
            if barrier.mutation is not None and barrier.is_stop_any():
                return


class UnionExecutor(MergeExecutor):
    """N-way stream union = barrier-aligned merge (reference union.rs is
    merge without the exchange); schemas must match."""

    def __init__(self, channels: Sequence[Channel], schema: Schema):
        super().__init__(channels, schema)
        self.identity = f"Union({len(self.channels)})"


class NoOpExecutor(StatelessUnaryExecutor):
    """Identity passthrough (reference no_op.rs — plan-shape padding)."""

    identity = "NoOp"
    # Mesh-chain fusion: identity is trivially safe per-shard, so NoOp
    # plan padding must not break the prelude-capable producer walk
    # (q5's source -> project -> NoOp leg). It does no device work, so
    # un-hollowed NoOps never count a host round trip either.
    mesh_hollow = False
    mesh_chain_hop = None

    def mesh_prelude_fn(self):
        return lambda chunk: chunk

    def map_chunk(self, chunk: StreamChunk) -> StreamChunk:
        return chunk


class ExpandExecutor(StatelessUnaryExecutor):
    """Grouping-sets row multiplication (reference expand.rs): each input
    row is emitted once per subset, with non-subset columns NULLed and a
    flag column identifying the subset. One jitted program emits one chunk
    of capacity n_subsets * input_capacity."""

    def __init__(self, input: Executor, column_subsets: Sequence[Sequence[int]]):
        super().__init__(input)
        self.subsets = [tuple(s) for s in column_subsets]
        in_fields = list(input.schema)
        self.schema = Schema(tuple(
            in_fields + [type(in_fields[0])("flag", DataType.INT64)]))
        self.identity = f"Expand({len(self.subsets)} subsets)"
        self._step = jit_state(self._step_impl, name="expand_step")

    def _step_impl(self, chunk: StreamChunk) -> StreamChunk:
        K = len(self.subsets)
        N = chunk.capacity

        def tiled(a):
            return jnp.tile(a, K)

        cols = []
        for j, c in enumerate(chunk.columns):
            data = tiled(c.data)
            valid = tiled(c.valid_mask())
            # NULL out columns not in the subset for each copy
            keep = np.zeros(K * N, dtype=bool)
            for k, subset in enumerate(self.subsets):
                if j in subset:
                    keep[k * N:(k + 1) * N] = True
            valid = valid & jnp.asarray(keep)
            cols.append(Column(data, valid))
        flag = jnp.repeat(jnp.arange(K, dtype=jnp.int64), N)
        cols.append(Column(flag))
        return StreamChunk(tuple(cols), tiled(chunk.ops),
                           tiled(chunk.vis), self.schema)

    def map_chunk(self, chunk: StreamChunk) -> StreamChunk:
        return self._step(chunk)


class FlowControlExecutor(Executor):
    """Rate limiter (reference flow_control.rs): a token bucket of
    `rows_per_sec`; a chunk that exceeds the available tokens WAITS in
    place, which backpressures everything behind it (barriers included) —
    messages are never reordered across epochs, matching the reference's
    in-order await on its rate limiter. Throttle mutations adjust the
    rate at runtime."""

    def __init__(self, input: Executor, actor_id: int,
                 rows_per_sec: Optional[int]):
        self.input = input
        self.actor_id = actor_id
        self.schema = input.schema
        self.pk_indices = input.pk_indices
        self.limit = rows_per_sec
        self.identity = f"FlowControl({rows_per_sec}/s)"

    async def execute(self):
        import time

        from .message import ThrottleMutation
        tokens = 0.0
        last = time.monotonic()
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk) and self.limit is not None:
                need = msg.num_rows_host()
                while True:
                    if self.limit == 0:
                        # rate 0 pauses the stream IN PLACE (barriers wait
                        # behind the chunk; to pause without stalling
                        # checkpoints use a PauseMutation at the source)
                        await asyncio.sleep(0.05)
                        continue
                    now = time.monotonic()
                    tokens = min(tokens + (now - last) * self.limit,
                                 float(max(self.limit, need)))
                    last = now
                    if tokens >= need:
                        tokens -= need
                        break
                    await asyncio.sleep((need - tokens) / self.limit)
                yield msg
            elif isinstance(msg, Barrier):
                if isinstance(msg.mutation, ThrottleMutation):
                    for aid, lim in msg.mutation.limits:
                        if aid == self.actor_id:
                            self.limit = lim
                yield msg
            else:
                yield msg


class WatermarkFilterExecutor(Executor):
    """Generate watermarks from an event-time column and filter late rows
    (reference watermark_filter.rs): wm = max(seen ts) - lag; rows with
    ts < wm are dropped; the current wm per vnode persists in a state
    table so recovery resumes monotonically."""

    def __init__(self, input: Executor, time_col: int, lag_us: int = 0,
                 state_table: Optional[StateTable] = None):
        self.input = input
        self.schema = input.schema
        self.pk_indices = input.pk_indices
        self.time_col = time_col
        self.lag_us = lag_us
        self.state_table = state_table
        self.identity = f"WatermarkFilter(col={time_col}, lag={lag_us}us)"
        self._wm: Optional[int] = None
        self._max_dev = None
        self._step = jit_state(self._step_impl, name="watermark_filter_step")

    def _step_impl(self, chunk: StreamChunk, cur_max):
        ts = chunk.columns[self.time_col].data
        # filter against the watermark BEFORE this chunk, then advance:
        # in-chunk disorder must not retroactively drop rows the emitted
        # watermark still admits (reference filters at the current wm)
        keep = chunk.vis & (ts >= cur_max - self.lag_us)
        seen = jnp.where(chunk.vis, ts, cur_max)
        new_max = jnp.maximum(cur_max, jnp.max(seen))
        return StreamChunk(chunk.columns, chunk.ops, keep,
                           chunk.schema), new_max

    async def execute(self):
        first = True
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                if self._max_dev is None:
                    seed = getattr(self, "_recovered_max", None)
                    self._max_dev = jnp.asarray(
                        seed if seed is not None else -(1 << 62),
                        dtype=jnp.int64)
                out, self._max_dev = self._step(msg, self._max_dev)
                yield out
            elif isinstance(msg, Barrier):
                if first or msg.kind is BarrierKind.INITIAL:
                    first = False
                    if self.state_table is not None:
                        self.state_table.init_epoch(msg.epoch.curr)
                        row = self.state_table.get_row((0,))
                        if row is not None:
                            self._wm = row[1]
                            # the persisted value is the WATERMARK (already
                            # lag-subtracted); the running max must be
                            # wm + lag or recovery would re-admit rows
                            # below the emitted watermark
                            self._max_dev = None
                            self._recovered_max = self._wm + self.lag_us
                    yield msg
                    continue
                # ONE fetch per barrier (transfer-poison rules apply on
                # tunneled TPUs; use lag-free sources there instead)
                if self._max_dev is not None:
                    cur = int(np.asarray(self._max_dev))
                    wm = cur - self.lag_us
                    if self._wm is None or wm > self._wm:
                        self._wm = wm
                        yield Watermark(self.time_col,
                                        self.schema[self.time_col].data_type,
                                        wm)
                if self.state_table is not None:
                    if self._wm is not None:
                        self.state_table.write_chunk_rows(
                            [(int(OP_INSERT), (0, self._wm))])
                    self.state_table.commit(msg.epoch.curr)
                yield msg
            else:
                yield msg

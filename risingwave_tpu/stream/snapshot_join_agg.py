"""Barrier snapshot-recompute for "join against your own aggregate".

Reference shape (TPC-H q17, /root/reference/e2e_test/tpch/):

    SELECT sum(L.x) / 7.0
    FROM L JOIN P ON P.k = L.fk
           JOIN (SELECT fk, 0.2*avg(q) AS thr FROM L GROUP BY fk) A
             ON A.fk = L.fk AND L.q < A.thr
    WHERE <filters on P>

The changelog plan for this is a RETRACTION STORM: every L row shifts
its group's aggregate, so the agg subquery updates its row, the join
re-emits EVERY stored L row of that group, and the final agg retracts
and re-adds them all — per chunk. The reference pays the same storm
through its hash-join cache (hash_join.rs): this is inherent to
changelog propagation, not an implementation defect.

TPU re-design: don't propagate the storm — re-evaluate. All inputs of
the sub-plan are APPEND-ONLY, so the whole sub-plan is a pure function
of the accumulated input prefixes. The executor accumulates inputs in
dense device stores and, at each barrier, ONE jitted O(n) program
recomputes per-group aggregates (sort + segment reductions), the
threshold predicate, dim-key membership, and the final global
aggregates — then emits the one-row changelog diff vs the previous
barrier. Zero per-chunk output work, no match buffers, no storms. This
is the snapshot-diff pattern the retractable TopN / OverWindow /
DynamicFilter executors already use, generalized to the
join-against-own-aggregate sub-plan (VERDICT r4 next-round #1).

Durability: append-only stores persist as append-only row logs
((_pos, row) per StateTable) written at each barrier; recovery reloads
the logs and re-runs the snapshot program once to restore the
last-emitted output (the same trick sorted_join.py uses to rebuild
degrees: recompute beats persisting derived state).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, StreamChunk, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
    op_sign,
)
from ..common.types import Field, Schema
from ..expr.agg import AggCall, AggKind
from ..ops.jit_state import jit_state
from .align import LEFT, RIGHT, barrier_align
from .executor import Executor
from .message import Barrier, BarrierKind, Watermark

_I64_MAX = jnp.iinfo(jnp.int64).max


def _valid_of(col: Column, cap: int) -> jnp.ndarray:
    if col.valid is None:
        return jnp.ones(cap, dtype=bool)
    return col.valid


class SnapshotJoinAggExecutor(Executor):
    """Fused (L ⋈ dim ⋈ group-agg(L)) → global agg, evaluated by
    snapshot recompute at barriers.

    fact (LEFT input): the append-only L stream; all rows accumulate.
    dim (RIGHT input): the append-only dimension stream; only its key
    column is stored (after `dim_filter`), and its key must be unique
    (enforced by the planner: it is the source's declared primary key),
    so membership is a mask — never a row multiplier.
    """

    def __init__(self, fact: Executor, dim: Executor, *,
                 fact_key: int,
                 dim_key: int,
                 sub_agg_calls: Sequence[AggCall],
                 sub_items: Sequence,          # Expr over [sub agg outputs]
                 residue,                      # Expr over [L cols ++ sub items]
                 final_agg_calls: Sequence[AggCall],
                 final_items: Sequence,        # Expr over [final agg outputs]
                 out_names: Sequence[str],
                 out_types: Sequence,
                 fact_filter=None,             # Expr over L cols (fact side only)
                 sub_filter=None,              # Expr over L cols (agg side only)
                 dim_filter=None,              # Expr over dim cols
                 capacity: int = 1 << 17,
                 dim_capacity: int = 1 << 14,
                 state_tables: Optional[tuple] = None,
                 watchdog_interval: Optional[int] = 1):
        self.inputs = (fact, dim)
        self.fact_key = fact_key
        self.dim_key = dim_key
        self.sub_agg_calls = tuple(sub_agg_calls)
        self.sub_specs = tuple(c.spec() for c in self.sub_agg_calls)
        self.sub_items = tuple(sub_items)
        self.residue = residue
        self.final_agg_calls = tuple(final_agg_calls)
        self.final_specs = tuple(c.spec() for c in self.final_agg_calls)
        self.final_items = tuple(final_items)
        self.fact_filter = fact_filter
        self.sub_filter = sub_filter
        self.dim_filter = dim_filter
        self.capacity = int(capacity)
        self.dim_capacity = int(dim_capacity)
        self.state_tables = tuple(state_tables) if state_tables \
            else (None, None)
        if watchdog_interval not in (None, 1):
            raise ValueError(
                "watchdog_interval must be 1 (check before every "
                "checkpoint commit) or None (transfer-free mode)")
        self.watchdog_interval = watchdog_interval
        self.schema = Schema(tuple(
            Field(n, t) for n, t in zip(out_names, out_types)))
        if len(fact.schema) > 63:
            raise ValueError(
                "snapshot-join-agg fact schema exceeds the 63-column "
                "validity bitmask used for persistence")
        self.pk_indices = ()
        self.identity = "SnapshotJoinAgg"

        self._fact_schema = fact.schema
        self._init_stores()
        # previous emission (device): per-item value + validity, plus the
        # emitted flag — all stay on device so a barrier costs zero d2h
        # in watchdog-free mode
        self._prev = tuple(
            jnp.zeros((), dtype=t.jnp_dtype) for t in out_types)
        self._prev_valid = tuple(jnp.zeros((), dtype=bool)
                                 for _ in out_types)
        self._emitted = jnp.zeros((), dtype=bool)
        # errs[0] = fact overflow, errs[1] = dim overflow,
        # errs[2] = retraction seen on an append-only input
        self._errs = jnp.zeros(3, dtype=jnp.int32)
        # appends thread (store arrays, count, errs) — re-bound at the
        # call sites, aliased nowhere else: donate. _flush reads the
        # stores (NOT donated — they stay live) and consumes/replaces the
        # previous-emission triplet (args 5-7).
        self._append_fact = jit_state(self._append_fact_impl,
                                      donate_argnums=(0, 1, 2, 3),
                                      name="snapshot_join_agg_append_fact")
        self._append_dim = jit_state(self._append_dim_impl,
                                     donate_argnums=(0, 1, 2),
                                     name="snapshot_join_agg_append_dim")
        self._flush = jit_state(self._flush_impl, donate_argnums=(5, 6, 7),
                                name="snapshot_join_agg_flush")
        self._dirty = False
        # host upper bounds for growth triggers (no d2h on the hot path)
        self._applied_rows_upper = 0
        self._applied_dim_upper = 0
        self._persist_cursor = [0, 0]

    # ------------------------------------------------------------- state
    def _init_stores(self):
        C, Cd = self.capacity, self.dim_capacity
        sch = self._fact_schema
        self._fcols = tuple(
            jnp.zeros(C, dtype=f.data_type.jnp_dtype) for f in sch)
        self._fvalids = tuple(jnp.zeros(C, dtype=bool) for _ in sch)
        self._fn = jnp.zeros((), dtype=jnp.int32)
        self._dkeys = jnp.zeros(Cd, dtype=jnp.int64)
        self._dn = jnp.zeros((), dtype=jnp.int32)

    def fence_tokens(self) -> list:
        toks = [self._fn, self._dn, self._emitted]
        for i in self.inputs:
            toks.extend(i.fence_tokens())
        return toks

    # ----------------------------------------------------------- appends
    def _append_fact_impl(self, fcols, fvalids, fn, errs, chunk):
        C = fcols[0].shape[0]
        take = chunk.vis & (op_sign(chunk.ops) > 0)
        retract = jnp.sum(
            (chunk.vis & (op_sign(chunk.ops) < 0)).astype(jnp.int32),
            dtype=jnp.int32)
        rank = jnp.cumsum(take.astype(jnp.int32)) - 1
        n_new = jnp.sum(take.astype(jnp.int32), dtype=jnp.int32)
        dest = jnp.where(take & (fn + rank < C), fn + rank, C)
        overflow = jnp.maximum(fn + n_new - C, 0)
        new_cols = tuple(
            c.at[dest].set(col.data, mode="drop")
            for c, col in zip(fcols, chunk.columns))
        new_valids = tuple(
            v.at[dest].set(_valid_of(col, chunk.capacity), mode="drop")
            for v, col in zip(fvalids, chunk.columns))
        new_n = jnp.minimum(fn + n_new, C).astype(jnp.int32)
        errs = errs.at[0].add(overflow.astype(jnp.int32))
        errs = errs.at[2].add(retract)
        return new_cols, new_valids, new_n, errs

    def _append_dim_impl(self, dkeys, dn, errs, chunk):
        Cd = dkeys.shape[0]
        take = chunk.vis & (op_sign(chunk.ops) > 0)
        retract = jnp.sum(
            (chunk.vis & (op_sign(chunk.ops) < 0)).astype(jnp.int32),
            dtype=jnp.int32)
        kcol = chunk.columns[self.dim_key]
        take &= _valid_of(kcol, chunk.capacity)
        if self.dim_filter is not None:
            p = self.dim_filter.eval(list(chunk.columns))
            take &= p.data.astype(bool) & _valid_of(p, chunk.capacity)
        rank = jnp.cumsum(take.astype(jnp.int32)) - 1
        n_new = jnp.sum(take.astype(jnp.int32), dtype=jnp.int32)
        dest = jnp.where(take & (dn + rank < Cd), dn + rank, Cd)
        overflow = jnp.maximum(dn + n_new - Cd, 0)
        new_keys = dkeys.at[dest].set(
            kcol.data.astype(jnp.int64), mode="drop")
        new_n = jnp.minimum(dn + n_new, Cd).astype(jnp.int32)
        errs = errs.at[1].add(overflow.astype(jnp.int32))
        errs = errs.at[2].add(retract)
        return new_keys, new_n, errs

    # ------------------------------------------------------------- flush
    def _flush_impl(self, fcols, fvalids, fn, dkeys, dn,
                    prev, prev_valid, emitted):
        C = fcols[0].shape[0]
        live = jnp.arange(C) < fn
        fk = fcols[self.fact_key].astype(jnp.int64)
        # a NULL join/group key never matches the dim or the A side
        # (SQL equi semantics): push those rows into the sentinel region
        # with the dead lanes so they join nothing and pollute no group
        skey = jnp.where(live & fvalids[self.fact_key], fk, _I64_MAX)
        order = jnp.argsort(skey)
        live_s = live[order]
        sfk = skey[order]
        cols_s = tuple(c[order] for c in fcols)
        valids_s = tuple(v[order] for v in fvalids)
        newrun = jnp.concatenate(
            [jnp.ones(1, dtype=bool), sfk[1:] != sfk[:-1]])
        gid = (jnp.cumsum(newrun) - 1).astype(jnp.int32)
        env_fact = [Column(d, v) for d, v in zip(cols_s, valids_s)]

        sub_sign = live_s.astype(jnp.int32)
        if self.sub_filter is not None:
            p = self.sub_filter.eval(env_fact)
            sub_sign = jnp.where(
                p.data.astype(bool) & _valid_of(p, C), sub_sign, 0)
        sub_outs = []
        for call, spec in zip(self.sub_agg_calls, self.sub_specs):
            if call.arg is None:
                vals = jnp.zeros(C, dtype=spec.state_dtype)
                rs = sub_sign
            else:
                vals = cols_s[call.arg]
                rs = jnp.where(valids_s[call.arg], sub_sign, 0)
            st = spec.partial(vals, rs, gid, C)
            cnt = jax.ops.segment_sum(
                (rs != 0).astype(jnp.int32), gid, C)
            out_valid = (cnt > 0) if call.kind is not AggKind.COUNT \
                else jnp.ones(C, dtype=bool)
            sub_outs.append(Column(spec.emit(st), out_valid))
        # per-group item exprs, gathered back to the row level by gid
        # (each row's lookup key IS the group key — the planner enforces
        # that the A-side equi column equals the GROUP BY column)
        row_sub = []
        for e in self.sub_items:
            c = e.eval(sub_outs)
            row_sub.append(Column(
                c.data[gid],
                None if c.valid is None else c.valid[gid]))

        if self.residue is not None:
            pred = self.residue.eval(env_fact + row_sub)
            keep = pred.data.astype(bool) & _valid_of(pred, C)
        else:
            keep = jnp.ones(C, dtype=bool)
        if self.fact_filter is not None:
            p = self.fact_filter.eval(env_fact)
            keep &= p.data.astype(bool) & _valid_of(p, C)

        Cd = dkeys.shape[0]
        dlive = jnp.arange(Cd) < dn
        sd = jnp.sort(jnp.where(dlive, dkeys, _I64_MAX))
        pos = jnp.searchsorted(sd, sfk)
        member = (sd[jnp.clip(pos, 0, Cd - 1)] == sfk) & (pos < dn)
        if self.sub_filter is not None:
            # a group whose rows ALL fail the subquery WHERE produces no
            # A row, so the inner join drops its fact rows (residue
            # validity covers sum/min/max/avg outputs, but count() is 0
            # and valid — existence must be checked explicitly)
            gexists = jax.ops.segment_sum(
                (sub_sign != 0).astype(jnp.int32), gid, C) > 0
            member &= gexists[gid]

        msign = (live_s & keep & member).astype(jnp.int32)
        seg0 = jnp.zeros(C, dtype=jnp.int32)
        fin_outs = []
        for call, spec in zip(self.final_agg_calls, self.final_specs):
            if call.arg is None:
                vals = jnp.zeros(C, dtype=spec.state_dtype)
                rs = msign
            else:
                vals = cols_s[call.arg]
                rs = jnp.where(valids_s[call.arg], msign, 0)
            st = spec.partial(vals, rs, seg0, 1)
            nz = jnp.sum((rs != 0).astype(jnp.int32))
            out_valid = jnp.ones(1, dtype=bool) \
                if call.kind is AggKind.COUNT else (nz > 0)[None]
            fin_outs.append(Column(spec.emit(st), out_valid))
        out_cols = [e.eval(fin_outs) for e in self.final_items]
        cur = tuple(c.data[0] for c in out_cols)
        cur_valid = tuple(_valid_of(c, 1)[0] for c in out_cols)

        same = jnp.ones((), dtype=bool)
        for a, b, av, bv in zip(prev, cur, prev_valid, cur_valid):
            same &= (av == bv) & ((a == b) | ~bv)
        changed = ~(emitted & same)
        # one chunk, capacity 2: [prev as U-, cur as U+/Insert]
        ops = jnp.where(
            emitted,
            jnp.asarray([OP_UPDATE_DELETE, OP_UPDATE_INSERT],
                        dtype=jnp.int8),
            jnp.asarray([OP_INSERT, OP_INSERT], dtype=jnp.int8))
        vis = jnp.stack([changed & emitted, changed])
        chunk_cols = tuple(
            Column(jnp.stack([p, c]), jnp.stack([pv, cv]))
            for p, c, pv, cv in zip(prev, cur, prev_valid, cur_valid))
        out = StreamChunk(chunk_cols, ops, vis, self.schema)
        return cur, cur_valid, jnp.ones((), dtype=bool), out

    # ------------------------------------------------------- housekeeping
    def _check_watchdog(self):
        errs = [int(x) for x in np.asarray(self._errs)]
        if errs[0]:
            raise RuntimeError(
                f"snapshot-join-agg fact store overflow ({errs[0]} rows "
                f"dropped; capacity {self.capacity})")
        if errs[1]:
            raise RuntimeError(
                f"snapshot-join-agg dim store overflow ({errs[1]} rows "
                f"dropped; capacity {self.dim_capacity})")
        if errs[2]:
            raise RuntimeError(
                "snapshot-join-agg saw retractions on an append-only "
                "input — the planner must not fuse retracting inputs")

    def _maybe_grow(self):
        """Double the fact store while the live count crowds capacity
        (watchdog mode reads the true device count; the jitted programs
        re-trace at the new static shape)."""
        n = int(np.asarray(self._fn))
        grew = False
        while n > 0.7 * self.capacity:
            self.capacity *= 2
            grew = True
        if grew:
            C = self.capacity
            pad = lambda a: jnp.concatenate(
                [a, jnp.zeros(C - a.shape[0], dtype=a.dtype)])
            self._fcols = tuple(pad(c) for c in self._fcols)
            self._fvalids = tuple(pad(v) for v in self._fvalids)
        nd = int(np.asarray(self._dn))
        grew_d = False
        while nd > 0.7 * self.dim_capacity:
            self.dim_capacity *= 2
            grew_d = True
        if grew_d:
            Cd = self.dim_capacity
            self._dkeys = jnp.concatenate(
                [self._dkeys,
                 jnp.zeros(Cd - self._dkeys.shape[0], dtype=jnp.int64)])

    # ----------------------------------------------------------- persist
    def _persist(self, barrier: Barrier) -> None:
        for s, (st, n_dev) in enumerate(
                zip(self.state_tables, (self._fn, self._dn))):
            if st is None:
                continue
            n = int(np.asarray(n_dev))
            lo = self._persist_cursor[s]
            if n > lo:
                pos = np.arange(lo, n, dtype=np.int64)
                if s == LEFT:
                    # per-cell validity rides as a packed bitmask column
                    # (NULL cells must survive recovery — their data
                    # lanes are undefined)
                    vbits = np.zeros(n - lo, dtype=np.int64)
                    for k, v in enumerate(self._fvalids):
                        vbits |= np.asarray(
                            v[lo:n]).astype(np.int64) << k
                    cols = [pos] + [np.asarray(c[lo:n])
                                    for c in self._fcols] + [vbits]
                else:
                    cols = [pos, np.asarray(self._dkeys[lo:n])]
                st.write_chunk_columns(
                    np.full(n - lo, OP_INSERT, dtype=np.int8), cols,
                    np.ones(n - lo, dtype=bool))
                self._persist_cursor[s] = n
            st.commit(barrier.epoch.curr)

    def recover(self) -> None:
        if all(st is None for st in self.state_tables):
            return
        rows_f = [r for _, r in self.state_tables[LEFT].iter_all()] \
            if self.state_tables[LEFT] is not None else []
        rows_d = [r for _, r in self.state_tables[RIGHT].iter_all()] \
            if self.state_tables[RIGHT] is not None else []
        while len(rows_f) > 0.7 * self.capacity:
            self.capacity *= 2
        while len(rows_d) > 0.7 * self.dim_capacity:
            self.dim_capacity *= 2
        self._init_stores()
        if rows_f:
            rows_f.sort(key=lambda r: r[0])
            arrays = [
                np.asarray([r[k + 1] for r in rows_f],
                           dtype=f.data_type.np_dtype)
                for k, f in enumerate(self._fact_schema)]
            vbits = np.asarray([r[1 + len(self._fact_schema)]
                                for r in rows_f], dtype=np.int64)
            C = self.capacity
            self._fcols = tuple(
                jnp.asarray(np.concatenate(
                    [a, np.zeros(C - len(a), dtype=a.dtype)]))
                for a in arrays)
            self._fvalids = tuple(
                jnp.asarray(np.concatenate(
                    [((vbits >> k) & 1).astype(bool),
                     np.zeros(C - len(rows_f), dtype=bool)]))
                for k in range(len(self._fact_schema)))
            self._fn = jnp.asarray(len(rows_f), dtype=jnp.int32)
        if rows_d:
            rows_d.sort(key=lambda r: r[0])
            keys = np.asarray([r[1] for r in rows_d], dtype=np.int64)
            Cd = self.dim_capacity
            self._dkeys = jnp.asarray(np.concatenate(
                [keys, np.zeros(Cd - len(keys), dtype=np.int64)]))
            self._dn = jnp.asarray(len(rows_d), dtype=jnp.int32)
        self._persist_cursor = [len(rows_f), len(rows_d)]
        self._applied_rows_upper = len(rows_f)
        self._applied_dim_upper = len(rows_d)
        if rows_f or rows_d:
            # restore the last-emitted output: rows reach the log only
            # via a barrier whose flush already emitted, so the
            # recomputed output equals what downstream last saw
            self._prev, self._prev_valid, self._emitted, _ = self._flush(
                self._fcols, self._fvalids, self._fn, self._dkeys,
                self._dn, self._prev, self._prev_valid, self._emitted)

    # ------------------------------------------------------------ stream
    async def execute(self):
        first = True
        async for kind, s, msg in barrier_align(*self.inputs):
            if kind == "chunk":
                if s == LEFT:
                    (self._fcols, self._fvalids, self._fn,
                     self._errs) = self._append_fact(
                        self._fcols, self._fvalids, self._fn,
                        self._errs, msg)
                    self._applied_rows_upper += msg.capacity
                else:
                    self._dkeys, self._dn, self._errs = self._append_dim(
                        self._dkeys, self._dn, self._errs, msg)
                    self._applied_dim_upper += msg.capacity
                if self.watchdog_interval is None and (
                        self._applied_rows_upper > 0.9 * self.capacity
                        or self._applied_dim_upper
                        > 0.9 * self.dim_capacity):
                    # growth needs the true counts; without the
                    # watchdog's barrier d2h, pay one here instead of
                    # overflowing (and surface any pending errors —
                    # they must never be swallowed in this mode)
                    self._check_watchdog()
                    self._maybe_grow()
                    self._applied_rows_upper = int(np.asarray(self._fn))
                    self._applied_dim_upper = int(np.asarray(self._dn))
                self._dirty = True
            elif kind == "barrier":
                barrier: Barrier = msg
                if first or barrier.kind is BarrierKind.INITIAL:
                    first = False
                    for st in self.state_tables:
                        if st is not None:
                            st.init_epoch(barrier.epoch.curr)
                    self.recover()
                    yield barrier
                    continue
                stopping = barrier.mutation is not None \
                    and barrier.is_stop_any()
                if self._dirty:
                    self._dirty = False
                    if self.watchdog_interval:
                        self._check_watchdog()
                        self._maybe_grow()
                    (self._prev, self._prev_valid, self._emitted,
                     out) = self._flush(
                        self._fcols, self._fvalids, self._fn,
                        self._dkeys, self._dn, self._prev,
                        self._prev_valid, self._emitted)
                    self._persist(barrier)
                    yield out
                elif stopping and self.watchdog_interval:
                    self._check_watchdog()
                    for st in self.state_tables:
                        if st is not None:
                            st.commit(barrier.epoch.curr)
                else:
                    for st in self.state_tables:
                        if st is not None:
                            st.commit(barrier.epoch.curr)
                yield barrier
            else:
                # watermarks do not pass a global aggregate (no group
                # column survives) — same as SimpleAgg
                continue

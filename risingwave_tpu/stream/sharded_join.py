"""Vnode-sharded SortedJoin — the streaming join under shard_map over a mesh.

Reference: a hash-distributed join fragment is N parallel actors, each
owning a vnode slice, fed by HashDataDispatcher on the JOIN KEY from both
sides (src/stream/src/executor/hash_join.rs:478 under dispatch.rs:679) —
matching rows land on the same actor because both dispatchers hash the
same key values.

On a TPU mesh the dispatcher+merge pair collapses INTO the jitted step
(same re-design as ShardedHashAggExecutor, sharded_agg.py): each side's
sorted state lives sharded along the `vnode` mesh axis, and both sides'
chunks route to the shard owning vnode = crc32(key) & 255 — identical
hashing on both sides => co-partitioned probes are shard-local. The
per-shard output chunks concatenate along the shard axis into one global
changelog chunk. `capacity` is PER SHARD.

Like the sharded agg, the default input plane is the FUSED MESH SHUFFLE
(`mesh_shuffle=True`): the chunk enters row-sliced over the mesh axis and
`parallel/exchange.mesh_ingest_chunk` routes rows to their owner shard
with one in-program `lax.all_to_all` — exchange + probe + state update is
ONE device program per chunk, with shuffle overflow accumulated on device
and fail-stopped at the barrier watchdog. Chunks whose capacity does not
divide by the shard count (and `mesh_shuffle=False`) fall back to the
replicated-and-masked plane.

Inherits ALL semantics (inner/outer, degrees, per-chunk eviction,
netting) from SortedJoinExecutor — `_apply_impl` / `_evict_impl` run
unchanged inside shard_map.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.chunk import StreamChunk
from ..common.vnode import compute_vnodes
from ..ops.jit_state import jit_state
from ..parallel.exchange import mesh_ingest_chunk, shuffle_cap_out
from ..parallel.mesh import VNODE_AXIS, shard_map, vnode_to_shard
from .align import LEFT, RIGHT
from .executor import Executor
from .sorted_join import SortedJoinExecutor, SortedSideState, _empty_sorted_side


def _scalar_n(state: SortedSideState) -> SortedSideState:
    return SortedSideState(state.khash, state.cols, state.valids,
                           state.degree, state.n.reshape(()))


def _vec_n(state: SortedSideState) -> SortedSideState:
    return SortedSideState(state.khash, state.cols, state.valids,
                           state.degree, state.n.reshape((1,)))


class ShardedSortedJoinExecutor(SortedJoinExecutor):
    def __init__(self, left: Executor, right: Executor, mesh: Mesh,
                 mesh_shuffle: bool = True, mesh_shuffle_slack: int = 0,
                 mesh_shuffle_adaptive: bool = True, **kwargs):
        self.mesh = mesh
        self.n_shards = mesh.shape[VNODE_AXIS]
        self._routing = jnp.asarray(vnode_to_shard(self.n_shards))
        self.mesh_shuffle = bool(mesh_shuffle)
        self.mesh_shuffle_slack = int(mesh_shuffle_slack)
        if self.mesh_shuffle_slack \
                and kwargs.get("watchdog_interval", 1) is None:
            raise ValueError(
                "mesh_shuffle_slack > 0 needs the barrier watchdog fetch "
                "(watchdog_interval=1): shuffle drops would otherwise go "
                "unchecked — transfer-free pipelines must use slack 0 "
                "(zero-drop sizing)")
        self.mesh_shuffle_applies = 0
        # adaptive shuffle slack + mesh-chain preludes: same contract as
        # ShardedHashAggExecutor (the agg carries the full commentary)
        self.mesh_shuffle_adaptive = (
            bool(mesh_shuffle_adaptive) and self.mesh_shuffle_slack == 0
            and kwargs.get("watchdog_interval", 1) is not None)
        self._cap_hint = None
        self._fill_ewma = 0.0
        self._fill_peak = 0
        self._fill_obs = 0
        self._mesh_preludes: dict = {}   # side -> tuple of prelude fns
        self.mesh_chain = None
        # mesh-plane replay point (sharded_agg.py MeshIngestLog): the
        # uncommitted (side, chunk) ingest suffix, held by reference
        from .sharded_agg import MeshIngestLog
        self.ingest_log = MeshIngestLog()
        super().__init__(left, right, **kwargs)
        shard, repl = P(VNODE_AXIS), P()

        def make_apply(side, mf):
            def apply_sharded(own, other, errs, chunk, wm):
                my = jax.lax.axis_index(VNODE_AXIS)
                key_cols = [chunk.columns[i].data
                            for i in self.key_indices[side]]
                vn = compute_vnodes(key_cols)
                mine = chunk.vis & (self._routing[vn] == my)
                local = StreamChunk(chunk.columns, chunk.ops, mine,
                                    chunk.schema)
                out = self._apply_impl(_scalar_n(own), _scalar_n(other),
                                       errs[0], local, wm, side,
                                       match_factor=mf)
                own2, odeg, cols, ops, vis, errs2, _ = out
                return (_vec_n(own2), odeg, cols, ops, vis, errs2[None],
                        own2.n.reshape((1,)))
            # donation mirrors the parent's: ONLY the sharded error
            # accumulator (arg 2) — the side states stay aliased by the
            # per-shard snapshot diff base (_snap)
            return jit_state(shard_map(
                apply_sharded, mesh=mesh,
                in_specs=(shard, shard, shard, repl, repl),
                out_specs=(shard, shard, shard, shard, shard, shard,
                           shard)), donate_argnums=(2,),
                name=f"sharded_join_apply_s{side}")

        # ---- fused mesh shuffle: exchange + probe in ONE program ----
        # the chunk enters SHARDED over the row axis; the in-mesh
        # all_to_all routes rows to the shard owning their join-key
        # vnode, then the local sorted state probes/updates exactly the
        # owned rows. `dropped` (arg 3) accumulates shuffle overflow per
        # shard for the barrier watchdog's fail-stop.
        def make_apply_fused(side, mf, use_preludes):
            def apply_fused(own, other, errs, dropped, sendocc, chunk,
                            wm):
                # preludes transform RAW source chunks; recovery's state
                # replay feeds rows already in join-input schema, so its
                # trace (use_preludes=False) must skip them
                pres = (self._mesh_preludes.get(side, ())
                        if use_preludes else ())
                for fn in pres:
                    chunk = fn(chunk)
                cap = self._trace_cap(chunk.capacity)
                local, n_drop, fill = mesh_ingest_chunk(
                    chunk, self.key_indices[side], self._routing,
                    VNODE_AXIS, self.n_shards, cap)
                out = self._apply_impl(_scalar_n(own), _scalar_n(other),
                                       errs[0], local, wm, side,
                                       match_factor=mf)
                own2, odeg, cols, ops, vis, errs2, _ = out
                return (_vec_n(own2), odeg, cols, ops, vis, errs2[None],
                        (dropped[0] + n_drop)[None],
                        jnp.maximum(sendocc[0], fill)[None],
                        own2.n.reshape((1,)))
            # donation: the error + shuffle-drop + send-demand
            # accumulators (threaded); side states stay aliased by the
            # snapshot diff base (_snap)
            return jit_state(shard_map(
                apply_fused, mesh=mesh,
                in_specs=(shard, shard, shard, shard, shard, shard,
                          repl),
                out_specs=(shard,) * 9), donate_argnums=(2, 3, 4),
                name=f"sharded_join_apply_fused_s{side}")

        # sharded programs trace per (side, match_factor, fused): the
        # steady state uses the per-side factors, recovery's generous
        # replay buffer gets its own trace instead of being refused
        applies: dict = {}

        def apply_dispatch(own, other, errs, chunk, wm, side,
                           match_factor=None):
            mf = match_factor or self.match_factors[side]
            fused = (self.mesh_shuffle
                     and chunk.capacity % self.n_shards == 0)
            # state replay (recover) feeds join-schema rows, not raw
            # source chunks: skip chain preludes AND the ingest log
            use_pre = not getattr(self, "_state_replay", False)
            # programs also key by the adaptive cap hint active at trace
            # time (None = zero-drop sizing)
            key = (side, mf, fused, self._cap_hint if fused else None,
                   use_pre)
            if key not in applies:
                applies[key] = (make_apply_fused(side, mf, use_pre)
                                if fused else make_apply(side, mf))
            if fused:
                # replay point: retain the ingest by reference before
                # the fused program consumes it (sharded_agg.py
                # MeshIngestLog — the mesh-plane uncommitted suffix).
                # State-replay chunks are NOT raw ingest and must not
                # be re-notable.
                if use_pre:
                    self.ingest_log.note((side, chunk))
                (own2, odeg, cols, ops, vis, errs2, self._dropped_dev,
                 self._send_occ_dev, n) = applies[key](
                    own, other, errs, self._dropped_dev,
                    self._send_occ_dev, chunk, wm)
                self.mesh_shuffle_applies += 1
                return own2, odeg, cols, ops, vis, errs2, n
            # per-chunk host-plane fallback: hollowed producer stages (if
            # any) run here eagerly; the crossing counts against the chain
            if use_pre and self._mesh_preludes.get(side):
                for fn in self._mesh_preludes[side]:
                    chunk = fn(chunk)
            if use_pre and self.mesh_chain is not None:
                from .monitor import mesh_host_round_trip
                mesh_host_round_trip(self.mesh_chain)
            return applies[key](own, other, errs, chunk, wm)
        self._apply = apply_dispatch

        def set_mesh_preludes(side, fns, chain=None):
            assert self.mesh_shuffle_applies == 0, \
                "mesh preludes must install before the first fused " \
                "dispatch"
            self._mesh_preludes[side] = tuple(fns)
            if chain is not None:
                self.mesh_chain = chain
        self.set_mesh_preludes = set_mesh_preludes

        def make_evict(side):
            def evict_sharded(own, wm, kh):
                return _vec_n(self._evict_impl(_scalar_n(own), wm, kh,
                                               side))
            return jit_state(shard_map(
                evict_sharded, mesh=mesh, in_specs=(shard, repl, repl),
                out_specs=shard), name=f"sharded_join_evict_s{side}")

        evicts = {LEFT: make_evict(LEFT), RIGHT: make_evict(RIGHT)}
        self._evict = lambda own, wm, kh, side: evicts[side](own, wm, kh)

        # sharded accumulators replace the parent's scalars
        sharding = NamedSharding(mesh, P(VNODE_AXIS))
        self._errs_dev = jax.device_put(
            jnp.zeros((self.n_shards, 3), dtype=jnp.int32), sharding)
        zero = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        self._n_dev = [zero, zero]
        # own buffer, NOT an alias of `zero`: the fused apply DONATES the
        # drop accumulator, and donating a buffer `_n_dev` still holds
        # would delete it out from under the watchdog fetch
        self._dropped_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        self._send_occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        self.sides = [self._sharded_empty(s) for s in (LEFT, RIGHT)]
        # one packed fetch per barrier: summed errs + shuffle drops +
        # max send-bucket demand (the adaptive slack signal)
        self._watchdog_pack_sh = jit_state(
            lambda errs, dr, so: jnp.concatenate(
                [jnp.sum(errs, axis=0), jnp.sum(dr)[None],
                 jnp.max(so)[None]]),
            name="sharded_join_watchdog_pack")

    def _sharded_empty(self, side: int) -> SortedSideState:
        S = self.n_shards
        local = _empty_sorted_side(self.capacity[side],
                                   self._col_dtypes[side])
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))

        def expand(x):
            if x.ndim == 0:
                g = jnp.zeros(S, dtype=x.dtype)
            else:
                g = jnp.tile(x, (S,) + (1,) * (x.ndim - 1))
            return jax.device_put(g, sharding)

        return jax.tree_util.tree_map(expand, local)

    def _empty(self, side: int) -> SortedSideState:
        # called by the parent constructor before the mesh fields exist;
        # replaced by _sharded_empty right after
        return _empty_sorted_side(self.capacity[side],
                                  self._col_dtypes[side])

    # ------------------------------------------------------- durability
    def _shard_slice(self, st: SortedSideState, sh: int,
                     side: int) -> SortedSideState:
        """Shard sh's LOCAL view of a global [S*C] side state."""
        C = self.capacity[side]
        lo = sh * C
        return SortedSideState(
            st.khash[lo:lo + C],
            tuple(c[lo:lo + C] for c in st.cols),
            tuple(v[lo:lo + C] for v in st.valids),
            st.degree[lo:lo + C],
            st.n[sh].reshape(()))

    def _persist(self, barrier) -> None:
        """Durable flush of the sharded sides: per-shard snapshot diffs
        (each shard's slice is a valid local sorted state, the parent's
        diff program is shape-local), with ALL shards'/sides' payloads
        shipped in TWO d2h calls — one counts fetch, one packed buffer
        (the per-call fetch tax would otherwise multiply by 2·S·sides).
        The diff programs dispatch AT the barrier (against non-donated
        snapshot bases); the blocking fetches run as PURE waits on the
        uploader thread, with the count-dependent slicing/packing done in
        a loop-side continuation (two threads dispatching concurrently
        deadlocks jax)."""
        from ..common.chunk import OP_DELETE, OP_INSERT
        from ..utils.d2h import (fetch_flat, finish_prefix_groups,
                                 prepare_prefix_groups)
        # stamp the interval's replay point with the epoch this barrier
        # seals; the coordinator drops it when that epoch commits
        self.ingest_log.seal(barrier.epoch.prev)
        tables = [st for st in (self.state_tables[LEFT],
                                self.state_tables[RIGHT]) if st is not None]
        if not tables:
            return
        pending = []     # (table, [per-shard diff tuples])
        for s in (LEFT, RIGHT):
            st = self.state_tables[s]
            if st is None:
                continue
            if self._flush_dirty[s]:
                diffs = [self._diff(
                    self._shard_slice(self.sides[s], sh, s),
                    self._shard_slice(self._snap[s], sh, s))
                    for sh in range(self.n_shards)]
                pending.append((st, diffs))
                self._snap[s] = self.sides[s]
                self._flush_dirty[s] = False
        counts_dev = (jnp.stack(
            [x for _, diffs in pending
             for d in diffs for x in (d[1], d[3])])
            if pending else None)
        new_epoch = barrier.epoch.curr
        cell: dict = {}

        def wait_counts():
            return np.asarray(counts_dev) if counts_dev is not None else None

        def cont_prepare(counts):
            if counts is None:
                return
            cell["counts"] = counts
            groups, ci = [], 0
            for _, diffs in pending:
                for d in diffs:
                    nd, ni = int(counts[ci]), int(counts[ci + 1])
                    ci += 2
                    groups.append((list(d[0]), nd))
                    groups.append((list(d[2]), ni))
            cell["prep"] = prepare_prefix_groups(groups)

        def wait_flat():
            prep = cell.get("prep")
            return fetch_flat(prep[0]) if prep is not None else None

        def cont_apply(host_flat):
            prep = cell.get("prep")
            if prep is not None:
                fetched = finish_prefix_groups(host_flat, prep[1], prep[2])
                counts = cell["counts"]
                gi = ci = 0
                for st, diffs in pending:
                    for d in diffs:
                        nd, ni = int(counts[ci]), int(counts[ci + 1])
                        ci += 2
                        del_cols = fetched[gi]
                        ins_cols = fetched[gi + 1]
                        gi += 2
                        if nd:
                            st.write_chunk_columns(
                                np.full(nd, OP_DELETE, dtype=np.int8),
                                del_cols, np.ones(nd, dtype=bool))
                        if ni:
                            st.write_chunk_columns(
                                np.full(ni, OP_INSERT, dtype=np.int8),
                                ins_cols, np.ones(ni, dtype=bool))
            for st in tables:
                st.commit(new_epoch)

        tables[0].store.defer_flush(barrier.epoch.prev,
                                    (wait_counts, cont_prepare),
                                    (wait_flat, cont_apply),
                                    table_id=tables[0].table_id)

    def _recover_reset(self, s: int, rows: list) -> None:
        """Per-shard capacity is sized by the WORST shard's row count
        (rows route by vnode-of-key, same as the apply-path masking)."""
        if rows:
            keys = [np.asarray([r[k] for r in rows], dtype=np.int64)
                    for k in self.key_indices[s]]
            from ..common.vnode import compute_vnodes_numpy
            shard_of = np.asarray(self._routing)[
                compute_vnodes_numpy(keys)]
            worst = int(np.bincount(
                shard_of, minlength=self.n_shards).max())
        else:
            worst = 0
        while worst > 0.7 * self.capacity[s]:
            self.capacity[s] *= 2
        self.sides[s] = self._sharded_empty(s)

    # ------------------------------------------------- HBM memory manager
    @property
    def mem_shards(self) -> int:
        """Shard count for the memory manager's per-shard breakdown
        (the side states split evenly over the mesh axis)."""
        return self.n_shards

    def state_shard_bytes(self) -> int:
        return self.state_bytes() // self.n_shards

    def _mem_local_slices(self, s: int) -> list:
        """Spill programs run per shard slice — each is a valid local
        sorted side (the same shape trick the sharded persist diff uses),
        so the parent's pack/range kernels apply unchanged."""
        return [self._shard_slice(self.sides[s], sh, s)
                for sh in range(self.n_shards)]

    def _mem_live_ns(self) -> list:
        """Worst-shard occupancy per side (capacity is PER SHARD)."""
        vals = np.asarray(jnp.concatenate([self.sides[LEFT].n,
                                           self.sides[RIGHT].n]))
        S = self.n_shards
        return [int(vals[:S].max()), int(vals[S:].max())]

    # --------------------------------------------------------- watchdog
    def _trace_cap(self, local_rows: int) -> int:
        """Send capacity at trace time: manual slack override, else the
        adaptive hint (sharded_agg._trace_cap, same contract)."""
        if not self.mesh_shuffle_adaptive or self._cap_hint is None:
            return shuffle_cap_out(local_rows, self.n_shards,
                                   self.mesh_shuffle_slack)
        return min(local_rows, max(64, self._cap_hint))

    def _note_send_fill(self, fill: int) -> None:
        """Asymmetric EWMA + peak floor over the observed per-destination
        demand (sharded_agg._note_send_fill carries the commentary)."""
        if not self.mesh_shuffle_adaptive:
            return
        if fill > self._fill_ewma:
            self._fill_ewma = float(fill)
        else:
            self._fill_ewma = 0.8 * self._fill_ewma + 0.2 * fill
        self._fill_peak = max(self._fill_peak, fill)
        self._fill_obs += 1
        if self._fill_obs < 3:
            return
        worst = max(self._fill_ewma, float(self._fill_peak), 1.0)
        self._cap_hint = 1 << (int(2 * worst) - 1).bit_length()

    def _check_watchdog(self) -> None:
        vals = np.asarray(self._watchdog_pack_sh(self._errs_dev,
                                                 self._dropped_dev,
                                                 self._send_occ_dev))
        n_mo, n_miss, n_ro, n_drop, fill = (int(x) for x in vals)
        self._note_send_fill(fill)
        sharding = NamedSharding(self.mesh, P(VNODE_AXIS))
        self._send_occ_dev = jax.device_put(
            jnp.zeros(self.n_shards, dtype=jnp.int32), sharding)
        if n_drop:
            # fail-stop before this epoch's checkpoint commits (same
            # contract as the sharded agg's shuffle-overflow check)
            from ..utils.metrics import MESH_SHUFFLE_DROPPED
            MESH_SHUFFLE_DROPPED.inc(n_drop)
            raise RuntimeError(
                f"mesh shuffle overflow: {n_drop} rows dropped en route "
                f"to their owner shard (per-pair send capacity sized by "
                f"mesh_shuffle_slack={self.mesh_shuffle_slack}; 0 = "
                f"zero-drop sizing)")
        if n_mo:
            raise RuntimeError(
                f"sharded-join match-buffer overflow ({n_mo} dropped)")
        if n_ro:
            raise RuntimeError(
                f"sharded-join state overflow ({n_ro} rows dropped; "
                f"per-shard capacity {self.capacity})")
        if n_miss:
            raise RuntimeError(
                f"sharded-join changelog inconsistency: {n_miss} deletes "
                f"matched no stored row")

"""Append-only dedup executor.

Reference: src/stream/src/executor/dedup/append_only_dedup.rs — emit only
the first row seen for each dedup-key; later duplicates are dropped. Input
must be append-only (the reference builds this only under append-only
plans); delete-like rows are counted on device and fail-stopped at the
barrier, before the epoch's checkpoint commits.

TPU re-design: the seen-key set is the open-addressing `HashTable` in HBM.
One jitted step per chunk: probe (which keys already existed), insert, and
keep exactly the first in-chunk occurrence of each new key (segment-min of
row ids per slot). Keys newly seen since the last checkpoint are tracked in
a device bitmap and compacted out once per barrier for the StateTable
(pk-only rows, like the reference's dedup state table).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import StreamChunk, OP_INSERT, op_sign
from ..ops.hash_table import HashTable, lookup, lookup_or_insert
from ..ops.jit_state import jit_state
from ..state.state_table import StateTable
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier


class AppendOnlyDedupExecutor(StatefulUnaryExecutor):
    def __init__(self, input: Executor, dedup_key_indices: Sequence[int],
                 capacity: int = 1 << 16,
                 state_table: Optional[StateTable] = None,
                 watchdog_interval: Optional[int] = 1):
        self.input = input
        self.key_indices = tuple(dedup_key_indices)
        self.schema = input.schema
        self.pk_indices = self.key_indices
        self.capacity = capacity
        self.identity = f"AppendOnlyDedup(keys={self.key_indices})"
        self._key_dtypes = tuple(
            input.schema[i].data_type.jnp_dtype for i in self.key_indices)
        self.table = HashTable.empty(capacity, self._key_dtypes)
        self.fresh = jnp.zeros(capacity, dtype=bool)  # new since persist
        # table, fresh bitmap, and error accumulator are threaded (the
        # only refs are re-bound in on_chunk) — donate; _fresh_keys is a
        # read-only persistence view, never donated
        self._apply = jit_state(self._apply_impl, donate_argnums=(0, 1, 2),
                                name="dedup_apply")
        self._fresh_keys = jit_state(self._fresh_keys_impl,
                                     name="dedup_fresh_keys")
        self._errs_dev = jnp.zeros((), dtype=jnp.int32)
        self._init_stateful(state_table, watchdog_interval)

    def fence_tokens(self) -> list:
        return [self.table.keys[0]] + super().fence_tokens()

    def _apply_impl(self, table: HashTable, fresh, errs,
                    chunk: StreamChunk):
        # append-only contract: delete-like rows are a violation (counted
        # on device, fail-stopped pre-commit) and never touch the state
        active = chunk.vis & (op_sign(chunk.ops) > 0)
        n_viol = jnp.sum((chunk.vis & (op_sign(chunk.ops) < 0))
                         .astype(jnp.int32))
        key_cols = [chunk.columns[i].data for i in self.key_indices]
        N = chunk.capacity
        pre = lookup(table, key_cols, active)         # existing keys
        table2, slots, n_un = lookup_or_insert(table, key_cols, active)
        C = table2.capacity
        new = active & (pre < 0) & (slots >= 0)
        # first in-chunk occurrence per slot wins
        row_ids = jnp.arange(N, dtype=jnp.int32)
        seg = jnp.where(new, slots, C)
        first = jax.ops.segment_min(row_ids, seg, C + 1)
        keep = new & (first[jnp.clip(slots, 0, C)] == row_ids)
        fresh2 = fresh.at[seg].set(True, mode="drop")
        return table2, fresh2, errs + n_un + n_viol, keep

    def _fresh_keys_impl(self, table: HashTable, fresh):
        """Compact the fresh keys to the front (for persistence)."""
        C = table.capacity
        rank = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        sel = jnp.zeros(C, dtype=jnp.int32).at[
            jnp.where(fresh, rank, C)].set(jnp.arange(C, dtype=jnp.int32),
                                           mode="drop")
        n = jnp.sum(fresh.astype(jnp.int32))
        return tuple(k[sel] for k in table.keys), n

    # -------------------------------------------------------------- hooks
    def on_chunk(self, chunk: StreamChunk) -> StreamChunk:
        self.table, self.fresh, self._errs_dev, keep = self._apply(
            self.table, self.fresh, self._errs_dev, chunk)
        return StreamChunk(chunk.columns, chunk.ops, keep, chunk.schema)

    def check_watchdog(self) -> None:
        n = int(np.asarray(self._errs_dev))
        if n:
            raise RuntimeError(
                f"dedup overflow or append-only violation ({n} rows, "
                f"capacity {self.capacity})")

    def persist(self, barrier: Barrier, flushed) -> None:
        if self.state_table is None:
            return
        keys, n = self._fresh_keys(self.table, self.fresh)
        n = int(n)
        if n:
            keys_np = [np.asarray(k)[:n] for k in keys]
            rows = [(int(OP_INSERT), tuple(k[r].item() for k in keys_np))
                    for r in range(n)]
            self.state_table.write_chunk_rows(rows)
        self.fresh = jnp.zeros(self.capacity, dtype=bool)
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        rows = [row for _, row in self.state_table.iter_all()]
        if not rows:
            return
        n = len(rows)
        cap = self.capacity
        while n > 0.7 * cap:
            cap *= 2
        if cap != self.capacity:
            self.capacity = cap
            self.fresh = jnp.zeros(cap, dtype=bool)
        key_cols = [
            jnp.asarray(np.asarray([r[j] for r in rows]), dtype=dt)
            for j, dt in enumerate(self._key_dtypes)]
        table = HashTable.empty(cap, self._key_dtypes)
        self.table, _, n_un = lookup_or_insert(
            table, key_cols, jnp.ones(n, dtype=bool))
        assert int(n_un) == 0

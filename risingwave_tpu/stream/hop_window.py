"""HopWindow executor — stateless sliding-window expansion.

Reference: src/stream/src/executor/hop_window.rs:386 — each input row is
emitted once per window it falls into (window_size / window_slide copies)
with computed window_start / window_end columns appended; pure map, no
state. Here each copy is its own output chunk (same static capacity as the
input — XLA-friendly), emitted back-to-back: copy k shifts the aligned
window start back by k slides.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import DataType, Field, Schema
from .executor import Executor, StatelessUnaryExecutor
from .message import Watermark


class HopWindowExecutor(StatelessUnaryExecutor):
    def __init__(self, input: Executor, time_col: int,
                 window_slide_us: int, window_size_us: int,
                 output_indices: Sequence[int] | None = None):
        super().__init__(input)
        assert window_size_us > 0 and window_slide_us > 0
        self.time_col = time_col
        self.slide = window_slide_us
        self.size = window_size_us
        self.n_windows = math.ceil(window_size_us / window_slide_us)
        in_fields = list(input.schema)
        self.schema = Schema(tuple(
            in_fields + [Field("window_start", DataType.TIMESTAMP),
                         Field("window_end", DataType.TIMESTAMP)]))
        self.window_start_idx = len(in_fields)
        self.window_end_idx = len(in_fields) + 1
        self.identity = (f"HopWindow(col={time_col}, slide={window_slide_us}us, "
                         f"size={window_size_us}us)")
        self._step = jax.jit(self._step_impl, static_argnums=1)

    def _step_impl(self, chunk: StreamChunk, k: int) -> StreamChunk:
        ts = chunk.columns[self.time_col].data
        # aligned window containing ts, shifted back k slides. floor-div
        # handles negative timestamps correctly (pre-epoch event time).
        ws = (jnp.floor_divide(ts, self.slide) - k) * self.slide
        we = ws + self.size
        # row in window iff ws <= ts < we; ws <= ts always holds, the upper
        # bound can fail when slide does not divide size
        vis = chunk.vis & (ts < we)
        cols = chunk.columns + (Column(ws), Column(we))
        return StreamChunk(cols, chunk.ops, vis, self.schema)

    async def execute(self):
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                for k in range(self.n_windows):
                    yield self._step(msg, k)
            elif isinstance(msg, Watermark):
                wm = self.map_watermark(msg)
                if wm is not None:
                    yield wm
            else:
                yield msg

    def map_watermark(self, wm: Watermark):
        if wm.col_idx == self.time_col:
            # a watermark on event time implies one on window_start lagged
            # by the full window size (reference derives the same bound)
            ws = (wm.val // self.slide - (self.n_windows - 1)) * self.slide
            return Watermark(self.window_start_idx, DataType.TIMESTAMP, ws)
        return wm

"""HopWindow executor — stateless sliding-window expansion.

Reference: src/stream/src/executor/hop_window.rs:386 — each input row is
emitted once per window it falls into (window_size / window_slide copies)
with computed window_start / window_end columns appended; pure map, no
state. The whole expansion is ONE jitted program emitting ONE chunk of
static capacity n_windows * input_capacity (copy k shifts the aligned
window start back by k slides). One big program beats n_windows small ones:
per-program dispatch overhead through the TPU tunnel is the dominant cost
for sub-ms kernels, and downstream executors amortize their own per-chunk
overhead over n_windows times more rows.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import DataType, Field, Schema
from .executor import Executor, StatelessUnaryExecutor
from .message import Watermark
from ..ops.jit_state import jit_state


class HopWindowExecutor(StatelessUnaryExecutor):
    # Mesh-chain fusion: hollow hop passes raw chunks through; the K-fold
    # expansion runs per-shard inside the downstream fused program (see
    # ProjectExecutor — same contract; hop is row-wise per input row, the
    # K copies of a row stay on the producing shard until the shuffle).
    mesh_hollow = False
    mesh_chain_hop = None

    def mesh_prelude_fn(self):
        return self._step_impl

    def __init__(self, input: Executor, time_col: int,
                 window_slide_us: int, window_size_us: int,
                 output_indices: Sequence[int] | None = None):
        super().__init__(input)
        assert window_size_us > 0 and window_slide_us > 0
        self.time_col = time_col
        self.slide = window_slide_us
        self.size = window_size_us
        self.n_windows = math.ceil(window_size_us / window_slide_us)
        in_fields = list(input.schema)
        full_fields = in_fields + [Field("window_start", DataType.TIMESTAMP),
                                   Field("window_end", DataType.TIMESTAMP)]
        ws_full, we_full = len(in_fields), len(in_fields) + 1
        # output pruning (reference hop_window.rs applies output_indices);
        # window_start_idx / window_end_idx are OUTPUT positions (-1 = pruned)
        self.output_indices = (tuple(output_indices) if output_indices is not None
                               else tuple(range(len(full_fields))))
        self._ws_full, self._we_full = ws_full, we_full
        self.schema = Schema(tuple(full_fields[i] for i in self.output_indices))
        def _outpos(full_idx: int) -> int:
            return self.output_indices.index(full_idx) if full_idx in self.output_indices else -1
        self.window_start_idx = _outpos(ws_full)
        self.window_end_idx = _outpos(we_full)
        self.identity = (f"HopWindow(col={time_col}, slide={window_slide_us}us, "
                         f"size={window_size_us}us)")
        self._step = jit_state(self._step_impl, name="hop_window_step")

    def _step_impl(self, chunk: StreamChunk) -> StreamChunk:
        K = self.n_windows
        ts = chunk.columns[self.time_col].data
        ks = jnp.repeat(jnp.arange(K, dtype=ts.dtype), chunk.capacity)
        tiled = lambda a: jnp.tile(a, K)
        ts_t = tiled(ts)
        # aligned window containing ts, shifted back k slides. floor-div
        # handles negative timestamps correctly (pre-epoch event time).
        ws = (jnp.floor_divide(ts_t, self.slide) - ks) * self.slide
        we = ws + self.size
        # row in window iff ws <= ts < we; ws <= ts always holds, the upper
        # bound can fail when slide does not divide size
        vis = tiled(chunk.vis) & (ts_t < we)
        full = tuple(
            Column(tiled(c.data), None if c.valid is None else tiled(c.valid))
            for c in chunk.columns) + (Column(ws), Column(we))
        cols = tuple(full[i] for i in self.output_indices)
        return StreamChunk(cols, tiled(chunk.ops), vis, self.schema)

    async def execute(self):
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                if self.mesh_hollow:
                    yield msg       # expansion runs fused downstream
                    continue
                if self.mesh_chain_hop is not None:
                    from .monitor import mesh_host_round_trip
                    mesh_host_round_trip(self.mesh_chain_hop)
                yield self._step(msg)
            elif isinstance(msg, Watermark):
                wm = self.map_watermark(msg)
                if wm is not None:
                    yield wm
            else:
                yield msg

    def map_watermark(self, wm: Watermark):
        if wm.col_idx == self.time_col:
            # a watermark on event time implies one on window_start lagged
            # by the full window size (reference derives the same bound)
            if self.window_start_idx < 0:
                return None
            ws = (wm.val // self.slide - (self.n_windows - 1)) * self.slide
            return Watermark(self.window_start_idx, DataType.TIMESTAMP, ws)
        # input-column watermarks remap through the output pruning
        if wm.col_idx in self.output_indices:
            return wm.with_idx(self.output_indices.index(wm.col_idx))
        return None

"""Mesh-sharded retractable top-N — q5-shaped ranking ON the mesh plane.

`RetractableTopNExecutor`'s dense sorted store and snapshot-diff flush,
sharded over the vnode mesh axis (sharded_store.py carries the plumbing:
fused `mesh_ingest_chunk` shuffle + per-interval `lax.scan`, watchdog
fail-stop, `MeshIngestLog` replay, durable persist/seal/recovery through
the sharded layout).

Two ranking modes, picked by the plan shape:

* GROUPED (`group_key_indices` non-empty): rows route on the group key,
  so every group lives whole on one shard and the parent's rank-within-
  group flush runs per shard unchanged — ranks never cross shards.

* GLOBAL (the binder's `ORDER BY ... LIMIT k` lowering: no group key):
  rows route on the STREAM KEY (delete/insert netting needs pk
  co-location), so the top-k spans shards. The flush then runs in two
  stages inside one program: each shard locally ranks its rows and
  contributes its best `offset+limit` CANDIDATES (any globally-top row
  is locally-top: local rank never exceeds global rank under the same
  total order), an `all_gather` over the mesh axis replicates the
  S*(offset+limit) candidate rows, and every shard re-ranks them to the
  identical global top set — the emitted diff is vis-masked to shard 0
  so the output appears once. The candidate gather moves O(S*k) rows
  over ICI per barrier, not O(n): the store itself never leaves the
  shards.

Both modes rank by the parent's exact (order keys, row-key hash) total
order, so the selected set — and therefore the emitted diff — is
bit-identical to the single-device executor's.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, OP_DELETE, OP_INSERT
from ..ops.hash_table import stable_lexsort
from ..parallel.mesh import VNODE_AXIS
from .executor import Executor
from .retract_top_n import RetractableTopNExecutor
from .sharded_store import ShardedSortedStoreMixin
from .sorted_join import _HSENTINEL, key_hash


class ShardedTopNExecutor(ShardedSortedStoreMixin, RetractableTopNExecutor):

    _SEC_COUNT = "top_n"
    _overflow_what = "sharded top-N store"

    def __init__(self, input: Executor,
                 group_key_indices: Sequence[int],
                 order_col=None, limit: int = 0, offset: int = 0,
                 descending: bool = False,
                 order_specs: Optional[Sequence[tuple]] = None,
                 capacity: int = 1 << 11,
                 state_table=None,
                 pk_indices: Optional[Sequence[int]] = None,
                 watchdog_interval: Optional[int] = 1,
                 *, mesh, mesh_shuffle: bool = True,
                 mesh_shuffle_slack: int = 0,
                 mesh_shuffle_adaptive: bool = True):
        # parent ctor builds the single-device [C] store + programs;
        # _init_sharded replaces them with the [S*C] mesh-sharded layout
        # (capacity is PER SHARD from here on)
        super().__init__(input, group_key_indices, order_col, limit,
                         offset, descending, order_specs, capacity,
                         state_table, pk_indices, watchdog_interval)
        self.global_mode = not self.group_key_indices
        # global mode routes on the stream key: a retraction carries the
        # same pk as its insert, so netting stays shard-local
        self.route_key_indices = (self.group_key_indices
                                  or self.pk_indices)
        if self.global_mode:
            assert self.offset + self.limit <= capacity, \
                "global top-N needs offset+limit <= per-shard capacity " \
                "(each shard contributes that many candidates)"
        self._init_sharded(mesh, mesh_shuffle, mesh_shuffle_slack,
                           mesh_shuffle_adaptive, watchdog_interval)
        self.identity = (f"ShardedTopN[S={self.n_shards}]"
                         f"(g={self.group_key_indices}, "
                         f"by={self.order_specs}, k={limit})")

    # ------------------------------------------------------------- flush
    def _flush_local(self, khash, cols, valids, n, top_hash, top_cols,
                     top_valids, top_n):
        if not self.global_mode:
            # groups are co-located: the parent's per-group rank diff is
            # exact on each shard's slice
            return self._flush_impl(khash, cols, valids, n, top_hash,
                                    top_cols, top_valids, top_n)
        return self._flush_impl_global(khash, cols, valids, n, top_hash,
                                       top_cols, top_valids, top_n)

    def _okeys_of(self, cols):
        # the parent's descending encodings: order comparisons must be
        # IDENTICAL local vs global or candidate pruning would be unsound
        okeys = []
        for c, desc in reversed(self.order_specs):
            oval = cols[c]
            if jnp.issubdtype(oval.dtype, jnp.floating):
                okeys.append(-oval if desc else oval)
            else:
                okeys.append(~oval if desc else oval)
        return okeys

    def _flush_impl_global(self, khash, cols, valids, n, top_hash,
                           top_cols, top_valids, top_n):
        C = self.capacity
        S = self.n_shards
        K = min(C, self.offset + self.limit)
        G = S * K
        imax = jnp.iinfo(jnp.int64).max
        live = jnp.arange(C, dtype=jnp.int32) < n

        # stage 1 — local rank: each shard's best K rows are the only
        # possible global top members (same total order ⇒ local rank is
        # a lower bound on global rank)
        order = stable_lexsort(tuple(
            [khash] + self._okeys_of(cols)
            + [jnp.where(live, jnp.zeros(C, dtype=jnp.int64), imax)]))
        cand = order[:K]

        def g(x):
            return jax.lax.all_gather(x, VNODE_AXIS, tiled=True)

        g_live = g(live[cand])
        g_khash = g(khash[cand])
        g_cols = [g(c[cand]) for c in cols]
        g_valids = [g(v[cand]) for v in valids]

        # stage 2 — global re-rank of the S*K replicated candidates;
        # dead padding sorts last, rank == position (single group)
        gorder = stable_lexsort(tuple(
            [g_khash] + self._okeys_of(g_cols)
            + [jnp.where(g_live, jnp.zeros(G, dtype=jnp.int64), imax)]))
        s_live = g_live[gorder]
        pos = jnp.arange(G, dtype=jnp.int32)
        in_top = s_live & (pos >= self.offset) \
            & (pos < self.offset + self.limit)
        s_cols = [c[gorder] for c in g_cols]
        s_valids = [v[gorder] for v in g_valids]
        rhash = key_hash(s_cols)
        topk = jnp.where(in_top, rhash, _HSENTINEL)
        torder = jnp.argsort(topk, stable=True)
        n_top = jnp.sum(in_top.astype(jnp.int32))

        def fit(x, fill):
            # the diff state is [C] per shard; sentinel/zero padding
            # keeps the hash array sorted for the searchsorted probe
            if G >= C:
                return x[:C]
            return jnp.concatenate(
                [x, jnp.full(C - G, fill, dtype=x.dtype)])

        new_hash = fit(topk[torder], _HSENTINEL)
        new_cols = tuple(fit(c[torder], jnp.zeros((), dtype=c.dtype))
                         for c in s_cols)
        new_valids = tuple(fit(v[torder], False) for v in s_valids)

        def member(a_hash, a_n, b_hash):
            i = jnp.clip(jnp.searchsorted(b_hash, a_hash), 0, C - 1)
            return (jnp.arange(C) < a_n) & (b_hash[i] == a_hash)

        old_still = member(top_hash, top_n, new_hash)
        emit_del = (jnp.arange(C) < top_n) & ~old_still
        new_was = member(new_hash, n_top, top_hash)
        emit_ins = (jnp.arange(C) < n_top) & ~new_was
        # every shard computed the IDENTICAL diff from the replicated
        # candidates — emit it once (shard 0's slice of the output)
        once = jax.lax.axis_index(VNODE_AXIS) == 0
        out_cols = tuple(
            Column(jnp.concatenate([tc, nc]), jnp.concatenate([tv, nv]))
            for tc, nc, tv, nv in zip(top_cols, new_cols, top_valids,
                                      new_valids))
        ops = jnp.concatenate([jnp.full(C, OP_DELETE, dtype=jnp.int8),
                               jnp.full(C, OP_INSERT, dtype=jnp.int8)])
        vis = jnp.concatenate([emit_del, emit_ins]) & once
        return (new_hash, new_cols, new_valids, n_top.astype(jnp.int32),
                out_cols, ops, vis)

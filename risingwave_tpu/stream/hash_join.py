"""HashJoin executor — streaming two-sided equi-join with device state.

Reference: src/stream/src/executor/hash_join.rs (JoinSide :76,141; aligned
2-input loop `into_stream` :478; `eq_join_oneside` :792) and the join state
at managed_state/join/mod.rs:238-268 — each side keeps a multimap
join_key -> rows; a chunk from one side probes the OTHER side's map to emit
joined changelog rows, then updates its OWN map.

TPU re-design: each side's multimap is a struct-of-arrays in HBM —
  * key_table: open-addressing HashTable over the join-key columns [CK]
  * head[CK]:  first row index of the key's chain (-1 = empty)
  * rows/valids: per-column row store [CR] + next[CR] links + live[CR]
Applying a chunk is ONE jitted step: probe the other side's key table, walk
all chains in lock-step (a while_loop over the longest chain, each iteration
a cumsum-compaction append into a fixed-capacity match buffer), then apply
deletes (chain walk + claim contest tombstones one instance per delete) and
inserts (batch row allocation + vectorized multi-push-front chain link that
handles duplicate keys within the chunk by sorting rows by key slot).

Changelog contract: an insert-like input row emits Insert matches, a
delete-like row emits Delete matches (update pairs degrade to Delete/Insert,
as the reference does when pairs cannot be kept adjacent). Inner join only —
degree tables for outer joins are the next increment.

Deletion identifies rows by the side's pk within the key chain. Rows are
never unlinked (chains stay intact); tombstones are reclaimed by the
barrier-time rebuild, exactly like HashAgg's zombie purge.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, StreamChunk, OP_DELETE, OP_INSERT, op_sign,
)
from ..common.types import Field, Schema
from ..memory.accounting import pytree_bytes
from ..memory.spill import HostSpill
from ..ops.hash_table import (HashTable, lookup, lookup_or_insert,
                              lru_stamp, pack_rows, stable_lexsort)
from ..ops.jit_state import jit_state
from ..state.state_table import StateTable
from .align import LEFT, RIGHT, barrier_align
from .executor import Executor
from .message import Barrier, BarrierKind, Watermark


@jax.tree_util.register_pytree_node_class
@dataclass
class JoinSideState:
    """Device state of one join side (key table cap CK, row store cap CR)."""

    key_table: HashTable                 # over join-key columns [CK]
    head: jnp.ndarray                    # int32 [CK], -1 = empty chain
    rows: tuple[jnp.ndarray, ...]        # per input column [CR]
    valids: tuple[jnp.ndarray, ...]      # per input column bool [CR]
    next: jnp.ndarray                    # int32 [CR]
    live: jnp.ndarray                    # bool [CR]
    dirty: jnp.ndarray                   # bool [CR] — changed since persist
    top: jnp.ndarray                     # int32 scalar — rows ever allocated

    def tree_flatten(self):
        return ((self.key_table, self.head, self.rows, self.valids,
                 self.next, self.live, self.dirty, self.top), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        kt, head, rows, valids, nxt, live, dirty, top = children
        return cls(kt, head, tuple(rows), tuple(valids), nxt, live, dirty, top)

    @property
    def key_capacity(self) -> int:
        return self.head.shape[0]

    @property
    def row_capacity(self) -> int:
        return self.live.shape[0]


def _empty_side(key_capacity: int, row_capacity: int,
                key_dtypes: Sequence, col_dtypes: Sequence) -> JoinSideState:
    return JoinSideState(
        key_table=HashTable.empty(key_capacity, key_dtypes),
        head=jnp.full(key_capacity, -1, dtype=jnp.int32),
        rows=tuple(jnp.zeros(row_capacity, dtype=dt) for dt in col_dtypes),
        valids=tuple(jnp.zeros(row_capacity, dtype=bool) for _ in col_dtypes),
        next=jnp.full(row_capacity, -1, dtype=jnp.int32),
        live=jnp.zeros(row_capacity, dtype=bool),
        dirty=jnp.zeros(row_capacity, dtype=bool),
        top=jnp.int32(0),
    )


def _bulk_insert(side: JoinSideState, slots: jnp.ndarray, ins: jnp.ndarray,
                 col_data: Sequence[jnp.ndarray], col_valid: Sequence[jnp.ndarray],
                 dirty_vals: jnp.ndarray):
    """Insert the masked rows into the side's row store + chains.

    slots: key slot per row (from lookup_or_insert); ins: bool mask; rows with
    the SAME key slot within the batch are chained among themselves (sorted by
    slot, linked in batch order, head points at the batch's last row — the
    probe order of a chain is reverse insertion order, which is fine for an
    unordered multimap). Returns (side', n_row_overflow).
    """
    CK = side.key_capacity
    CR = side.row_capacity
    N = slots.shape[0]
    n_ins = jnp.sum(ins.astype(jnp.int32))
    rank = jnp.cumsum(ins.astype(jnp.int32)) - 1
    new_ridx = side.top + rank                       # row id per inserted row
    ok = ins & (new_ridx < CR)
    tgt = jnp.where(ok, new_ridx, CR)
    rows = tuple(r.at[tgt].set(d.astype(r.dtype), mode="drop")
                 for r, d in zip(side.rows, col_data))
    valids = tuple(v.at[tgt].set(m, mode="drop")
                   for v, m in zip(side.valids, col_valid))
    live = side.live.at[tgt].set(True, mode="drop")
    dirty = side.dirty.at[tgt].set(dirty_vals, mode="drop")

    # chain link: sort batch rows by key slot so same-slot rows are adjacent
    seg = jnp.where(ok, slots, CK)
    order = jnp.argsort(seg, stable=True)            # [N]
    sseg = seg[order]
    sridx = new_ridx[order]
    prev_same = jnp.concatenate([jnp.array([False]), sseg[1:] == sseg[:-1]])
    prev_ridx = jnp.concatenate([jnp.array([0], dtype=sridx.dtype), sridx[:-1]])
    old_head = side.head[jnp.clip(sseg, 0, CK - 1)]
    nxt_val = jnp.where(prev_same, prev_ridx, old_head).astype(jnp.int32)
    s_ok = ok[order]
    nxt = side.next.at[jnp.where(s_ok, sridx, CR)].set(nxt_val, mode="drop")
    is_last = jnp.concatenate([sseg[:-1] != sseg[1:], jnp.array([True])])
    head = side.head.at[
        jnp.where(s_ok & is_last, sseg, CK)].set(sridx.astype(jnp.int32), mode="drop")
    top = jnp.minimum(side.top + n_ins, CR).astype(jnp.int32)
    n_overflow = jnp.maximum(side.top + n_ins - CR, 0)
    return JoinSideState(side.key_table, head, rows, valids, nxt, live,
                         dirty, top), n_overflow


class HashJoinExecutor(Executor):
    """Inner equi-join. Output schema = left columns ++ right columns
    (optionally projected by output_indices); output pk = left pk ++ right pk.

    condition: optional expression over the FULL (left++right) output row,
    applied as a post-probe filter (the reference's non-equi `cond`)."""

    def __init__(self, left: Executor, right: Executor,
                 left_key_indices: Sequence[int],
                 right_key_indices: Sequence[int],
                 left_pk_indices: Sequence[int],
                 right_pk_indices: Sequence[int],
                 key_capacity: int = 1 << 14,
                 row_capacity: int = 1 << 16,
                 match_factor: int = 2,
                 condition=None,
                 output_indices: Optional[Sequence[int]] = None,
                 state_tables: Optional[tuple[StateTable, StateTable]] = None,
                 clean_watermark_cols: tuple[Optional[int], Optional[int]] = (None, None),
                 watchdog_interval: Optional[int] = 1):
        self.inputs = (left, right)
        self.key_indices = (tuple(left_key_indices), tuple(right_key_indices))
        self.pk_indices_side = (tuple(left_pk_indices), tuple(right_pk_indices))
        assert len(self.key_indices[0]) == len(self.key_indices[1])
        lt, rt = left.schema, right.schema
        for li, ri in zip(*self.key_indices):
            assert lt[li].data_type.np_dtype == rt[ri].data_type.np_dtype, \
                f"join key dtype mismatch {lt[li]} vs {rt[ri]}"
        self._key_dtypes = tuple(
            lt[i].data_type.jnp_dtype for i in self.key_indices[0])
        self._col_dtypes = (
            tuple(f.data_type.jnp_dtype for f in lt),
            tuple(f.data_type.jnp_dtype for f in rt),
        )
        full_fields = [Field(f"l_{f.name}" if f.name in {g.name for g in rt} else f.name,
                             f.data_type, f.scale) for f in lt]
        full_fields += [Field(f"r_{f.name}" if f.name in {g.name for g in lt} else f.name,
                              f.data_type, f.scale) for f in rt]
        self.output_indices = (tuple(output_indices) if output_indices is not None
                               else tuple(range(len(full_fields))))
        self.schema = Schema(tuple(full_fields[i] for i in self.output_indices))
        out_pk_full = (tuple(self.pk_indices_side[0])
                       + tuple(len(lt) + i for i in self.pk_indices_side[1]))
        self.pk_indices = tuple(self.output_indices.index(i)
                                for i in out_pk_full if i in self.output_indices)
        self.key_capacity = [key_capacity, key_capacity]
        self.row_capacity = [row_capacity, row_capacity]
        self.match_factor = match_factor
        self.condition = condition
        self.state_tables = state_tables or (None, None)
        self.clean_cols = tuple(clean_watermark_cols)
        self._pending_clean: list[Optional[int]] = [None, None]
        self.identity = (f"HashJoin(l={self.key_indices[0]}, "
                         f"r={self.key_indices[1]})")
        self.sides = [self._empty(s) for s in (LEFT, RIGHT)]
        # Donation: the OWN side (arg 0) and the error accumulator (arg 2)
        # are threaded — `self.sides[s] = self._apply(self.sides[s], ...)`
        # holds the only reference — so their table buffers update in
        # place. The OTHER side (arg 1) is read-only and must never be
        # donated: it is still live as self.sides[1 - s].
        self._apply = jit_state(self._apply_impl, static_argnames=("side",),
                                donate_argnums=(0, 2), name="hash_join_apply")
        self._persist_view = jit_state(self._persist_view_impl,
                                       name="hash_join_persist_view")
        self._evict = jit_state(self._evict_impl, static_argnames=("side",),
                                donate_argnums=(0,), name="hash_join_evict")
        self._evict_rows = jit_state(self._evict_rows_impl,
                                     static_argnames=("side",),
                                     name="hash_join_evict_rows")
        self._stats = jit_state(self._stats_impl, name="hash_join_stats")
        self._rehash = jit_state(self._rehash_impl,
                                 static_argnames=("side", "new_ck", "new_cr"),
                                 donate_argnums=(0,), name="hash_join_rehash")
        # multi-chunk apply: consecutive same-side chunks inside one
        # barrier interval scan through the probe/update step in ONE
        # dispatch; the run drains on side switch, barrier, or watermark,
        # so cross-side and chunk/watermark ordering are preserved exactly
        self._use_chunk_batching = True
        self._batch_max = 8
        self._run_chunks: list[StreamChunk] = []
        self._run_side: Optional[int] = None
        self._apply_scans: dict = {}
        self.rebuilds = 0
        # 1 = fetch + fail-stop before every checkpoint commit; None =
        # NO fetch ever, not even at stop (see HashAggExecutor: on a
        # tunneled TPU the first d2h transfer permanently degrades
        # dispatch, so latency-critical pipelines keep the whole process
        # transfer-free and rest on CPU-backend tests for correctness)
        if watchdog_interval not in (None, 1):
            raise ValueError(
                "watchdog_interval must be 1 or None (a lagged check would "
                "let checkpoints commit unverified state)")
        self.watchdog_interval = watchdog_interval
        self._dirty_since_flush = [False, False]
        # device-resident watchdog accumulator + latest per-side load stats;
        # fetched once per barrier (see _apply_impl docstring)
        self._errs_dev = jnp.zeros(4, dtype=jnp.int32)
        zero = jnp.zeros((), dtype=jnp.int32)
        self._occ_dev = [zero, zero]
        self._top_dev = [zero, zero]
        self._occ_known = [0, 0]
        self._top_known = [0, 0]
        self._watchdog_pack = jit_state(
            lambda errs, ol, tl, orr, tr: jnp.concatenate(
                [errs, jnp.stack([ol, tl, orr, tr])]),
            name="hash_join_watchdog_pack")
        # watermark bookkeeping: per side, last seen watermark per key position
        self._key_wms: list[dict[int, int]] = [{}, {}]
        self._emitted_key_wm: dict[int, int] = {}
        # ---- HBM memory manager hooks (memory/manager.py): per-ROW
        # int64 LRU epoch stamps per side; cold clean rows tombstone +
        # spill to host, the shrinking rehash reclaims their HBM, and a
        # later touch (probe, delete, or same-key insert) reloads the
        # key's rows at drain time before the chunk applies.
        self._mem_lru_on = False
        self._slot_epoch: list = [None, None]      # int64 [CR] per side
        self._spill = [HostSpill(), HostSpill()]
        self.mem_evicted_bytes = 0
        self.mem_reload_count = 0
        # keys the reload-LFU guard kept resident through an eviction
        # round (memory/manager.py ReloadGuard, set as self.mem_guard)
        self.mem_guard_protected = 0
        self._lru_stamp = jit_state(self._lru_stamp_impl,
                                    donate_argnums=(1,),
                                    name="hash_join_lru_stamp")
        self._mem_stats = jit_state(self._mem_stats_impl,
                                    name="hash_join_mem_stats")
        self._mem_pack = jit_state(self._mem_pack_impl,
                                   name="hash_join_mem_pack")
        self._mem_evict_apply = jit_state(self._mem_evict_impl,
                                          donate_argnums=(0,),
                                          name="hash_join_mem_evict")
        self._mem_reloads: dict = {}

    def fence_tokens(self) -> list:
        toks = [s.top for s in self.sides if s is not None]
        return toks + super().fence_tokens()

    def _empty(self, side: int) -> JoinSideState:
        return _empty_side(self.key_capacity[side], self.row_capacity[side],
                           self._key_dtypes, self._col_dtypes[side])

    # ------------------------------------------------------------- apply
    def _apply_impl(self, own: JoinSideState, other: JoinSideState,
                    errs: jnp.ndarray, chunk: StreamChunk, side: int):
        """Probe `other`, emit matches, update `own`. Returns
        (own', match buffers, errs', occ, top) — errs is the int32[4]
        device accumulator [unresolved, delete-miss, match-overflow,
        row-overflow]; it stays on device and the host fetches it once per
        barrier (a d2h copy serializes into the device stream, so per-chunk
        fetches would gate throughput on copy latency)."""
        key_idx = self.key_indices[side]
        pk_idx = self.pk_indices_side[side]
        N = chunk.capacity
        CRo = other.row_capacity
        CRs = own.row_capacity
        CKs = own.key_capacity
        M = self.match_factor * N

        key_cols = [chunk.columns[i].data for i in key_idx]
        key_valid = jnp.ones(N, dtype=bool)
        for i in key_idx:
            key_valid &= chunk.columns[i].valid_mask()
        active = chunk.vis & key_valid               # NULL keys never join
        signs = op_sign(chunk.ops)
        row_ids = jnp.arange(N, dtype=jnp.int32)

        # ---- within-chunk pk-run resolution ----
        # The reference applies rows strictly in order, so one chunk may
        # insert AND delete the same pk. Lexsort active rows by pk (row order
        # as tiebreak); each equal-pk run nets out to at most one effective
        # stored-row delete (the run's first op, if delete-like) and one
        # effective insert (the run's last op, if insert-like). Probe
        # emission below still uses every row — only STATE updates net out.
        sort_keys = [row_ids]                        # least significant
        for p in pk_idx:
            sort_keys.append(chunk.columns[p].data)
        sort_keys.append(~active)                    # inactive rows last
        order = stable_lexsort(tuple(sort_keys))
        s_act = active[order]
        same = s_act[1:] & s_act[:-1]
        for p in pk_idx:
            d = chunk.columns[p].data[order]
            same = same & (d[1:] == d[:-1])
        run_start = jnp.concatenate([jnp.array([True]), ~same])
        run_end = jnp.concatenate([~same, jnp.array([True])])
        s_signs = signs[order]
        eff_del_s = run_start & (s_signs < 0) & s_act
        eff_ins_s = run_end & (s_signs > 0) & s_act
        is_del = jnp.zeros(N, dtype=bool).at[order].set(eff_del_s)
        is_ins = jnp.zeros(N, dtype=bool).at[order].set(eff_ins_s)

        # ---- probe the other side: lock-step chain walk ----
        oslot = lookup(other.key_table, key_cols, active)
        cursor = jnp.where(oslot >= 0,
                           other.head[jnp.clip(oslot, 0, None)], -1)

        def pcond(st):
            cursor, m, _, _ = st
            return jnp.any(cursor >= 0)

        def pbody(st):
            cursor, m, out_own, out_oth = st
            cc = jnp.clip(cursor, 0, None)
            alive = (cursor >= 0) & other.live[cc]
            rank = jnp.cumsum(alive.astype(jnp.int32)) - 1
            pos = m + rank
            tgt = jnp.where(alive & (pos < M), pos, M)
            out_own = out_own.at[tgt].set(row_ids, mode="drop")
            out_oth = out_oth.at[tgt].set(cursor, mode="drop")
            m = (m + jnp.sum(alive.astype(jnp.int32))).astype(jnp.int32)
            cursor = jnp.where(cursor >= 0, other.next[cc], -1)
            return cursor, m, out_own, out_oth

        _, m_total, out_own, out_oth = jax.lax.while_loop(
            pcond, pbody,
            (cursor, jnp.int32(0),
             jnp.zeros(M, dtype=jnp.int32), jnp.zeros(M, dtype=jnp.int32)))
        n_match_overflow = jnp.maximum(m_total - M, 0)

        # ---- own-side update: deletes first (update pairs retract the OLD
        # row before the new one lands — reference applies rows in order) ----
        own_table, slots, n_un = lookup_or_insert(own.key_table, key_cols, active)
        own = JoinSideState(own_table, own.head, own.rows, own.valids,
                            own.next, own.live, own.dirty, own.top)
        dcur = jnp.where(is_del & (slots >= 0),
                         own.head[jnp.clip(slots, 0, None)], -1)

        def dcond(st):
            dcur = st[0]
            return jnp.any(dcur >= 0)

        def dbody(st):
            dcur, live, dirty, found = st
            cc = jnp.clip(dcur, 0, None)
            alive = (dcur >= 0) & live[cc]
            pkm = jnp.ones(N, dtype=bool)
            for p in pk_idx:
                pkm &= own.rows[p][cc] == chunk.columns[p].data.astype(own.rows[p].dtype)
            cand = alive & pkm & ~found
            # claim contest: at most one delete consumes a given row
            claim = jnp.full(CRs, N, dtype=jnp.int32)
            claim = claim.at[jnp.where(cand, dcur, CRs)].min(row_ids, mode="drop")
            win = cand & (claim[cc] == row_ids)
            live = live.at[jnp.where(win, dcur, CRs)].set(False, mode="drop")
            dirty = dirty.at[jnp.where(win, dcur, CRs)].set(True, mode="drop")
            found = found | win
            dcur = jnp.where(found | (dcur < 0), -1, own.next[cc])
            return dcur, live, dirty, found

        _, live2, dirty2, found = jax.lax.while_loop(
            dcond, dbody,
            (dcur, own.live, own.dirty, jnp.zeros(N, dtype=bool)))
        n_del_miss = jnp.sum((is_del & ~found).astype(jnp.int32))
        own = JoinSideState(own.key_table, own.head, own.rows, own.valids,
                            own.next, live2, dirty2, own.top)

        # ---- inserts ----
        own, n_row_overflow = _bulk_insert(
            own, slots, is_ins,
            [c.data for c in chunk.columns],
            [c.valid_mask() for c in chunk.columns],
            jnp.ones(N, dtype=bool))

        # ---- output assembly: left cols ++ right cols ----
        m_ok = jnp.minimum(m_total, M)
        out_vis = jnp.arange(M) < m_ok
        own_cols = [Column(jnp.take(c.data, out_own, axis=0),
                           jnp.take(c.valid_mask(), out_own, axis=0))
                    for c in chunk.columns]
        oc = jnp.clip(out_oth, 0, None)
        oth_cols = [Column(r[oc], v[oc])
                    for r, v in zip(other.rows, other.valids)]
        cols = own_cols + oth_cols if side == LEFT else oth_cols + own_cols
        ops_out = jnp.where(jnp.take(signs, out_own) > 0,
                            OP_INSERT, OP_DELETE).astype(jnp.int8)
        occ = jnp.sum(own.key_table.occupied.astype(jnp.int32))
        errs = errs + jnp.stack([
            n_un, n_del_miss, n_match_overflow, n_row_overflow,
        ]).astype(jnp.int32)
        return (own, tuple(cols), ops_out, out_vis, errs, occ, own.top)

    # ------------------------------------------------------- persistence
    def _persist_view_impl(self, side_state: JoinSideState):
        """Compacted dirty rows -> (cols..., valid flags..., ops, vis)."""
        CR = side_state.row_capacity
        dirty = side_state.dirty
        rank = jnp.cumsum(dirty.astype(jnp.int32)) - 1
        ids = jnp.arange(CR, dtype=jnp.int32)
        sel = jnp.zeros(CR, dtype=jnp.int32).at[
            jnp.where(dirty, rank, CR)].set(ids, mode="drop")
        n_dirty = jnp.sum(dirty.astype(jnp.int32))
        vis = ids < n_dirty
        ops = jnp.where(side_state.live[sel], OP_INSERT, OP_DELETE).astype(jnp.int8)
        cols = tuple(r[sel] for r in side_state.rows)
        return cols, ops, vis

    def _persist(self, barrier: Barrier) -> None:
        """Overlap-friendly durable flush (see HashAggExecutor._persist):
        the persist/evict views dispatch here against non-donated buffers
        and the dirty bits reset on-device immediately; the blocking d2h
        + columnar writes + commit run as a staged deferred store flush —
        inline by default, on the background uploader in pipelined mode.
        Both sides' payloads (full persist views + evict-delete prefixes)
        pack into ONE flat fetch; evict counts ride a separate tiny
        counts fetch first."""
        jobs = []    # (state_table, persist-view arrays|None, evict|None)
        ev_counts = []
        for s in (LEFT, RIGHT):
            st = self.state_tables[s]
            if st is None:
                continue
            dev = None
            if self._dirty_since_flush[s]:
                cols, ops, vis = self._persist_view(self.sides[s])
                dev = [ops, vis] + list(cols)
                side = self.sides[s]
                self.sides[s] = JoinSideState(
                    side.key_table, side.head, side.rows, side.valids,
                    side.next, side.live,
                    jnp.zeros(side.row_capacity, dtype=bool), side.top)
                self._dirty_since_flush[s] = False
            ev = None
            if self._pending_clean[s] is not None \
                    and self.clean_cols[s] is not None:
                ev_cols_dev, n_ev = self._evict_rows(
                    self.sides[s], self._pending_clean[s], side=s)
                ev = list(ev_cols_dev)
                ev_counts.append(jnp.ravel(n_ev))
            jobs.append((st, dev, ev))
        if not jobs:
            return
        from ..utils.d2h import (fetch_flat, finish_prefix_groups,
                                 prepare_prefix_groups)
        counts_dev = jnp.concatenate(ev_counts) if ev_counts else None
        new_epoch = barrier.epoch.curr
        cell: dict = {}

        def wait_counts():
            return np.asarray(counts_dev) if counts_dev is not None else None

        def cont_prepare(counts):
            groups, plan, ci = [], [], 0
            for _, dev, ev in jobs:
                g_dev = g_ev = None
                n_ev = 0
                if dev is not None:
                    g_dev = len(groups)
                    groups.append((dev, int(dev[0].shape[0])))  # full view
                if ev is not None:
                    n_ev = int(counts[ci])
                    ci += 1
                    if n_ev:
                        g_ev = len(groups)
                        groups.append((ev, n_ev))
                plan.append((g_dev, g_ev, n_ev))
            cell["plan"] = plan
            if groups:
                cell["prep"] = prepare_prefix_groups(groups)

        def wait_flat():
            prep = cell.get("prep")
            return fetch_flat(prep[0]) if prep is not None else None

        def cont_apply(host_flat):
            prep = cell.get("prep")
            outs = (finish_prefix_groups(host_flat, prep[1], prep[2])
                    if prep is not None else [])
            for (st, _, _), (g_dev, g_ev, n_ev) in zip(jobs, cell["plan"]):
                if g_dev is not None:
                    host = outs[g_dev]
                    vis_np = host[1].astype(bool, copy=False)
                    if vis_np.any():
                        # columnar batch write (state_table.rs:946): the
                        # C++ codec path, no per-row Python
                        st.write_chunk_columns(host[0], host[2:], vis_np)
                if g_ev is not None:
                    st.write_chunk_columns(
                        np.full(n_ev, OP_DELETE, dtype=np.int8),
                        outs[g_ev], np.ones(n_ev, dtype=bool))
                st.commit(new_epoch)

        jobs[0][0].store.defer_flush(barrier.epoch.prev,
                                     (wait_counts, cont_prepare),
                                     (wait_flat, cont_apply),
                                     table_id=jobs[0][0].table_id)

    def _evict_rows_impl(self, side_state: JoinSideState, wm, side: int):
        col = self.clean_cols[side]
        CR = side_state.row_capacity
        evict = side_state.live & (side_state.rows[col] < wm)
        rank = jnp.cumsum(evict.astype(jnp.int32)) - 1
        sel = jnp.zeros(CR, dtype=jnp.int32).at[
            jnp.where(evict, rank, CR)].set(jnp.arange(CR, dtype=jnp.int32),
                                            mode="drop")
        n = jnp.sum(evict.astype(jnp.int32))
        return tuple(r[sel] for r in side_state.rows), n

    def _evict_impl(self, side_state: JoinSideState, wm, side: int):
        col = self.clean_cols[side]
        keep = ~(side_state.live & (side_state.rows[col] < wm))
        return JoinSideState(
            side_state.key_table, side_state.head, side_state.rows,
            side_state.valids, side_state.next, side_state.live & keep,
            side_state.dirty, side_state.top)

    def recover(self) -> None:
        # spilled rows live in the durable tables too; recovery rebuilds
        # everything resident and drops the host spill
        for sp in self._spill:
            sp.clear()
        self._slot_epoch = [None, None]
        for s in (LEFT, RIGHT):
            st = self.state_tables[s]
            if st is None:
                continue
            rows = [r for _, r in st.iter_all()]
            if not rows:
                continue
            n = len(rows)
            self.row_capacity[s] = max(self.row_capacity[s],
                                       1 << (int(n / 0.7)).bit_length())
            self.key_capacity[s] = max(self.key_capacity[s],
                                       1 << (int(n / 0.7)).bit_length())
            self.sides[s] = self._empty(s)
            cap = 1 << max(1, (n - 1).bit_length())
            sch = self.inputs[s].schema
            arrays = [np.asarray([r[i] for r in rows], dtype=f.data_type.np_dtype)
                      for i, f in enumerate(sch)]
            chunk = StreamChunk.from_numpy(sch, arrays, capacity=cap)
            out = self._apply(self.sides[s],
                              self._empty(1 - s) if self.sides[1 - s] is None
                              else self.sides[1 - s], self._errs_dev, chunk,
                              side=s)
            self.sides[s] = out[0]
            self._errs_dev = out[4]
            # recovery rows are already durable: clear dirty
            side = self.sides[s]
            self.sides[s] = JoinSideState(
                side.key_table, side.head, side.rows, side.valids, side.next,
                side.live, jnp.zeros(side.row_capacity, dtype=bool), side.top)

    # ------------------------------------------------- HBM memory manager
    def state_bytes(self) -> int:
        extras = tuple(g for g in self._slot_epoch if g is not None)
        return pytree_bytes((self.sides, extras))

    @property
    def mem_spilled_rows(self) -> int:
        return self._spill[LEFT].rows + self._spill[RIGHT].rows

    def memory_enable_lru(self) -> None:
        self._mem_lru_on = True

    def _lru_stamp_impl(self, dirty, slot_epoch, epoch):
        return lru_stamp(slot_epoch, dirty, epoch)

    def _mem_stamp(self, s: int, epoch: int) -> None:
        if self._slot_epoch[s] is None \
                or self._slot_epoch[s].shape[0] != self.row_capacity[s]:
            self._slot_epoch[s] = jnp.full(self.row_capacity[s], epoch,
                                           dtype=jnp.int64)
            return
        self._slot_epoch[s] = self._lru_stamp(
            self.sides[s].dirty, self._slot_epoch[s], epoch)

    def _mem_stats_impl(self, side_state: JoinSideState, slot_epoch):
        return side_state.live & ~side_state.dirty, slot_epoch

    def _mem_pack_impl(self, side_state: JoinSideState, slot_epoch, thresh):
        evict = (side_state.live & ~side_state.dirty
                 & (slot_epoch <= thresh))
        return pack_rows(evict, list(side_state.rows)
                         + list(side_state.valids))

    def _mem_evict_impl(self, side_state: JoinSideState, slot_epoch,
                        thresh):
        """Tombstone the cold rows (chains stay intact); the shrinking
        rehash right after reclaims the slots."""
        drop = (side_state.live & ~side_state.dirty
                & (slot_epoch <= thresh))
        return JoinSideState(
            side_state.key_table, side_state.head, side_state.rows,
            side_state.valids, side_state.next, side_state.live & ~drop,
            side_state.dirty, side_state.top)

    def _mem_fetch_stats(self, s: int, epoch: int):
        """(live mask, stamps, cold stamps asc, this-interval churn) for
        one side in ONE packed fetch."""
        from ..utils.d2h import fetch_columns
        live_dev, ep_dev = self._mem_stats(self.sides[s],
                                           self._slot_epoch[s])
        live_np, ep_np = fetch_columns([live_dev, ep_dev])
        live_np = live_np.astype(bool)
        cold = np.sort(ep_np[live_np & (ep_np < epoch)])
        return live_np, ep_np, cold, int((ep_np == epoch).sum())

    @staticmethod
    def _mem_cap_for(n_survive: int, touched_now: int) -> int:
        """Survivors + one interval of fresh rows at 0.35 target load —
        no immediate re-grow, no mid-epoch overflow."""
        c = 256
        while n_survive + touched_now > 0.35 * c:
            c *= 2
        return c

    def _mem_do_evict(self, s: int, epoch: int, thresh: int,
                      new_cr: int, survivors_hint: int) -> int:
        """Pack + spill side `s` rows stamped <= thresh, tombstone them,
        rehash the row store at new_cr. Returns bytes freed."""
        from ..utils.d2h import fetch_prefix_groups
        guard = getattr(self, "mem_guard", None)
        t_dev = jnp.int64(thresh)
        cols_dev, n_dev = self._mem_pack(self.sides[s],
                                         self._slot_epoch[s], t_dev)
        n = int(np.asarray(n_dev))
        nc = len(self._col_dtypes[s])
        protected: list = []
        if n:
            host = fetch_prefix_groups([(list(cols_dev), n)])[0]
            for r in range(n):
                vals = tuple(host[c][r].item() for c in range(nc))
                valids = tuple(bool(host[nc + c][r]) for c in range(nc))
                key = tuple(vals[i] for i in self.key_indices[s])
                if guard is not None \
                        and guard.is_protected((id(self), s), key):
                    # reload-LFU guard: probe-hot key — keep it
                    # device-resident, re-insert after the rehash
                    protected.append((vals, valids))
                else:
                    self._spill[s].add(key, (vals, valids))
        before = pytree_bytes(self.sides[s])
        self.sides[s] = self._mem_evict_apply(
            self.sides[s], self._slot_epoch[s], t_dev)
        self.sides[s] = self._rehash(
            self.sides[s], side=s, new_ck=self.key_capacity[s],
            new_cr=new_cr)
        self.row_capacity[s] = new_cr
        self._slot_epoch[s] = None
        self.rebuilds += 1
        occ2, _, top2 = self._stats(self.sides[s])
        self._occ_known[s], self._top_known[s] = int(occ2), int(top2)
        if protected:
            self._mem_reload_rows(s, protected)
            self.mem_guard_protected += len(protected)
            guard.note_protected(len(protected))
        freed = max(0, before - pytree_bytes(self.sides[s]))
        self.mem_evicted_bytes += freed
        return freed

    def memory_evict(self, target_bytes: int, epoch: int) -> int:
        """Budget response: spill each side's coldest rows to host and
        rehash the row store smaller. Runs between epochs (manager
        hook); dirty rows never spill — the persist path owns them until
        the next flush."""
        if not self._mem_lru_on:
            return 0
        freed_total = 0
        order = sorted((LEFT, RIGHT),
                       key=lambda s: -pytree_bytes(self.sides[s]))
        for s in order:
            if freed_total >= target_bytes:
                break
            if self._slot_epoch[s] is None:
                continue
            live_np, ep_np, cold, touched_now = \
                self._mem_fetch_stats(s, epoch)
            if cold.size == 0:
                continue
            total_live = int(live_np.sum())
            bps = max(1, pytree_bytes(self.sides[s])
                      // max(1, self.row_capacity[s]))
            removed, thresh = 0, None
            for t in np.unique(cold):
                removed = int((cold <= t).sum())
                thresh = int(t)
                if (self.row_capacity[s]
                        - self._mem_cap_for(total_live - removed,
                                            touched_now)) * bps \
                        >= target_bytes - freed_total:
                    break
            new_cr = self._mem_cap_for(total_live - removed, touched_now)
            if thresh is None or new_cr >= self.row_capacity[s]:
                continue
            freed_total += self._mem_do_evict(s, epoch, thresh, new_cr,
                                              total_live - removed)
        return freed_total

    def memory_maintain(self, epoch: int) -> None:
        """Steady-state LRU tick: spill cold rows BEFORE a side's row
        store reaches the growth threshold — eviction is the plan,
        capacity resize the fallback."""
        if not self._mem_lru_on:
            return
        for s in (LEFT, RIGHT):
            if self._slot_epoch[s] is None:
                continue
            if self._top_known[s] <= 0.55 * self.row_capacity[s]:
                continue
            live_np, ep_np, cold, touched_now = \
                self._mem_fetch_stats(s, epoch)
            if cold.size == 0:
                continue
            total_live = int(live_np.sum())
            need = (total_live + touched_now
                    - int(0.35 * self.row_capacity[s]))
            removed, thresh = 0, None
            for t in np.unique(cold):
                removed = int((cold <= t).sum())
                thresh = int(t)
                if removed >= need:
                    break
            new_cr = min(self.row_capacity[s],
                         self._mem_cap_for(total_live - removed,
                                           touched_now))
            self._mem_do_evict(s, epoch, thresh, new_cr,
                               total_live - removed)

    def _mem_check_reload(self, side: int, chunks: list) -> None:
        """Read-through miss handling before a run applies: a chunk from
        `side` probes the other side and mutates its own, so spilled keys
        on EITHER side that the chunk's keys touch reload first."""
        if not (self._spill[LEFT] or self._spill[RIGHT]):
            return
        from ..utils.d2h import fetch_columns
        key_idx = self.key_indices[side]
        nk = len(key_idx)
        arrays = []
        for ch in chunks:
            arrays.extend(ch.columns[i].data for i in key_idx)
            arrays.append(ch.vis)
        host = fetch_columns(arrays)
        keys: list = []
        seen: set = set()
        for ci in range(len(chunks)):
            part = host[ci * (nk + 1):(ci + 1) * (nk + 1)]
            idx = np.flatnonzero(part[-1].astype(bool))
            for vals in zip(*(c[idx] for c in part[:nk])):
                k = tuple(v.item() for v in vals)
                if k not in seen:
                    seen.add(k)
                    keys.append(k)
        guard = getattr(self, "mem_guard", None)
        for t in (side, 1 - side):
            touched = self._spill[t].take_touched(keys)
            if touched:
                if guard is not None:
                    guard.note((id(self), t), list(touched))
                self._mem_reload_rows(
                    t, [rw for rows in touched.values() for rw in rows])
                self.mem_reload_count += len(touched)
                from ..utils.metrics import HBM_RELOADS
                HBM_RELOADS.inc(len(touched))

    def _mem_reload_rows(self, t: int, entries: list) -> None:
        """Re-insert spilled rows into side `t`'s store (clean — they are
        already durable); rides the same bulk-insert machinery recovery
        replays through."""
        if not entries:
            return
        n = len(entries)
        # pre-grow so the reload cannot overflow the row store
        if self._top_known[t] + n > 0.7 * self.row_capacity[t] \
                or self._occ_known[t] + n > 0.7 * self.key_capacity[t]:
            new_cr, new_ck = self.row_capacity[t], self.key_capacity[t]
            while self._top_known[t] + n > 0.7 * new_cr:
                new_cr *= 2
            while self._occ_known[t] + n > 0.7 * new_ck:
                new_ck *= 2
            self.sides[t] = self._rehash(self.sides[t], side=t,
                                         new_ck=new_ck, new_cr=new_cr)
            self.row_capacity[t], self.key_capacity[t] = new_cr, new_ck
            self._slot_epoch[t] = None
            occ2, _, top2 = self._stats(self.sides[t])
            self._occ_known[t], self._top_known[t] = int(occ2), int(top2)
        B = 1 << max(0, (n - 1).bit_length())
        pad = entries + [entries[0]] * (B - n)
        active = jnp.asarray(np.arange(B) < n)
        col_data = tuple(
            jnp.asarray(np.asarray([e[0][c] for e in pad],
                                   dtype=np.dtype(dt)))
            for c, dt in enumerate(self._col_dtypes[t]))
        col_valid = tuple(
            jnp.asarray(np.asarray([e[1][c] for e in pad], dtype=bool))
            for c in range(len(self._col_dtypes[t])))
        prog = self._mem_reloads.get((B, t))
        if prog is None:
            prog = jit_state(partial(self._mem_reload_impl, side=t),
                             donate_argnums=(0, 1),
                             name=f"hash_join_mem_reload{B}_s{t}")
            self._mem_reloads[(B, t)] = prog
        self.sides[t], self._errs_dev = prog(
            self.sides[t], self._errs_dev, col_data, col_valid, active)
        self._top_known[t] += n

    def _mem_reload_impl(self, own: JoinSideState, errs, col_data,
                         col_valid, active, side: int):
        key_cols = [col_data[i] for i in self.key_indices[side]]
        table, slots, n_un = lookup_or_insert(own.key_table, key_cols,
                                              active)
        own = JoinSideState(table, own.head, own.rows, own.valids,
                            own.next, own.live, own.dirty, own.top)
        B = active.shape[0]
        own, n_ro = _bulk_insert(own, slots, active & (slots >= 0),
                                 col_data, col_valid,
                                 jnp.zeros(B, dtype=bool))
        zero = jnp.int32(0)
        errs = errs + jnp.stack([n_un.astype(jnp.int32), zero, zero,
                                 n_ro.astype(jnp.int32)])
        return own, errs

    def _clean_spilled(self, s: int, wm) -> None:
        """Watermark cleaning of evicted (spilled) join rows: rows whose
        clean column fell below the watermark can never match again —
        drop them from the spill and tombstone them durably."""
        col = self.clean_cols[s]
        if col is None or not self._spill[s]:
            return
        dead_rows: list = []
        for k in list(self._spill[s].keys()):
            rows = self._spill[s].pop(k)
            for vals, valids in rows:
                if vals[col] < wm:
                    dead_rows.append((vals, valids))
                else:
                    self._spill[s].add(k, (vals, valids))
        if dead_rows and self.state_tables[s] is not None:
            self.state_tables[s].write_chunk_rows(
                [(int(OP_DELETE), vals) for vals, _ in dead_rows])

    # ---------------------------------------------------------- rebuild
    def _stats_impl(self, side_state: JoinSideState):
        occ = jnp.sum(side_state.key_table.occupied.astype(jnp.int32))
        live = jnp.sum(side_state.live.astype(jnp.int32))
        # live distinct keys: a key is live if its chain has a live row
        CR = side_state.row_capacity
        return occ, live, side_state.top

    def _rehash_impl(self, side_state: JoinSideState, side: int,
                     new_ck: int, new_cr: int) -> JoinSideState:
        """Compact live rows into a fresh side (zombie purge / growth)."""
        CR = side_state.row_capacity
        keep = side_state.live | side_state.dirty   # dirty dead rows must
        # survive until persisted as deletes
        rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
        sel = jnp.zeros(CR, dtype=jnp.int32).at[
            jnp.where(keep, rank, CR)].set(jnp.arange(CR, dtype=jnp.int32),
                                           mode="drop")
        n_keep = jnp.sum(keep.astype(jnp.int32))
        act = jnp.arange(CR) < n_keep
        key_cols = [side_state.rows[i][sel] for i in self.key_indices[side]]
        fresh = _empty_side(new_ck, new_cr, self._key_dtypes,
                            self._col_dtypes[side])
        table, slots, n_un = lookup_or_insert(fresh.key_table, key_cols,
                                              act & side_state.live[sel])
        fresh = JoinSideState(table, fresh.head, fresh.rows, fresh.valids,
                              fresh.next, fresh.live, fresh.dirty, fresh.top)
        fresh, _ = _bulk_insert(
            fresh, slots, act & side_state.live[sel],
            [r[sel] for r in side_state.rows],
            [v[sel] for v in side_state.valids],
            side_state.dirty[sel])
        # dirty dead rows: append after live ones (not linked into chains)
        dead = act & ~side_state.live[sel] & side_state.dirty[sel]
        rank_d = jnp.cumsum(dead.astype(jnp.int32)) - 1
        tgt = jnp.where(dead, fresh.top + rank_d, new_cr)
        rows = tuple(fr.at[tgt].set(r[sel], mode="drop")
                     for fr, r in zip(fresh.rows, side_state.rows))
        dirty = fresh.dirty.at[tgt].set(True, mode="drop")
        top = jnp.minimum(fresh.top + jnp.sum(dead.astype(jnp.int32)),
                          new_cr).astype(jnp.int32)
        return JoinSideState(fresh.key_table, fresh.head, rows, fresh.valids,
                             fresh.next, fresh.live, dirty, top)

    def _maybe_rebuild(self) -> None:
        for s in (LEFT, RIGHT):
            ck, cr = self.key_capacity[s], self.row_capacity[s]
            # load knowledge from the barrier watchdog fetch gates the
            # (rare, blocking) exact stats readback — same scheme as HashAgg
            if self._occ_known[s] <= 0.7 * ck and self._top_known[s] <= 0.7 * cr:
                continue
            occ, live, top = self._stats(self.sides[s])
            occ, live, top = int(occ), int(live), int(top)
            if occ <= 0.7 * ck and top <= 0.7 * cr:
                continue
            new_ck = ck * 2 if occ > 0.35 * ck else ck
            new_cr = cr * 2 if live > 0.35 * cr else cr
            self.sides[s] = self._rehash(self.sides[s], side=s,
                                         new_ck=new_ck, new_cr=new_cr)
            self.key_capacity[s], self.row_capacity[s] = new_ck, new_cr
            self._slot_epoch[s] = None       # geometry changed: restamp
            self.rebuilds += 1
            occ2, _, top2 = self._stats(self.sides[s])
            self._occ_known[s], self._top_known[s] = int(occ2), int(top2)

    # --------------------------------------------------------- watchdog
    def _check_watchdog(self) -> None:
        """ONE small blocking fetch of the device-accumulated error counts
        and per-side load stats — called per BARRIER, never per chunk (a
        per-chunk d2h fetch gates throughput on copy latency, and
        `copy_to_host_async` stalls completion-event delivery for seconds
        on a tunneled TPU). Errors fail-stop BEFORE this epoch's checkpoint
        commits; recovery replays from the last committed epoch."""
        vals = np.asarray(self._watchdog_pack(
            self._errs_dev, self._occ_dev[LEFT], self._top_dev[LEFT],
            self._occ_dev[RIGHT], self._top_dev[RIGHT]))
        n_un, n_miss, n_mo, n_ro = (int(x) for x in vals[:4])
        for s in (LEFT, RIGHT):
            self._occ_known[s] = int(vals[4 + 2 * s])
            self._top_known[s] = int(vals[5 + 2 * s])
        if n_un:
            raise RuntimeError(
                f"join key-table overflow ({n_un} keys unresolved)")
        if n_mo:
            raise RuntimeError(
                f"join match-buffer overflow ({n_mo} matches dropped; "
                f"raise match_factor)")
        if n_ro:
            raise RuntimeError(
                f"join row-store overflow ({n_ro} rows dropped)")
        if n_miss:
            raise RuntimeError(
                f"join changelog inconsistency: {n_miss} deletes matched "
                f"no stored row")

    # ---------------------------------------------------- multi-chunk apply
    def _make_apply_scan(self, k: int, side: int):
        def scan_impl(own: JoinSideState, other: JoinSideState,
                      errs: jnp.ndarray, *chunks):
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *chunks)

            def step(carry, chunk):
                own_, errs_ = carry
                own_, cols, ops, vis, errs_, occ, top = self._apply_impl(
                    own_, other, errs_, chunk, side)
                return (own_, errs_), (cols, ops, vis, occ, top)

            (own2, errs2), (cols, ops, vis, occs, tops) = jax.lax.scan(
                step, (own, errs), stacked)
            # stacked per-step outputs [k, M] flatten into one chunk of
            # capacity k*M — row order is step-major, matching the
            # sequential per-chunk emission order
            flat_cols = tuple(
                Column(c.data.reshape(-1),
                       None if c.valid is None else c.valid.reshape(-1))
                for c in cols)
            return (own2, flat_cols, ops.reshape(-1), vis.reshape(-1),
                    errs2, occs[-1], tops[-1])

        return jit_state(scan_impl, donate_argnums=(0, 2),
                         name=f"hash_join_apply_scan{k}_s{side}")

    def _run_matches(self, side: int, chunk: StreamChunk) -> bool:
        p = self._run_chunks
        return (self._run_side == side and bool(p)
                and p[-1].capacity == chunk.capacity
                and jax.tree_util.tree_structure(p[-1])
                == jax.tree_util.tree_structure(chunk))

    def _enqueue_chunk(self, side: int, chunk: StreamChunk) -> list:
        if not self._use_chunk_batching:
            self._run_chunks, self._run_side = [chunk], side
            return self._drain_run()
        outs = []
        if self._run_chunks and not self._run_matches(side, chunk):
            outs.extend(self._drain_run())
        self._run_chunks.append(chunk)
        self._run_side = side
        if len(self._run_chunks) >= self._batch_max:
            outs.extend(self._drain_run())
        return outs

    def _drain_run(self) -> list:
        run, s = self._run_chunks, self._run_side
        if not run:
            return []
        self._run_chunks, self._run_side = [], None
        self._mem_check_reload(s, run)
        if len(run) == 1:
            (self.sides[s], cols, ops, vis, self._errs_dev, occ,
             top) = self._apply(self.sides[s], self.sides[1 - s],
                                self._errs_dev, run[0], side=s)
        else:
            # power-of-two batch bucket; fillers are all-invisible views
            # of the last chunk (no probe, no state change, no matches)
            k = 1 << (len(run) - 1).bit_length()
            if k > len(run):
                last = run[-1]
                filler = StreamChunk(last.columns, last.ops,
                                     jnp.zeros(last.capacity, dtype=bool),
                                     last.schema)
                run = run + [filler] * (k - len(run))
            scan = self._apply_scans.get((k, s))
            if scan is None:
                scan = self._make_apply_scan(k, s)
                self._apply_scans[(k, s)] = scan
            (self.sides[s], cols, ops, vis, self._errs_dev, occ,
             top) = scan(self.sides[s], self.sides[1 - s],
                         self._errs_dev, *run)
        self._occ_dev[s], self._top_dev[s] = occ, top
        self._dirty_since_flush[s] = True
        out = StreamChunk(
            tuple(cols[i] for i in self.output_indices), ops, vis,
            self.schema)
        if self.condition is not None:
            pred = self.condition.eval(cols)
            out = out.mask(pred.data & pred.valid_mask())
        return [out]

    # ----------------------------------------------------------- stream
    async def execute(self):
        first = True
        async for kind, s, msg in barrier_align(*self.inputs):
            if kind == "chunk":
                for out in self._enqueue_chunk(s, msg):
                    yield out
            elif kind == "barrier":
                for out in self._drain_run():
                    yield out
                barrier: Barrier = msg
                if first or barrier.kind is BarrierKind.INITIAL:
                    first = False
                    for st in self.state_tables:
                        if st is not None:
                            st.init_epoch(barrier.epoch.curr)
                    self.recover()
                    yield barrier
                    continue
                stopping = barrier.mutation is not None and barrier.is_stop_any()
                # watchdog_interval=None => NO fetch ever, not even at stop
                # (same contract as HashAggExecutor: one d2h transfer
                # permanently degrades tunneled-TPU dispatch); correctness
                # in that mode rests on CPU-backend tests + the device-side
                # purge below.
                if self.watchdog_interval and (
                        stopping or any(self._dirty_since_flush)):
                    self._check_watchdog()
                # LRU epoch stamp BEFORE persist resets the dirty bits
                if self._mem_lru_on:
                    for s2 in (LEFT, RIGHT):
                        if self._dirty_since_flush[s2]:
                            self._mem_stamp(s2, barrier.epoch.curr)
                self._persist(barrier)
                for s2 in (LEFT, RIGHT):
                    if (self._pending_clean[s2] is not None
                            and self.clean_cols[s2] is not None):
                        self._clean_spilled(s2, self._pending_clean[s2])
                        self.sides[s2] = self._evict(
                            self.sides[s2], self._pending_clean[s2], side=s2)
                        self._pending_clean[s2] = None
                        if self.watchdog_interval is None:
                            # transfer-free mode: reclaim tombstoned rows
                            # with a same-capacity device rehash — without
                            # occupancy readbacks the host can never
                            # trigger one (see HashAggExecutor)
                            self.sides[s2] = self._rehash(
                                self.sides[s2], side=s2,
                                new_ck=self.key_capacity[s2],
                                new_cr=self.row_capacity[s2])
                self._maybe_rebuild()
                yield barrier
            else:
                # watermark: drain first so emitted outputs precede it
                for out in self._drain_run():
                    yield out
                wm: Watermark = msg
                if self.clean_cols[s] is not None and wm.col_idx == self.clean_cols[s]:
                    self._pending_clean[s] = wm.val
                # key-column watermarks: emit min over both sides on both
                # output key positions (reference join watermark derivation)
                if wm.col_idx in self.key_indices[s]:
                    kpos = self.key_indices[s].index(wm.col_idx)
                    self._key_wms[s][kpos] = wm.val
                    other_wm = self._key_wms[1 - s].get(kpos)
                    if other_wm is not None:
                        val = min(wm.val, other_wm)
                        if self._emitted_key_wm.get(kpos) != val:
                            self._emitted_key_wm[kpos] = val
                            n_left = len(self.inputs[LEFT].schema)
                            for full_idx in (self.key_indices[LEFT][kpos],
                                             n_left + self.key_indices[RIGHT][kpos]):
                                if full_idx in self.output_indices:
                                    yield Watermark(
                                        self.output_indices.index(full_idx),
                                        wm.data_type, val)

"""OverWindow executor (append-only) — per-partition window functions.

Reference: src/stream/src/executor/over_window/ (general.rs keeps a
per-partition cache over a delta btree; eowc.rs is the emit-on-close
variant) with window states from expr/core/src/window_function/state/.

TPU re-design (append-only subset): partitions live in the same
open-addressing HashTable as HashAgg; per-partition state is one scalar
per window call (row counter for ROW_NUMBER/RANK over arrival order,
running aggregate for SUM/COUNT/MIN/MAX over the unbounded-preceding
frame). Applying a chunk is ONE jitted step: slot assignment, in-chunk
rank within partition (stable sort by slot), output column = partition
state + in-chunk prefix, then a segment-reduce folds the chunk into the
state. Rows emit IMMEDIATELY with their window values (append-only
streams never retract prior outputs, so no flush diffing is needed —
the reference's general path buffers for exactly the retraction case).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column, StreamChunk, OP_INSERT, op_sign
from ..common.types import DataType, Field, Schema
from ..expr.agg import AggCall, AggKind
from ..ops.hash_table import HashTable, lookup_or_insert, stable_lexsort
from ..ops.jit_state import jit_state
from ..state.state_table import StateTable
from .executor import Executor, StatefulUnaryExecutor
from .message import Barrier, Watermark

ROW_NUMBER = "row_number"


class OverWindowExecutor(StatefulUnaryExecutor):
    """Append-only over-window. `calls` is a list of either the string
    "row_number" or an AggCall (running aggregate over the
    unbounded-preceding frame, in arrival order). Output schema = input
    columns ++ one column per call."""

    def __init__(self, input: Executor,
                 partition_key_indices: Sequence[int],
                 calls: Sequence,
                 capacity: int = 1 << 14,
                 state_table: Optional[StateTable] = None,
                 watchdog_interval: Optional[int] = 1):
        self.input = input
        self.partition_key_indices = tuple(partition_key_indices)
        self.calls = tuple(calls)
        in_fields = list(input.schema)
        out_fields = list(in_fields)
        self._specs = []
        for j, c in enumerate(self.calls):
            if c == ROW_NUMBER:
                out_fields.append(Field(f"row_number{j}", DataType.INT64))
                self._specs.append(None)
            else:
                assert isinstance(c, AggCall)
                out_fields.append(Field(f"w{j}", c.ret_type))
                self._specs.append(c.spec())
        self.schema = Schema(tuple(out_fields))
        self.pk_indices = input.pk_indices
        self.capacity = capacity
        self.identity = (f"OverWindow(partition={self.partition_key_indices},"
                         f" calls={len(self.calls)})")
        self._key_dtypes = tuple(
            input.schema[i].data_type.jnp_dtype
            for i in self.partition_key_indices)
        self.table = HashTable.empty(capacity, self._key_dtypes)
        self.counts = jnp.zeros(capacity, dtype=jnp.int64)
        # slots touched since the last persist — the delta to write at the
        # barrier (sibling hash_agg persists only its flush view; ADVICE r2)
        self.dirty = jnp.zeros(capacity, dtype=bool)
        self.agg_states = tuple(
            (spec.init_state((capacity,)) if spec is not None else None)
            for spec in self._specs)
        # all five threaded state args (table, counts, agg_states, dirty,
        # errs) are re-bound in on_chunk and aliased nowhere else: donate
        self._apply = jit_state(self._apply_impl,
                                donate_argnums=(0, 1, 2, 3, 4),
                                name="over_window_apply")
        self._errs_dev = jnp.zeros((), dtype=jnp.int32)
        self._init_stateful(state_table, watchdog_interval)

    def fence_tokens(self) -> list:
        return [self.counts] + super().fence_tokens()

    # --------------------------------------------------------- chunk step
    def _apply_impl(self, table, counts, agg_states, dirty, errs,
                    chunk: StreamChunk):
        N = chunk.capacity
        C = self.capacity
        active = chunk.vis & (op_sign(chunk.ops) > 0)   # append-only
        n_viol = jnp.sum((chunk.vis & (op_sign(chunk.ops) < 0))
                         .astype(jnp.int32))
        key_cols = [chunk.columns[i].data
                    for i in self.partition_key_indices]
        table, slots, n_un = lookup_or_insert(table, key_cols, active)
        ok = slots >= 0
        seg = jnp.where(ok, slots, C)

        # arrival rank within partition for this chunk: ONE stable sort
        # by slot preserves row order within each partition
        order = jnp.argsort(seg, stable=True)
        sseg = seg[order]
        new_run = jnp.concatenate([jnp.array([True]),
                                   sseg[1:] != sseg[:-1]])
        pos = jnp.arange(N, dtype=jnp.int32)
        run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
        s_rank = pos - run_start
        rank = jnp.zeros(N, dtype=jnp.int64).at[order].set(
            s_rank.astype(jnp.int64))

        out_cols = list(chunk.columns)
        new_agg_states = []
        for j, (c, spec) in enumerate(zip(self.calls, self._specs)):
            if spec is None:                      # row_number: 1-based
                vals = counts[jnp.clip(seg, 0, C - 1)] + rank + 1
                out_cols.append(Column(jnp.where(ok, vals, 0)))
                new_agg_states.append(None)
                continue
            col = (chunk.columns[c.arg] if c.arg is not None else None)
            values = (col.data if col is not None
                      else jnp.zeros(N, dtype=spec.state_dtype))
            valid_in = (col.valid_mask() if col is not None
                        else jnp.ones(N, dtype=bool))
            signs = jnp.where(ok & valid_in, 1, 0).astype(jnp.int32)
            # running value per row = partition state + in-chunk prefix
            # INCLUDING the row: segmented inclusive prefix in sorted order
            sv = values[order].astype(spec.state_dtype)
            ssigns = signs[order]
            if c.kind is AggKind.COUNT:
                contrib = ssigns.astype(jnp.int64)
            elif c.kind is AggKind.SUM:
                contrib = jnp.where(ssigns > 0, sv, 0)
            else:
                ident = spec.init
                contrib = jnp.where(ssigns > 0, sv, ident)
            if c.kind in (AggKind.COUNT, AggKind.SUM):
                run_base = jnp.cumsum(contrib) - contrib
                seg_base = run_base[run_start]
                prefix = run_base - seg_base + contrib
            else:
                # segmented min/max scan: reset at run starts by comparing
                # against the prefix from the run start only
                def seg_scan(op, x):
                    def f(a, b):
                        av, ai = a
                        bv, bi = b
                        keep = bi > ai
                        return (jnp.where(keep, bv, op(av, bv)),
                                jnp.maximum(ai, bi))
                    v, _ = jax.lax.associative_scan(
                        f, (x, run_start.astype(jnp.int32)))
                    return v
                prefix = seg_scan(
                    jnp.minimum if c.kind is AggKind.MIN else jnp.maximum,
                    contrib)
            st = agg_states[j]
            base = st[jnp.clip(sseg, 0, C - 1)]
            run_vals = spec.combine(base, prefix)
            out = jnp.zeros(N, dtype=st.dtype).at[order].set(run_vals)
            out_cols.append(Column(jnp.where(ok, out, 0).astype(
                c.ret_type.jnp_dtype)))
            part = spec.partial(values, signs, seg, C + 1)[:C]
            new_agg_states.append(spec.combine(st, part))

        counts2 = counts + jax.ops.segment_sum(
            ok.astype(jnp.int64), seg, C + 1)[:C]
        dirty2 = dirty.at[jnp.where(ok, seg, C)].set(True, mode="drop")
        out_chunk = StreamChunk(tuple(out_cols), chunk.ops,
                                chunk.vis & ok, self.schema)
        return (table, counts2, tuple(new_agg_states), dirty2,
                errs + n_un + n_viol, out_chunk)

    # -------------------------------------------------------------- hooks
    def check_watchdog(self) -> None:
        n = int(np.asarray(self._errs_dev))
        if n:
            raise RuntimeError(
                f"over-window overflow or append-only violation ({n} "
                f"rows, capacity {self.capacity})")

    def on_chunk(self, chunk: StreamChunk) -> StreamChunk:
        (self.table, self.counts, self.agg_states, self.dirty,
         self._errs_dev, out) = self._apply(
            self.table, self.counts, self.agg_states, self.dirty,
            self._errs_dev, chunk)
        self._dirty_persist = True
        return out

    def persist(self, barrier: Barrier, flushed) -> None:
        if self.state_table is None:
            return
        if not getattr(self, "_dirty_persist", False):
            self.state_table.commit(barrier.epoch.curr)
            return
        self._dirty_persist = False
        # delta persistence: only slots touched since the last barrier are
        # written, through the columnar batch path (state_table.rs:946)
        idx = np.flatnonzero(np.asarray(self.dirty)
                             & np.asarray(self.table.occupied))
        if idx.size:
            cols = [np.asarray(k)[idx] for k in self.table.keys]
            cols.append(np.asarray(self.counts)[idx])
            cols += [np.asarray(s)[idx] for s in self.agg_states
                     if s is not None]
            self.state_table.write_chunk_columns(
                np.full(idx.size, OP_INSERT, dtype=np.int8), cols,
                np.ones(idx.size, dtype=bool))
            self.dirty = jnp.zeros(self.capacity, dtype=bool)
        self.state_table.commit(barrier.epoch.curr)

    def recover_state(self, epoch: int) -> None:
        rows = [row for _, row in self.state_table.iter_all()]
        if not rows:
            return
        nk = len(self.partition_key_indices)
        key_cols = [jnp.asarray(np.asarray([r[j] for r in rows]), dtype=dt)
                    for j, dt in enumerate(self._key_dtypes)]
        table, slots, n_un = lookup_or_insert(
            HashTable.empty(self.capacity, self._key_dtypes), key_cols,
            jnp.ones(len(rows), dtype=bool))
        assert int(n_un) == 0
        self.table = table
        self.counts = self.counts.at[slots].set(
            jnp.asarray(np.asarray([r[nk] for r in rows],
                                   dtype=np.int64)))
        off = nk + 1
        new_states = []
        for spec, st in zip(self._specs, self.agg_states):
            if spec is None:
                new_states.append(None)
                continue
            vals = jnp.asarray(np.asarray([r[off] for r in rows]),
                               dtype=spec.state_dtype)
            new_states.append(st.at[slots].set(vals))
            off += 1
        self.agg_states = tuple(new_states)

    def map_watermark(self, wm: Watermark) -> Optional[Watermark]:
        return wm if wm.col_idx < len(self.input.schema) else None

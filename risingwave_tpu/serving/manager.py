"""ServingManager — the per-coordinator serving authority.

Owned by the BarrierCoordinator exactly like the MemoryManager: MVs
register at CREATE (Session wires the Materialize executor's
`serving_hook`), and `on_barrier` runs at every collected barrier — the
one moment the epoch is complete and every actor idle — to advance each
MV's SnapshotCache to the sealed epoch. Because all caches advance in
the same synchronous hook, any set of snapshots pinned between barriers
shares one epoch: multi-MV queries (joins) are consistent by
construction and never race a commit or compaction.

Cache lifecycle: registration alone costs nothing (the changelog hook
drops its buffer at each barrier while inactive). The first query that
misses marks the MV `wanted`; the next collected barrier performs the
ONE full store scan (epoch-bounded, staged epochs included) and from
then on the cache advances incrementally. Recovery tears the manager
down with its coordinator, so caches invalidate and rebuild from the
recovered epoch automatically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..utils.metrics import (
    GLOBAL_METRICS, SERVING_CACHE_HITS, SERVING_CACHE_MISSES,
)
from .cache import MvChangelogHook, Snapshot, SnapshotCache
from .pool import ServingPool


@dataclass
class _MvEntry:
    name: str
    table: object                  # the MV's StateTable (key layout + scan)
    schema: object
    pk_indices: tuple
    # one hook per materialize ACTOR: a parallel-materialize MV has N
    # vnode-partitioned executors, each publishing its own effective
    # changelog; their pk sets are disjoint by construction, so the
    # barrier-time drain merges them per epoch in any order
    hooks: list
    cache: Optional[SnapshotCache] = None
    wanted: bool = False
    hits: int = 0
    misses: int = 0
    point_lookups: int = 0


class ServingManager:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.pool = ServingPool()
        self._mvs: dict[str, _MvEntry] = {}
        self.collected_epoch = 0

    def configure(self, enabled: Optional[bool] = None,
                  max_concurrency: Optional[int] = None,
                  timeout_ms: Optional[int] = None) -> None:
        """SET serving_cache / serving_max_concurrency /
        serving_query_timeout_ms (re-applied after recovery)."""
        if enabled is not None:
            self.enabled = bool(enabled)
        self.pool.configure(max_concurrency=max_concurrency,
                            timeout_ms=timeout_ms)

    # ------------------------------------------------------ registration
    def register_mv(self, name: str, table, schema, pk_indices,
                    n_hooks: int = 1) -> list[MvChangelogHook]:
        """Register an MV's serving entry; returns one changelog hook
        per Materialize actor (`n_hooks` — parallel-materialize MVs
        attach one to each executor; their vnode-disjoint changelogs
        merge at the barrier). Re-registration (rescale, recovery
        replay) starts a fresh entry — the cache rebuilds."""
        hooks = [MvChangelogHook(name) for _ in range(n_hooks)]
        self._mvs[name] = _MvEntry(name, table, schema, tuple(pk_indices),
                                   hooks)
        return hooks

    def unregister_mv(self, name: str) -> None:
        if self._mvs.pop(name, None) is not None:
            # drop the labelled series entirely — a zeroed gauge for a
            # dropped MV would linger in /metrics (and rw_metrics) forever
            GLOBAL_METRICS.remove("serving_cache_rows", mv=name)

    # ----------------------------------------------------------- barrier
    def on_barrier(self, barrier) -> None:
        """Collected-barrier hook: advance every cache to the epoch this
        barrier sealed; build newly-wanted caches with one epoch-bounded
        store scan (staged epochs <= the sealed epoch are visible, so the
        build agrees exactly with the changelog the hook buffers next)."""
        epoch = barrier.epoch.prev
        self.collected_epoch = epoch
        for ent in self._mvs.values():
            if ent.cache is not None:
                ent.cache.advance(self._drain_hooks(ent, epoch), epoch)
            elif ent.wanted:
                self._build(ent, epoch)
            if ent.cache is not None:
                GLOBAL_METRICS.gauge("serving_cache_rows",
                                     mv=ent.name).set(
                    float(ent.cache.snapshot.row_count))

    @staticmethod
    def _drain_hooks(ent: _MvEntry, epoch: int) -> list:
        """Merge every hook's stamped batches per epoch, ascending. A
        parallel MV's actors write disjoint pk sets (vnode-partitioned
        state), so the within-epoch merge order cannot change the
        applied result."""
        if len(ent.hooks) == 1:
            return ent.hooks[0].drain(epoch)
        by_epoch: dict[int, list] = {}
        for hook in ent.hooks:
            for e, rows in hook.drain(epoch):
                by_epoch.setdefault(e, []).extend(rows)
        return [(e, by_epoch[e]) for e in sorted(by_epoch)]

    def _build(self, ent: _MvEntry, epoch: int) -> None:
        from ..state.storage_table import StorageTable
        # the layout table may carry one actor's vnode bitmap; the
        # StorageTable rebinds the full vnode space, so the build scan
        # covers every actor's slice of the shared table id
        storage = StorageTable.for_state_table(ent.table)
        rows, keys = storage.snapshot_with_keys(max_epoch=epoch)
        cache = SnapshotCache(ent.name, ent.schema, ent.pk_indices,
                              storage._layout)
        cache.build(rows, keys, epoch)
        ent.cache = cache
        for hook in ent.hooks:
            hook.activate()

    # ----------------------------------------------------------- pinning
    def pin(self, names) -> Optional[dict]:
        """Pin one consistent snapshot per MV (all at the same collected
        epoch) or None if ANY is uncached — all-or-nothing keeps a
        multi-MV query on a single epoch. A miss marks the MV wanted so
        the next barrier builds it."""
        if not self.enabled or not names:
            return None
        names = list(dict.fromkeys(names))   # self-joins pin ONCE per MV
        miss = False
        for n in names:
            ent = self._mvs.get(n)
            if ent is None:
                return None            # not a cacheable MV at all
            if ent.cache is None or ent.cache.snapshot is None:
                ent.wanted = True
                ent.misses += 1
                SERVING_CACHE_MISSES.inc()
                miss = True
        if miss:
            return None
        out: dict[str, Snapshot] = {}
        for n in names:
            ent = self._mvs[n]
            snap = ent.cache.snapshot
            snap.pins += 1
            ent.hits += 1
            SERVING_CACHE_HITS.inc()
            out[n] = snap
        return out

    def unpin(self, pins: dict) -> None:
        for snap in pins.values():
            snap.pins -= 1

    def note_point_lookup(self, name: str) -> None:
        ent = self._mvs.get(name)
        if ent is not None:
            ent.point_lookups += 1

    # --------------------------------------------------------- reporting
    def report(self) -> list[dict]:
        rows = []
        for name in sorted(self._mvs):
            ent = self._mvs[name]
            cache = ent.cache
            rows.append({
                "mv": name,
                "epoch": cache.snapshot.epoch if cache else 0,
                "rows": cache.snapshot.row_count if cache else 0,
                "hits": ent.hits,
                "misses": ent.misses,
                "point_lookups": ent.point_lookups,
                "applied_rows": cache.applied_rows if cache else 0,
                "rebuilds": cache.rebuilds if cache else 0,
            })
        return rows

    def render(self) -> list[str]:
        from ..utils.metrics import SERVING_LATENCY
        lines = [f"serving: {'on' if self.enabled else 'off'} "
                 f"epoch={self.collected_epoch} "
                 f"inflight={self.pool.active} "
                 f"max_concurrency={self.pool.max_concurrency} "
                 f"qps={self.pool.qps():.1f} "
                 f"p50={SERVING_LATENCY.percentile(0.5) * 1e3:.2f}ms "
                 f"p99={SERVING_LATENCY.percentile(0.99) * 1e3:.2f}ms"]
        for r in self.report():
            lines.append(
                f"  {r['mv']}: epoch={r['epoch']} rows={r['rows']} "
                f"hits={r['hits']} misses={r['misses']} "
                f"point_lookups={r['point_lookups']} "
                f"applied={r['applied_rows']} rebuilds={r['rebuilds']}")
        return lines

"""Serving layer — read-optimized query path between the stream engine
and the batch/pgwire frontends.

Reference: the reference design's serving half (batch RowSeqScan over a
committed Hummock snapshot, src/batch/src/executor/ + the frontend's
local execution mode) — here rebuilt around three pieces the TPU build
needs to serve heavy read traffic without touching the dataflow:

  * SnapshotCache (cache.py): a per-MV columnar numpy snapshot
    maintained INCREMENTALLY from the Materialize executor's changelog,
    advanced at each collected barrier and tagged with that epoch.
  * point-lookup index (cache.py / executor.py): a pk -> row hash index
    over the cache so `SELECT ... WHERE pk = const` is O(1), never a
    scan.
  * concurrent execution (pool.py): queries over pinned snapshots run
    off the event loop in a bounded thread pool with admission control
    and per-query timeouts, so a big scan no longer stalls barrier
    injection.
"""

from .cache import MvChangelogHook, SnapshotCache, Snapshot
from .manager import ServingManager
from .pool import ServingPool, ServingTimeout

__all__ = [
    "MvChangelogHook", "SnapshotCache", "Snapshot", "ServingManager",
    "ServingPool", "ServingTimeout",
]

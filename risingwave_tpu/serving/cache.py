"""SnapshotCache — an epoch-tagged columnar snapshot of one MV.

The Materialize executor publishes its effective changelog (post
conflict-resolution upserts/deletes) through an `MvChangelogHook`; the
ServingManager drains the hook at every collected barrier and calls
`advance`, so the cache tracks the MV exactly one barrier behind the
stream — at the epoch the barrier just sealed — without ever re-scanning
the LSM. A full scan happens only on first touch and after recovery.

Concurrency model (the epoch pin): queries never read the cache's
mutable state directly. `snapshot` is an immutable published view; a
query PINS it on the event loop before moving to a worker thread and
unpins after. `advance` runs on the event loop between epochs:

  * pins == 0  -> nobody can observe the current snapshot, so the live
    mask / pk index mutate in place (zero-copy steady state);
  * pins  > 0  -> the mutable state is first detached (live mask + pk
    index copied), so the pinned snapshot's arrays are frozen forever
    and worker threads race nothing.

Row storage is append-only: updates tombstone the old position and
append the new version, so data columns at positions a pinned snapshot
can see are immutable by construction. Scans compact live rows in
STORE-KEY ORDER (vnode ++ memcomparable(pk)), which makes cached results
bit-identical — including row order — to the StorageTable full-scan
path.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from ..common.types import Schema

# effective changelog ops (post conflict-resolution): PUT upserts by pk
# (matching the state table's last-write-wins mem-table), DEL removes
OP_PUT = 1
OP_DEL = -1

_MIN_CAPACITY = 64


class Snapshot:
    """Immutable published view of one MV at one epoch. All fields are
    frozen once the snapshot is observable by a worker thread (see the
    module docstring's pin protocol)."""

    __slots__ = ("schema", "pk_indices", "cols", "valids", "live",
                 "rowkeys", "n", "pk_index", "epoch", "pins",
                 "_compact", "_lock")

    def __init__(self, schema: Schema, pk_indices: tuple, cols, valids,
                 live, rowkeys, n: int, pk_index: dict, epoch: int):
        self.schema = schema
        self.pk_indices = pk_indices
        self.cols = cols
        self.valids = valids
        self.live = live
        self.rowkeys = rowkeys
        self.n = n
        self.pk_index = pk_index
        self.epoch = epoch
        self.pins = 0
        self._compact = None
        self._lock = threading.Lock()

    @property
    def row_count(self) -> int:
        return len(self.pk_index)

    def lookup(self, pk: tuple) -> Optional[int]:
        """pk -> row position (the point-lookup index probe)."""
        return self.pk_index.get(pk)

    def point_rel(self, pos: Optional[int]):
        """(cols, valids) for zero or one row — the O(1) read."""
        if pos is None:
            return ([c[:0].copy() for c in self.cols],
                    [v[:0].copy() for v in self.valids])
        return ([c[pos:pos + 1].copy() for c in self.cols],
                [v[pos:pos + 1].copy() for v in self.valids])

    def compact(self):
        """(cols, valids) of the live rows in store-key order — the scan
        form. Memoized per snapshot; safe to call from worker threads."""
        with self._lock:
            if self._compact is None:
                idx = np.flatnonzero(self.live[:self.n])
                order = sorted(idx.tolist(), key=self.rowkeys.__getitem__)
                o = np.asarray(order, dtype=np.int64)
                self._compact = ([c[o] for c in self.cols],
                                 [v[o] for v in self.valids])
            return self._compact


class SnapshotCache:
    """Mutable per-MV cache state; publishes immutable Snapshots."""

    def __init__(self, name: str, schema: Schema,
                 pk_indices: Sequence[int], layout):
        self.name = name
        self.schema = schema
        self.pk_indices = tuple(pk_indices)
        # a StateTable carrying the MV's key layout: delta rows get the
        # same `vnode ++ memcomparable(pk)` ordering key the store scan
        # yields, so cached and scanned row order agree exactly
        self._layout = layout
        self._np_dtypes = [np.dtype(f.data_type.np_dtype) for f in schema]
        self._cap = 0
        self._n = 0
        self._cols: list[np.ndarray] = []
        self._valids: list[np.ndarray] = []
        self._live: Optional[np.ndarray] = None
        self._rowkeys: list[bytes] = []
        self._pk_index: dict = {}
        self.snapshot: Optional[Snapshot] = None
        self.applied_rows = 0     # changelog rows applied incrementally
        self.rebuilds = 0         # full rescans (first touch / recovery)

    # ------------------------------------------------------------- keys
    def _canon(self, v, j: int):
        if v is None:
            return None
        return np.asarray(v, dtype=self._np_dtypes[j]).item()

    def canon_pk_of_row(self, row: tuple) -> tuple:
        return tuple(self._canon(row[i], i) for i in self.pk_indices)

    def _key_of_pk(self, pk: tuple) -> bytes:
        return self._layout.key_of_pk(pk, self._layout.vnode_of_pk(pk))

    # ------------------------------------------------------------ build
    def build(self, rows: list, keys: list, epoch: int) -> None:
        """Full (re)build from a consistent store scan at `epoch` —
        `rows`/`keys` in store-key order (StorageTable.snapshot_with_keys)."""
        n = len(rows)
        self._cap = max(_MIN_CAPACITY, 1 << max(0, (n - 1).bit_length()))
        self._cols = []
        self._valids = []
        for j, f in enumerate(self.schema):
            arr = np.zeros(self._cap, dtype=self._np_dtypes[j])
            val = np.zeros(self._cap, dtype=bool)
            for i, r in enumerate(rows):
                v = r[j]
                if v is not None:
                    arr[i] = v
                    val[i] = True
            self._cols.append(arr)
            self._valids.append(val)
        self._live = np.zeros(self._cap, dtype=bool)
        self._live[:n] = True
        self._rowkeys = list(keys)
        self._n = n
        self._pk_index = {self.canon_pk_of_row(r): i
                          for i, r in enumerate(rows)}
        self.rebuilds += 1
        self._publish(epoch)

    # ---------------------------------------------------------- advance
    def advance(self, batches: list, epoch: int) -> None:
        """Apply drained changelog batches `[(epoch, [(op, row), ...])]`
        (ascending epochs <= `epoch`) and publish the new snapshot."""
        snap = self.snapshot
        if snap is not None and snap.pins > 0:
            # detach: the pinned snapshot keeps the current mask/index
            # untouched forever; mutation continues on private copies
            self._live = self._live.copy()
            self._pk_index = dict(self._pk_index)
        for _e, rows in batches:
            for op, row in rows:
                pk = self.canon_pk_of_row(row)
                if op == OP_DEL:
                    pos = self._pk_index.pop(pk, None)
                    if pos is not None:
                        self._live[pos] = False
                else:
                    old = self._pk_index.get(pk)
                    if old is not None:
                        self._live[old] = False
                        key = self._rowkeys[old]
                    else:
                        key = self._key_of_pk(pk)
                    self._append(row, key)
                    self._pk_index[pk] = self._n - 1
                self.applied_rows += 1
        self._publish(epoch)

    def _append(self, row: tuple, key: bytes) -> None:
        pos = self._n
        if pos >= self._cap:
            new_cap = max(_MIN_CAPACITY, self._cap * 2)
            self._cols = [self._grow(c, new_cap) for c in self._cols]
            self._valids = [self._grow(v, new_cap) for v in self._valids]
            self._live = self._grow(self._live, new_cap)
            self._cap = new_cap
        for j, v in enumerate(row):
            if v is None:
                self._cols[j][pos] = 0
                self._valids[j][pos] = False
            else:
                self._cols[j][pos] = v
                self._valids[j][pos] = True
        self._live[pos] = True
        self._rowkeys.append(key)
        self._n = pos + 1

    @staticmethod
    def _grow(arr: np.ndarray, cap: int) -> np.ndarray:
        out = np.zeros(cap, dtype=arr.dtype)
        out[:len(arr)] = arr
        return out

    def _publish(self, epoch: int) -> None:
        self.snapshot = Snapshot(
            self.schema, self.pk_indices, list(self._cols),
            list(self._valids), self._live, self._rowkeys, self._n,
            self._pk_index, epoch)


class MvChangelogHook:
    """Attached to a MaterializeExecutor as `serving_hook`: collects the
    epoch's effective changelog rows and stamps them with the sealed
    epoch at each barrier. The buffer holds AT MOST one barrier interval
    while the MV has no cache (stamped batches are dropped at the
    barrier), so never-queried MVs cost nothing."""

    __slots__ = ("name", "active", "_pending", "_by_epoch")

    def __init__(self, name: str):
        self.name = name
        self.active = False
        self._pending: list = []
        self._by_epoch: list = []   # [(sealed_epoch, rows)]

    def on_rows(self, rows: list) -> None:
        self._pending.extend(rows)

    def on_barrier(self, sealed_epoch: int) -> None:
        rows = self._pending
        self._pending = []
        if self.active and rows:
            self._by_epoch.append((sealed_epoch, rows))

    def drain(self, upto_epoch: int) -> list:
        """Stamped batches with epoch <= upto_epoch, ascending."""
        out = [b for b in self._by_epoch if b[0] <= upto_epoch]
        self._by_epoch = [b for b in self._by_epoch if b[0] > upto_epoch]
        return out

    def activate(self) -> None:
        """Start buffering stamped batches. `_pending` is PRESERVED: the
        actor runs ahead of barrier collection, so by the time the
        manager builds the cache (at collection) the hook may already
        hold the next open interval's rows — dropping them would lose
        that interval forever. Everything <= the build epoch was
        dropped at its own barrier (inactive stamps discard) and is in
        the build scan; `_by_epoch` is necessarily empty here."""
        self.active = True

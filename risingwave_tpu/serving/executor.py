"""Serving-side query execution over pinned snapshots.

Two read paths, both reusing the batch engine's operators so results are
bit-identical to the StorageTable scan path:

  * point lookup: `SELECT ... WHERE pk = const` (all pk columns bound to
    literals) probes the snapshot's pk index and runs the NORMAL batch
    pipeline over the zero-or-one matched row — O(result), never a scan;
    residual predicates, projections, aggregates, ORDER BY and LIMIT all
    evaluate unchanged on the tiny relation.
  * cached scan: the snapshot's compacted columns (live rows in
    store-key order) replace the LSM scan + row decode; everything
    downstream of the scan is the stock batch pipeline.

This module is pure numpy + host dicts — safe on ServingPool worker
threads (no jax dispatch off the event loop).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common.types import DataType, GLOBAL_DICT
from ..frontend import sql as ast
from ..frontend.batch import _Rel, run_batch_select_full
from ..frontend.binder import BindError, Scope, split_conjuncts
from ..utils.metrics import SERVING_POINT_LOOKUPS

_UNSET = object()


def rel_mv_names(rel) -> Optional[list]:
    """Every MV name a FROM clause reads, or None if any relation is not
    a plain table reference (those queries take the legacy path)."""
    if isinstance(rel, ast.TableRel):
        return [rel.name]
    if isinstance(rel, ast.JoinRel):
        left = rel_mv_names(rel.left)
        right = rel_mv_names(rel.right)
        if left is None or right is None:
            return None
        return left + right
    return None


def run_pinned_select(catalog, sel, pins, serving=None):
    """Execute a SELECT against pinned snapshots ->
    (names, types, rows)."""
    point = _try_point_lookup(sel, pins)
    if point is not None:
        SERVING_POINT_LOOKUPS.inc()
        if serving is not None:
            serving.note_point_lookup(sel.rel.name)

        def scan(_catalog, _name, _alias):
            return point
    else:
        def scan(_catalog, name, alias):
            snap = pins[name]
            cols, valids = snap.compact()
            return _Rel(list(cols), list(valids),
                        Scope.of(snap.schema, alias or name))
    return run_batch_select_full(catalog, sel, scan=scan)


def _lit_value(e):
    if isinstance(e, ast.Lit):
        return True, e.value
    if isinstance(e, ast.UnOp) and e.op == "neg" \
            and isinstance(e.arg, ast.Lit) \
            and isinstance(e.arg.value, (int, float)):
        return True, -e.arg.value
    return False, None


def _eq_col_lit(conj, scope: Scope):
    """`col = literal` (either side) -> (col_index, value), else None."""
    if not (isinstance(conj, ast.BinOp) and conj.op == "equal"):
        return None
    for a, b in ((conj.left, conj.right), (conj.right, conj.left)):
        if isinstance(a, ast.ColRef):
            ok, v = _lit_value(b)
            if ok:
                try:
                    idx, _t = scope.resolve(a)
                except BindError:
                    return None
                return idx, v
    return None


def _try_point_lookup(sel, pins) -> Optional[_Rel]:
    """If the WHERE clause binds EVERY pk column of a single pinned MV to
    a literal, probe the index and return the <=1-row relation; the full
    pipeline (including the original WHERE) then runs over it, so extra
    conjuncts and expressions behave exactly as on the scan path."""
    rel = sel.rel
    if not isinstance(rel, ast.TableRel) or rel.name not in pins:
        return None
    snap = pins[rel.name]
    if sel.where is None or not snap.pk_indices:
        return None
    scope = Scope.of(snap.schema, rel.alias or rel.name)
    need = {i: _UNSET for i in snap.pk_indices}
    for conj in split_conjuncts(sel.where):
        m = _eq_col_lit(conj, scope)
        if m is not None and m[0] in need and need[m[0]] is _UNSET:
            need[m[0]] = m[1]
    if any(v is _UNSET for v in need.values()):
        return None
    pk = []
    for i in snap.pk_indices:
        v = need[i]
        if v is None:
            # `pk = NULL` is SQL-NULL, never true: empty result
            return _Rel(*snap.point_rel(None), scope)
        dt = snap.schema[i].data_type
        if dt is DataType.VARCHAR:
            if not isinstance(v, str):
                return None
            pk.append(int(GLOBAL_DICT.get_or_insert(v)))
            continue
        if isinstance(v, str):
            return None
        try:
            c = np.asarray(v, dtype=dt.np_dtype).item()
        except (OverflowError, ValueError):
            return None
        if c != v:
            # lossy coercion (e.g. float literal vs int column): the
            # equality can only be decided by the generic evaluator
            return None
        pk.append(c)
    pos = snap.lookup(tuple(pk))
    return _Rel(*snap.point_rel(pos), scope)

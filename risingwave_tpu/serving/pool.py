"""ServingPool — bounded concurrent execution for serving queries.

Queries over pinned snapshots are pure numpy + host dicts, so they run
on worker threads (`asyncio.to_thread`) without touching jax — the same
pure-wait discipline the staged-flush protocol enforces for the
checkpoint uploader (state/store.py `defer_flush`): only the event loop
ever dispatches device work. The pool adds:

  * admission control: at most `max_concurrency` queries execute at
    once (SET serving_max_concurrency); excess callers queue, with the
    wait accounted in `serving_admission_wait_seconds_total`;
  * per-query timeouts (SET serving_query_timeout_ms): the awaiting
    client gets a timeout error immediately; the worker thread cannot
    be interrupted mid-numpy, so it is ABANDONED — it finishes in the
    background and only then releases its admission slot and snapshot
    pins (cleanup runs on the loop via the done callback);
  * the serving health series: QPS, latency percentiles, inflight.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Callable, Optional

from ..utils.metrics import (
    SERVING_ADMISSION_WAIT, SERVING_INFLIGHT, SERVING_LATENCY,
    SERVING_QUERIES, SERVING_TIMEOUTS,
)


class ServingTimeout(Exception):
    """Raised to the caller when a query exceeds the serving timeout."""


class ServingPool:
    def __init__(self, max_concurrency: int = 4, timeout_ms: int = 0):
        self.max_concurrency = max(1, int(max_concurrency))
        self.timeout_ms = int(timeout_ms)
        self._active = 0
        self._slot_free = asyncio.Event()
        self._slot_free.set()
        self._done_times: deque = deque(maxlen=2048)

    def configure(self, max_concurrency: Optional[int] = None,
                  timeout_ms: Optional[int] = None) -> None:
        if max_concurrency is not None:
            self.max_concurrency = max(1, int(max_concurrency))
            self._slot_free.set()      # re-evaluate queued waiters
        if timeout_ms is not None:
            self.timeout_ms = int(timeout_ms)

    @property
    def active(self) -> int:
        return self._active

    def qps(self, window_s: float = 5.0) -> float:
        """Completions per second over the trailing window."""
        now = time.monotonic()
        n = sum(1 for t in self._done_times if now - t <= window_s)
        return n / window_s

    async def run(self, fn: Callable, cleanup: Optional[Callable] = None):
        """Execute `fn()` on a worker thread under admission control.
        `cleanup` runs on the event loop once the thread ACTUALLY
        finishes (even if the awaiting client timed out or vanished) —
        snapshot unpinning rides here so the loop never mutates arrays a
        live thread is reading."""
        t0 = time.monotonic()
        while self._active >= self.max_concurrency:
            self._slot_free.clear()
            await self._slot_free.wait()
        waited = time.monotonic() - t0
        if waited > 0:
            SERVING_ADMISSION_WAIT.inc(waited)
        self._active += 1
        # inc/dec (not set): the done-callback of an ABANDONED query can
        # race a fresh admission; set() from both sides loses updates,
        # the locked inc/dec pair cannot
        SERVING_INFLIGHT.inc()
        fut = asyncio.ensure_future(asyncio.to_thread(fn))

        def _done(_f):
            self._active -= 1
            SERVING_INFLIGHT.dec()
            self._slot_free.set()
            self._done_times.append(time.monotonic())
            SERVING_QUERIES.inc()
            SERVING_LATENCY.observe(time.monotonic() - t0)
            if cleanup is not None:
                cleanup()

        fut.add_done_callback(_done)
        timeout_s = (self.timeout_ms / 1000.0) if self.timeout_ms else None
        try:
            if timeout_s is None:
                return await asyncio.shield(fut)
            return await asyncio.wait_for(asyncio.shield(fut), timeout_s)
        except asyncio.TimeoutError:
            SERVING_TIMEOUTS.inc()
            raise ServingTimeout(
                f"serving query exceeded {self.timeout_ms}ms "
                f"(SET serving_query_timeout_ms)") from None
        except asyncio.CancelledError:
            # the client vanished; the thread finishes in the background
            # and the done callback releases its slot/pins
            raise

"""Worker — the compute-node process of the deployment.

Reference: the compute node role (compute/src/server.rs:86): it receives
plan fragments from the control plane, builds executors through the same
from_proto registry, and exchanges data with peers.

One listener serves TWO protocols, selected by the connection's first
frame:

  * legacy fragment offload (stream/remote_fragment.py): a pickled spec
    dict ships ONE Node subtree; the worker runs it as an identity-less
    proxied child and streams everything back — kept for v1 remote
    fragments (`SET streaming_fragment_worker`);
  * the cluster control plane (cluster/compute_node.py): the first frame
    is an RPC request (`hello`), after which this process is a
    FIRST-CLASS compute node — it registers with meta, builds and OWNS
    its assigned actors over vnode-partitioned fragments, runs a local
    barrier manager, seals + uploads its own state, and serves its own
    /metrics.

Run: python -m risingwave_tpu.worker [port] [--monitor-port N]
(port 0 = ephemeral; the chosen port prints as the first stdout line
for orchestration).
"""

from __future__ import annotations

import asyncio
import json
import os
import pickle
import struct
import sys


def _pin_jax_platform() -> None:
    """Honor JAX_PLATFORMS IN-PROCESS before any jax use.

    Deployment images may carry a sitecustomize that updates jax.config
    at interpreter startup (e.g. to the real accelerator), which beats
    the environment variable — so a parent that spawned this worker with
    JAX_PLATFORMS=cpu would still get a worker touching (and possibly
    hanging on) the device. jax.config.update wins over both."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


async def _recv_blob(reader) -> bytes:
    ln = struct.unpack("!i", await reader.readexactly(4))[0]
    return await reader.readexactly(ln)


async def _send_blob(writer, blob: bytes) -> None:
    writer.write(struct.pack("!i", len(blob)) + blob)
    await writer.drain()


class _StubCoord:
    """Builders never touch the coordinator; actors (which do) are not
    used in the worker — barriers ride the data stream."""

    def register_source(self, q) -> None:
        pass

    def register_actor(self, a) -> None:
        pass


_MONITOR_PORT = 0        # set by main(); workers have ONE listener


async def _handle(reader, writer) -> None:
    from .common.types import Schema  # noqa: F401  (pickle needs types)
    from .plan.build import BUILDERS, ActorCtx, BuildEnv
    from .plan.graph import Exchange
    from .state import MemoryStateStore
    from .stream.message import Barrier
    from .stream.remote_exchange import RemoteInput, RemoteOutput

    peer = writer.get_extra_info("peername")[0]
    try:
        spec = pickle.loads(await _recv_blob(reader))
    except (asyncio.IncompleteReadError, ConnectionResetError):
        writer.close()
        return
    if isinstance(spec, dict) and "method" in spec:
        # cluster control plane: this connection IS meta — promote the
        # process to a first-class compute node for its lifetime
        from .cluster.compute_node import serve_connection
        await serve_connection(reader, writer, spec,
                               monitor_port=_MONITOR_PORT)
        return
    ins = []
    for sch in spec["in_schemas"]:
        ins.append(await RemoteInput(sch, host="0.0.0.0",
                                     queue_depth=8).start())
    await _send_blob(writer, json.dumps(
        {"input_ports": [r.port for r in ins]}).encode())
    out = await RemoteOutput(peer, spec["out_port"]).connect()

    env = BuildEnv(MemoryStateStore(), _StubCoord())
    ctx = ActorCtx(env=env, fragment=None, actor_id=0, actor_idx=0,
                   vnode_bitmap=None, table_ids={})
    pending = list(ins)

    def build(n):
        if isinstance(n, Exchange):
            return pending.pop(0)     # pre-order = port assignment order
        inputs = [build(i) for i in n.inputs]
        args = dict(n.args)
        args["durable"] = False       # v1: remote fragments are volatile
        return BUILDERS[n.kind](args, inputs, ctx, id(n))

    chain = build(spec["node"])
    stop_id = spec.get("stop_actor_id")
    try:
        async for msg in chain.execute():
            await out.send(msg)
            if isinstance(msg, Barrier) and msg.mutation is not None \
                    and (msg.is_stop(stop_id) if stop_id is not None
                         else msg.is_stop_any()):
                break
    except (ConnectionResetError, asyncio.IncompleteReadError, OSError):
        pass            # main went away (crash/recovery): drop fragment
    finally:
        try:
            await out.close()
        except Exception:  # noqa: BLE001
            pass
        for r in ins:
            await r.stop()
        writer.close()


async def serve(port: int = 0, host: str = "127.0.0.1"):
    server = await asyncio.start_server(_handle, host, port)
    print(server.sockets[0].getsockname()[1], flush=True)
    async with server:
        await server.serve_forever()


def main(argv=None) -> None:
    global _MONITOR_PORT
    argv = sys.argv[1:] if argv is None else argv
    args = list(argv)
    if "--monitor-port" in args:
        i = args.index("--monitor-port")
        _MONITOR_PORT = int(args[i + 1])
        del args[i:i + 2]
    port = int(args[0]) if args else 0
    _pin_jax_platform()
    # cluster compute nodes compile the same per-shape programs the
    # coordinator does: share the persistent compilation cache so a
    # worker restarted by recovery starts hot
    from .utils.compile_cache import enable_persistent_cache
    enable_persistent_cache()
    asyncio.run(serve(port))


if __name__ == "__main__":
    main()

"""Retractable MIN/MAX state — materialized-input top-K value buffers.

Reference: src/stream/src/executor/aggregation/minput.rs — retractable
extrema keep the input values materialized in a state table with a cached
top-N window; deleting the current extremum refills from the next cached
value (or the state table on cache miss).

TPU re-design: per group, a dense buffer of the K best DISTINCT values
with multiplicities, entirely in HBM:

    vals [C, K]   sorted best-first (desc for max, asc for min)
    cnts [C, K]   multiplicity per value (0 = empty cell)
    lossy [C]     True once any insert was dropped past the K-th value —
                  from then on deletes of untracked values are legal

One jitted update per chunk: net (group, value) deltas by run-reduction,
top-K chunk candidates per group, then a per-row 2K merge (sort + adjacent
equal-value combine) — the same merge shape as GroupTopN. Inconsistencies
(a delete that matches no tracked value while the buffer is NOT lossy, or
a buffer that empties while rows remain and history was lossy) are counted
on device and fail-stopped by the executor watchdog before the checkpoint
commits; the reference instead refills from its state table, which is the
durable follow-up for this design (buffer persists with the lossy flag).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .hash_table import stable_lexsort, stable_lexsort_rows


def _order_key(vals, is_max):
    if not is_max:
        return vals
    # ints: bitwise-not is a monotone-decreasing map with no overflow at
    # the dtype extremes (unary minus overflows at iinfo.min);
    # floats: negation is safe (-inf is fine)
    if jnp.issubdtype(vals.dtype, jnp.floating):
        return -vals
    return jnp.invert(vals)


def extrema_empty(C: int, K: int, dtype) -> tuple:
    return (jnp.zeros((C, K), dtype=dtype),
            jnp.zeros((C, K), dtype=jnp.int32),
            jnp.zeros(C, dtype=bool))


def extrema_update(state: tuple, values, valid_in, signs, seg, C: int,
                   is_max: bool):
    """Apply one chunk's rows to the buffers.

    values: [N] input column; valid_in: [N] non-null mask; signs: [N] in
    {-1, 0, +1}; seg: [N] group slot (C = trash). Returns
    (state', n_err int32)."""
    vals, cnts, lossy = state
    K = vals.shape[1]
    N = values.shape[0]
    act = (signs != 0) & valid_in & (seg < C)
    sgs = jnp.where(act, signs, 0)
    sseg = jnp.where(act, seg, C)

    # ---- net delta per (group, value) run ----
    okey = _order_key(values, is_max)
    order = stable_lexsort((okey, sseg))
    o_seg = sseg[order]
    o_val = values[order]
    o_sign = sgs[order]
    leader = jnp.concatenate([jnp.array([True]),
                              (o_seg[1:] != o_seg[:-1])
                              | (o_val[1:] != o_val[:-1])])
    run_id = jnp.cumsum(leader.astype(jnp.int32)) - 1
    run_delta_all = jax.ops.segment_sum(o_sign, run_id, N)
    run_delta = run_delta_all[run_id]           # per sorted row

    # per-SIGN candidate ranks (zero-delta runs consume no slots):
    # positives and negatives each get K candidate slots per group. Keeping
    # the best-K inserts is sound (a dropped insert cannot belong to the
    # merged top-K this chunk; if it matters later the group is lossy and
    # underflow fail-stops). Deletes target TRACKED values (<= K distinct
    # per group), so K delete slots suffice unless one chunk deletes more
    # than K distinct values of one group — that residue cannot be applied
    # to a bounded buffer soundly, so it always fail-stops.
    pos = jnp.arange(N, dtype=jnp.int32)

    def rank_among(mask):
        """Rank of each masked leader within its group, in value order."""
        cnt = jnp.cumsum((leader & mask & (o_seg < C)).astype(jnp.int32))
        seg_start = jax.lax.cummax(jnp.where(
            jnp.concatenate([jnp.array([True]), o_seg[1:] != o_seg[:-1]]),
            pos, 0))
        return (cnt - 1) - (cnt[seg_start] - (leader & mask
                                              & (o_seg < C))[seg_start])

    is_pos = run_delta > 0
    is_neg = run_delta < 0
    rank_pos = rank_among(is_pos)
    rank_neg = rank_among(is_neg)

    keep_pos = leader & (o_seg < C) & is_pos & (rank_pos < K)
    drop_pos = leader & (o_seg < C) & is_pos & (rank_pos >= K)
    keep_neg = leader & (o_seg < C) & is_neg & (rank_neg < K)
    drop_neg = leader & (o_seg < C) & is_neg & (rank_neg >= K)
    lossy_seg = jnp.where(drop_pos, o_seg, C)
    lossy2 = lossy.at[lossy_seg].set(True, mode="drop")
    err_dropped_del = jnp.sum(drop_neg.astype(jnp.int32))

    def scatter_cand(keep, rank):
        tgt_row = jnp.where(keep, o_seg, C)
        tgt_col = jnp.where(keep, jnp.minimum(rank, K - 1), 0)
        cv = jnp.zeros((C + 1, K), dtype=vals.dtype)
        cv = cv.at[tgt_row, tgt_col].set(o_val, mode="drop")
        cc = jnp.zeros((C + 1, K), dtype=jnp.int32)
        cc = cc.at[tgt_row, tgt_col].set(run_delta, mode="drop")
        return cv[:C], cc[:C]

    cand_vals_p, cand_cnts_p = scatter_cand(keep_pos, rank_pos)
    cand_vals_n, cand_cnts_n = scatter_cand(keep_neg, rank_neg)
    cand_vals = jnp.concatenate([cand_vals_p, cand_vals_n], axis=1)
    cand_cnts = jnp.concatenate([cand_cnts_p, cand_cnts_n], axis=1)

    # ---- per-group 3K merge (K state + K insert-cands + K delete-cands)
    m_vals = jnp.concatenate([vals, cand_vals], axis=1)
    m_cnts = jnp.concatenate([cnts, cand_cnts], axis=1)
    m_valid = m_cnts != 0
    sort_idx = stable_lexsort_rows((_order_key(m_vals, is_max), ~m_valid))
    s_vals = jnp.take_along_axis(m_vals, sort_idx, axis=1)
    s_cnts = jnp.take_along_axis(m_cnts, sort_idx, axis=1)
    s_valid = jnp.take_along_axis(m_valid, sort_idx, axis=1)
    # adjacent equal-value combine (state values and cand values are each
    # distinct, so at most one duplicate pair per value)
    dup = (s_valid[:, 1:] & s_valid[:, :-1]
           & (s_vals[:, 1:] == s_vals[:, :-1]))
    add = jnp.where(dup, s_cnts[:, 1:], 0)
    s_cnts = s_cnts.at[:, :-1].add(add)
    s_valid = s_valid.at[:, 1:].set(jnp.where(dup, False, s_valid[:, 1:]))
    # negative residue = delete of an untracked value
    neg = s_valid & (s_cnts < 0)
    err_neg = jnp.sum((neg & ~lossy2[:, None]).astype(jnp.int32))
    s_valid = s_valid & (s_cnts > 0)
    # resort (combined zeros / negatives drop out), keep best K
    sort2 = stable_lexsort_rows((_order_key(s_vals, is_max), ~s_valid))
    f_vals = jnp.take_along_axis(s_vals, sort2, axis=1)
    f_cnts = jnp.take_along_axis(s_cnts, sort2, axis=1)
    f_valid = jnp.take_along_axis(s_valid, sort2, axis=1)
    spill = jnp.any(f_valid[:, K:], axis=1)
    lossy3 = lossy2 | spill
    out_vals = jnp.where(f_valid[:, :K], f_vals[:, :K], 0)
    out_cnts = jnp.where(f_valid[:, :K], f_cnts[:, :K], 0)
    n_err = err_dropped_del + err_neg
    return (out_vals, out_cnts, lossy3), n_err


def extrema_emit(state: tuple, init, dtype):
    """Best value per group (identity where the buffer is empty)."""
    vals, cnts, _ = state
    has = cnts[:, 0] > 0
    return jnp.where(has, vals[:, 0], jnp.asarray(init, dtype=dtype))


def extrema_underflow(state: tuple, row_count) -> jnp.ndarray:
    """Groups with live rows, an empty buffer, and lossy history — the
    extremum is unknowable without a durable refill: fail-stop count."""
    vals, cnts, lossy = state
    empty = cnts[:, 0] <= 0
    return jnp.sum((empty & lossy & (row_count > 0)).astype(jnp.int32))


def extrema_gather(state: tuple, sel, tgt, C_new: int, K: int, dtype):
    """Rehash support: move group g's buffers via compaction select `sel`
    and scatter to `tgt` (same contract as the scalar agg states)."""
    vals, cnts, lossy = state
    e_vals = jnp.zeros((C_new, K), dtype=dtype)
    e_cnts = jnp.zeros((C_new, K), dtype=jnp.int32)
    e_lossy = jnp.zeros(C_new, dtype=bool)
    return (e_vals.at[tgt].set(vals[sel], mode="drop"),
            e_cnts.at[tgt].set(cnts[sel], mode="drop"),
            e_lossy.at[tgt].set(lossy[sel], mode="drop"))


def extrema_mask_keep(state: tuple, keep) -> tuple:
    """Watermark eviction: zero the buffers of evicted groups."""
    vals, cnts, lossy = state
    return (jnp.where(keep[:, None], vals, 0),
            jnp.where(keep[:, None], cnts, 0),
            lossy & keep)

"""jit_state — the one jax.jit wrapper for state-threading programs.

Every stateful executor jits a handful of step programs (`_apply`,
`_flush`, `_evict`, `_rehash`, ...) and threads a large device-resident
state pytree through them functionally.  Wrapping them uniformly here buys
two things the raw `jax.jit` call sites could not:

* **Buffer donation** — `donate_argnums` marks the threaded state (and
  device-resident accumulators) as consumed, so XLA reuses the table
  buffers in place instead of allocating a fresh copy of the full state
  every chunk.  The hot-path cost of NOT donating is one full HBM
  alloc+copy of the hash-table arrays per chunk per executor.  Donation is
  real on this stack's CPU backend too (donated arrays are deleted), which
  keeps aliasing bugs visible under the tier-1 tests instead of only on
  TPU.  CALLERS MUST NOT hold other references to donated arrays — the
  executors thread `self.state = self._apply(self.state, ...)`, which is
  exactly the safe shape.  State that is aliased elsewhere (snapshot diff
  bases, `prev_*` emission copies) must NOT be donated; those call sites
  say so explicitly.

* **Dispatch / recompile accounting** — the north-star workloads are
  host-dispatch-bound (bench.py: a 0.4 ms program pays 400+ ms dispatch in
  the degraded-tunnel regime), so dispatches-per-barrier-interval and
  recompiles-after-warmup are first-class metrics.  The wrapper counts a
  dispatch per call and a compile per trace (the traced Python body runs
  exactly once per new static signature), into both per-program labelled
  counters and the process totals `jit_compile_count` /
  `device_dispatch_count` in GLOBAL_METRICS (surfaced by the `\\metrics`
  REPL command and scripts/dispatch_profile.py).
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import jax

from ..utils.metrics import (
    DEVICE_DISPATCHES, GLOBAL_METRICS, JIT_COMPILES,
)

# A donated buffer whose shape matches no output (e.g. a growing rehash)
# is simply not reused; jax warns per lowering. The fallback is the
# pre-donation behavior, not an error — keep the logs quiet.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


class StateJit:
    """A jitted program with donation + dispatch/recompile counters.

    Call it exactly like the jitted function. `dispatches` / `compiles`
    expose host-side totals for tests and the dispatch_profile harness.
    """

    def __init__(self, fn, *, donate_argnums: Sequence[int] = (),
                 static_argnums=None, static_argnames=None,
                 name: Optional[str] = None):
        self.name = name or getattr(fn, "__name__", "step").lstrip("_")
        self._dispatch_c = GLOBAL_METRICS.counter(
            "device_dispatch_count", program=self.name)
        self._compile_c = GLOBAL_METRICS.counter(
            "jit_compile_count", program=self.name)

        def traced(*args, **kwargs):
            # runs once per trace == once per compiled signature
            self._compile_c.inc()
            JIT_COMPILES.inc()
            return fn(*args, **kwargs)

        jit_kwargs: dict = {}
        if donate_argnums:
            jit_kwargs["donate_argnums"] = tuple(donate_argnums)
        if static_argnums is not None:
            jit_kwargs["static_argnums"] = static_argnums
        if static_argnames is not None:
            jit_kwargs["static_argnames"] = static_argnames
        self._jitted = jax.jit(traced, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        self._dispatch_c.inc()
        DEVICE_DISPATCHES.inc()
        return self._jitted(*args, **kwargs)

    @property
    def dispatches(self) -> int:
        return int(self._dispatch_c.value)

    @property
    def compiles(self) -> int:
        return int(self._compile_c.value)


def jit_state(fn, *, donate_argnums: Sequence[int] = (),
              static_argnums=None, static_argnames=None,
              name: Optional[str] = None) -> StateJit:
    """`jax.jit` with buffer donation for the threaded state pytree plus
    dispatch/recompile counters. Drop-in at every stateful executor's jit
    call site; see the module docstring for the donation aliasing rules."""
    return StateJit(fn, donate_argnums=donate_argnums,
                    static_argnums=static_argnums,
                    static_argnames=static_argnames, name=name)

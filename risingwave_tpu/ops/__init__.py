"""Device kernels shared across executors: hashing, open-addressing tables."""

from .hash_table import HashTable, lookup, lookup_or_insert, needs_rebuild

__all__ = ["HashTable", "lookup", "lookup_or_insert", "needs_rebuild"]

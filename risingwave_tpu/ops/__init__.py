"""Device kernels shared across executors: hashing, open-addressing
tables, and the donated state-threading jit wrapper."""

from .hash_table import HashTable, lookup, lookup_or_insert, needs_rebuild
from .jit_state import StateJit, jit_state

__all__ = ["HashTable", "StateJit", "jit_state", "lookup",
           "lookup_or_insert", "needs_rebuild"]

"""Device-resident open-addressing hash table — the state backbone of
HashAgg and HashJoin.

Reference analogue: the executors' group/join hash maps (`JoinHashMap`,
src/stream/src/executor/managed_state/join/mod.rs; `AggGroup` cache keyed by
`HashKey`, hash_agg.rs:50-56). On TPU the map is a struct-of-arrays in HBM:
fixed-capacity key columns + occupancy, probed with linear open addressing.
The whole insert-or-lookup for a chunk is ONE compiled while_loop — no
per-row host control flow.

Parallel-insert race (two new keys landing on the same empty slot in the
same probe round) resolves by scatter-min of row ids: the winner claims the
slot, same-key losers match it on the next round, different-key losers
advance. Rows advance past occupied non-matching slots (linear probing).

Deletion policy: slots are never freed (freeing breaks probe chains).
Groups that empty out stay as zombies; the owner monitors live/zombie load
via `needs_rebuild` and rebuilds (optionally growing) by re-inserting live
entries — that is also the capacity-doubling growth path flagged in
SURVEY.md §7 hard-parts (a).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.vnode import crc32_columns


@jax.tree_util.register_pytree_node_class
@dataclass
class HashTable:
    """keys: per-key-column [C] arrays; occupied: bool [C]."""

    keys: tuple[jnp.ndarray, ...]
    occupied: jnp.ndarray

    def tree_flatten(self):
        return (self.keys, self.occupied), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        keys, occupied = children
        return cls(tuple(keys), occupied)

    @property
    def capacity(self) -> int:
        return self.occupied.shape[0]

    @staticmethod
    def empty(capacity: int, key_dtypes: Sequence) -> "HashTable":
        return HashTable(
            tuple(jnp.zeros(capacity, dtype=dt) for dt in key_dtypes),
            jnp.zeros(capacity, dtype=bool),
        )


def _hash_to_slot(key_cols: Sequence[jnp.ndarray], capacity: int) -> jnp.ndarray:
    # crc32 of the key bytes (same family as vnode hashing) -> starting slot
    return (crc32_columns(key_cols) % jnp.uint32(capacity)).astype(jnp.int32)


def lookup_or_insert(table: HashTable, key_cols: Sequence[jnp.ndarray],
                     active: jnp.ndarray, max_probes: int = 0):
    """Find or claim a slot for every active row.

    key_cols: [N] arrays matching table.keys dtypes; active: bool [N]
    (invisible rows resolve immediately to slot -1).

    Returns (table', slots int32 [N] (-1 for inactive/unresolved),
    n_unresolved int32 scalar). n_unresolved > 0 means the table is too
    full / probe-bound — the caller must rebuild larger and retry.
    """
    C = table.capacity
    N = key_cols[0].shape[0]
    if max_probes == 0:
        max_probes = C  # full linear scan worst case
    row_ids = jnp.arange(N, dtype=jnp.int32)
    start = _hash_to_slot(key_cols, C)

    def keys_match_at(slot_keys, key_cols):
        m = jnp.ones(N, dtype=bool)
        for tk, k in zip(slot_keys, key_cols):
            m &= tk == k
        return m

    def cond(st):
        _, _, resolved, _, it = st
        return jnp.any(~resolved) & (it < max_probes)

    def body(st):
        keys, occupied, resolved, slot, it = st
        occ = occupied[slot]
        slot_keys = tuple(tk[slot] for tk in keys)
        match = occ & keys_match_at(slot_keys, key_cols)
        found = ~resolved & match
        empty = ~resolved & ~occ
        # claim contest: min row id per contested slot wins
        claim = jnp.full(C, N, dtype=jnp.int32)
        claim = claim.at[jnp.where(empty, slot, C)].min(row_ids, mode="drop")
        winner = empty & (claim[slot] == row_ids)
        w_idx = jnp.where(winner, slot, C)
        keys = tuple(tk.at[w_idx].set(k, mode="drop")
                     for tk, k in zip(keys, key_cols))
        occupied = occupied.at[w_idx].set(True, mode="drop")
        resolved2 = resolved | found | winner
        # advance only on occupied-mismatch; losers of a claim retry the
        # same slot (it now holds the winner's key — may be theirs)
        advance = ~resolved2 & occ & ~match
        slot = jnp.where(advance, (slot + 1) % C, slot)
        return keys, occupied, resolved2, slot, it + 1

    init = (table.keys, table.occupied, ~active, start, jnp.int32(0))
    keys, occupied, resolved, slot, _ = jax.lax.while_loop(cond, body, init)
    n_unresolved = jnp.sum(~resolved, dtype=jnp.int32)
    slots = jnp.where(resolved & active, slot, -1)
    return HashTable(keys, occupied), slots, n_unresolved


def lookup(table: HashTable, key_cols: Sequence[jnp.ndarray],
           active: jnp.ndarray, max_probes: int = 0):
    """Read-only probe: slot of each active row's key, -1 if absent.

    Probing stops at the first never-occupied slot in the chain (slots are
    never freed, so an empty slot terminates the chain definitively).
    """
    C = table.capacity
    N = key_cols[0].shape[0]
    if max_probes == 0:
        max_probes = C
    start = _hash_to_slot(key_cols, C)

    def cond(st):
        searching, _, it = st
        return jnp.any(searching) & (it < max_probes)

    def body(st):
        searching, slot, it = st
        occ = table.occupied[slot]
        matched = jnp.ones(N, dtype=bool)
        for tk, k in zip(table.keys, key_cols):
            matched &= tk[slot] == k
        hit = searching & occ & matched
        miss_end = searching & ~occ          # chain ended: not present
        advance = searching & occ & ~matched
        searching2 = searching & ~hit & ~miss_end
        slot2 = jnp.where(advance, (slot + 1) % C, slot)
        # resolved rows keep their slot on hit; a miss parks at -1
        return searching2, jnp.where(miss_end, -1, slot2), it + 1

    searching, slot, _ = jax.lax.while_loop(
        cond, body, (active, start.astype(jnp.int32), jnp.int32(0)))
    # rows still searching after max_probes: treat as absent
    return jnp.where(active & ~searching, slot, -1)


def load(table: HashTable) -> jnp.ndarray:
    """Occupied fraction (live + zombie) — rebuild trigger input."""
    return jnp.mean(table.occupied.astype(jnp.float32))


def needs_rebuild(n_occupied: int, n_live: int, capacity: int,
                  hi: float = 0.7) -> tuple[bool, int]:
    """Host-side policy: rebuild when load > hi. Grow 2x only if the LIVE
    set itself crowds the table; a zombie-heavy table rebuilds at the same
    capacity (purge)."""
    if n_occupied <= hi * capacity:
        return False, capacity
    if n_live > 0.5 * hi * capacity:
        return True, capacity * 2
    return True, capacity

"""Device-resident bucketed hash table — the state backbone of HashAgg and
HashJoin.

Reference analogue: the executors' group/join hash maps (`JoinHashMap`,
src/stream/src/executor/managed_state/join/mod.rs; `AggGroup` cache keyed by
`HashKey`, hash_agg.rs:50-56). On TPU the map is a struct-of-arrays in HBM:
fixed-capacity key columns + occupancy.

Layout: capacity C = B buckets x S slots (S static). A key hashes to TWO
candidate buckets (two halves of a splitmix64 chain over the key columns
— power-of-two-choices); it lives in exactly one of their 2S slots. This shape is chosen for the
hardware: a lookup is ONE vectorized [N, 2S] gather + compare — constant
cost, no data-dependent probe loop — and an insert is two device sorts plus
scatters. The previous design (linear open addressing driven by a
`lax.while_loop` claim contest) had per-chunk cost proportional to the
longest probe chain, which degrades sharply with load/clustering: a
saturated table turned one chunk into an O(C)-iteration loop that stalled
the device (observed: TPU watchdog killing the worker). Bounded bucket
probing makes the worst case a constant.

Two-choice balancing keeps bucket overflow improbable up to ~0.7 load
(classic power-of-two-choices: max load ~ mean + lg lg B). Overflow is
reported, never silent: `lookup_or_insert` returns `n_unresolved`, and the
owning executor fail-stops / rebuilds larger (its existing policy).

Within-bucket occupancy is a PREFIX: inserts append at the bucket's fill
point and slots are never freed (groups that empty out stay as zombies;
owners monitor live/zombie load via `needs_rebuild` and rebuild by
re-inserting live entries — also the capacity-growth path flagged in
SURVEY.md §7 hard-parts (a)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp

# Slots per bucket. 16 keeps the two-choice overflow probability negligible
# at the 0.7 rebuild threshold while the [N, 2S] compare stays one small
# vectorized gather per chunk.

BUCKET_SLOTS = 16

def compact_mask(mask: jnp.ndarray):
    """The cumsum-scatter compaction idiom used all over the state
    kernels, factored once: for bool [C] `mask`, returns (sel, n) where
    sel int32 [C] holds the indices of the set bits in its first n
    entries (garbage past n) and n is the device count."""
    C = mask.shape[0]
    rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
    sel = jnp.zeros(C, dtype=jnp.int32).at[
        jnp.where(mask, rank, C)].set(jnp.arange(C, dtype=jnp.int32),
                                      mode="drop")
    return sel, jnp.sum(mask.astype(jnp.int32))


def pack_rows(mask: jnp.ndarray, arrays):
    """Group pack kernel for eviction/spill paths: compact the masked
    slots of every array to the buffer prefix in one gather pass.
    Returns (packed arrays tuple, device count) — only the first n rows
    of each packed array are meaningful."""
    sel, n = compact_mask(mask)
    return tuple(a[sel] for a in arrays), n


def lru_stamp(stamp: jnp.ndarray, touched: jnp.ndarray, epoch) -> jnp.ndarray:
    """Advance a per-slot LRU epoch stamp from one interval's touched-slot
    bitmap: one elementwise select per barrier, nothing on the data path.
    (Bucket hashing gives slots no spatial locality, so hotness is tracked
    per SLOT — coarser vnode/bucket group ranges would mix hot and cold
    keys and evict nothing.)"""
    return jnp.where(touched, jnp.int64(epoch), stamp)


def stable_lexsort(keys):
    """np.lexsort semantics (last key primary) as ITERATED single-key
    stable argsorts. jnp.lexsort lowers to one variadic sort whose XLA
    compile time explodes with key count and length (measured: 42s for a
    3-key sort of 32k rows on TPU vs 8s total for this form); K successive
    stable sorts are the textbook definition and compile linearly."""
    order = jnp.argsort(keys[0], stable=True)
    for k in keys[1:]:
        order = order[jnp.argsort(k[order], stable=True)]
    return order


def stable_lexsort_rows(keys):
    """Per-row (axis=1) variant for [C, K] buffers."""
    order = jnp.argsort(keys[0], axis=1, stable=True)
    for k in keys[1:]:
        step = jnp.argsort(jnp.take_along_axis(k, order, axis=1), axis=1,
                           stable=True)
        order = jnp.take_along_axis(order, step, axis=1)
    return order


@jax.tree_util.register_pytree_node_class
@dataclass
class HashTable:
    """keys: per-key-column [C] arrays; occupied: bool [C]."""

    keys: tuple[jnp.ndarray, ...]
    occupied: jnp.ndarray

    def tree_flatten(self):
        return (self.keys, self.occupied), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        keys, occupied = children
        return cls(tuple(keys), occupied)

    @property
    def capacity(self) -> int:
        return self.occupied.shape[0]

    @staticmethod
    def empty(capacity: int, key_dtypes: Sequence) -> "HashTable":
        assert capacity % BUCKET_SLOTS == 0 and capacity >= 2 * BUCKET_SLOTS, \
            f"capacity {capacity} must be a multiple of {BUCKET_SLOTS}"
        return HashTable(
            tuple(jnp.zeros(capacity, dtype=dt) for dt in key_dtypes),
            jnp.zeros(capacity, dtype=bool),
        )


def _bucket_pair(key_cols: Sequence[jnp.ndarray], n_buckets: int):
    """Two independent candidate buckets per row (int32 [N] each), plus a
    per-key tiebreak bit so equal-fill choices split ~50/50 (without it, a
    burst of new keys within one chunk — where fills are all read
    pre-chunk — would pile into every key's first choice).

    The candidates come from a splitmix64 chain over the key columns, NOT
    from crc32: CRC is linear over GF(2), so structured key sets (window
    multiples x small ids — the windowed-agg shape) project onto few
    residues mod a small bucket count and saturate bucket pairs at 30%
    global load (observed: 16/16 buckets at 335/1024 occupancy after a
    memory-eviction rehash batch-reinserted such keys). The multiply-
    xorshift mix is non-linear, so those sets disperse like random keys.
    The crc stays the DISTRIBUTION hash (vnodes) — this only places rows
    within a device table, nothing durable moves."""
    h = jnp.full(key_cols[0].shape[0], 0x243F6A8885A308D3,
                 dtype=jnp.uint64)
    for c in key_cols:
        x = h ^ (c.astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15))
        x = x + jnp.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        h = x ^ (x >> jnp.uint64(31))
    nb = jnp.uint64(n_buckets)
    h1 = ((h & jnp.uint64(0xFFFFFFFF)) % nb).astype(jnp.int32)
    h2 = ((h >> jnp.uint64(32)) % nb).astype(jnp.int32)
    tie = ((h >> jnp.uint64(31)) & jnp.uint64(1)).astype(bool)
    return h1, h2, tie


def _candidates(table: HashTable, key_cols: Sequence[jnp.ndarray]):
    """[N, 2S] candidate slot ids + occupancy + key match per row."""
    S = BUCKET_SLOTS
    B = table.capacity // S
    N = key_cols[0].shape[0]
    h1, h2, tie = _bucket_pair(key_cols, B)
    bases = jnp.stack([h1 * S, h2 * S], axis=1)            # [N, 2]
    cand = (bases[:, :, None] + jnp.arange(S, dtype=jnp.int32)).reshape(N, 2 * S)
    occ = table.occupied[cand]
    match = occ
    for tk, k in zip(table.keys, key_cols):
        match = match & (tk[cand] == k[:, None])
    return h1, h2, tie, cand, occ, match


def lookup(table: HashTable, key_cols: Sequence[jnp.ndarray],
           active: jnp.ndarray, max_probes: int = 0):
    """Read-only probe: slot of each active row's key, -1 if absent.

    One vectorized compare against both candidate buckets — constant cost.
    (`max_probes` is accepted for API compatibility; probing is inherently
    bounded by the bucket shape.)
    """
    _, _, _, cand, _, match = _candidates(table, key_cols)
    has = match.any(axis=1)
    sel = jnp.argmax(match, axis=1)
    slot = jnp.take_along_axis(cand, sel[:, None], axis=1)[:, 0]
    return jnp.where(active & has, slot, -1)


def lookup_or_insert(table: HashTable, key_cols: Sequence[jnp.ndarray],
                     active: jnp.ndarray, max_probes: int = 0):
    """Find or claim a slot for every active row.

    key_cols: [N] arrays matching table.keys dtypes; active: bool [N]
    (invisible rows resolve immediately to slot -1).

    Returns (table', slots int32 [N] (-1 for inactive/unresolved),
    n_unresolved int32 scalar). n_unresolved > 0 means both candidate
    buckets of some new key are full — the caller must rebuild larger and
    retry (two-choice balancing makes this improbable below ~0.7 load).

    Insert algorithm (no data-dependent loops):
      1. match pass as in `lookup`;
      2. first device sort groups missing rows by key (in-chunk dedup:
         each distinct new key forms a run, its first row is the leader);
      3. each leader picks the emptier of its two buckets (pre-chunk fill —
         within-bucket occupancy is a prefix, so fill = occ.sum);
      4. second device sort ranks leaders within their chosen bucket, the
         run's slot = bucket*S + fill + rank;
      5. scatter keys/occupancy for leaders; run members inherit the
         leader's slot via a segmented gather; unsort.
    """
    S = BUCKET_SLOTS
    C = table.capacity
    N = key_cols[0].shape[0]
    row_ids = jnp.arange(N, dtype=jnp.int32)

    h1, h2, tie, cand, occ, match = _candidates(table, key_cols)
    has = match.any(axis=1)
    msel = jnp.argmax(match, axis=1)
    mslot = jnp.take_along_axis(cand, msel[:, None], axis=1)[:, 0]

    fill1 = occ[:, :S].sum(axis=1, dtype=jnp.int32)
    fill2 = occ[:, S:].sum(axis=1, dtype=jnp.int32)
    choose2 = (fill2 < fill1) | ((fill2 == fill1) & tie)
    c_bucket = jnp.where(choose2, h2, h1)
    c_fill = jnp.minimum(fill1, fill2)

    miss = active & ~has

    # ---- sort 1: group missing rows by key (runs of identical keys) ----
    sort_keys = [row_ids]
    for k in key_cols:
        sort_keys.append(k)
    sort_keys.append(~miss)                       # primary: missing first
    order = stable_lexsort(tuple(sort_keys))
    s_miss = miss[order]
    same = s_miss[1:] & s_miss[:-1]
    for k in key_cols:
        sk = k[order]
        same = same & (sk[1:] == sk[:-1])
    is_leader = s_miss & jnp.concatenate([jnp.array([True]), ~same])
    run_id = jnp.cumsum(is_leader.astype(jnp.int32)) - 1    # per sorted row
    s_bucket = c_bucket[order]
    s_fill = c_fill[order]

    # ---- sort 2: rank leaders within their chosen bucket ----
    B_sentinel = C // S                            # non-leaders sort last
    rank_key = jnp.where(is_leader, s_bucket, B_sentinel)
    order2 = stable_lexsort((jnp.arange(N, dtype=jnp.int32), rank_key))
    r_bucket = rank_key[order2]
    new_bucket = jnp.concatenate(
        [jnp.array([True]), r_bucket[1:] != r_bucket[:-1]])
    pos = jnp.arange(N, dtype=jnp.int32)
    bucket_start = jax.lax.cummax(jnp.where(new_bucket, pos, 0))
    rank = pos - bucket_start
    r_fill = s_fill[order2]
    r_leader = is_leader[order2]
    r_ok = r_leader & (r_fill + rank < S)
    r_slot = jnp.where(r_ok, r_bucket * S + r_fill + rank, -1)

    # scatter leader slots back to sorted-1 space, then spread over runs
    slot_s1 = jnp.zeros(N, dtype=jnp.int32).at[order2].set(r_slot)
    leader_slot_by_run = jnp.full(N + 1, -1, dtype=jnp.int32).at[
        jnp.where(is_leader, run_id, N)].set(
            jnp.where(is_leader, slot_s1, -1), mode="drop")
    s_ins_slot = jnp.where(s_miss, leader_slot_by_run[run_id], -1)

    # ---- write leaders' keys/occupancy ----
    w_idx = jnp.where(r_ok, r_slot, C)
    orig2 = order[order2]                          # sorted-2 -> original row
    keys = tuple(tk.at[w_idx].set(k[orig2], mode="drop")
                 for tk, k in zip(table.keys, key_cols))
    occupied = table.occupied.at[w_idx].set(True, mode="drop")

    # ---- unsort + combine ----
    ins_slot = jnp.zeros(N, dtype=jnp.int32).at[order].set(s_ins_slot)
    slots = jnp.where(has, mslot, jnp.where(miss, ins_slot, -1))
    slots = jnp.where(active, slots, -1)
    n_unresolved = jnp.sum((active & (slots < 0)).astype(jnp.int32))
    return HashTable(keys, occupied), slots, n_unresolved


def load(table: HashTable) -> jnp.ndarray:
    """Occupied fraction (live + zombie) — rebuild trigger input."""
    return jnp.mean(table.occupied.astype(jnp.float32))


def needs_rebuild(n_occupied: int, n_live: int, capacity: int,
                  hi: float = 0.7) -> tuple[bool, int]:
    """Host-side policy: rebuild when load > hi. Grow 2x only if the LIVE
    set itself crowds the table; a zombie-heavy table rebuilds at the same
    capacity (purge)."""
    if n_occupied <= hi * capacity:
        return False, capacity
    if n_live > 0.5 * hi * capacity:
        return True, capacity * 2
    return True, capacity

"""Meta service — worker registry, fragment placement, cluster deploys.

Reference: src/meta/src/ — the meta node owns the cluster: compute nodes
register and heartbeat (`ClusterManager`, lease-based liveness), the
stream manager places fragments over parallel units by vnode range
(`schedule.rs`), the `GlobalBarrierManager` injects barriers per worker
and collects per-worker completion, and Hummock versions commit only
after every worker's SSTs landed.

`ClusterManager` here is owned by the Session once `SET cluster =
'host:port,host:port'` runs:

  * registry: one `WorkerHandle` per compute node, heartbeat pings on an
    interval, lease expiry or connection loss fails the worker (which
    fails in-flight barriers fast and hands the session's tick-path
    auto-recovery a smaller live set to re-place onto);
  * placement: fragment actor idx -> live worker (vnode bitmaps are
    per-actor-idx, so a fragment's vnode ranges land spread across the
    live set; after a worker death the SAME vnode-partitioned state
    re-reads under the new placement — the rescale machinery's
    contract);
  * deploy: two-phase — every worker derives identical ids from the
    pickled graph (plan/build.py `assign_graph_ids`), phase 1 opens the
    inbound DCN receivers and reports ports, phase 2 connects senders
    and spawns actors;
  * checkpoint commit: the coordinator's background committer waits for
    every worker's sealed report, then installs their SSTs into the
    shared manifest (state/hummock.py `commit_remote`).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

from .rpc import RpcConn

# disjoint SST-id namespaces: meta allocates low ids; each worker gets a
# 2^40 block per (generation, ordinal) so concurrent uploads over the
# shared object store can never collide, across recoveries included
SST_ID_BLOCK = 1 << 40
MAX_WORKERS_PER_GEN = 64


@dataclass
class WorkerInfo:
    worker_id: int
    addr: str
    alive: bool = True
    pid: int = 0
    jax_platform: str = ""
    monitor_port: int = 0
    last_heartbeat: float = field(default_factory=time.monotonic)
    lease_s: float = 10.0

    @property
    def lease_remaining_s(self) -> float:
        return max(0.0, self.lease_s
                   - (time.monotonic() - self.last_heartbeat))


class WorkerHandle:
    """Meta's live handle to one compute node."""

    def __init__(self, manager: "ClusterManager", worker_id: int,
                 addr: str):
        self.manager = manager
        self.worker_id = worker_id
        self.addr = addr
        self.info = WorkerInfo(worker_id, addr,
                               lease_s=manager.lease_s)
        self.conn: Optional[RpcConn] = None
        self.failure: Optional[BaseException] = None
        # epoch -> sealed sst ids (value) or Future (a waiter got there
        # first); the background committer awaits these per checkpoint
        self._sealed: dict[int, object] = {}

    @property
    def host(self) -> str:
        return self.addr.rsplit(":", 1)[0]

    async def connect(self) -> None:
        host, _, port = self.addr.rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        self.conn = RpcConn(
            reader, writer,
            handler=lambda m, a: self.manager._on_push(self, m, a),
            on_closed=lambda exc: self.manager._on_worker_lost(self, exc))
        self.conn.start()

    async def call(self, method: str, timeout: Optional[float] = None,
                   **args):
        return await self.conn.call(method, timeout=timeout, **args)

    async def inject(self, barrier) -> None:
        await self.conn.push("inject", barrier=barrier)

    async def notify_committed(self, epoch: int) -> None:
        """Meta committed `epoch` cluster-wide: the worker drops its
        retained sealed batches and trims its replay buffers (local
        channels + DCN legs) to the uncommitted suffix."""
        await self.conn.push("committed", epoch=epoch)

    # ------------------------------------------------------ sealed reports
    def on_sealed(self, epoch: int, sst_ids: list) -> None:
        cur = self._sealed.get(epoch)
        if isinstance(cur, asyncio.Future):
            if not cur.done():
                cur.set_result(list(sst_ids))
            self._sealed.pop(epoch, None)
        else:
            self._sealed[epoch] = list(sst_ids)

    async def wait_sealed(self, epoch: int) -> list:
        """The committer's wait for this worker's sealed report; fails
        fast once the worker is gone (the parked error then rides the
        coordinator's fail-stop into auto-recovery)."""
        if self.failure is not None:
            raise ConnectionResetError(
                f"worker {self.worker_id} failed: {self.failure}")
        cur = self._sealed.pop(epoch, None)
        if cur is not None and not isinstance(cur, asyncio.Future):
            return cur
        fut = asyncio.get_running_loop().create_future()
        self._sealed[epoch] = fut
        try:
            return await fut
        finally:
            self._sealed.pop(epoch, None)

    def fail(self, exc: BaseException) -> None:
        self.failure = exc
        self.info.alive = False
        for v in list(self._sealed.values()):
            if isinstance(v, asyncio.Future) and not v.done():
                v.set_exception(ConnectionResetError(
                    f"worker {self.worker_id} failed: {exc}"))
        self._sealed.clear()

    async def close(self) -> None:
        if self.conn is not None:
            await self.conn.close()


class ClusterDeployment:
    """Meta-side record of one streaming job deployed over the cluster.
    Duck-types the parts of plan/build.py `Deployment` the Session
    touches (roots for the MV shadow table, stop, empty task/actor
    lists — the real actors live in the workers)."""

    def __init__(self, manager: "ClusterManager", deploy_id: int,
                 coord, all_actor_ids: frozenset,
                 roots: Optional[dict] = None,
                 rebuild_info: Optional[dict] = None):
        self.manager = manager
        self.deploy_id = deploy_id
        self.coord = coord
        self.all_actor_ids = all_actor_ids
        self.roots = roots or {}
        self.actors: list = []
        self.tasks: list = []
        self.source_queues: list = []
        self.memory_names: list = []
        # everything per-worker partial recovery needs to re-place the
        # dead worker's actors: {"graph","placement","actors","tables",
        # "schemas","scope","ddl_config"} (plan/build.assign_graph_ids
        # derived the same ids on every process)
        self.rebuild_info = rebuild_info
        # actor id -> fragment id, for failure classification
        self.actor_fragment = {}
        if rebuild_info is not None:
            for fid, ids in rebuild_info["actors"].items():
                for aid in ids:
                    self.actor_fragment[aid] = fid

    def spawn(self) -> "ClusterDeployment":
        return self

    async def stop(self) -> None:
        """Stop barrier over the workers' actors, then worker-side
        cleanup. The stop checkpoint commits through the normal cluster
        path (stop_all drains uploads), so dropped state is durable."""
        try:
            await self.coord.stop_all(self.all_actor_ids)
        finally:
            self.manager.deployments.pop(self.deploy_id, None)
            for h in self.manager.live_workers():
                try:
                    await h.call("stop_deployment", timeout=30,
                                 deploy_id=self.deploy_id)
                except Exception:  # noqa: BLE001 — dying worker: recovery owns it
                    pass


class _ShadowRoot:
    """Stands in for a materialize executor at meta: carries the shared
    vnode-partitioned MV table handle (batch SELECTs scan its COMMITTED
    snapshot — exactly the state the cluster commit protocol makes
    visible)."""

    def __init__(self, table, schema):
        self.table = table
        self.schema = schema
        self.identity = "ClusterMaterialize"


class ClusterManager:
    """The session's cluster authority (SET cluster = 'addr,addr')."""

    def __init__(self, session, addrs: list[str],
                 heartbeat_s: float = 2.0, lease_s: float = 45.0):
        # lease default is generous: a compute node's event loop blocks
        # for the duration of any single XLA compile (tens of seconds
        # for the big join shapes on CPU), and a ping parked behind a
        # compile is NOT a dead worker. Connection loss still detects a
        # real death immediately — the lease only covers wedged-alive.
        self.session = session
        self.addrs = list(addrs)
        self.heartbeat_s = heartbeat_s
        self.lease_s = lease_s
        self.workers: dict[int, WorkerHandle] = {}
        self.generation = 0
        self._next_deploy = 1
        self._hb_task: Optional[asyncio.Task] = None
        # live ClusterDeployments by deploy id (partial recovery walks
        # them to compute the rebuild closure)
        self.deployments: dict[int, ClusterDeployment] = {}

    # ------------------------------------------------------------ registry
    def live_workers(self) -> list[WorkerHandle]:
        return [h for h in self.workers.values() if h.info.alive]

    async def connect(self) -> None:
        """Register every configured compute node: connect, hello (store
        spec + SST block + config snapshot), start heartbeats, attach
        the workers to the live coordinator."""
        store_spec = self._store_spec()
        self.generation += 1
        for i, addr in enumerate(self.addrs):
            wid = i + 1
            h = WorkerHandle(self, wid, addr)
            await h.connect()
            info = await h.call(
                "hello", timeout=60, worker_id=wid, store=store_spec,
                sst_id_base=self._sst_base(i),
                config=self._worker_config(len(self.addrs)))
            h.info.pid = info.get("pid", 0)
            h.info.jax_platform = info.get("jax_platform", "")
            h.info.monitor_port = info.get("monitor_port", 0)
            self.workers[wid] = h
        self._register_with_coord()
        if self._hb_task is None or self._hb_task.done():
            self._hb_task = asyncio.get_running_loop().create_task(
                self._heartbeat_loop(), name="cluster-heartbeat")

    def _store_spec(self) -> dict:
        objects = getattr(self.session.store, "objects", None)
        root = getattr(objects, "root", None) if objects is not None \
            else None
        if root is None:
            raise ValueError(
                "cluster mode needs a durable Hummock store over a "
                "filesystem object store shared with the workers "
                "(Session(store=HummockStateStore(LocalFsObjectStore("
                "path))))")
        return {"kind": "hummock_fs", "root": root}

    def _sst_base(self, ordinal: int) -> int:
        return SST_ID_BLOCK * (
            self.generation * MAX_WORKERS_PER_GEN + ordinal + 1)

    def _worker_config(self, n_workers: int) -> dict:
        """Session vars a compute node honors, with the cluster HBM
        budget partitioned per worker (memory/manager.py
        partition_budget)."""
        from ..memory.manager import partition_budget
        cfg = self.session.config
        return {
            "hbm_budget_bytes": partition_budget(
                cfg.get("hbm_budget_bytes", 0), max(1, n_workers)),
            "memory_eviction_policy": cfg.get("memory_eviction_policy",
                                              "lru"),
            "metric_level": cfg.get("metric_level", "info"),
            "barrier_stall_threshold_ms": cfg.get(
                "barrier_stall_threshold_ms", 60000),
            "checkpoint_max_inflight": cfg.get("checkpoint_max_inflight",
                                               2),
            "streaming_chunk_coalesce": cfg.get(
                "streaming_chunk_coalesce", 0),
            # chaos harness: cluster fault points (dcn_drop,
            # worker_crash_partial) live in WORKER processes — arming
            # rides the config push so `SET fault_injection` on the
            # meta session reaches every node's process-global injector
            "fault_injection": cfg.get("fault_injection", ""),
            "partial_recovery": cfg.get("partial_recovery", 1),
        }

    def _register_with_coord(self) -> None:
        coord = self.session.coord
        for h in self.live_workers():
            coord.register_worker(h)

    async def push_config(self) -> None:
        """Re-partition + forward the config-derived knobs to every live
        worker (SET hbm_budget_bytes / metric_level / ... in cluster
        mode applies cluster-wide)."""
        live = self.live_workers()
        cfg = self._worker_config(len(live))
        for h in live:
            try:
                await h.call("set_config", timeout=30, config=cfg)
            except Exception:  # noqa: BLE001 — dying worker: detector owns it
                pass

    # --------------------------------------------------- failure detection
    def _on_worker_lost(self, handle: WorkerHandle, exc) -> None:
        if not handle.info.alive:
            return
        handle.fail(exc)
        self.session.coord.worker_failed(handle.worker_id, exc)

    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_s)
            for h in self.live_workers():
                try:
                    await h.call("ping", timeout=self.lease_s)
                    h.info.last_heartbeat = time.monotonic()
                except Exception as e:  # noqa: BLE001 — lease expiry
                    self._on_worker_lost(h, e)

    async def _on_push(self, handle: WorkerHandle, method: str,
                       args: dict) -> None:
        if method == "collected":
            self.session.coord.collect_worker(args["worker_id"],
                                              args["epoch"])
        elif method == "sealed":
            # piggybacked distributed-trace bundle: the worker ships its
            # closed epoch spans with the sealed report; stitch them
            # into meta's per-epoch timelines before the committer wakes
            spans = args.get("spans")
            if spans:
                self.session.coord.tracer.ingest_worker(
                    handle.worker_id, spans)
            handle.on_sealed(args["epoch"], args["sst_ids"])
        elif method == "failed":
            # an ACTOR died on that node (often collateral: its DCN peer
            # on a killed worker vanished) — fail the in-flight epochs so
            # recovery runs, but the worker PROCESS is alive and will be
            # reset + re-placed onto; only connection loss / lease expiry
            # marks the handle itself dead. Stale reports racing an
            # in-progress rebuild are dropped (their actors are already
            # being torn down). The report carries the worker's failed
            # actor IDS (globally unique) so the classifier can scope
            # the radius to their downstream closure instead of the
            # whole cluster.
            if not getattr(self.session, "_recovering", False):
                err = RuntimeError(args.get("error",
                                            "worker actor failure"))
                actors = args.get("actors") or []
                for aid in actors:
                    self.session.coord.actor_failed(aid, err)
                if not actors:
                    self.session.coord.worker_failed(
                        handle.worker_id, err)

    # -------------------------------------------------------------- deploy
    def _check_supported(self, graph) -> None:
        """Refuse plans cluster v1 cannot run correctly — loudly, at
        deploy time, never silently wrong."""
        from ..plan.build import (_state_table_keys,
                                  infer_fragment_schemas)

        def state_fields(n, ins):
            """The input fields that actually LAND in the node's state
            tables (aggs persist group keys + agg states, not their
            whole input; joins/materialize/top-n persist full rows)."""
            if n.kind in ("hash_agg", "simple_agg"):
                idx = set(n.args.get("group_key_indices", ()))
                for c in n.args.get("agg_calls", ()):
                    a = getattr(c, "arg", None)
                    if isinstance(a, int):
                        idx.add(a)
                return [ins[0][i] for i in sorted(idx)
                        if i < len(ins[0])]
            return [f for s in ins for f in s]

        def on_node(n, ins):
            if n.kind == "stream_scan":
                raise ValueError(
                    "cluster v1: MV-on-MV (stream_scan taps) is not "
                    "supported — create the MV directly on sources, or "
                    "feed the consumer from a changelog subscription "
                    "(logstore/subscription.py, the serving-replica "
                    "path)")
            if n.args.get("connector") == "broker":
                # split discovery assigns LIVE connector objects over an
                # AddSplitsMutation and the broker sink needs the
                # meta-local exactly-once log — neither crosses the
                # worker wire in v1
                raise ValueError(
                    "cluster v1: broker sources/sinks are not supported "
                    "— run the broker pipeline on the meta session")
            if n.kind == "sink" and int(n.args.get("exactly_once", 0)):
                # a compute node's store handle never owns the manifest,
                # so it cannot observe meta's commit point — the
                # exactly-once log-store delivery (logstore/log.py) is
                # meta-local in v1. Cluster sinks deliver directly at
                # the barrier (at-least-once with per-epoch atomicity);
                # refuse the stronger contract instead of degrading it
                # silently.
                raise ValueError(
                    "cluster v1: exactly_once sinks are not supported "
                    "(workers cannot observe the meta commit point); "
                    "omit exactly_once or deploy the sink on the meta "
                    "session")
            if n.kind != "nexmark_source" and _state_table_keys(
                    n.kind, n.args, None):
                for f in state_fields(n, ins):
                    if f.data_type.is_dict_encoded:
                        raise ValueError(
                            "cluster v1: dict-encoded column "
                            f"{f.name!r} ({f.data_type.value}) in "
                            f"{n.kind} state — per-worker string "
                            "dictionaries are not yet reconciled "
                            "across the shared store; project it "
                            "away below the stateful operator")

        infer_fragment_schemas(graph, on_node=on_node)

    def placement(self, graph) -> dict:
        """Fragment actor idx -> worker id, over the LIVE set: parallel
        fragments spread contiguous vnode ranges across workers;
        singletons round-robin by fragment id."""
        live = sorted(h.worker_id for h in self.live_workers())
        assert live, "no live workers"
        out: dict = {}
        rr = 0
        for fid in graph.topo_order():
            f = graph.fragments[fid]
            if f.parallelism == 1:
                out[fid] = [live[rr % len(live)]]
                rr += 1
            else:
                out[fid] = [live[i % len(live)]
                            for i in range(f.parallelism)]
        return out

    async def deploy(self, graph, scope: str, mv_fragment: int,
                     want_table: bool):
        """Two-phase cluster deploy of one planned graph. Returns the
        ClusterDeployment (with the MV shadow root when `want_table`)."""
        from ..plan.build import assign_graph_ids, fragment_node_order
        from ..state.state_table import StateTable
        self._check_supported(graph)
        session = self.session
        deploy_id = self._next_deploy
        self._next_deploy += 1
        placement = self.placement(graph)
        actor_base = session.env._next_actor_id
        table_base = session.env._next_table_id
        actors, tables, next_actor, next_table = assign_graph_ids(
            graph, actor_base, table_base)
        # advance the session allocators past this deployment so the
        # next job's ids stay globally unique (recovery re-floors via
        # the DDL log exactly like the single-process path)
        session.env._next_actor_id = next_actor
        session.env._next_table_id = next_table
        ddl_config = {k: session.config[k]
                      for k in ("streaming_chunk_coalesce",)
                      if k in session.config}
        ddl_config["partial_recovery"] = bool(
            session.config.get("partial_recovery", 1))
        live = self.live_workers()
        ports: dict = {}
        for h in live:
            r = await h.call("deploy_prepare", timeout=120,
                             deploy_id=deploy_id, graph=graph,
                             placement=placement,
                             actor_id_base=actor_base,
                             table_id_base=table_base,
                             ddl_config=ddl_config, scope=scope)
            for edge_key, port in r.items():
                ports[edge_key] = (h.host, port)
        for h in live:
            await h.call("deploy_start", timeout=300,
                         deploy_id=deploy_id, ports=ports)
        all_ids = frozenset(a for ids in actors.values() for a in ids)
        roots = {}
        if want_table:
            # shadow of the materialize state table: same deterministic
            # table id every worker derived, read at meta over the
            # committed manifest (vnode-complete — no bitmap)
            frag = graph.fragments[mv_fragment]
            mat = fragment_node_order(frag)[-1]
            assert mat.kind == "materialize", mat.kind
            from ..plan.build import infer_fragment_schemas
            schemas = infer_fragment_schemas(graph)
            sch = schemas[mv_fragment]
            node_idx = len(fragment_node_order(frag)) - 1
            tid = tables[mv_fragment][(mv_fragment, node_idx)]
            table = StateTable(session.store, table_id=tid, schema=sch,
                               pk_indices=tuple(mat.args["pk_indices"]))
            roots[mv_fragment] = [_ShadowRoot(table, sch)]
        from ..plan.build import infer_fragment_schemas as _schemas
        dep = ClusterDeployment(
            self, deploy_id, session.coord, all_ids, roots,
            rebuild_info={"graph": graph, "placement": placement,
                          "actors": actors, "tables": tables,
                          "schemas": _schemas(graph), "scope": scope,
                          "ddl_config": ddl_config})
        self.deployments[deploy_id] = dep
        return dep

    # ------------------------------------------ per-worker partial recovery
    @staticmethod
    def _actor_pairs(graph, fid, d_fid):
        up, d = graph.fragments[fid], graph.fragments[d_fid]
        for u in range(up.parallelism):
            for di in range(d.parallelism):
                if up.dispatch == "simple" and up.parallelism > 1 \
                        and u != di:
                    continue          # NoShuffle pairs 1:1
                yield u, di

    def plan_partial(self, dead_wid, failed_actor_ids):
        """Worker-radius feasibility + closure computation. The rebuild
        set per deployment is {the dead worker's actors (re-placed onto
        survivors, minimal movement, original parallelism) plus the
        reported failed actors} closed over downstream consumption —
        every consumer of a dead producer holds a partial prefix of the
        aborted interval and rebuilds with it. Survivors' actors
        outside the closure keep running; their stores stay open at the
        committed manifest. Returns the plan shipped to the workers, or
        None when the radius cannot be proven contained (-> full)."""
        live = self.live_workers()
        if not live:
            return None
        committed = self.session.store.committed_epoch()
        if committed <= 0:
            return None       # no committed base barrier to rebuild from
        failed = set(failed_actor_ids or ())
        rr = 0
        per_dep: dict = {}
        rebuilt_ids: list[int] = []
        for did, dep in self.deployments.items():
            info = dep.rebuild_info
            if info is None:
                return None
            graph, placement = info["graph"], info["placement"]
            actors = info["actors"]
            seed = set()
            for fid, ws in placement.items():
                for idx, w in enumerate(ws):
                    if (dead_wid is not None and w == dead_wid) \
                            or actors[fid][idx] in failed:
                        seed.add((fid, idx))
            if not seed:
                continue
            edges = graph.edges()
            closure = set(seed)
            changed = True
            while changed:
                changed = False
                for (fid, d_fid, _k) in edges:
                    for u, di in self._actor_pairs(graph, fid, d_fid):
                        if (fid, u) in closure \
                                and (d_fid, di) not in closure:
                            closure.add((d_fid, di))
                            changed = True
            # feasibility: a fragment must not mix closure and
            # non-closure actors on ONE worker — the staged-write
            # discard is per (worker, table), and mixed ownership would
            # drop a surviving actor's uncommitted rows with the dead
            # one's
            for fid, ws in placement.items():
                by_w: dict = {}
                for idx, w in enumerate(ws):
                    by_w.setdefault(w, []).append((fid, idx) in closure)
                for flags in by_w.values():
                    if any(flags) and not all(flags):
                        return None
            # new placement: ONLY the dead worker's slots move
            live_ids = sorted(h.worker_id for h in live)
            new_placement: dict = {}
            for fid, ws in placement.items():
                row = list(ws)
                for idx, w in enumerate(ws):
                    if dead_wid is not None and w == dead_wid:
                        row[idx] = live_ids[rr % len(live_ids)]
                        rr += 1
                new_placement[fid] = row
            # edge dispositions for the rebuild (cluster/compute_node.py
            # routes each leg by kind):
            #   frontier_local     surviving producer, same worker,
            #                      consumer in place -> begin_replay
            #   frontier_rewind    surviving producer, consumer rebuilt
            #                      in place behind its server -> in-band
            #                      'R' rewind over the (re)connected leg
            #   frontier_reconnect surviving producer, consumer
            #                      re-placed -> fresh server + rewind
            #   intra_local        both rebuilt, co-located -> fresh
            #                      channel
            #   intra_remote       both rebuilt, split -> fresh pair
            edge_plan = []
            for (fid, d_fid, k) in edges:
                for u, di in self._actor_pairs(graph, fid, d_fid):
                    if (d_fid, di) not in closure:
                        continue
                    p_in = (fid, u) in closure
                    wp_new = new_placement[fid][u]
                    wc_new = new_placement[d_fid][di]
                    wc_old = placement[d_fid][di]
                    if p_in:
                        kind = ("intra_local" if wp_new == wc_new
                                else "intra_remote")
                    elif wc_old == wc_new:
                        kind = ("frontier_local" if wp_new == wc_new
                                else "frontier_rewind")
                    else:
                        kind = "frontier_reconnect"
                    edge_plan.append({"key": (fid, d_fid, k, u, di),
                                      "kind": kind})
            closure_map: dict = {}
            for fid, idx in sorted(closure):
                closure_map.setdefault(fid, []).append(idx)
            per_dep[did] = {"closure": closure_map,
                            "new_placement": new_placement,
                            "edges": edge_plan}
            for fid, idxs in closure_map.items():
                rebuilt_ids.extend(actors[fid][i] for i in idxs)
        if not per_dep:
            return None           # nothing maps — a stale report
        return {"dead_worker": dead_wid, "deployments": per_dep,
                "committed_epoch": committed,
                # every epoch injected so far that is not committed is
                # DEAD (never re-injected); rebuilt consumer legs filter
                # its barriers so merges with live-joining rebuilt
                # sources stay aligned
                "stale_ceiling": self.session.coord._prev_epoch,
                "rebuilt_actors": sorted(rebuilt_ids)}

    async def partial_recover(self, plan) -> list[int]:
        """Execute the worker radius: prune the dead worker, two-phase
        partial rebuild on the survivors (quiesce/restage/fresh servers,
        then build/reconnect/rewind/spawn), resume the epoch stream on
        the SAME coordinator. Any exception propagates — the session
        falls back to the full cluster rebuild."""
        session = self.session
        coord = session.coord
        # 1. abort the in-flight commit queue: an epoch the dead worker
        # never sealed can never commit; survivors RESTAGE their share
        # (state/hummock.py restage_unconfirmed) so nothing durable is
        # lost, and the parked wait_sealed error is subsumed
        await coord.abort_uploads()
        coord.clear_upload_failure()
        dead_wid = plan["dead_worker"]
        if dead_wid is not None:
            h = self.workers.pop(dead_wid, None)
            coord.remove_worker(dead_wid)
            if h is not None:
                await h.close()
        live = self.live_workers()
        if not live:
            raise RuntimeError("cluster: no live workers")
        # 2. phase 1: every survivor quiesces its closure actors,
        # restages unconfirmed seals, discards the closure's staged
        # writes, and opens fresh inbound servers for re-placed legs
        ports: dict = {}
        for h in live:
            r = await h.call("partial_prepare", timeout=120,
                             dead_worker=dead_wid,
                             plans=plan["deployments"],
                             committed_epoch=plan["committed_epoch"],
                             stale_ceiling=plan["stale_ceiling"])
            for ek, port in r.items():
                ports[ek] = (h.host, port)
        # 3. phase 2: rebuild the closure actors (same global ids),
        # connect fresh legs, rewind surviving producers into the
        # rebuilt consumers, spawn
        for h in live:
            await h.call("partial_start", timeout=300,
                         plans=plan["deployments"], ports=ports,
                         committed_epoch=plan["committed_epoch"],
                         stale_ceiling=plan["stale_ceiling"])
        # 4. phase 3: with EVERY worker's rebuilt consumers live, the
        # surviving producer legs stream their uncommitted suffix (a
        # rewind before all spawns could deadlock on the credit window).
        # Workers rewind concurrently — each worker in turn fans its
        # own legs out in parallel (compute_node.rpc_partial_rewind);
        # per-leg order is preserved because one task owns one leg
        await asyncio.gather(
            *(h.call("partial_rewind", timeout=300) for h in live))
        # the new placement is authoritative for any LATER recovery
        for did, dplan in plan["deployments"].items():
            dep = self.deployments.get(did)
            if dep is not None and dep.rebuild_info is not None:
                dep.rebuild_info["placement"] = dplan["new_placement"]
        coord.clear_failure()
        return plan["rebuilt_actors"]

    # ------------------------------------------------------------ recovery
    async def reset_all(self) -> None:
        """Crash path: abandon every worker's actors (stores keep their
        uncommitted buffers until reopen)."""
        for h in self.live_workers():
            try:
                await h.call("reset", timeout=60)
            except Exception as e:  # noqa: BLE001
                self._on_worker_lost(h, e)

    async def on_recovery(self) -> None:
        """Rebuild entry (the session swapped in a fresh coordinator):
        prune dead workers, reset + reopen survivors' stores at the
        committed manifest with fresh SST blocks, re-register."""
        self.generation += 1
        dead = [wid for wid, h in self.workers.items()
                if not h.info.alive]
        for wid in dead:
            h = self.workers.pop(wid)
            await h.close()
        store_spec = self._store_spec()
        for i, h in enumerate(sorted(self.live_workers(),
                                     key=lambda x: x.worker_id)):
            try:
                await h.call("reset", timeout=60, store=store_spec,
                             sst_id_base=self._sst_base(i))
                await h.call("set_config", timeout=30,
                             config=self._worker_config(
                                 len(self.live_workers())))
            except Exception as e:  # noqa: BLE001
                self._on_worker_lost(h, e)
        if not self.live_workers():
            raise RuntimeError("cluster: no live workers to recover onto")
        self._register_with_coord()

    # -------------------------------------------------------- observability
    async def scrape_all(self) -> dict[int, str]:
        """worker_id -> that node's /metrics text (the meta monitor
        merges them under a `worker` label — one Prometheus scrape sees
        the whole cluster)."""
        out = {}
        for h in self.live_workers():
            try:
                out[h.worker_id] = await h.call("scrape", timeout=10)
            except Exception:  # noqa: BLE001 — scrape never fails the plane
                pass
        return out

    async def memory_report_all(self) -> list[dict]:
        """Cluster-wide HBM accounting: every worker's MemoryManager
        report with the executor labels prefixed by the owning worker."""
        rows: list[dict] = []
        for h in self.live_workers():
            try:
                for r in await h.call("memory_report", timeout=10):
                    r = dict(r)
                    r["executor"] = f"w{h.worker_id}/{r['executor']}"
                    rows.append(r)
            except Exception:  # noqa: BLE001
                pass
        return rows

    async def events_all(self, limit=None, kind=None,
                         since=None) -> dict[int, list]:
        """worker_id -> that node's worker-local event records — SHOW
        events / /debug/events stitch them (tagged worker=wN) into one
        cluster-wide incident timeline. Best-effort: an unreachable
        worker contributes nothing (its durable log is read on the
        next query once it re-registers)."""
        out: dict[int, list] = {}
        for h in self.live_workers():
            try:
                out[h.worker_id] = await h.call(
                    "events", timeout=10, limit=limit, kind=kind,
                    since=since)
            except Exception:  # noqa: BLE001 — observability best-effort
                pass
        return out

    async def dump_tasks_all(self) -> dict[int, str]:
        """worker_id -> that node's own stuck-barrier report (in-flight
        epochs with remaining LOCAL actors + its await tree) — the
        watchdog and /debug/await_tree merge one section per worker."""
        out = {}
        for h in self.live_workers():
            try:
                out[h.worker_id] = await h.call("dump_tasks", timeout=10)
            except Exception as e:  # noqa: BLE001 — diagnosis is best-effort
                out[h.worker_id] = f"(unreachable: {e!r})"
        return out

    async def profile_all(self, kind: str, seconds: float = 0.0) \
            -> dict[int, str]:
        """Fan one /debug/profile/* trigger out to every live worker;
        worker_id -> that node's profile text (merged under wN/ prefixes
        by the monitor, mirroring the /metrics merge). Timed profiles
        run CONCURRENTLY so the wall clock is one window, not N."""
        method = f"profile_{kind}"
        args = {} if kind == "device" else {"seconds": seconds}
        live = list(self.live_workers())
        # every worker samples the SAME window; timeout covers the
        # window plus rpc slack
        timeout = max(10.0, float(seconds) * 2 + 10.0)

        async def one(h):
            try:
                return h.worker_id, await h.call(method, timeout=timeout,
                                                 **args)
            except Exception as e:  # noqa: BLE001
                return h.worker_id, f"(unreachable: {e!r})"

        return dict(await asyncio.gather(*(one(h) for h in live)))

    def registry_rows(self) -> list[tuple]:
        """SHOW cluster."""
        rows = []
        for wid in sorted(self.workers):
            h = self.workers[wid]
            rows.append((f"w{wid}", h.addr,
                         "alive" if h.info.alive else "dead",
                         h.info.jax_platform, str(h.info.pid),
                         f"{h.info.lease_remaining_s:.1f}s",
                         str(h.info.monitor_port or "")))
        return rows

    async def stop(self) -> None:
        if self._hb_task is not None and not self._hb_task.done():
            self._hb_task.cancel()
            try:
                await self._hb_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for h in list(self.workers.values()):
            self.session.coord.remove_worker(h.worker_id)
            await h.close()
        self.workers.clear()

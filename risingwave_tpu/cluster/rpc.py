"""Control-plane RPC — the meta <-> compute-node wire.

Reference: the meta/CN gRPC services (proto/stream_service.proto,
proto/meta.proto — InjectBarrier, BarrierComplete, heartbeats). Between
TRUSTED processes of one deployment the wire form is a length-prefixed
pickle of plain dicts/dataclasses (the same v1 IR convention
stream/remote_fragment.py established), multiplexed on one TCP
connection:

  {"id": n>0, "method": m, "args": {...}}   request (expects response)
  {"id": -n,  "ok": bool, "result"/"error"} response to request n
  {"id": 0,   "method": m, "args": {...}}   push (no response)

Both sides run the same `RpcConn`: `call()` awaits a response,
`push()` fires and forgets (barrier injection, collection reports),
`serve()` drains inbound frames into a handler. A broken connection
fails every pending call and fires `on_closed` — the caller's failure
detector (worker lease expiry / meta loss), never a silent hang.
"""

from __future__ import annotations

import asyncio
import pickle
import struct
import traceback
from typing import Awaitable, Callable, Optional


async def send_blob(writer: asyncio.StreamWriter, blob: bytes) -> None:
    writer.write(struct.pack("!i", len(blob)) + blob)
    await writer.drain()


async def recv_blob(reader: asyncio.StreamReader) -> bytes:
    ln = struct.unpack("!i", await reader.readexactly(4))[0]
    return await reader.readexactly(ln)


class RpcError(RuntimeError):
    """Remote handler raised; message carries the remote traceback tail."""


class RpcConn:
    """One multiplexed control connection (either side)."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 handler: Optional[Callable[[str, dict],
                                            Awaitable]] = None,
                 on_closed: Optional[Callable[[BaseException], None]] = None):
        self._reader = reader
        self._writer = writer
        self._handler = handler
        self._on_closed = on_closed
        self._next_id = 1
        self._pending: dict[int, asyncio.Future] = {}
        self._wlock = asyncio.Lock()
        self._serve_task: Optional[asyncio.Task] = None
        self.closed = False

    # ------------------------------------------------------------- sending
    async def _send(self, msg: dict) -> None:
        blob = pickle.dumps(msg)
        async with self._wlock:
            await send_blob(self._writer, blob)

    async def call(self, method: str, timeout: Optional[float] = None,
                   **args):
        """Request/response; raises RpcError on remote failure,
        ConnectionError if the peer goes away mid-call."""
        if self.closed:
            raise ConnectionResetError(f"rpc connection closed ({method})")
        rid = self._next_id
        self._next_id += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            await self._send({"id": rid, "method": method, "args": args})
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(rid, None)

    async def push(self, method: str, **args) -> None:
        """One-way notification (barrier inject, collected/sealed
        reports). Delivery order is TCP order."""
        if self.closed:
            raise ConnectionResetError(f"rpc connection closed ({method})")
        await self._send({"id": 0, "method": method, "args": args})

    # ----------------------------------------------------------- receiving
    def start(self, first_msg: Optional[dict] = None) -> "RpcConn":
        """Spawn the read loop. `first_msg` replays a frame the caller
        already consumed while sniffing the protocol (worker.py serves
        the legacy fragment protocol and this one on a single port)."""
        self._serve_task = asyncio.create_task(
            self._serve(first_msg), name="rpc-conn")
        return self

    async def _serve(self, first_msg: Optional[dict]) -> None:
        exc: BaseException = ConnectionResetError("peer closed")
        try:
            if first_msg is not None:
                await self._dispatch(first_msg)
            while True:
                msg = pickle.loads(await recv_blob(self._reader))
                await self._dispatch(msg)
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, OSError) as e:
            exc = e
        except asyncio.CancelledError:
            exc = ConnectionResetError("rpc connection cancelled")
        finally:
            self.closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(
                        ConnectionResetError(f"peer went away: {exc}"))
            self._pending.clear()
            if self._on_closed is not None:
                try:
                    self._on_closed(exc)
                except Exception:  # noqa: BLE001 — detector must not kill IO
                    pass
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _dispatch(self, msg: dict) -> None:
        rid = msg.get("id", 0)
        if rid < 0:                       # response to our call
            fut = self._pending.get(-rid)
            if fut is not None and not fut.done():
                if msg.get("ok"):
                    fut.set_result(msg.get("result"))
                else:
                    fut.set_exception(RpcError(msg.get("error", "remote error")))
            return
        method, args = msg.get("method", ""), msg.get("args", {})
        if rid == 0:                      # push: handle inline, no reply
            if self._handler is not None:
                # pushes are ORDERED (inject N before inject N+1): await
                # the handler rather than spawning, so a slow consumer
                # backpressures through TCP instead of reordering. A
                # push has no response channel, so a handler failure
                # must NOT kill the read loop (e.g. an inject arriving
                # on an already-failed local coordinator — the failure
                # was already reported on its own path).
                try:
                    await self._handler(method, args)
                except asyncio.CancelledError:
                    raise
                except BaseException as e:  # noqa: BLE001
                    import sys as _s
                    print(f"[rpc] push handler {method!r} failed: "
                          f"{type(e).__name__}: {e}", file=_s.stderr)
            return
        # request: run as a task so a slow handler (graph build) never
        # blocks barrier pushes behind it
        asyncio.create_task(self._answer(rid, method, args),
                            name=f"rpc-{method}")

    async def _answer(self, rid: int, method: str, args: dict) -> None:
        try:
            result = (await self._handler(method, args)
                      if self._handler is not None else None)
            reply = {"id": -rid, "ok": True, "result": result}
        except BaseException as e:  # noqa: BLE001 — ship it to the caller
            tb = traceback.format_exc(limit=8)
            reply = {"id": -rid, "ok": False,
                     "error": f"{type(e).__name__}: {e}\n{tb}"}
        try:
            await self._send(reply)
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass

    async def close(self) -> None:
        self.closed = True
        if self._serve_task is not None and not self._serve_task.done():
            self._serve_task.cancel()
            try:
                await self._serve_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001
            pass


async def start_rpc_server(handler_factory, host: str = "127.0.0.1",
                           port: int = 0):
    """Serve this control-plane protocol on a listening socket: each
    accepted connection gets its own `RpcConn`. `handler_factory(conn)`
    returns `(handler, on_closed)` — the conn is constructed first so
    handlers can push back on it (the subscription server's changelog
    stream, logstore/subscription.py, is the first user; the serving
    replica's lookup endpoint is the second). Returns the
    asyncio.Server; the bound port is
    `server.sockets[0].getsockname()[1]`."""
    async def on_conn(reader, writer):
        conn = RpcConn(reader, writer)
        handler, on_closed = handler_factory(conn)
        conn._handler = handler
        conn._on_closed = on_closed
        conn.start()

    return await asyncio.start_server(on_conn, host=host, port=port)

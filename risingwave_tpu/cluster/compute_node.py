"""Compute node — the first-class worker of the cluster control plane.

Reference: src/compute/src/server.rs — a compute node registers with
meta, receives its assigned plan fragments, builds the actors LOCALLY
(`LocalStreamManager::build_actors`), exchanges data with peers, runs a
local barrier manager that collects its own actors and reports
per-worker completion, and syncs its shared buffer into SSTs that META
commits.

Here the node is an asyncio process (served by `risingwave_tpu.worker`
on the same port as the legacy fragment protocol — the connection's
first frame selects the protocol):

  * owns a `HummockStateStore` handle over the SHARED object store,
    with `manifest_owner = False` and a disjoint SST-id block: it
    seals + uploads its own epochs, installs them into its local L0 for
    read-through, and reports the SST ids to meta — the manifest swap
    (commit point) happens only on meta, after ALL workers reported;
  * builds its assigned actors with `plan/build.py build_partial_graph`
    over ids every process derives identically (`assign_graph_ids`);
  * runs its own `BarrierCoordinator` as the LocalBarrierManager:
    meta's `inject` push fans the barrier into local source queues,
    collection of all local actors triggers the `collected` report;
  * carries its own HBM budget (partitioned from the cluster budget by
    meta) and its own monitor HTTP endpoint (`--monitor-port`).
"""

from __future__ import annotations

import asyncio
import os
import sys
from typing import Optional

from .rpc import RpcConn


class _MonitorShim:
    """What meta/monitor_service.py needs from a 'session': the live
    coordinator, the store, and a recovery counter."""

    def __init__(self, node: "ComputeNode"):
        self._node = node
        self.recoveries = 0

    @property
    def coord(self):
        return self._node.coord

    @property
    def store(self):
        return self._node.store

    cluster = None


class ComputeNode:
    """One control connection's worth of compute-node state. The meta
    connection is the node's life line: when it drops, every deployment
    dies with it (meta re-places the fragments over the survivors)."""

    def __init__(self, conn: RpcConn, host: str = "127.0.0.1"):
        self.conn = conn
        self.host = host
        self.worker_id: Optional[int] = None
        self.store = None
        self.coord = None
        self.config: dict = {}
        # deploy_id -> {dep, remote_ins, remote_outs}
        self.deployments: dict[int, dict] = {}
        self._pending: dict[int, dict] = {}
        self.monitor = None
        self._monitor_port = 0

    # --------------------------------------------------------- RPC surface
    async def handle(self, method: str, args: dict):
        fn = getattr(self, f"rpc_{method}", None)
        if fn is None:
            raise ValueError(f"unknown compute-node method {method!r}")
        return await fn(**args)

    async def rpc_ping(self):
        return {"worker_id": self.worker_id,
                "actors": sum(len(d["dep"].actors)
                              for d in self.deployments.values())}

    async def rpc_hello(self, worker_id: int, store: dict,
                        sst_id_base: int, config: dict,
                        monitor_port: int = 0):
        import jax
        self.worker_id = worker_id
        self._open_store(store, sst_id_base)
        # the CLI's --monitor-port wins over meta's (operator-pinned)
        monitor_port = self.config.pop("__monitor_port", 0) or monitor_port
        self._fresh_coordinator(config)
        if monitor_port:
            from ..meta.monitor_service import MonitorService
            self.monitor = await MonitorService(
                _MonitorShim(self), port=monitor_port).start()
            self._monitor_port = self.monitor.port
        return {"worker_id": worker_id, "pid": os.getpid(),
                "jax_platform": jax.default_backend(),
                "monitor_port": self._monitor_port}

    def _open_store(self, spec: dict, sst_id_base: int) -> None:
        from ..state import HummockStateStore, LocalFsObjectStore
        assert spec.get("kind", "hummock_fs") == "hummock_fs", spec
        store = HummockStateStore(LocalFsObjectStore(spec["root"]))
        store.manifest_owner = False
        store.set_sst_id_block(sst_id_base)
        self.store = store

    def _fresh_coordinator(self, config: dict) -> None:
        from ..meta.barrier_manager import BarrierCoordinator
        self.config.update(config or {})
        self.coord = BarrierCoordinator(
            self.store,
            checkpoint_max_inflight=self.config.get(
                "checkpoint_max_inflight", 2))
        self.coord.commit_listener = self._on_committed
        self._apply_config()

    def _apply_config(self) -> None:
        cfg = self.config
        self.coord.memory.configure(
            budget_bytes=cfg.get("hbm_budget_bytes", 0),
            policy=cfg.get("memory_eviction_policy", "lru"))
        self.coord.stats.configure(cfg.get("metric_level", "info"))
        thr = cfg.get("barrier_stall_threshold_ms", 60000)
        self.coord.stall_threshold_ms = float(thr) if thr > 0 else None
        if "checkpoint_max_inflight" in cfg:
            self.coord.checkpoint_max_inflight = \
                cfg["checkpoint_max_inflight"]

    async def rpc_set_config(self, config: dict):
        self.config.update(config)
        self._apply_config()
        return {"applied": sorted(config)}

    def _on_committed(self, epoch: int, sst_ids: list) -> None:
        """Local seal+upload+L0-install finished for `epoch`: report the
        SSTs so meta can commit once every worker reported (runs on the
        loop from the coordinator's uploader)."""
        asyncio.get_running_loop().create_task(
            self.conn.push("sealed", worker_id=self.worker_id,
                           epoch=epoch, sst_ids=list(sst_ids)))

    # ------------------------------------------------------------- deploy
    async def rpc_deploy_prepare(self, deploy_id: int, graph,
                                 placement: dict, actor_id_base: int,
                                 table_id_base: int, ddl_config: dict,
                                 scope: str):
        """Phase 1: derive all ids locally, start a RemoteInput server
        per inbound cross-worker edge leg, report the ports."""
        from ..plan.build import (assign_graph_ids, cluster_remote_edges,
                                  infer_fragment_schemas)
        from ..stream.remote_exchange import RemoteInput
        actors, tables, _, _ = assign_graph_ids(graph, actor_id_base,
                                                table_id_base)
        schemas = infer_fragment_schemas(graph)
        remote_ins: dict = {}
        for edge_key, _uw, dw in cluster_remote_edges(graph, placement):
            if dw != self.worker_id:
                continue
            up_fid = edge_key[0]
            rx = await RemoteInput(schemas[up_fid], host="0.0.0.0",
                                   queue_depth=8).start()
            remote_ins[edge_key] = rx
        self._pending[deploy_id] = dict(
            graph=graph, placement=placement, actors=actors,
            tables=tables, schemas=schemas, remote_ins=remote_ins,
            ddl_config=ddl_config, scope=scope)
        return {k: rx.port for k, rx in remote_ins.items()}

    async def rpc_deploy_start(self, deploy_id: int, ports: dict):
        """Phase 2: connect RemoteOutputs to peer ports, build + spawn
        this node's actors."""
        from ..plan.build import (BuildEnv, build_partial_graph,
                                  cluster_remote_edges)
        from ..stream.remote_exchange import RemoteOutput
        p = self._pending.pop(deploy_id)
        remote_outs: dict = {}
        for edge_key, uw, _dw in cluster_remote_edges(p["graph"],
                                                      p["placement"]):
            if uw != self.worker_id:
                continue
            host, port = ports[edge_key]
            remote_outs[edge_key] = await RemoteOutput(host,
                                                       port).connect()
        env = BuildEnv(self.store, self.coord,
                       chunk_coalesce_max=p["ddl_config"].get(
                           "streaming_chunk_coalesce", 0))
        env.memory_scope = p["scope"]
        dep = build_partial_graph(
            p["graph"], env, p["placement"], self.worker_id,
            p["actors"], p["tables"], p["schemas"], p["remote_ins"],
            remote_outs)
        env.memory_scope = None
        dep.spawn()
        self.deployments[deploy_id] = dict(
            dep=dep, remote_ins=p["remote_ins"], remote_outs=remote_outs)
        return {"actors": sorted(a.actor_id for a in dep.actors)}

    # ------------------------------------------------------------ barriers
    async def rpc_inject(self, barrier):
        """Meta's per-worker barrier injection (push): fan into local
        source queues NOW (ordering with the next inject rides the
        connection's frame order), collect + report in the background."""
        b = await self.coord.inject_remote(barrier)
        asyncio.get_running_loop().create_task(self._collect_one(b))

    async def _collect_one(self, barrier) -> None:
        try:
            await self.coord.wait_collected(barrier)
            await self.conn.push("collected", worker_id=self.worker_id,
                                 epoch=barrier.epoch.curr)
        except ConnectionResetError:
            pass                      # meta gone; process will be reset
        except Exception as e:  # noqa: BLE001 — local actor death
            try:
                await self.conn.push("failed", worker_id=self.worker_id,
                                     error=f"{type(e).__name__}: {e}")
            except ConnectionResetError:
                pass

    # ------------------------------------------------------------ teardown
    async def rpc_stop_deployment(self, deploy_id: int):
        """Clean up ONE deployment after meta drove its stop barrier
        (actors have exited; deregister them and close the DCN legs)."""
        d = self.deployments.pop(deploy_id, None)
        if d is not None:
            await self._teardown(d)
        return {}

    async def _teardown(self, d: dict) -> None:
        dep = d["dep"]
        for t in dep.tasks:
            if not t.done():
                t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for a in dep.actors:
            self.coord.actor_ids.discard(a.actor_id)
            self.coord.stats.unregister(a.actor_id)
        for q in dep.source_queues:
            if q in self.coord.source_queues:
                self.coord.source_queues.remove(q)
        for n in dep.memory_names:
            self.coord.memory.unregister(n)
        for out in d["remote_outs"].values():
            try:
                await out.close()
            except Exception:  # noqa: BLE001
                pass
        for rx in d["remote_ins"].values():
            try:
                await rx.stop()
            except Exception:  # noqa: BLE001
                pass

    async def rpc_reset(self, store: Optional[dict] = None,
                        sst_id_base: Optional[int] = None):
        """Recovery entry (meta's re-place): abandon every deployment,
        abort in-flight uploads, reopen the store at the CURRENT
        committed manifest, fresh coordinator."""
        for d in list(self.deployments.values()):
            await self._teardown(d)
        self.deployments.clear()
        for p in self._pending.values():
            for rx in p["remote_ins"].values():
                try:
                    await rx.stop()
                except Exception:  # noqa: BLE001
                    pass
        self._pending.clear()
        if self.coord is not None:
            await self.coord.abort_uploads()
        if store is not None:
            self._open_store(store, sst_id_base or 1)
        self._fresh_coordinator({})
        return {"committed_epoch": self.store.committed_epoch()}

    # -------------------------------------------------------- observability
    async def rpc_scrape(self):
        """This node's full metrics exposition — meta's monitor merges it
        into the cluster-wide /metrics with a worker label."""
        from ..utils.metrics import GLOBAL_METRICS
        return GLOBAL_METRICS.render_prometheus()

    async def rpc_memory_report(self):
        return self.coord.memory.report() if self.coord is not None else []

    async def closed(self) -> None:
        """Meta connection died: this node's actors are orphans — tear
        everything down so the process is reusable by the next meta."""
        for d in list(self.deployments.values()):
            await self._teardown(d)
        self.deployments.clear()
        if self.coord is not None:
            await self.coord.abort_uploads()
        if self.monitor is not None:
            await self.monitor.stop()
            self.monitor = None


async def serve_connection(reader, writer, first_msg: dict,
                           monitor_port: int = 0) -> None:
    """Entry from risingwave_tpu.worker: the connection's first frame was
    a compute-node RPC request — serve the control protocol on it."""
    node: Optional[ComputeNode] = None
    done = asyncio.Event()

    def on_closed(exc):
        done.set()

    async def handler(method, args):
        return await node.handle(method, args)

    conn = RpcConn(reader, writer, handler=handler, on_closed=on_closed)
    host = writer.get_extra_info("sockname")[0]
    node = ComputeNode(conn, host=host)
    if monitor_port:
        node.config["__monitor_port"] = monitor_port
    conn.start(first_msg)
    await done.wait()
    await node.closed()


def main(argv=None) -> None:
    """Standalone launch: `python -m risingwave_tpu.cluster.compute_node
    [port] [--monitor-port N]` — identical to `risingwave_tpu.worker`
    (one listener serves both the legacy fragment protocol and the
    cluster control plane)."""
    from .. import worker
    worker.main(argv)


if __name__ == "__main__":
    main(sys.argv[1:])

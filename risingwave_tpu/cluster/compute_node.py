"""Compute node — the first-class worker of the cluster control plane.

Reference: src/compute/src/server.rs — a compute node registers with
meta, receives its assigned plan fragments, builds the actors LOCALLY
(`LocalStreamManager::build_actors`), exchanges data with peers, runs a
local barrier manager that collects its own actors and reports
per-worker completion, and syncs its shared buffer into SSTs that META
commits.

Here the node is an asyncio process (served by `risingwave_tpu.worker`
on the same port as the legacy fragment protocol — the connection's
first frame selects the protocol):

  * owns a `HummockStateStore` handle over the SHARED object store,
    with `manifest_owner = False` and a disjoint SST-id block: it
    seals + uploads its own epochs, installs them into its local L0 for
    read-through, and reports the SST ids to meta — the manifest swap
    (commit point) happens only on meta, after ALL workers reported;
  * builds its assigned actors with `plan/build.py build_partial_graph`
    over ids every process derives identically (`assign_graph_ids`);
  * runs its own `BarrierCoordinator` as the LocalBarrierManager:
    meta's `inject` push fans the barrier into local source queues,
    collection of all local actors triggers the `collected` report;
  * carries its own HBM budget (partitioned from the cluster budget by
    meta) and its own monitor HTTP endpoint (`--monitor-port`).
"""

from __future__ import annotations

import asyncio
import os
import sys
from typing import Optional

from .rpc import RpcConn


class _MonitorShim:
    """What meta/monitor_service.py needs from a 'session': the live
    coordinator, the store, and a recovery counter."""

    def __init__(self, node: "ComputeNode"):
        self._node = node
        self.recoveries = 0
        self.recovery_ring = None

    @property
    def event_log(self):
        # the node's OWN log (durable once hello opened the store):
        # /debug/events on a worker's monitor port reads the same
        # records meta stitches into the cluster-wide view
        return self._node.event_log

    @property
    def coord(self):
        return self._node.coord

    @property
    def store(self):
        return self._node.store

    cluster = None


class ComputeNode:
    """One control connection's worth of compute-node state. The meta
    connection is the node's life line: when it drops, every deployment
    dies with it (meta re-places the fragments over the survivors)."""

    def __init__(self, conn: RpcConn, host: str = "127.0.0.1"):
        self.conn = conn
        self.host = host
        self.worker_id: Optional[int] = None
        self.store = None
        self.coord = None
        self.config: dict = {}
        # deploy_id -> {dep, remote_ins, remote_outs, info}
        self.deployments: dict[int, dict] = {}
        self._pending: dict[int, dict] = {}
        self.monitor = None
        self._monitor_port = 0
        # recent injected barriers keyed by epoch.prev — the base for a
        # partial rebuild's synthetic INITIAL (the barrier with
        # epoch.prev == committed sealed the committed epoch)
        self._barriers_by_prev: dict[int, object] = {}
        # sealed reports pushed so far (the worker_crash_partial fault
        # point counts these)
        self._sealed_reports = 0
        # epochs whose closed trace spans already shipped to meta
        # (piggybacked on the sealed report — the distributed-trace
        # bundle of utils/trace.py)
        self._shipped_spans: set[int] = set()
        # worker-local event log: in-memory ring until hello opens the
        # store, then crc-framed segments under the shared root
        # (subdir events_w<id>) — incident records survive THIS
        # worker's own crash and meta stitches them into SHOW events
        from ..meta.event_log import EventLog
        self.event_log = EventLog(None)
        self._store_root: Optional[str] = None

    # --------------------------------------------------------- RPC surface
    async def handle(self, method: str, args: dict):
        fn = getattr(self, f"rpc_{method}", None)
        if fn is None:
            raise ValueError(f"unknown compute-node method {method!r}")
        return await fn(**args)

    async def rpc_ping(self):
        return {"worker_id": self.worker_id,
                "actors": sum(len(d["dep"].actors)
                              for d in self.deployments.values()),
                # store identity: a partial recovery must NOT reopen a
                # survivor's store (tests assert this stays stable)
                "store_id": id(self.store)}

    async def rpc_hello(self, worker_id: int, store: dict,
                        sst_id_base: int, config: dict,
                        monitor_port: int = 0):
        import jax
        self.worker_id = worker_id
        from ..stream import remote_exchange
        remote_exchange.WORKER_ID = worker_id   # dcn_drop worker= filter
        self._open_store(store, sst_id_base)
        # the CLI's --monitor-port wins over meta's (operator-pinned)
        monitor_port = self.config.pop("__monitor_port", 0) or monitor_port
        self._fresh_coordinator(config)
        self.event_log.emit("worker_boot", worker_id=worker_id,
                            pid=os.getpid())
        if monitor_port:
            from ..meta.monitor_service import MonitorService
            self.monitor = await MonitorService(
                _MonitorShim(self), port=monitor_port).start()
            self._monitor_port = self.monitor.port
        return {"worker_id": worker_id, "pid": os.getpid(),
                "jax_platform": jax.default_backend(),
                "monitor_port": self._monitor_port}

    def _open_store(self, spec: dict, sst_id_base: int) -> None:
        from ..state import HummockStateStore, LocalFsObjectStore
        assert spec.get("kind", "hummock_fs") == "hummock_fs", spec
        store = HummockStateStore(LocalFsObjectStore(spec["root"]))
        store.manifest_owner = False
        store.set_sst_id_block(sst_id_base)
        self.store = store
        self._store_root = spec["root"]
        self._reopen_event_log()

    def _reopen_event_log(self) -> None:
        """Durable worker-local log once both identity and store root
        are known; reopening replays the previous incarnation's tail
        (torn-tail framing), so the crash IS in the record."""
        from ..meta.event_log import EventLog
        if self.worker_id is None or not self._store_root:
            return
        self.event_log.close()
        self.event_log = EventLog(
            self._store_root, subdir=f"events_w{self.worker_id}")

    def _fresh_coordinator(self, config: dict) -> None:
        from ..meta.barrier_manager import BarrierCoordinator
        self.config.update(config or {})
        self.coord = BarrierCoordinator(
            self.store,
            checkpoint_max_inflight=self.config.get(
                "checkpoint_max_inflight", 2))
        self.coord.commit_listener = self._on_committed
        self._apply_config()

    def _apply_config(self) -> None:
        cfg = self.config
        self.coord.memory.configure(
            budget_bytes=cfg.get("hbm_budget_bytes", 0),
            policy=cfg.get("memory_eviction_policy", "lru"))
        self.coord.stats.configure(cfg.get("metric_level", "info"))
        thr = cfg.get("barrier_stall_threshold_ms", 60000)
        self.coord.stall_threshold_ms = float(thr) if thr > 0 else None
        if "checkpoint_max_inflight" in cfg:
            self.coord.checkpoint_max_inflight = \
                cfg["checkpoint_max_inflight"]
        if "fault_injection" in cfg:
            # cluster fault points fire in THIS process (dcn_drop in
            # the DCN send path, worker_crash_partial below); meta
            # forwards the SET spec with the config push
            from ..utils.faults import FAULTS
            try:
                FAULTS.arm(cfg["fault_injection"])
            except ValueError:
                pass            # meta already validated at SET time

    async def rpc_set_config(self, config: dict):
        self.config.update(config)
        self._apply_config()
        return {"applied": sorted(config)}

    def _on_committed(self, epoch: int, sst_ids: list) -> None:
        """Local seal+upload+L0-install finished for `epoch`: report the
        SSTs so meta can commit once every worker reported (runs on the
        loop from the coordinator's uploader)."""
        from ..utils.faults import FAULTS
        self._sealed_reports += 1
        if FAULTS.active and FAULTS.hit(
                "worker_crash_partial", worker=self.worker_id,
                seals=self._sealed_reports) is not None:
            # deterministic worker death at the k-th sealed report
            # (SET fault_injection='worker_crash_partial:worker=W,at=k'
            # on the meta session; the spec rides the config push —
            # EVERY node arms it, so the worker= filter picks the one
            # victim) — a hard exit, exactly a kill -9 mid-epoch
            os._exit(43)
        # piggyback this node's closed (not-yet-shipped) epoch spans on
        # the sealed report: meta stitches them into its per-epoch
        # timeline (EpochTracer.ingest_worker) with zero extra RPCs
        spans = None
        if self.coord is not None:
            pend = self.coord.tracer.unshipped(self._shipped_spans)
            if pend:
                spans = [t.to_dict() for t in pend]
                self._shipped_spans.update(t.epoch for t in pend)
                if len(self._shipped_spans) > 512:
                    keep = sorted(self._shipped_spans)[-128:]
                    self._shipped_spans = set(keep)
        asyncio.get_running_loop().create_task(
            self.conn.push("sealed", worker_id=self.worker_id,
                           epoch=epoch, sst_ids=list(sst_ids),
                           spans=spans))

    # ------------------------------------------------------------- deploy
    async def rpc_deploy_prepare(self, deploy_id: int, graph,
                                 placement: dict, actor_id_base: int,
                                 table_id_base: int, ddl_config: dict,
                                 scope: str):
        """Phase 1: derive all ids locally, start a RemoteInput server
        per inbound cross-worker edge leg, report the ports."""
        from ..plan.build import (assign_graph_ids, cluster_remote_edges,
                                  infer_fragment_schemas)
        from ..stream.remote_exchange import RemoteInput
        actors, tables, _, _ = assign_graph_ids(graph, actor_id_base,
                                                table_id_base)
        schemas = infer_fragment_schemas(graph)
        remote_ins: dict = {}
        for edge_key, _uw, dw in cluster_remote_edges(graph, placement):
            if dw != self.worker_id:
                continue
            up_fid = edge_key[0]
            rx = await RemoteInput(schemas[up_fid], host="0.0.0.0",
                                   queue_depth=8).start()
            remote_ins[edge_key] = rx
        self._pending[deploy_id] = dict(
            graph=graph, placement=placement, actors=actors,
            tables=tables, schemas=schemas, remote_ins=remote_ins,
            ddl_config=ddl_config, scope=scope)
        return {k: rx.port for k, rx in remote_ins.items()}

    async def rpc_deploy_start(self, deploy_id: int, ports: dict):
        """Phase 2: connect RemoteOutputs to peer ports, build + spawn
        this node's actors."""
        from ..plan.build import (BuildEnv, build_partial_graph,
                                  cluster_remote_edges)
        from ..stream.remote_exchange import RemoteOutput
        p = self._pending.pop(deploy_id)
        replay = bool(p["ddl_config"].get("partial_recovery", 1))
        remote_outs: dict = {}
        for edge_key, uw, _dw in cluster_remote_edges(p["graph"],
                                                      p["placement"]):
            if uw != self.worker_id:
                continue
            host, port = ports[edge_key]
            remote_outs[edge_key] = await RemoteOutput(
                host, port, replay=replay).connect()
        env = BuildEnv(self.store, self.coord,
                       chunk_coalesce_max=p["ddl_config"].get(
                           "streaming_chunk_coalesce", 0),
                       partial_recovery=replay)
        env.memory_scope = p["scope"]
        dep = build_partial_graph(
            p["graph"], env, p["placement"], self.worker_id,
            p["actors"], p["tables"], p["schemas"], p["remote_ins"],
            remote_outs)
        env.memory_scope = None
        dep.spawn()
        # everything a per-worker partial rebuild needs rides with the
        # deployment record (graph/ids/schemas + the live edge objects)
        self.deployments[deploy_id] = dict(
            dep=dep, remote_ins=p["remote_ins"], remote_outs=remote_outs,
            info=dict(graph=p["graph"], placement=p["placement"],
                      actors=p["actors"], tables=p["tables"],
                      schemas=p["schemas"], scope=p["scope"],
                      ddl_config=p["ddl_config"]))
        self.event_log.emit(
            "deploy", deploy_id=deploy_id, scope=p["scope"],
            actors=sorted(a.actor_id for a in dep.actors))
        return {"actors": sorted(a.actor_id for a in dep.actors)}

    # ------------------------------------------------------------ barriers
    async def rpc_inject(self, barrier):
        """Meta's per-worker barrier injection (push): fan into local
        source queues NOW (ordering with the next inject rides the
        connection's frame order), collect + report in the background."""
        # remember recent barriers by the epoch they seal: a partial
        # rebuild synthesizes its INITIAL from the committed one
        self._barriers_by_prev[barrier.epoch.prev] = barrier
        while len(self._barriers_by_prev) > 64:
            del self._barriers_by_prev[min(self._barriers_by_prev)]
        # dead-actor sweep BEFORE injecting: a failure whose report was
        # lost (e.g. it raced a concurrent partial recovery, whose
        # quiesce cleared this node's local marker) would otherwise
        # hang every future epoch silently — the actor's task is done,
        # nobody re-reports, meta waits forever. Self-heal by
        # re-reporting instead of injecting into a broken topology.
        dead = sorted(
            a.actor_id
            for d in self.deployments.values()
            for a, t in zip(d["dep"].actors, d["dep"].tasks)
            if t.done() and a.actor_id in self.coord.actor_ids)
        if dead:
            await self.conn.push(
                "failed", worker_id=self.worker_id,
                error=f"actors {dead} dead at inject", actors=dead)
            return
        b = await self.coord.inject_remote(barrier)
        asyncio.get_running_loop().create_task(self._collect_one(b))

    async def _collect_one(self, barrier) -> None:
        try:
            await self.coord.wait_collected(barrier)
            await self.conn.push("collected", worker_id=self.worker_id,
                                 epoch=barrier.epoch.curr)
        except ConnectionResetError:
            pass                      # meta gone; process will be reset
        except Exception as e:  # noqa: BLE001 — local actor death
            self.event_log.emit(
                "actor_failed", error=f"{type(e).__name__}: {e}",
                actors=sorted(a for a in self.coord.failed_actors
                              if a > 0))
            try:
                # the failed actor ids let meta scope the radius to
                # their downstream closure (worker-partial recovery)
                # instead of resetting the whole cluster
                await self.conn.push(
                    "failed", worker_id=self.worker_id,
                    error=f"{type(e).__name__}: {e}",
                    actors=sorted(a for a in self.coord.failed_actors
                                  if a > 0))
            except ConnectionResetError:
                pass

    async def rpc_committed(self, epoch: int):
        """Meta's cluster commit covered `epoch`: drop the retained
        sealed batches (state/hummock.py) and trim every replay buffer
        — local channels, DCN output legs, mesh ingest logs — to the
        uncommitted suffix."""
        if self.store is not None:
            confirm = getattr(self.store, "confirm_committed", None)
            if confirm is not None:
                confirm(epoch)
        if self.coord is not None:
            self.coord._trim_replay_buffers(epoch)
        for d in self.deployments.values():
            for out in d["remote_outs"].values():
                if out.replay_enabled:
                    out.trim_replay(epoch)
        return {}

    # ---------------------------------------- per-worker partial recovery
    async def _teardown_actors(self, d: dict, actor_ids: list) -> None:
        """Cancel + deregister a subset of one deployment's actors (the
        closure members this worker hosted) without touching anything
        else — the surviving actors, their channels, and the store stay
        live."""
        dep = d["dep"]
        ids = set(actor_ids)
        for i, a in enumerate(dep.actors):
            if a.actor_id not in ids:
                continue
            t = dep.tasks[i] if i < len(dep.tasks) else None
            if t is not None and not t.done():
                t.cancel()
            if t is not None:
                try:
                    await t
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        kept = [(a, t) for a, t in zip(dep.actors, dep.tasks)
                if a.actor_id not in ids]
        dep.actors = [a for a, _ in kept]
        dep.tasks = [t for _, t in kept]
        for aid in sorted(ids):
            self.coord.actor_ids.discard(aid)
            self.coord.stats.unregister(aid)
            for name in dep.actor_memory_names.pop(aid, []):
                self.coord.memory.unregister(name)
                if name in dep.memory_names:
                    dep.memory_names.remove(name)
            for q in dep.actor_source_queues.pop(aid, []):
                if q in self.coord.source_queues:
                    self.coord.source_queues.remove(q)
                if q in dep.source_queues:
                    dep.source_queues.remove(q)
            root = dep.actor_root.pop(aid, None)
            fid = dep.actor_fragment.pop(aid, None)
            if fid is not None:
                if aid in dep.frag_actor_ids.get(fid, ()):
                    dep.frag_actor_ids[fid].remove(aid)
                if root is not None and root in dep.roots.get(fid, ()):
                    dep.roots[fid].remove(root)
            if aid in dep.mesh_actor_ids:
                self.coord.unregister_mesh_fragment(aid)
                dep.mesh_actor_ids.remove(aid)
            for obj in (dep.frag_ingest_logs.pop(fid, [])
                        if fid is not None else []):
                self.coord.unregister_replay_channels([obj])
                dep.replay_channels = [c for c in dep.replay_channels
                                       if c is not obj]

    def _drop_local_channel(self, d: dict, edge) -> None:
        """Remove a (now intra-closure) local channel so a fresh one
        replaces it — queued leftovers belong to dead incarnations."""
        fid, d_fid, k, u, di = edge
        mat = d["dep"].rebuild_info["channels"].get((fid, d_fid, k), {})
        ch = mat.pop((u, di), None)
        if ch is not None:
            self.coord.unregister_replay_channels([ch])
            d["dep"].replay_channels = [
                c for c in d["dep"].replay_channels if c is not ch]

    async def rpc_partial_prepare(self, dead_worker, plans: dict,
                                  committed_epoch: int,
                                  stale_ceiling=None):
        """Phase 1 of the per-worker partial recovery: quiesce this
        worker's closure actors, RESTAGE the sealed-but-unconfirmed
        batches (epochs the dead worker kept from committing), discard
        the closure's staged writes, tear down the legs being replaced,
        and open fresh RemoteInput servers for edges whose consumer
        lands here. The store handle stays OPEN at the committed
        manifest — no reopen, no manifest reload."""
        from ..stream.remote_exchange import RemoteInput
        # finished local seals land in the unconfirmed retention first
        await self.coord.drain_uploads()
        restage = getattr(self.store, "restage_unconfirmed", None)
        if restage is not None:
            restage()
        # keep the store OPEN but re-point it at the CURRENT committed
        # manifest: re-placed actors recover the dead worker's vnode
        # ranges through this handle, and the deploy-time manifest
        # snapshot predates everything the cluster committed since
        refresh = getattr(self.store, "refresh_manifest", None)
        if refresh is not None:
            refresh()
        out_ports: dict = {}
        for did, dplan in plans.items():
            d = self.deployments.get(did)
            if d is None:
                raise RuntimeError(
                    f"partial recovery: unknown deployment {did}")
            info = d["info"]
            old_placement = info["placement"]
            actors = info["actors"]
            closure = {(fid, idx)
                       for fid, idxs in dplan["closure"].items()
                       for idx in idxs}
            mine_old = [(fid, idx) for (fid, idx) in closure
                        if old_placement[fid][idx] == self.worker_id]
            await self._teardown_actors(
                d, [actors[fid][idx] for fid, idx in mine_old])
            # drop exactly the closure's staged uncommitted writes on
            # this worker (vnode-disjoint: the discard never touches a
            # surviving actor's rows — the planner refused mixed
            # fragments)
            discard_tables = set()
            for fid, _idx in mine_old:
                discard_tables.update(info["tables"][fid].values())
            if discard_tables:
                self.store.discard_staged_tables(discard_tables)
            # edge legs being replaced/reused
            for e in dplan["edges"]:
                fid, d_fid, k, u, di = edge = tuple(e["key"])
                kind = e["kind"]
                wc_new = dplan["new_placement"][d_fid][di]
                if kind == "frontier_rewind":
                    if wc_new == self.worker_id:
                        d["remote_ins"][edge].expect_rewind(
                            stale_ceiling=stale_ceiling)
                    continue
                if kind == "frontier_local":
                    continue            # reused; armed in phase 2
                # intra_* and frontier_reconnect: fresh resources — the
                # old leg objects (if this worker held either end) die
                old_rx = d["remote_ins"].pop(edge, None)
                if old_rx is not None:
                    await old_rx.stop()
                self._drop_local_channel(d, edge)
                if kind != "intra_local" and wc_new == self.worker_id:
                    rx = await RemoteInput(info["schemas"][fid],
                                           host="0.0.0.0",
                                           queue_depth=8).start()
                    rx.stale_ceiling = stale_ceiling
                    d.setdefault("fresh_ins", {})[edge] = rx
                    out_ports[(did,) + edge] = rx.port
            # close output legs whose producer was a closure actor here
            for ek, out in list(d["remote_outs"].items()):
                fid2, _dfid2, _k2, u2, _di2 = ek
                if (fid2, u2) in closure \
                        and old_placement[fid2][u2] == self.worker_id:
                    await out.close()
                    d["remote_outs"].pop(ek)
        self.coord.clear_failure()
        return out_ports

    async def rpc_partial_start(self, plans: dict, ports: dict,
                                committed_epoch: int,
                                stale_ceiling=None):
        """Phase 2: rebuild the closure actors assigned here (same
        global ids/tables), wire fresh legs, arm frontier replay, spawn,
        then rewind surviving output legs into the rebuilt consumers."""
        from ..plan.build import BuildEnv, build_closure_actors
        from ..stream.exchange import Channel
        from ..stream.message import Barrier, BarrierKind
        from ..stream.remote_exchange import RemoteOutput
        base = self._barriers_by_prev.get(committed_epoch)
        if base is None:
            raise RuntimeError(
                f"partial recovery: no barrier on record sealing "
                f"committed epoch {committed_epoch}")
        init_barrier = Barrier(base.epoch, BarrierKind.INITIAL, None, (),
                               base.inject_time_ns)
        rewinds = []
        spawned: list[int] = []
        for did, dplan in plans.items():
            d = self.deployments.get(did)
            if d is None:
                raise RuntimeError(
                    f"partial recovery: unknown deployment {did}")
            info = d["info"]
            graph = info["graph"]
            new_placement = dplan["new_placement"]
            replay = bool(info["ddl_config"].get("partial_recovery", 1))
            kinds = {tuple(e["key"]): e["kind"] for e in dplan["edges"]}
            dep = d["dep"]
            channels = dep.rebuild_info["channels"]
            # fresh intra-closure legs (producer side pre-connects)
            fresh_local: dict = {}
            for edge, kind in kinds.items():
                fid, d_fid, k, u, di = edge
                if kind == "intra_local" \
                        and new_placement[fid][u] == self.worker_id:
                    ch = Channel(64)
                    if replay:
                        ch.enable_replay()
                        dep.replay_channels.append(ch)
                        self.coord.register_replay_channels([ch])
                    fresh_local[edge] = ch
                    channels.setdefault((fid, d_fid, k), {})[(u, di)] = ch
                elif kind == "intra_remote" \
                        and new_placement[fid][u] == self.worker_id:
                    host, port = ports[(did,) + edge]
                    out = await RemoteOutput(host, port,
                                             replay=replay).connect()
                    d["remote_outs"][edge] = out
            d["remote_ins"].update(d.pop("fresh_ins", {}))

            def in_leg(up_fid, fid2, k, u, di, _d=d, _kinds=kinds,
                       _fresh=fresh_local, _chans=channels):
                edge = (up_fid, fid2, k, u, di)
                kind = _kinds.get(edge)
                if kind == "intra_local":
                    return _fresh[edge]
                if kind == "frontier_local":
                    return _chans[(up_fid, fid2, k)][(u, di)]
                return _d["remote_ins"][edge]

            def out_leg(fid2, d_fid, k, u, di, _d=d, _kinds=kinds,
                        _fresh=fresh_local):
                edge = (fid2, d_fid, k, u, di)
                if _kinds.get(edge) == "intra_local":
                    return _fresh[edge]
                return _d["remote_outs"][edge]

            env = BuildEnv(self.store, self.coord,
                           chunk_coalesce_max=info["ddl_config"].get(
                               "streaming_chunk_coalesce", 0),
                           partial_recovery=replay)
            env.memory_scope = info["scope"]
            new_actors = build_closure_actors(
                graph, env, dep, new_placement, self.worker_id,
                info["actors"], info["tables"], info["schemas"],
                dplan["closure"], in_leg, out_leg)
            env.memory_scope = None
            # arm frontier replay on reused local channels feeding
            # rebuilt consumers here
            for edge, kind in kinds.items():
                fid, d_fid, k, u, di = edge
                if kind == "frontier_local" \
                        and new_placement[d_fid][di] == self.worker_id:
                    channels[(fid, d_fid, k)][(u, di)].begin_replay(
                        stale_ceiling=stale_ceiling)
            # rebuilt SOURCE actors have no inbound frontier: preload
            # the synthetic INITIAL (committed base) so they re-seek
            # committed offsets and propagate it down the intra legs
            for a in new_actors:
                for q in dep.actor_source_queues.get(a.actor_id, []):
                    q.put_nowait(init_barrier)
            # install + spawn (replace old slots, append re-placed ones)
            by_id = {a.actor_id: i for i, a in enumerate(dep.actors)}
            for a in new_actors:
                i = by_id.get(a.actor_id)
                if i is None:
                    dep.actors.append(a)
                    dep.tasks.append(a.spawn())
                else:
                    dep.actors[i] = a
                    dep.tasks[i] = a.spawn()
                spawned.append(a.actor_id)
            # surviving producers on this worker hold legs into REBUILT
            # consumers — queue the rewinds for phase 3: a rewind can
            # only stream once EVERY worker's consumers are live (a
            # suffix longer than the credit window would otherwise
            # deadlock two workers rewinding into each other's
            # not-yet-spawned actors)
            for edge, kind in kinds.items():
                fid, d_fid, k, u, di = edge
                if kind not in ("frontier_rewind", "frontier_reconnect"):
                    continue
                if new_placement[fid][u] != self.worker_id:
                    continue
                out = d["remote_outs"][edge]
                if kind == "frontier_reconnect":
                    host, port = ports[(did,) + edge]
                    rewinds.append((out, host, port))
                else:
                    rewinds.append((out, None, None))
            # new placement is authoritative for later recoveries
            info["placement"] = new_placement
        self._pending_rewinds = rewinds
        return {"spawned": sorted(spawned)}

    async def rpc_partial_rewind(self):
        """Phase 3: stream the uncommitted suffix from every surviving
        producer leg into its rebuilt consumer (all workers' actors are
        live by now; live sends on a rewinding leg park until the
        suffix is through, so the consumer sees committed-base INITIAL,
        suffix, live — in order). Legs rewind CONCURRENTLY — they are
        independent ordered streams, and exactly one task drains each
        leg's suffix sequentially, so per-leg frame order is preserved
        while the wall clock is the slowest leg instead of the sum
        (serial streaming was the PR 11 follow-up in ROADMAP 2e).

        The LAST leg is awaited as a bare coroutine, not a task: the
        replayed suffix wakes the rebuilt consumer actors, whose first
        dispatch can compile for seconds — a task-based resume (plain
        gather) queues this handler's response BEHIND that compile and
        charges it to the recovery window. A direct await resumes the
        handler synchronously after the leg's final write, and awaiting
        the (by then usually done) head tasks returns without yielding,
        so the response beats the compile exactly like the old serial
        path did."""
        rewinds, self._pending_rewinds = \
            getattr(self, "_pending_rewinds", []), []
        replayed = 0
        if rewinds:
            *head, (lout, lhost, lport) = rewinds
            tasks = [asyncio.create_task(out.rewind_replay(host, port))
                     for out, host, port in head]
            try:
                replayed += await lout.rewind_replay(lhost, lport)
            except BaseException:
                for t in tasks:
                    t.cancel()
                raise
            for t in tasks:
                replayed += await t
        return {"replayed": replayed}

    # ------------------------------------------------------------ teardown
    async def rpc_stop_deployment(self, deploy_id: int):
        """Clean up ONE deployment after meta drove its stop barrier
        (actors have exited; deregister them and close the DCN legs)."""
        d = self.deployments.pop(deploy_id, None)
        if d is not None:
            await self._teardown(d)
            self.event_log.emit("stop_deployment", deploy_id=deploy_id,
                                scope=d["info"].get("scope"))
        return {}

    async def _teardown(self, d: dict) -> None:
        dep = d["dep"]
        for t in dep.tasks:
            if not t.done():
                t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for a in dep.actors:
            self.coord.actor_ids.discard(a.actor_id)
            self.coord.stats.unregister(a.actor_id)
        for q in dep.source_queues:
            if q in self.coord.source_queues:
                self.coord.source_queues.remove(q)
        for n in dep.memory_names:
            self.coord.memory.unregister(n)
        for out in d["remote_outs"].values():
            try:
                await out.close()
            except Exception:  # noqa: BLE001
                pass
        for rx in d["remote_ins"].values():
            try:
                await rx.stop()
            except Exception:  # noqa: BLE001
                pass

    async def rpc_reset(self, store: Optional[dict] = None,
                        sst_id_base: Optional[int] = None):
        """Recovery entry (meta's re-place): abandon every deployment,
        abort in-flight uploads, reopen the store at the CURRENT
        committed manifest, fresh coordinator."""
        for d in list(self.deployments.values()):
            await self._teardown(d)
        self.deployments.clear()
        for p in self._pending.values():
            for rx in p["remote_ins"].values():
                try:
                    await rx.stop()
                except Exception:  # noqa: BLE001
                    pass
        self._pending.clear()
        if self.coord is not None:
            await self.coord.abort_uploads()
        if store is not None:
            self._open_store(store, sst_id_base or 1)
        self._fresh_coordinator({})
        self.event_log.emit(
            "worker_reset",
            committed_epoch=self.store.committed_epoch())
        return {"committed_epoch": self.store.committed_epoch()}

    # -------------------------------------------------------- observability
    async def rpc_events(self, limit=None, since=None, kind=None):
        """This node's local event records (worker-local crc-framed
        log) — meta stitches them into SHOW events / /debug/events
        tagged worker=wN."""
        return self.event_log.records(limit=limit, since=since,
                                      kind=kind)

    async def rpc_scrape(self):
        """This node's full metrics exposition — meta's monitor merges it
        into the cluster-wide /metrics with a worker label."""
        from ..utils.metrics import GLOBAL_METRICS
        return GLOBAL_METRICS.render_prometheus()

    async def rpc_memory_report(self):
        return self.coord.memory.report() if self.coord is not None else []

    async def rpc_dump_tasks(self):
        """This node's own stuck-barrier diagnosis: in-flight epochs
        with THEIR remaining (local) actor ids, plus the local await
        tree — meta's watchdog merges one section per worker so a
        wedged cluster epoch names worker, actor, and parked frame."""
        from ..utils.trace import format_stuck_barrier_report
        if self.coord is None:
            return "(no coordinator)"
        lines = []
        for epoch, st in sorted(self.coord._epochs.items()):
            lines.append(f"in-flight epoch {epoch}: remaining actors "
                         f"{sorted(st.remaining)}")
        lines.append(format_stuck_barrier_report(self.coord))
        return "\n".join(lines)

    async def rpc_profile_cpu(self, seconds: float = 2.0):
        """On-demand cpu profile of THIS worker process (collapsed
        stacks); sampling blocks a helper thread, never the loop."""
        from ..utils.profiler import profile_cpu
        return await asyncio.to_thread(profile_cpu, seconds)

    async def rpc_profile_heap(self, seconds: float = 2.0):
        from ..utils.profiler import profile_heap
        return await asyncio.to_thread(profile_heap, seconds)

    async def rpc_profile_device(self):
        from ..utils.profiler import profile_device
        return profile_device(self.coord)

    async def closed(self) -> None:
        """Meta connection died: this node's actors are orphans — tear
        everything down so the process is reusable by the next meta."""
        for d in list(self.deployments.values()):
            await self._teardown(d)
        self.deployments.clear()
        if self.coord is not None:
            await self.coord.abort_uploads()
        if self.monitor is not None:
            await self.monitor.stop()
            self.monitor = None
        self.event_log.close()


async def serve_connection(reader, writer, first_msg: dict,
                           monitor_port: int = 0) -> None:
    """Entry from risingwave_tpu.worker: the connection's first frame was
    a compute-node RPC request — serve the control protocol on it."""
    node: Optional[ComputeNode] = None
    done = asyncio.Event()

    def on_closed(exc):
        done.set()

    async def handler(method, args):
        return await node.handle(method, args)

    conn = RpcConn(reader, writer, handler=handler, on_closed=on_closed)
    host = writer.get_extra_info("sockname")[0]
    node = ComputeNode(conn, host=host)
    if monitor_port:
        node.config["__monitor_port"] = monitor_port
    conn.start(first_msg)
    await done.wait()
    await node.closed()


def main(argv=None) -> None:
    """Standalone launch: `python -m risingwave_tpu.cluster.compute_node
    [port] [--monitor-port N]` — identical to `risingwave_tpu.worker`
    (one listener serves both the legacy fragment protocol and the
    cluster control plane)."""
    from .. import worker
    worker.main(argv)


if __name__ == "__main__":
    main(sys.argv[1:])

"""Cluster control plane — meta service + first-class compute nodes.

Reference: the meta node (src/meta/) driving N compute nodes
(src/compute/) over vnode-partitioned fragments: `GlobalBarrierManager`
injects barriers per worker and collects per-worker completion,
`LocalStreamManager::build_actors` builds each node's assigned actors
locally, and the Hummock version manifest commits only after every
worker's SSTs for the epoch are uploaded.

This package is that split for the TPU engine:

  * `rpc.py`          — the control-plane wire (length-prefixed pickle
                        frames between trusted processes, multiplexed
                        request/response + unsolicited pushes);
  * `meta_service.py` — `ClusterManager` (worker registry with
                        heartbeats/leases, vnode-range fragment
                        placement, two-phase cross-worker deploy,
                        metrics scrape aggregation) + `WorkerHandle`;
  * `compute_node.py` — the promoted worker (risingwave_tpu.worker
                        serves both protocols on one port): builds and
                        OWNS its assigned actors via plan/build.py's
                        partial build, runs its own BarrierCoordinator
                        as the LocalBarrierManager, seals + uploads its
                        own state, and exposes its own /metrics.

Barriers are injected over RPC into every worker's source queues and
collected per worker; a checkpoint commits at meta only after ALL
workers report their sealed SSTs (state/hummock.py `commit_remote`).
"""

from .meta_service import ClusterManager, WorkerInfo
from .rpc import RpcConn

__all__ = ["ClusterManager", "RpcConn", "WorkerInfo"]

"""Row serde: memcomparable key encoding + value encoding.

Reference: src/common/src/util/memcmp_encoding.rs and util/value_encoding/ —
primary keys are serialized so that byte order == row order (LSM range scans
give pk order for free), values are a compact fixed-layout encoding.

Subset choices for the TPU engine: all device types are fixed-width ints/
floats (types.py), so encoding is per-field:
  null flag byte (0x00 null / 0x01 value, nulls-first like the reference
  default) ++ order-preserving bytes:
    signed int  -> big-endian with sign bit flipped
    float       -> big-endian IEEE; if negative flip all bits else flip sign
    bool        -> single byte
    dict ids    -> int32 rule (NOTE: id order, not lexicographic string
                   order — ordered ops on strings take the host path)
Descending order flips all bytes (used by TopN/OverWindow orderings).
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

import numpy as np

from ..common.types import DataType, Schema

_INT_WIDTH = {
    DataType.INT16: 2, DataType.DATE: 4, DataType.INT32: 4,
    DataType.VARCHAR: 4, DataType.BYTEA: 4, DataType.JSONB: 4,
    DataType.INT64: 8, DataType.TIME: 8, DataType.TIMESTAMP: 8,
    DataType.TIMESTAMPTZ: 8, DataType.INTERVAL: 8, DataType.DECIMAL: 8,
    DataType.SERIAL: 8,
}


def _enc_int(v: int, width: int) -> bytes:
    bias = 1 << (8 * width - 1)
    return (int(v) + bias).to_bytes(width, "big")


def _dec_int(b: bytes) -> int:
    bias = 1 << (8 * len(b) - 1)
    return int.from_bytes(b, "big") - bias


def _enc_float(v: float, fmt: str) -> bytes:
    raw = struct.pack(">" + fmt, v)
    n = int.from_bytes(raw, "big")
    top = 1 << (8 * len(raw) - 1)
    n = (n ^ ((1 << (8 * len(raw))) - 1)) if (n & top) else (n | top)
    return n.to_bytes(len(raw), "big")


def _dec_float(b: bytes, fmt: str) -> float:
    n = int.from_bytes(b, "big")
    top = 1 << (8 * len(b) - 1)
    n = (n ^ top) if (n & top) else (n ^ ((1 << (8 * len(b))) - 1))
    return struct.unpack(">" + fmt, n.to_bytes(len(b), "big"))[0]


def encode_memcomparable(
    values: Sequence, types: Sequence[DataType], descending: Optional[Sequence[bool]] = None,
) -> bytes:
    out = bytearray()
    for i, (v, t) in enumerate(zip(values, types)):
        desc = bool(descending[i]) if descending is not None else False
        if v is None:
            field = b"\x00"
        else:
            if t is DataType.BOOLEAN:
                body = b"\x01" if v else b"\x00"
            elif t in (DataType.FLOAT32, DataType.FLOAT64):
                body = _enc_float(float(v), "f" if t is DataType.FLOAT32 else "d")
            else:
                body = _enc_int(int(v), _INT_WIDTH[t])
            field = b"\x01" + body
        if desc:
            field = bytes(0xFF - b for b in field)
        out += field
    return bytes(out)


def decode_memcomparable(
    data: bytes, types: Sequence[DataType], descending: Optional[Sequence[bool]] = None,
) -> tuple:
    vals = []
    pos = 0
    for i, t in enumerate(types):
        desc = bool(descending[i]) if descending is not None else False
        if t is DataType.BOOLEAN:
            width = 1
        elif t is DataType.FLOAT32:
            width = 4
        elif t is DataType.FLOAT64:
            width = 8
        else:
            width = _INT_WIDTH[t]
        flag = data[pos]
        if desc:
            flag = 0xFF - flag
        pos += 1
        if flag == 0x00:
            vals.append(None)
            continue
        body = data[pos:pos + width]
        if desc:
            body = bytes(0xFF - b for b in body)
        pos += width
        if t is DataType.BOOLEAN:
            vals.append(body[0] != 0)
        elif t in (DataType.FLOAT32, DataType.FLOAT64):
            vals.append(_dec_float(body, "f" if t is DataType.FLOAT32 else "d"))
        else:
            vals.append(_dec_int(body))
    return tuple(vals)


# ----------------------------------------------------------- value encoding

def _fmt_char(t: DataType) -> str:
    if t is DataType.BOOLEAN:
        return "?"
    if t is DataType.FLOAT32:
        return "f"
    if t is DataType.FLOAT64:
        return "d"
    w = _INT_WIDTH[t]
    return {2: "h", 4: "i", 8: "q"}[w]


class RowSerde:
    """Fixed-layout value encoding with a null bitmap prefix."""

    def __init__(self, schema: Schema):
        self.schema = schema
        self._fmt = "<" + "".join(_fmt_char(f.data_type) for f in schema)
        self._nbytes_nulls = (len(schema) + 7) // 8
        self._zeros = tuple(f.data_type.zero_value() for f in schema)

    def encode(self, values: Sequence) -> bytes:
        nulls = 0
        clean = []
        for i, v in enumerate(values):
            if v is None:
                nulls |= 1 << i
                clean.append(self._zeros[i])
            else:
                clean.append(v)
        return nulls.to_bytes(self._nbytes_nulls, "little") + struct.pack(self._fmt, *clean)

    def decode(self, data: bytes) -> tuple:
        nulls = int.from_bytes(data[: self._nbytes_nulls], "little")
        vals = struct.unpack(self._fmt, data[self._nbytes_nulls:])
        return tuple(None if (nulls >> i) & 1 else v for i, v in enumerate(vals))

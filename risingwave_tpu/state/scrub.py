"""Background integrity scrubber — the storage plane's health loop.

Reference: the reference dedicates a storage subsystem to object health
(SURVEY §2.5 — the compactor's object lifetime bookkeeping plus
`src/storage/backup/` verification); cloud LSM stores scrub at rest
because bit-rot and torn caches are detected cheapest BEFORE a recovery
needs the bytes. Same shape here, collapsed to a coordinator-owned pulse:

* **verify**: round-robin over every manifest-referenced object (SSTs,
  MANIFEST, CATALOG), a bounded `batch` per pulse, each read +
  crc-checked through `HummockStateStore.scrub_verify` — a transient
  mismatch re-reads once, a durable one quarantines + restores from the
  attached backup (state/hummock.py read-path rules). The reads run on
  a WORKER THREAD (the uploader discipline — the barrier path never
  pays an object fetch); each pulse harvests the previous job's
  findings (counters, event-log records) and schedules the next one.
  Without a running loop (unit tests driving pulses synchronously) the
  job runs inline.
* **orphan sweep**: SSTs visible under `ssts/` that no manifest
  references and no sealed/unconfirmed batch or in-flight background
  compaction is about to commit are orphans (a crashed upload's or an
  abandoned merge's leftovers — they used to leak forever). An orphan
  is DELETED only after being sighted in two consecutive pulses (grace:
  an object that appears mid-pulse could be a racing upload's fresh
  PUT), and never in cluster mode (meta cannot see worker uploads still
  in flight — it only counts them there).

Barrier-paced like the MemoryManager: `on_barrier` runs synchronously at
every collected barrier, throttled to every `interval` barriers, so
scrub state can never race an in-flight apply and a disabled scrubber
(interval=0) costs one integer compare per barrier.
"""

from __future__ import annotations

import asyncio
from typing import Optional


class StorageScrubber:
    """Owned by the BarrierCoordinator; active only over a durable
    manifest-owner Hummock store (everything else no-ops)."""

    def __init__(self, store, interval: int = 16, batch: int = 2):
        self.store = store
        self.interval = int(interval)   # barriers between pulses; 0=off
        self.batch = int(batch)         # objects verified per pulse
        self._count = 0
        self._cursor = 0
        # durable event log (meta/event_log.py) — the session attaches
        # it so a scrub finding leaves an operator-visible record
        self.event_log = None
        # orphans sighted last pulse — the two-sighting sweep grace
        self._orphan_seen: set[str] = set()
        # in-flight verification job (asyncio.to_thread)
        self._job: Optional[asyncio.Task] = None
        # report surface (SHOW storage)
        self.passes = 0
        self.verified = 0
        self.corruptions = 0
        self.orphans_live = 0
        self.orphans_swept = 0

    def configure(self, interval: Optional[int] = None,
                  batch: Optional[int] = None) -> None:
        if interval is not None:
            self.interval = int(interval)
        if batch is not None:
            self.batch = int(batch)

    # ------------------------------------------------------------ pulse
    def _active(self) -> bool:
        return (self.interval > 0
                and getattr(self.store, "manifest_owner", True)
                and getattr(self.store, "objects", None) is not None
                and hasattr(self.store, "scrub_verify"))

    def on_barrier(self, epoch: int, cluster_mode: bool = False) -> None:
        if not self._active():
            return
        self._count += 1
        if self._count % self.interval:
            return
        self._pulse(cluster_mode)

    def _referenced(self) -> list[str]:
        from .hummock import MANIFEST_PATH, _sst_path
        store = self.store
        paths = [_sst_path(t.sst_id) for t in store._l0]
        if store._l1 is not None:
            paths.append(_sst_path(store._l1.sst_id))
        for name in (MANIFEST_PATH, "CATALOG"):
            if store.objects.exists(name):
                paths.append(name)
        return paths

    def _pulse(self, cluster_mode: bool) -> None:
        from ..utils.metrics import (STORAGE_ORPHAN_OBJECTS,
                                     STORAGE_ORPHANS_SWEPT,
                                     STORAGE_SCRUB_PASSES)
        store = self.store
        objects = store.objects
        self.passes += 1
        STORAGE_SCRUB_PASSES.inc()
        # ---- verify a bounded slice of the referenced set, OFF-LOOP ----
        # harvest the previous job's findings first (reported here, at
        # the barrier); a job still running skips one verification beat
        schedule = True
        if self._job is not None:
            if self._job.done():
                job, self._job = self._job, None
                if not job.cancelled() and job.exception() is None:
                    self._harvest(job.result())
            else:
                schedule = False
        if schedule:
            refs = self._referenced()
            paths: list[str] = []
            if refs:
                paths = [refs[(self._cursor + k) % len(refs)]
                         for k in range(min(self.batch, len(refs)))]
                self._cursor = (self._cursor + self.batch) % len(refs)
            if paths:
                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None
                if loop is None:         # synchronous harness
                    self._harvest(self._verify_job(paths))
                else:
                    self._job = loop.create_task(asyncio.to_thread(
                        self._verify_job, paths))
        # ---- orphan accounting + grace-period sweep ----
        from .hummock import _sst_path
        try:
            listed = set(objects.list("ssts/"))
        except Exception:  # noqa: BLE001 — a flaky list skips the round
            return
        keep = {_sst_path(t.sst_id) for t in store._l0}
        if store._l1 is not None:
            keep.add(_sst_path(store._l1.sst_id))
        # sealed-but-uncommitted and sealed-but-unconfirmed batches are
        # IN FLIGHT, not orphaned — their commit installs them shortly;
        # so is the output of a background merge awaiting its install
        for b in list(getattr(store, "_sealed", ())) \
                + list(getattr(store, "_unconfirmed", ())):
            if b.sst_id is not None:
                keep.add(_sst_path(b.sst_id))
        for sst_id in getattr(store, "compaction_inflight", ()):
            keep.add(_sst_path(sst_id))
        orphans = listed - keep
        self.orphans_live = len(orphans)
        STORAGE_ORPHAN_OBJECTS.set(float(len(orphans)))
        if cluster_mode:
            # meta cannot prove a worker's fresh upload is not about to
            # be committed — count, never delete (the sweep runs when
            # the cluster detaches / on the single-process path)
            self._orphan_seen = orphans
            return
        swept = 0
        for path in sorted(orphans & self._orphan_seen):
            try:
                objects.delete(path)
                swept += 1
            except Exception:  # noqa: BLE001 — best-effort hygiene
                pass
        if swept:
            self.orphans_swept += swept
            STORAGE_ORPHANS_SWEPT.inc(swept)
            self.orphans_live -= swept
            STORAGE_ORPHAN_OBJECTS.set(float(self.orphans_live))
        self._orphan_seen = orphans - {p for p in self._orphan_seen
                                       if p in orphans}

    def _verify_job(self, paths: list[str]) -> list[tuple[str, bool]]:
        """Worker-thread half: the object fetches + crc checks.
        `scrub_verify` touches the object store only (quarantine/restore
        included), so a thread can run it while the stream computes."""
        out = []
        for path in paths:
            try:
                ok = self.store.scrub_verify(path)
            except Exception:  # noqa: BLE001 — scrub never kills a barrier
                ok = False
            out.append((path, ok))
        return out

    def _harvest(self, results: list[tuple[str, bool]]) -> None:
        """Loop-side half: report the findings at the barrier."""
        from ..utils.metrics import (STORAGE_SCRUB_CORRUPTIONS,
                                     STORAGE_SCRUB_OBJECTS)
        for path, ok in results:
            self.verified += 1
            STORAGE_SCRUB_OBJECTS.inc()
            if not ok:
                self.corruptions += 1
                STORAGE_SCRUB_CORRUPTIONS.inc()
                if self.event_log is not None:
                    self.event_log.emit("scrub_corruption", path=path)

    async def drain(self) -> None:
        """Quiesce: wait out an in-flight verification job and report
        its findings (recovery/shutdown/tests)."""
        if self._job is not None:
            job, self._job = self._job, None
            try:
                self._harvest(await job)
            except Exception:  # noqa: BLE001
                pass

    # ----------------------------------------------------------- report
    def report(self) -> dict:
        return {
            "interval": self.interval,
            "batch": self.batch,
            "passes": self.passes,
            "objects_verified": self.verified,
            "corruptions": self.corruptions,
            "orphans_live": self.orphans_live,
            "orphans_swept": self.orphans_swept,
        }

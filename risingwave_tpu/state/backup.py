"""Backup / restore of a Hummock deployment (manifest + SSTs + catalog).

Reference: src/storage/backup/src/ (meta snapshot + SST manifest backup,
restored into a fresh cluster). A backup is an object-store-level copy
taken in dependency order — SSTs first, the MANIFEST and CATALOG last —
so the copied manifest can only reference SSTs that were already copied
(SST files are immutable once uploaded; the manifest swap is the only
mutation). Callers must quiesce compaction/sync for full consistency;
`Session.backup()` takes the coordinator's rounds lock to guarantee it.

The copy is **incremental and generation-stamped**: every run bumps a
backup generation and copies ONLY objects the destination does not
already hold at the recorded checksum (SST immutability means a
same-name same-crc object never needs recopying; mutable objects —
MANIFEST, CATALOG, the dict log head, DML jsonl tails — recopy when
their crc moved). Each copied object is read back from the destination
and verified before it enters the backup manifest, and every restore
re-verifies EVERY recorded object against its crc — a corrupted backup
refuses loudly (`BackupCorruption`) instead of cold-starting a wrong
world. Objects the source dropped since the previous generation
(compaction victims) are pruned from the destination only AFTER the new
backup manifest is durable, mirroring the manifest-swap-then-delete
rule of the store itself.
"""

from __future__ import annotations

import json
import zlib
from typing import Optional

from .object_store import ObjectStore
from .sstable import frame_meta, unframe_meta, MetaCorruption

BACKUP_MANIFEST_PATH = "BACKUP_MANIFEST"


class BackupCorruption(Exception):
    """A backup object is missing or fails its recorded checksum — the
    restore (or verified read) refuses instead of serving it."""


def _manifest_last() -> tuple:
    # imported, not re-hardcoded: a rename of either constant must keep
    # the copy-ordering guarantee intact
    from .hummock import MANIFEST_PATH
    from ..frontend.session import CATALOG_PATH
    return (MANIFEST_PATH, CATALOG_PATH)


def load_backup_manifest(dst: ObjectStore) -> Optional[dict]:
    """The destination's backup manifest, or None for a fresh/legacy
    destination. A corrupt manifest raises — an incremental run must not
    silently trust (or silently discard) a damaged ledger."""
    if not dst.exists(BACKUP_MANIFEST_PATH):
        return None
    body = unframe_meta(dst.read(BACKUP_MANIFEST_PATH),
                        BACKUP_MANIFEST_PATH)
    m = json.loads(body)
    if m.get("format") != 2:
        raise BackupCorruption(
            f"unknown backup manifest format: {m.get('format')!r}")
    return m


def backup_objects(src: ObjectStore, dst: ObjectStore,
                   extra: Optional[dict] = None) -> dict:
    """Incremental generation-stamped copy of every src object into dst
    (manifest/catalog last), each copy read back + checksum-verified
    before it is recorded. `extra` maps name -> bytes for caller-held
    snapshots written last (Session passes the CATALOG it read under the
    rounds lock). Returns the summary: generation, per-run copied /
    skipped counts and the total recorded object count."""
    from ..utils.metrics import (BACKUP_GENERATION, BACKUP_OBJECTS_COPIED,
                                 BACKUP_OBJECTS_SKIPPED)
    extra = dict(extra or {})
    prev = load_backup_manifest(dst)
    gen = (prev["generation"] + 1) if prev else 1
    entries: dict[str, dict] = dict(prev["objects"]) if prev else {}
    last = [n for n in _manifest_last() if n not in extra]
    names = src.list("")
    # quarantined evidence is deliberately NOT backed up (it is the
    # corrupt bytes); the backup ledger itself never copies as data
    names = [n for n in names
             if not n.startswith("quarantine/")
             and n != BACKUP_MANIFEST_PATH]
    ordinary = [n for n in names if n not in last and n not in extra]
    copied = skipped = 0

    def _put_verified(name: str, data: bytes) -> None:
        nonlocal copied, skipped
        crc = zlib.crc32(data)
        ent = entries.get(name)
        if ent is not None and ent["crc"] == crc and dst.exists(name):
            skipped += 1
            return
        dst.upload(name, data)
        back = dst.read(name)          # read-back verify AT BACKUP TIME
        if zlib.crc32(back) != crc:
            raise BackupCorruption(
                f"backup copy of {name!r} failed read-back verification")
        entries[name] = {"crc": crc, "size": len(data), "generation": gen}
        copied += 1

    for n in ordinary:
        _put_verified(n, src.read(n))
    for n in last:
        if src.exists(n):
            _put_verified(n, src.read(n))
    for n, data in extra.items():
        _put_verified(n, data)
    # prune ledger entries whose source object is gone (compacted away):
    # manifest first, deletes strictly after — a crash between them
    # leaves harmless unreferenced extra objects, never a ledger entry
    # pointing at nothing
    live = set(names) | set(extra) | {n for n in last if src.exists(n)}
    pruned = sorted(n for n in entries if n not in live)
    for n in pruned:
        del entries[n]
    manifest = {"format": 2, "generation": gen, "objects": entries}
    dst.upload(BACKUP_MANIFEST_PATH,
               frame_meta(json.dumps(manifest).encode()))
    for n in pruned:
        dst.delete(n)
    BACKUP_OBJECTS_COPIED.inc(copied)
    BACKUP_OBJECTS_SKIPPED.inc(skipped)
    BACKUP_GENERATION.set(float(gen))
    return {"objects": len(entries), "copied": copied,
            "skipped": skipped, "pruned": len(pruned), "generation": gen}


def verify_backup(backup: ObjectStore) -> Optional[dict]:
    """Verify EVERY recorded object against its checksum; raises
    BackupCorruption on the first missing/mismatched object. Returns the
    backup manifest (None for a legacy destination with no ledger —
    nothing to verify against, the caller decides whether to trust it)."""
    m = load_backup_manifest(backup)
    if m is None:
        return None
    for name, ent in sorted(m["objects"].items()):
        if not backup.exists(name):
            raise BackupCorruption(f"backup object {name!r} is missing")
        data = backup.read(name)
        if zlib.crc32(data) != ent["crc"]:
            raise BackupCorruption(
                f"backup object {name!r} fails its checksum "
                f"(generation {ent['generation']})")
    return m


def read_backup_object(backup: ObjectStore, name: str) -> Optional[bytes]:
    """Checksum-verified read of ONE backup object (the quarantine-repair
    path): None when the backup has no (intact) record of it."""
    try:
        m = load_backup_manifest(backup)
    except (BackupCorruption, MetaCorruption, ValueError):
        return None
    if m is None or name not in m["objects"] or not backup.exists(name):
        return None
    data = backup.read(name)
    if zlib.crc32(data) != m["objects"][name]["crc"]:
        return None
    return data


def restore_objects(backup: ObjectStore, dest: ObjectStore) -> dict:
    """Cold-start restore: verify the whole backup, then copy every
    recorded object into `dest` (a FRESH primary store root). Returns
    {objects, generation}. A destination that already holds a manifest
    refuses — restoring over a live store would interleave two worlds."""
    from .hummock import MANIFEST_PATH
    if dest.exists(MANIFEST_PATH):
        raise BackupCorruption(
            "restore destination already holds a MANIFEST — refusing to "
            "overwrite a live store")
    m = verify_backup(backup)
    if m is None:
        raise BackupCorruption(
            "backup has no BACKUP_MANIFEST ledger — cannot verify; "
            "use restore_store() to adopt an unverified legacy copy")
    last = _manifest_last()
    ordered = ([n for n in sorted(m["objects"]) if n not in last]
               + [n for n in last if n in m["objects"]])
    for n in ordered:
        dest.upload(n, backup.read(n))
    return {"objects": len(ordered), "generation": m["generation"]}


def restore_store(backup: ObjectStore):
    """Open a HummockStateStore over a backup (or a copy of it) — the
    catalog/DDL log restores through Session.recover() as usual. The
    backup verifies first when it carries a ledger (one written by any
    current `backup_objects` run); a legacy ledger-less copy opens
    unverified for compatibility. NOTE: this ADOPTS the backup directory
    as the live store (new checkpoints write into it); use
    `restore_objects` + a fresh primary for a true cold start that
    leaves the backup immutable."""
    verify_backup(backup)
    from .hummock import HummockStateStore
    return HummockStateStore(backup)

"""Backup / restore of a Hummock deployment (manifest + SSTs + catalog).

Reference: src/storage/backup/src/ (meta snapshot + SST manifest backup,
restored into a fresh cluster). Here a backup is an object-store-level
copy taken in dependency order — SSTs first, the MANIFEST and CATALOG
last — so the copied manifest can only reference SSTs that were already
copied (SST files are immutable once uploaded; the manifest swap is the
only mutation). Callers must quiesce compaction/sync for full
consistency; `Session.backup()` takes the coordinator's rounds lock to
guarantee it.
"""

from __future__ import annotations

from .object_store import ObjectStore


def _manifest_last() -> tuple:
    # imported, not re-hardcoded: a rename of either constant must keep
    # the copy-ordering guarantee intact
    from .hummock import MANIFEST_PATH
    from ..frontend.session import CATALOG_PATH
    return (MANIFEST_PATH, CATALOG_PATH)


def backup_objects(src: ObjectStore, dst: ObjectStore,
                   skip: tuple = ()) -> dict:
    """Copy every object from src to dst, manifest/catalog LAST (`skip`
    lets the caller substitute its own snapshot of a name, e.g. the
    catalog read under the rounds lock). Returns a summary manifest."""
    last = [n for n in _manifest_last() if n not in skip]
    names = src.list("")
    ordinary = [n for n in names if n not in last and n not in skip]
    copied = 0
    for n in ordinary:
        dst.upload(n, src.read(n))
        copied += 1
    for n in last:
        if src.exists(n):
            dst.upload(n, src.read(n))
            copied += 1
    return {"objects": copied}


def restore_store(backup: ObjectStore):
    """Open a HummockStateStore over a backup (or a copy of it) — the
    catalog/DDL log restores through Session.recover() as usual."""
    from .hummock import HummockStateStore
    return HummockStateStore(backup)

"""Backup / restore of a Hummock deployment (manifest + SSTs + catalog).

Reference: src/storage/backup/src/ (meta snapshot + SST manifest backup,
restored into a fresh cluster). A backup is an object-store-level copy
taken in dependency order — SSTs first, the MANIFEST and CATALOG last —
so the copied manifest can only reference SSTs that were already copied
(SST files are immutable once uploaded; the manifest swap is the only
mutation). Callers must quiesce compaction/sync for full consistency;
`Session.backup()` takes the coordinator's rounds lock to guarantee it.

The copy is **incremental and generation-stamped**: every run bumps a
backup generation and copies ONLY objects the destination does not
already hold at the recorded checksum (SST immutability means a
same-name same-crc object never needs recopying; mutable objects —
MANIFEST, CATALOG, the dict log head, DML jsonl tails — recopy when
their crc moved). Each copied object is read back from the destination
and verified before it enters the backup manifest, and every restore
re-verifies EVERY recorded object against its crc — a corrupted backup
refuses loudly (`BackupCorruption`) instead of cold-starting a wrong
world. Objects the source dropped since the previous generation
(compaction victims) are pruned from the destination only AFTER the new
backup manifest is durable, mirroring the manifest-swap-then-delete
rule of the store itself.

**Point-in-time restore** (format 3): the ledger additionally records
the live object set of each RETAINED generation (`generations`), and
bytes still referenced by a retained generation survive overwrite/prune
under content-addressed `archive/<crc>/<name>` copies — written before
the manifest that references them, garbage-collected strictly after.
`restore_objects(..., generation=n)` then materializes any retained
generation exactly (`RESTORE FROM <dir> AT GENERATION <n>`), and
`verify_backup` checks every archived byte range too. Auxiliary
sources (broker data directories) ride the same ledger under a name
prefix via `aux=` and extract with `extract_backup_prefix`.
"""

from __future__ import annotations

import json
import zlib
from typing import Optional

from .object_store import ObjectStore
from .sstable import frame_meta, unframe_meta, MetaCorruption

BACKUP_MANIFEST_PATH = "BACKUP_MANIFEST"
ARCHIVE_PREFIX = "archive/"
DEFAULT_KEEP_GENERATIONS = 8


def _archive_name(name: str, crc: int) -> str:
    """Content-addressed home of a superseded object's bytes: the crc in
    the path keeps distinct historical versions of one name apart."""
    return f"{ARCHIVE_PREFIX}{crc & 0xFFFFFFFF:08x}/{name}"


class BackupCorruption(Exception):
    """A backup object is missing or fails its recorded checksum — the
    restore (or verified read) refuses instead of serving it."""


def _manifest_last() -> tuple:
    # imported, not re-hardcoded: a rename of either constant must keep
    # the copy-ordering guarantee intact
    from .hummock import MANIFEST_PATH
    from ..frontend.session import CATALOG_PATH
    return (MANIFEST_PATH, CATALOG_PATH)


def load_backup_manifest(dst: ObjectStore) -> Optional[dict]:
    """The destination's backup manifest, or None for a fresh/legacy
    destination. A corrupt manifest raises — an incremental run must not
    silently trust (or silently discard) a damaged ledger."""
    if not dst.exists(BACKUP_MANIFEST_PATH):
        return None
    body = unframe_meta(dst.read(BACKUP_MANIFEST_PATH),
                        BACKUP_MANIFEST_PATH)
    m = json.loads(body)
    if m.get("format") not in (2, 3):
        raise BackupCorruption(
            f"unknown backup manifest format: {m.get('format')!r}")
    return m


def backup_objects(src: ObjectStore, dst: ObjectStore,
                   extra: Optional[dict] = None,
                   aux: Optional[dict] = None,
                   keep_generations: int = DEFAULT_KEEP_GENERATIONS) -> dict:
    """Incremental generation-stamped copy of every src object into dst
    (manifest/catalog last), each copy read back + checksum-verified
    before it is recorded. `extra` maps name -> bytes for caller-held
    snapshots written last (Session passes the CATALOG it read under the
    rounds lock); `aux` maps a name prefix -> ObjectStore for auxiliary
    data directories (broker segment roots) backed up under
    `<prefix>/...` in the same ledger. The last `keep_generations`
    generations stay point-in-time restorable: bytes a retained
    generation still references survive overwrite/prune as
    content-addressed `archive/` copies (written BEFORE the manifest
    that references them; unreferenced archives garbage-collect strictly
    AFTER). Returns the summary: generation, per-run copied / skipped
    counts and the total recorded object count."""
    from ..utils.metrics import (BACKUP_GENERATION, BACKUP_OBJECTS_COPIED,
                                 BACKUP_OBJECTS_SKIPPED)
    extra = dict(extra or {})
    prev = load_backup_manifest(dst)
    gen = (prev["generation"] + 1) if prev else 1
    entries: dict[str, dict] = dict(prev["objects"]) if prev else {}
    generations: dict[str, dict] = dict(prev.get("generations") or {}) \
        if prev else {}
    if prev is not None and prev.get("format") == 2 and not generations:
        # upgrading a format-2 ledger: its current object set IS its one
        # restorable generation — record it so the upgrade loses nothing
        generations[str(prev["generation"])] = {
            n: {"crc": e["crc"], "size": e["size"]}
            for n, e in entries.items()}
    last = [n for n in _manifest_last() if n not in extra]
    names = src.list("")
    # quarantined evidence is deliberately NOT backed up (it is the
    # corrupt bytes); the backup ledger itself never copies as data
    names = [n for n in names
             if not n.startswith("quarantine/")
             and n != BACKUP_MANIFEST_PATH]
    for prefix, store in sorted((aux or {}).items()):
        p = prefix.strip("/")
        names += [f"{p}/{n}" for n in store.list("")
                  if not n.endswith(".tmp")]
    aux_read = {prefix.strip("/"): store
                for prefix, store in (aux or {}).items()}

    def _src_read(name: str) -> bytes:
        for p, store in aux_read.items():
            if name.startswith(p + "/"):
                return store.read(name[len(p) + 1:])
        return src.read(name)

    ordinary = [n for n in names if n not in last and n not in extra]
    copied = skipped = archived = 0

    def _archive_put(name: str, want_crc: int) -> None:
        """Preserve dst's CURRENT bytes of `name` (recorded at
        `want_crc`) under the archive before they are overwritten or
        pruned — only when they still verify; corrupt bytes are not
        worth keeping and verify_backup flags the loss."""
        nonlocal archived
        arc = _archive_name(name, want_crc)
        if dst.exists(arc) or not dst.exists(name):
            return
        old = dst.read(name)
        if zlib.crc32(old) != want_crc:
            return
        dst.upload(arc, old)
        archived += 1

    def _put_verified(name: str, data: bytes) -> None:
        nonlocal copied, skipped
        crc = zlib.crc32(data)
        ent = entries.get(name)
        if ent is not None and ent["crc"] == crc and dst.exists(name):
            skipped += 1
            return
        if ent is not None and ent["crc"] != crc:
            _archive_put(name, ent["crc"])
        dst.upload(name, data)
        back = dst.read(name)          # read-back verify AT BACKUP TIME
        if zlib.crc32(back) != crc:
            raise BackupCorruption(
                f"backup copy of {name!r} failed read-back verification")
        entries[name] = {"crc": crc, "size": len(data), "generation": gen}
        copied += 1

    for n in ordinary:
        _put_verified(n, _src_read(n))
    for n in last:
        if src.exists(n):
            _put_verified(n, src.read(n))
    for n, data in extra.items():
        _put_verified(n, data)
    live = set(names) | set(extra) | {n for n in last if src.exists(n)}
    # stamp this generation's object set, then retain only the newest
    # `keep_generations` of them (the current one always survives)
    generations[str(gen)] = {
        n: {"crc": entries[n]["crc"], "size": entries[n]["size"]}
        for n in sorted(live) if n in entries}
    kept = sorted((int(g) for g in generations), reverse=True)
    kept = set(kept[:max(1, int(keep_generations))])
    generations = {g: objs for g, objs in generations.items()
                   if int(g) in kept}
    # prune ledger entries whose source object is gone (compacted away):
    # archive the ones older generations still pin, write the manifest,
    # THEN delete — a crash between the steps leaves harmless extra
    # objects, never a ledger entry pointing at nothing
    pruned = sorted(n for n in entries if n not in live)
    pruned_ent = {n: entries.pop(n) for n in pruned}
    # bytes a retained generation references but the (post-prune)
    # current object set no longer holds at that crc must live in the
    # archive
    needed_arc: set[str] = set()
    for objs in generations.values():
        for n, e in objs.items():
            cur = entries.get(n)
            if cur is None or cur["crc"] != e["crc"]:
                needed_arc.add(_archive_name(n, e["crc"]))
    for n in pruned:
        if _archive_name(n, pruned_ent[n]["crc"]) in needed_arc:
            _archive_put(n, pruned_ent[n]["crc"])
    arc_garbage = sorted(n for n in dst.list(ARCHIVE_PREFIX)
                         if n not in needed_arc)
    manifest = {"format": 3, "generation": gen, "objects": entries,
                "generations": generations}
    dst.upload(BACKUP_MANIFEST_PATH,
               frame_meta(json.dumps(manifest).encode()))
    for n in pruned:
        dst.delete(n)
    for n in arc_garbage:
        dst.delete(n)
    BACKUP_OBJECTS_COPIED.inc(copied)
    BACKUP_OBJECTS_SKIPPED.inc(skipped)
    BACKUP_GENERATION.set(float(gen))
    return {"objects": len(entries), "copied": copied,
            "skipped": skipped, "pruned": len(pruned),
            "archived": archived, "generations": sorted(kept),
            "generation": gen}


def verify_backup(backup: ObjectStore) -> Optional[dict]:
    """Verify EVERY recorded object against its checksum; raises
    BackupCorruption on the first missing/mismatched object. Returns the
    backup manifest (None for a legacy destination with no ledger —
    nothing to verify against, the caller decides whether to trust it)."""
    m = load_backup_manifest(backup)
    if m is None:
        return None
    for name, ent in sorted(m["objects"].items()):
        if not backup.exists(name):
            raise BackupCorruption(f"backup object {name!r} is missing")
        data = backup.read(name)
        if zlib.crc32(data) != ent["crc"]:
            raise BackupCorruption(
                f"backup object {name!r} fails its checksum "
                f"(generation {ent['generation']})")
    # every retained generation must be materializable: names the
    # current set no longer holds at the recorded crc must verify from
    # their archive copies
    checked: set[str] = set()
    for g, objs in sorted((m.get("generations") or {}).items()):
        for name, ent in sorted(objs.items()):
            cur = m["objects"].get(name)
            if cur is not None and cur["crc"] == ent["crc"]:
                continue                       # verified above
            arc = _archive_name(name, ent["crc"])
            if arc in checked:
                continue
            if not backup.exists(arc):
                raise BackupCorruption(
                    f"archived object {arc!r} (generation {g}) is "
                    f"missing")
            if zlib.crc32(backup.read(arc)) != ent["crc"]:
                raise BackupCorruption(
                    f"archived object {arc!r} fails its checksum "
                    f"(generation {g})")
            checked.add(arc)
    return m


def read_backup_object(backup: ObjectStore, name: str) -> Optional[bytes]:
    """Checksum-verified read of ONE backup object (the quarantine-repair
    path): None when the backup has no (intact) record of it."""
    try:
        m = load_backup_manifest(backup)
    except (BackupCorruption, MetaCorruption, ValueError):
        return None
    if m is None or name not in m["objects"] or not backup.exists(name):
        return None
    data = backup.read(name)
    if zlib.crc32(data) != m["objects"][name]["crc"]:
        return None
    return data


def _generation_objects(m: dict, generation: Optional[int]) -> dict:
    """name -> BACKUP-side source name for the chosen generation (the
    top-level object when its crc still matches, the archive copy
    otherwise). `generation=None` means the newest."""
    if generation is None or generation == m["generation"]:
        return {n: n for n in m["objects"]}
    gens = m.get("generations") or {}
    objs = gens.get(str(int(generation)))
    if objs is None:
        have = ", ".join(sorted(gens, key=int)) or "none"
        raise BackupCorruption(
            f"generation {generation} is not retained by this backup "
            f"(retained: {have})")
    out: dict[str, str] = {}
    for name, ent in objs.items():
        cur = m["objects"].get(name)
        out[name] = (name if cur is not None and cur["crc"] == ent["crc"]
                     else _archive_name(name, ent["crc"]))
    return out


def restore_objects(backup: ObjectStore, dest: ObjectStore,
                    generation: Optional[int] = None) -> dict:
    """Cold-start restore: verify the whole backup, then copy every
    object of the chosen generation (default: newest) into `dest` (a
    FRESH primary store root), resolving superseded bytes from the
    archive. Returns {objects, generation}. A destination that already
    holds a manifest refuses — restoring over a live store would
    interleave two worlds."""
    from .hummock import MANIFEST_PATH
    if dest.exists(MANIFEST_PATH):
        raise BackupCorruption(
            "restore destination already holds a MANIFEST — refusing to "
            "overwrite a live store")
    m = verify_backup(backup)
    if m is None:
        raise BackupCorruption(
            "backup has no BACKUP_MANIFEST ledger — cannot verify; "
            "use restore_store() to adopt an unverified legacy copy")
    sources = _generation_objects(m, generation)
    last = _manifest_last()
    ordered = ([n for n in sorted(sources) if n not in last]
               + [n for n in last if n in sources])
    for n in ordered:
        dest.upload(n, backup.read(sources[n]))
    return {"objects": len(ordered),
            "generation": m["generation"] if generation is None
            else int(generation)}


def extract_backup_prefix(backup: ObjectStore, prefix: str,
                          dest: ObjectStore,
                          generation: Optional[int] = None) -> int:
    """Materialize the backup's auxiliary namespace `prefix/` (a broker
    data directory) into `dest`, stripping the prefix — each object is
    checksum-verified before it lands. Returns the object count."""
    m = load_backup_manifest(backup)
    if m is None:
        raise BackupCorruption("backup has no BACKUP_MANIFEST ledger")
    sources = _generation_objects(m, generation)
    p = prefix.strip("/") + "/"
    count = 0
    for name in sorted(sources):
        if not name.startswith(p):
            continue
        data = backup.read(sources[name])
        want = (m["objects"][name]["crc"] if sources[name] == name
                else int(sources[name].split("/", 2)[1], 16))
        if zlib.crc32(data) != want:
            raise BackupCorruption(
                f"backup object {name!r} fails its checksum")
        dest.upload(name[len(p):], data)
        count += 1
    return count


def restore_store(backup: ObjectStore):
    """Open a HummockStateStore over a backup (or a copy of it) — the
    catalog/DDL log restores through Session.recover() as usual. The
    backup verifies first when it carries a ledger (one written by any
    current `backup_objects` run); a legacy ledger-less copy opens
    unverified for compatibility. NOTE: this ADOPTS the backup directory
    as the live store (new checkpoints write into it); use
    `restore_objects` + a fresh primary for a true cold start that
    leaves the backup immutable."""
    verify_backup(backup)
    from .hummock import HummockStateStore
    return HummockStateStore(backup)

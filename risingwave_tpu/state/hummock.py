"""Hummock-lite — the durable LSM state store behind checkpoints.

Reference: src/storage/src/hummock/ (shared buffer -> L0 SST upload on
`sync`, version manifest via meta, compaction; store.rs:172-257 and
docs/checkpoint.md:38-44). The shape kept here:

- `ingest_batch` stages writes in a per-epoch shared buffer (immediately
  readable — mem-table read-through semantics match MemoryStateStore).
- `sync(epoch)` seals every buffered epoch <= `epoch`, merges them into ONE
  sorted run, uploads it as an L0 SST to the object store, then atomically
  swaps the manifest (the version-commit step meta performs in the
  reference). Only after the manifest lands is the epoch committed — a crash
  at any point recovers to the last manifest, never a torn state.
- Reads merge: shared buffer (newest epoch wins) > L0 (newest SST wins) > L1.
- When L0 grows past a threshold, a full compaction merges L0+L1 into one
  bottom-level SST and drops tombstones (the reference's compactor collapsed
  to its essential effect).

Recovery: `HummockStateStore.open(object_store)` reads the manifest and
serves `get`/`iter_range` at the committed version; `committed_epoch()`
seeds the barrier coordinator's epoch floor.
"""

from __future__ import annotations

import json
from typing import Iterator, Optional

from .object_store import ObjectStore
from .sstable import SsTable, build_sstable
from .store import StateStore, WriteBatch, lazy_merge_ranges

MANIFEST_PATH = "MANIFEST"


def _sst_path(sst_id: int) -> str:
    return f"ssts/{sst_id:010d}.sst"


class HummockStateStore(StateStore):
    L0_COMPACT_THRESHOLD = 8

    def __init__(self, object_store: ObjectStore):
        self.objects = object_store
        # epoch -> {key: value|None}; dict order = staging order within epoch
        self._shared: dict[int, dict[bytes, Optional[bytes]]] = {}
        self._l0: list[SsTable] = []   # newest first
        self._l1: Optional[SsTable] = None
        self._next_sst_id = 1
        self._committed_epoch = 0
        if object_store.exists(MANIFEST_PATH):
            self._load_manifest()

    # ------------------------------------------------------------ manifest
    def _load_manifest(self) -> None:
        m = json.loads(self.objects.read(MANIFEST_PATH))
        assert m.get("format") == 1, f"unknown manifest format {m}"
        self._committed_epoch = m["committed_epoch"]
        self._next_sst_id = m["next_sst_id"]
        self._l0 = [SsTable.parse(i, self.objects.read(_sst_path(i)))
                    for i in m["l0"]]
        self._l1 = (SsTable.parse(m["l1"], self.objects.read(_sst_path(m["l1"])))
                    if m["l1"] is not None else None)

    def _write_manifest(self) -> None:
        m = {
            "format": 1,
            "committed_epoch": self._committed_epoch,
            "next_sst_id": self._next_sst_id,
            "l0": [t.sst_id for t in self._l0],
            "l1": self._l1.sst_id if self._l1 is not None else None,
        }
        self.objects.upload(MANIFEST_PATH, json.dumps(m).encode())

    # --------------------------------------------------------------- reads
    def get(self, key: bytes) -> Optional[bytes]:
        for epoch in sorted(self._shared, reverse=True):
            buf = self._shared[epoch]
            if key in buf:
                return buf[key]
        for sst in self._l0:
            found, v = sst.get(key)
            if found:
                return v
        if self._l1 is not None:
            found, v = self._l1.get(key)
            if found:
                return v
        return None

    def iter_range(self, start: bytes, end: bytes,
                   committed_only: bool = False,
                   max_epoch: Optional[int] = None
                   ) -> Iterator[tuple[bytes, bytes]]:
        """committed_only=True reads the COMMITTED snapshot (SSTs under the
        manifest), excluding the uncommitted shared buffer — the batch/
        serving read isolation (reference: StorageTable::batch_iter at a
        pinned snapshot epoch, batch_table/storage_table.rs:646).
        max_epoch additionally bounds which shared-buffer epochs are
        visible (SSTs are always <= the last sync, which is <= any
        in-flight barrier epoch, so only staged epochs need filtering)."""
        streams = []
        if not committed_only:
            for epoch in sorted(self._shared, reverse=True):  # newest first
                if max_epoch is not None and epoch > max_epoch:
                    continue
                buf = self._shared[epoch]
                streams.append(sorted(
                    (k, v) for k, v in buf.items()
                    if start <= k and (not end or k < end)))
        for sst in self._l0:                      # newest first
            streams.append(sst.iter_range(start, end))
        if self._l1 is not None:
            streams.append(self._l1.iter_range(start, end))
        yield from lazy_merge_ranges(streams)

    def committed_epoch(self) -> int:
        return self._committed_epoch

    def reset_uncommitted(self) -> None:
        """Drop the shared buffer — the recovery entry point (reference:
        recovery resumes at the last committed Hummock version; anything
        newer was never externally visible). A process restart gets this
        for free; an in-process restart (rescale, failover tests) must
        call it or stale uncommitted epochs would leak into new ones."""
        self._shared.clear()

    # -------------------------------------------------------------- writes
    def ingest_batch(self, batch: WriteBatch) -> None:
        self._shared.setdefault(batch.epoch, {}).update(batch.puts)

    def sync(self, epoch: int) -> dict:
        sealed = sorted(e for e in self._shared if e <= epoch)
        merged: dict[bytes, Optional[bytes]] = {}
        for e in sealed:                         # oldest -> newest overlay
            merged.update(self._shared[e])
        new_ids: list[int] = []
        if merged:
            sst_id = self._next_sst_id
            self._next_sst_id += 1
            data = build_sstable(epoch, sorted(merged.items()))
            # upload BEFORE dropping the shared-buffer epochs: an upload
            # failure must leave the staged writes intact so a retry (or
            # fail-stop replay) can still commit them — popping first would
            # let a later sync() silently commit a manifest missing them
            self.objects.upload(_sst_path(sst_id), data)
            self._l0.insert(0, SsTable.parse(sst_id, data))
            new_ids.append(sst_id)
        for e in sealed:
            del self._shared[e]
        self._committed_epoch = max(self._committed_epoch, epoch)
        obsolete: list[int] = []
        if len(self._l0) > self.L0_COMPACT_THRESHOLD:
            obsolete = self._compact()
        # manifest swap = the commit point; object deletes strictly after
        self._write_manifest()
        for sst_id in obsolete:
            self.objects.delete(_sst_path(sst_id))
        return {"uncommitted_ssts": new_ids}

    # ---------------------------------------------------------- compaction
    def _compact(self) -> list[int]:
        """Full merge of L1 + L0 into one bottom-level SST; tombstones are
        dropped (nothing lives below L1). Returns obsolete sst ids — the
        caller deletes them only after the new manifest is durable."""
        merged: dict[bytes, Optional[bytes]] = {}
        if self._l1 is not None:
            merged.update(zip(self._l1.keys, self._l1.vals))
        for sst in reversed(self._l0):
            merged.update(zip(sst.keys, sst.vals))
        live = sorted((k, v) for k, v in merged.items() if v is not None)
        obsolete = [t.sst_id for t in self._l0]
        if self._l1 is not None:
            obsolete.append(self._l1.sst_id)
        sst_id = self._next_sst_id
        self._next_sst_id += 1
        data = build_sstable(self._committed_epoch, live)
        self.objects.upload(_sst_path(sst_id), data)
        self._l1 = SsTable.parse(sst_id, data)
        self._l0 = []
        return obsolete

    # ------------------------------------------------------------- helpers
    @classmethod
    def open(cls, object_store: ObjectStore) -> "HummockStateStore":
        """Recovery entry: attach to whatever the last manifest committed."""
        return cls(object_store)

"""Hummock-lite — the durable LSM state store behind checkpoints.

Reference: src/storage/src/hummock/ (shared buffer -> L0 SST upload on
`sync`, version manifest via meta, compaction; store.rs:172-257 and
docs/checkpoint.md:38-44). The shape kept here:

- `ingest_batch` stages writes in a per-epoch shared buffer (immediately
  readable — mem-table read-through semantics match MemoryStateStore).
- The checkpoint pipeline is split into three phases (reference: the
  event-handler uploader, src/storage/src/hummock/event_handler/uploader/ —
  epochs seal at the barrier, SSTs build/upload in background tasks, and
  the version commit applies them strictly in epoch order):
    * `seal(epoch)`   — cheap: move every buffered epoch <= `epoch` into an
      immutable SealedBatch on the sealed queue (no merging, no encoding).
    * `upload_sealed(batch)` — slow, thread-safe: merge the batch into ONE
      sorted run, build the SST, PUT it to the object store. Touches only
      the immutable batch and the object store, so a background thread can
      run it while the stream keeps computing.
    * `commit_sealed(batch)` — the commit point: insert the SST into L0,
      maybe compact, atomically swap the manifest. Refuses out-of-order
      commits (`batch` must be the oldest sealed batch). Only after the
      manifest lands is the epoch committed — a crash at any point recovers
      to the last manifest, never a torn state.
  `sync(epoch)` remains the inline composition of the three (seal + drain
  the sealed queue in order) for tests and non-pipelined callers.
- Reads merge: shared buffer (newest epoch wins) > sealed-but-uncommitted
  batches (newest first) > L0 (newest SST wins) > L1. committed_only reads
  see neither staged nor sealed data.
- When L0 grows past a threshold, a full compaction merges L0+L1 into one
  bottom-level SST and drops tombstones (the reference's compactor collapsed
  to its essential effect).

Recovery: `HummockStateStore.open(object_store)` reads the manifest and
serves `get`/`iter_range` at the committed version; `committed_epoch()`
seeds the barrier coordinator's epoch floor.
"""

from __future__ import annotations

import json
from typing import Iterator, Optional

from .object_store import ObjectStore, ResilientObjectStore
from .sstable import (SsTable, SsTableCorruption, build_sstable,
                      frame_meta, unframe_meta)
from .store import StateStore, WriteBatch, lazy_merge_ranges

MANIFEST_PATH = "MANIFEST"
QUARANTINE_PREFIX = "quarantine/"


def _sst_path(sst_id: int) -> str:
    return f"ssts/{sst_id:010d}.sst"


class SealedBatch:
    """Immutable snapshot of shared-buffer epochs <= seal_epoch, queued for
    background upload. The per-epoch dicts are kept distinct (not merged)
    so reads and `max_epoch` filtering keep exact shared-buffer semantics
    until the commit lands; the merge happens in `upload_sealed`, off the
    barrier path. `sst_id` is allocated at seal time (on the event loop, so
    ids stay ordered even with uploads in flight); `data` is set by the
    upload phase and is what `commit_sealed` installs into L0."""

    __slots__ = ("seal_epoch", "epochs", "sst_id", "data")

    def __init__(self, seal_epoch: int,
                 epochs: dict[int, dict[bytes, Optional[bytes]]]):
        self.seal_epoch = seal_epoch
        self.epochs = epochs
        self.sst_id: Optional[int] = None
        self.data: Optional[bytes] = None

    @property
    def is_empty(self) -> bool:
        return not any(self.epochs.values())


class CompactionTask:
    """One planned background merge: a contiguous OLDEST tail of L0
    (optionally plus L1). Planned on the event loop (`plan_compaction`
    allocates the output id and snapshots the immutable input SsTables),
    merged + uploaded on a worker thread (`merge_compaction` — touches
    only the snapshot and the object store), installed back on the loop
    at a commit point (`install_compaction` — one manifest swap). A crash
    between merge and install leaves at worst an orphan output object
    that the scrubber sweeps."""

    __slots__ = ("run_ids", "ssts", "l1_id", "l1_sst", "into_l1",
                 "out_sst_id", "out_epoch", "input_bytes", "data",
                 "keys_in", "keys_out")

    def __init__(self, runs: list["SsTable"], l1: Optional["SsTable"],
                 into_l1: bool, out_sst_id: int):
        self.run_ids = [t.sst_id for t in runs]   # newest-first, as in _l0
        self.ssts = runs
        self.l1_sst = l1
        self.l1_id = l1.sst_id if l1 is not None else None
        self.into_l1 = into_l1                    # output becomes the bottom
        self.out_sst_id = out_sst_id
        self.out_epoch = max([t.epoch for t in runs]
                             + ([l1.epoch] if l1 is not None else []))
        self.input_bytes = sum(_sst_bytes(t) for t in runs) \
            + (_sst_bytes(l1) if l1 is not None else 0)
        self.data: Optional[bytes] = None
        self.keys_in = sum(len(t) for t in runs) \
            + (len(l1) if l1 is not None else 0)
        self.keys_out = 0

    @property
    def input_ids(self) -> list[int]:
        return self.run_ids + ([self.l1_id] if self.l1_id is not None
                               else [])


def _sst_bytes(sst: SsTable) -> int:
    return sum(len(k) for k in sst.keys) \
        + sum(len(v) for v in sst.vals if v is not None)


class HummockStateStore(StateStore):
    L0_COMPACT_THRESHOLD = 8

    def __init__(self, object_store: ObjectStore,
                 backup_store: Optional[ObjectStore] = None):
        super().__init__()
        # every backend rides the retry layer: transient PUT/GET faults
        # absorb below the recovery machinery (bounded backoff, per-op
        # deadline); persistent faults keep the fail-stop path
        self.objects = ResilientObjectStore.wrap(object_store)
        # read-path integrity (see _read_sst): durably-corrupt objects
        # are quarantined here (paths) and — when a backup store is
        # attached — restored from their verified backup copy instead of
        # crash-looping; /healthz reports `degraded` while non-empty.
        # Attaching the backup AT OPEN (ctor arg; SET backup_path covers
        # the running session) matters for the reopen-after-corruption
        # path: the manifest load below already reads every referenced
        # SST, so a bit-rotted object heals during open instead of
        # crash-looping the restart
        self.quarantined: list[str] = []
        self.restored_objects: list[str] = []
        self.backup_store: Optional[ObjectStore] = backup_store
        # epoch -> {key: value|None}; dict order = staging order within epoch
        self._shared: dict[int, dict[bytes, Optional[bytes]]] = {}
        # sealed-but-uncommitted batches, oldest first (the uploader queue)
        self._sealed: list[SealedBatch] = []
        self._l0: list[SsTable] = []   # newest first
        self._l1: Optional[SsTable] = None
        self._next_sst_id = 1
        self._committed_epoch = 0
        # Cluster mode (cluster/): compute-node handles share this object
        # store but NEVER own the manifest — the meta handle is the single
        # writer (reference: only meta commits Hummock versions). A
        # non-owner installs its own SSTs into its local L0 for
        # read-through, skips the manifest swap, and never compacts
        # (compaction rewrites + deletes objects the manifest references).
        self.manifest_owner = True
        # Non-owner handles retain every batch they sealed + uploaded
        # until META confirms the cluster commit (the `committed` push):
        # an epoch the dead worker never sealed can NEVER commit, and
        # without retention the survivors' share of that epoch would
        # have left the staged model (sealed, locally installed) while
        # the manifest never learns of it — silent durable loss on the
        # next crash. Per-worker partial recovery RESTAGES these into
        # the shared buffer so the next checkpoint re-seals them.
        self._unconfirmed: list[SealedBatch] = []
        # Inline compaction is the STANDALONE fallback (stores driven by
        # sync() with no coordinator). When a BackgroundCompactor attaches
        # it flips this off: the commit path then does O(1) work and the
        # compactor owns every merge (state/compactor.py).
        self.inline_compaction = True
        # Output sst ids of in-flight background merges: the scrubber's
        # orphan keep-set must cover them (the object exists before any
        # manifest references it).
        self.compaction_inflight: set[int] = set()
        if self.objects.exists(MANIFEST_PATH):
            self._load_manifest()

    def set_sst_id_block(self, base: int) -> None:
        """Give this handle a disjoint SST-id namespace (cluster compute
        nodes): ids allocated by concurrent worker handles over one shared
        object store must never collide, so meta hands each worker a
        high block per deployment generation."""
        self._next_sst_id = max(self._next_sst_id, base)

    # ------------------------------------------------------------ manifest
    def _load_manifest(self) -> None:
        m = json.loads(unframe_meta(self.objects.read(MANIFEST_PATH),
                                    MANIFEST_PATH))
        assert m.get("format") == 1, f"unknown manifest format {m}"
        self._committed_epoch = m["committed_epoch"]
        self._next_sst_id = m["next_sst_id"]
        self._l0 = [self._read_sst(i) for i in m["l0"]]
        self._l1 = (self._read_sst(m["l1"])
                    if m["l1"] is not None else None)

    # --------------------------------------------------- read-path integrity
    def _read_sst(self, sst_id: int) -> SsTable:
        """Checksum-verified SST read with the transient/durable split:
        a crc mismatch retries ONCE (torn page cache / transient media —
        the re-read observes the real bytes); a second mismatch is
        DURABLE corruption — the object is quarantined and restored from
        its verified backup copy when one is attached, instead of
        crash-looping the recovery engine against the same bad bytes."""
        path = _sst_path(sst_id)
        try:
            return SsTable.parse(sst_id, self.objects.read(path))
        except SsTableCorruption:
            from ..utils.metrics import STORAGE_CRC_RETRIES
            STORAGE_CRC_RETRIES.inc()
            try:
                return SsTable.parse(sst_id, self.objects.read(path))
            except SsTableCorruption:
                return SsTable.parse(
                    sst_id, self._quarantine_and_restore(path))

    def _quarantine_and_restore(self, path: str) -> bytes:
        """Durable corruption: park the bad bytes under quarantine/ (the
        post-mortem evidence — never served again), then restore the
        object from the attached backup's checksum-verified copy. No
        backup (or the backup lacks it): raise — named, loud, and
        exactly-once-preserving (fail-stop, never silent serving)."""
        from ..utils.metrics import STORAGE_QUARANTINED, STORAGE_RESTORED
        try:
            bad = self.objects.read(path)
            self.objects.upload(
                QUARANTINE_PREFIX + path.replace("/", "_"), bad)
        except Exception:  # noqa: BLE001 — quarantine is best-effort
            pass
        if path not in self.quarantined:
            self.quarantined.append(path)
        STORAGE_QUARANTINED.set(float(len(self.quarantined)))
        if self.backup_store is not None:
            from .backup import read_backup_object
            data = read_backup_object(self.backup_store, path)
            if data is not None:
                self.objects.upload(path, data)
                self.restored_objects.append(path)
                STORAGE_RESTORED.inc()
                return data
        raise SsTableCorruption(
            f"{path}: durable corruption (quarantined) and no verified "
            f"backup copy to restore from")

    def scrub_verify(self, path: str) -> bool:
        """One scrubber probe: read + integrity-check `path` without
        mutating any in-memory state. Returns True when the object
        verifies (possibly after the one transient re-read), False when
        it is durably corrupt — quarantined, and restored when a backup
        is attached (the False return still marks the pass degraded so
        the operator sees the incident)."""

        def _check() -> None:
            data = self.objects.read(path)
            if path.startswith("ssts/"):
                SsTable.parse(0, data)
            else:
                json.loads(unframe_meta(data, path))

        try:
            _check()
            return True
        except SsTableCorruption:
            from ..utils.metrics import STORAGE_CRC_RETRIES
            STORAGE_CRC_RETRIES.inc()
            try:
                _check()
                return True
            except SsTableCorruption:
                try:
                    self._quarantine_and_restore(path)
                except SsTableCorruption:
                    pass      # quarantined without a backup: stay degraded
                return False
        except Exception:  # noqa: BLE001 — read errors own the fail-stop
            return False

    def refresh_manifest(self) -> None:
        """Re-point this handle at the CURRENT committed manifest
        without reopening (per-worker partial recovery: a surviving
        compute node's manifest snapshot is from deploy time, so reads
        of the DEAD worker's committed rows — re-placed actors
        recovering their vnode ranges, source offsets — would otherwise
        see a stale, possibly empty view). Staged buffers, retained
        batches and the worker's disjoint SST-id block are untouched;
        the local L0/L1 are replaced by the manifest's (which includes
        every worker's committed SSTs — this worker's own confirmed
        installs are manifest-covered by definition)."""
        keep_next = self._next_sst_id
        if self.objects.exists(MANIFEST_PATH):
            self._load_manifest()
        self._next_sst_id = max(self._next_sst_id, keep_next)

    def _write_manifest(self) -> None:
        m = {
            "format": 1,
            "committed_epoch": self._committed_epoch,
            "next_sst_id": self._next_sst_id,
            "l0": [t.sst_id for t in self._l0],
            "l1": self._l1.sst_id if self._l1 is not None else None,
        }
        self.objects.upload(MANIFEST_PATH,
                            frame_meta(json.dumps(m).encode()))

    # --------------------------------------------------------------- reads
    def get(self, key: bytes) -> Optional[bytes]:
        for epoch in sorted(self._shared, reverse=True):
            buf = self._shared[epoch]
            if key in buf:
                return buf[key]
        for batch in reversed(self._sealed):          # newest batch first
            for epoch in sorted(batch.epochs, reverse=True):
                buf = batch.epochs[epoch]
                if key in buf:
                    return buf[key]
        for sst in self._l0:
            found, v = sst.get(key)
            if found:
                return v
        if self._l1 is not None:
            found, v = self._l1.get(key)
            if found:
                return v
        return None

    def get_committed(self, key: bytes) -> Optional[bytes]:
        """Point get at the COMMITTED snapshot (SSTs under the manifest
        only): the shared buffer and the sealed-but-uncommitted queue
        are invisible, exactly like `iter_range(committed_only=True)`.
        The log store reads its delivery cursor here — a cursor staged
        by an epoch whose commit never landed dies with the crash, and
        resuming from it would skip the epochs it covered."""
        for sst in self._l0:
            found, v = sst.get(key)
            if found:
                return v
        if self._l1 is not None:
            found, v = self._l1.get(key)
            if found:
                return v
        return None

    def iter_range(self, start: bytes, end: bytes,
                   committed_only: bool = False,
                   max_epoch: Optional[int] = None
                   ) -> Iterator[tuple[bytes, bytes]]:
        """committed_only=True reads the COMMITTED snapshot (SSTs under the
        manifest), excluding the uncommitted shared buffer — the batch/
        serving read isolation (reference: StorageTable::batch_iter at a
        pinned snapshot epoch, batch_table/storage_table.rs:646).
        max_epoch additionally bounds which shared-buffer epochs are
        visible (SSTs are always <= the last sync, which is <= any
        in-flight barrier epoch, so only staged epochs need filtering)."""
        streams = []
        if not committed_only:
            buffers = [(e, self._shared[e])
                       for e in sorted(self._shared, reverse=True)]
            for batch in reversed(self._sealed):  # sealed = still staged
                buffers.extend(
                    (e, batch.epochs[e])
                    for e in sorted(batch.epochs, reverse=True))
            for epoch, buf in buffers:            # newest first
                if max_epoch is not None and epoch > max_epoch:
                    continue
                streams.append(sorted(
                    (k, v) for k, v in buf.items()
                    if start <= k and (not end or k < end)))
        for sst in self._l0:                      # newest first
            streams.append(sst.iter_range(start, end))
        if self._l1 is not None:
            streams.append(self._l1.iter_range(start, end))
        yield from lazy_merge_ranges(streams)

    def committed_epoch(self) -> int:
        return self._committed_epoch

    def reset_uncommitted(self) -> None:
        """Drop the shared buffer AND the sealed-but-uncommitted queue —
        the recovery entry point (reference: recovery resumes at the last
        committed Hummock version; anything newer was never externally
        visible). A process restart gets this for free; an in-process
        restart (rescale, failover tests) must call it or stale
        uncommitted epochs would leak into new ones. The caller must have
        stopped the background uploader first (BarrierCoordinator.
        abort_uploads) — an in-flight upload can at worst leave an orphan
        SST, which no manifest references."""
        self._shared.clear()
        self._sealed.clear()
        self._deferred.clear()
        self._unconfirmed.clear()

    # ------------------------------------------- worker commit confirmation
    def confirm_committed(self, epoch: int) -> None:
        """Meta's `committed` notification reached this worker handle:
        every retained batch the cluster commit covered is durable in
        the shared manifest — drop it from the retention list."""
        self._unconfirmed = [b for b in self._unconfirmed
                             if b.seal_epoch > epoch]

    def restage_unconfirmed(self) -> None:
        """Per-worker partial recovery: move every sealed-but-never-
        confirmed batch BACK into the shared buffer under its original
        epochs, so the next checkpoint re-seals (and meta re-commits)
        the survivors' share of the aborted epochs. Their local-L0
        installs are REMOVED: a rebuilt actor recovers its state by
        reading this handle, and the uncommitted suffix must be visible
        through the staged buffer ONLY — where the recovery's
        discard_staged_tables can drop the rebuilt fragments' share
        before the exchange replay re-derives it (left in L0 it would
        double-apply). Restaged epochs are older keys, so the next
        `seal` sweeps them in exact overlay order."""
        drop_ids = {b.sst_id for b in self._unconfirmed
                    if b.sst_id is not None}
        if drop_ids:
            self._l0 = [t for t in self._l0 if t.sst_id not in drop_ids]
        for b in self._unconfirmed:
            for e in sorted(b.epochs):
                buf = self._shared.setdefault(e, {})
                # original staging order preserved; existing (newer)
                # staged writes for the same epoch overlay the restage
                merged = dict(b.epochs[e])
                merged.update(buf)
                self._shared[e] = merged
        self._unconfirmed = []

    # -------------------------------------------------------------- writes
    def ingest_batch(self, batch: WriteBatch) -> None:
        self._shared.setdefault(batch.epoch, {}).update(batch.puts)

    # ------------------------------------------------- seal/upload/commit
    def seal(self, epoch: int) -> SealedBatch:
        """Phase 1, cheap (at the barrier / on the event loop): move every
        shared-buffer epoch <= `epoch` into an immutable SealedBatch on the
        sealed queue. The batch stays readable (and retryable: the staged
        writes are not dropped until `commit_sealed`) — the generalization
        of the old upload-before-drop invariant to a queue of batches."""
        assert not self._sealed or epoch >= self._sealed[-1].seal_epoch, \
            f"seal epochs must be monotone ({epoch} after " \
            f"{self._sealed[-1].seal_epoch})"
        eps = sorted(e for e in self._shared if e <= epoch)
        batch = SealedBatch(epoch, {e: self._shared.pop(e) for e in eps})
        if not batch.is_empty:
            batch.sst_id = self._next_sst_id
            self._next_sst_id += 1
        self._sealed.append(batch)
        return batch

    def upload_sealed(self, batch: SealedBatch) -> None:
        """Phase 2, slow: merge + build + PUT the batch's SST. Thread-safe
        (touches only the immutable batch and the object store), so the
        background uploader runs it via asyncio.to_thread while the stream
        keeps computing. No store state mutates here; a failure or a crash
        mid-upload leaves at worst an orphan object no manifest references."""
        if batch.sst_id is None or batch.data is not None:
            return
        merged: dict[bytes, Optional[bytes]] = {}
        for e in sorted(batch.epochs):           # oldest -> newest overlay
            merged.update(batch.epochs[e])
        data = build_sstable(batch.seal_epoch, sorted(merged.items()))
        self.objects.upload(_sst_path(batch.sst_id), data)
        batch.data = data

    def commit_sealed(self, batch: SealedBatch) -> dict:
        """Phase 3, the commit point (event loop only): install the SST
        into L0, advance the committed epoch, maybe compact, atomically
        swap the manifest. STRICTLY in seal order — `batch` must be the
        oldest sealed batch, so a fast epoch N+1 upload can never publish
        a manifest missing epoch N."""
        assert self._sealed and self._sealed[0] is batch, (
            "manifest swaps must land in seal order (epoch "
            f"{batch.seal_epoch} is not the oldest sealed batch)")
        new_ids: list[int] = []
        if batch.sst_id is not None:
            assert batch.data is not None, \
                "commit_sealed before upload_sealed"
            self._l0.insert(0, SsTable.parse(batch.sst_id, batch.data))
            new_ids.append(batch.sst_id)
        self._sealed.pop(0)
        self._committed_epoch = max(self._committed_epoch, batch.seal_epoch)
        if not self.manifest_owner:
            # compute-node handle: the local L0 install above gives this
            # worker read-through to its own flushed state; the COMMIT
            # POINT (manifest swap) belongs to meta, which installs these
            # SSTs via commit_remote only after every worker reported
            # sealed. No compaction either — meta owns object lifetime.
            # Retain the batch until meta's `committed` notification:
            # see _unconfirmed in __init__ (worker partial recovery).
            self._unconfirmed.append(batch)
            return {"uncommitted_ssts": new_ids}
        obsolete: list[int] = []
        if self.inline_compaction \
                and len(self._l0) > self.L0_COMPACT_THRESHOLD:
            obsolete = self._compact()
        # manifest swap = the commit point; object deletes strictly after
        self._write_manifest()
        for sst_id in obsolete:
            self.objects.delete(_sst_path(sst_id))
        return {"uncommitted_ssts": new_ids}

    def commit_remote(self, epoch: int, sst_ids: list[int]) -> None:
        """Meta-side commit of a cluster checkpoint: install the SSTs
        every compute node uploaded for `epoch` (disjoint key ranges —
        the state is vnode-partitioned) into L0 and swap the manifest.
        Called strictly in epoch order by the coordinator's background
        committer, and ONLY after all workers reported sealed — the
        cluster generalization of `commit_sealed`'s commit point."""
        assert self.manifest_owner, "only the meta handle commits"
        assert epoch > self._committed_epoch, \
            f"cluster commit out of order ({epoch} <= {self._committed_epoch})"
        for sst_id in sst_ids:
            self._l0.insert(0, self._read_sst(sst_id))
        self._committed_epoch = epoch
        obsolete: list[int] = []
        if self.inline_compaction \
                and len(self._l0) > self.L0_COMPACT_THRESHOLD:
            obsolete = self._compact()
        self._write_manifest()
        for sst_id in obsolete:
            self.objects.delete(_sst_path(sst_id))

    def sync(self, epoch: int) -> dict:
        """Inline composition of the pipeline: run any deferred executor
        flushes, seal, then drain the sealed queue in order (uploading
        batches the background path has not gotten to). Tests and the
        non-pipelined coordinator mode call this; the pipelined path calls
        the phases directly."""
        self.run_deferred(epoch)
        self.seal(epoch)
        new_ids: list[int] = []
        while self._sealed and self._sealed[0].seal_epoch <= epoch:
            b = self._sealed[0]
            self.upload_sealed(b)
            new_ids.extend(self.commit_sealed(b)["uncommitted_ssts"])
        return {"uncommitted_ssts": new_ids}

    # ---------------------------------------------------------- compaction
    def _compact(self) -> list[int]:
        """Full merge of L1 + L0 into one bottom-level SST; tombstones are
        dropped (nothing lives below L1). Returns obsolete sst ids — the
        caller deletes them only after the new manifest is durable."""
        merged: dict[bytes, Optional[bytes]] = {}
        if self._l1 is not None:
            merged.update(zip(self._l1.keys, self._l1.vals))
        for sst in reversed(self._l0):
            merged.update(zip(sst.keys, sst.vals))
        live = sorted((k, v) for k, v in merged.items() if v is not None)
        obsolete = [t.sst_id for t in self._l0]
        if self._l1 is not None:
            obsolete.append(self._l1.sst_id)
        sst_id = self._next_sst_id
        self._next_sst_id += 1
        data = build_sstable(self._committed_epoch, live)
        self.objects.upload(_sst_path(sst_id), data)
        self._l1 = SsTable.parse(sst_id, data)
        self._l0 = []
        return obsolete

    # ------------------------------------- background compaction protocol
    def l0_run_count(self) -> int:
        return len(self._l0)

    def read_amp(self) -> int:
        """Sorted runs a point read may have to consult (L0 runs + L1)."""
        return len(self._l0) + (1 if self._l1 is not None else 0)

    def plan_compaction(self, floor_epoch: int, max_runs: int,
                        max_bytes: int) -> Optional[CompactionTask]:
        """Pick a bounded merge: the OLDEST contiguous tail of L0,
        size-tiered (stop once the byte budget is spent), restricted to
        runs at or below the pin floor — a run newer than the floor is
        never rewritten, so no version or tombstone a pinned reader
        could need is ever collapsed. When the selection covers all of
        L0 the existing L1 joins (budget permitting) and the output
        becomes the new bottom level, where tombstones drop; otherwise
        the output is an L0 run at the tail position and tombstones are
        carried (older runs below may still hold the key). Returns None
        when nothing is eligible. Event-loop only (allocates the output
        sst id and registers it with the scrubber keep-set)."""
        assert self.manifest_owner, "only the manifest owner compacts"
        eligible: list[SsTable] = []           # oldest-first
        spent = 0
        for sst in reversed(self._l0):
            if sst.epoch > floor_epoch:
                break
            size = _sst_bytes(sst)
            if eligible and (len(eligible) >= max_runs
                             or spent + size > max_bytes):
                break
            eligible.append(sst)
            spent += size
        if not eligible:
            return None
        covers_l0 = len(eligible) == len(self._l0)
        l1 = None
        if covers_l0 and self._l1 is not None \
                and spent + _sst_bytes(self._l1) <= max_bytes:
            l1 = self._l1
        into_l1 = covers_l0 and (l1 is not None or self._l1 is None)
        if len(eligible) < 2 and not into_l1:
            return None                        # a 1-run rewrite buys nothing
        runs = list(reversed(eligible))        # back to newest-first order
        task = CompactionTask(runs, l1, into_l1, self._next_sst_id)
        self._next_sst_id += 1
        self.compaction_inflight.add(task.out_sst_id)
        return task

    def merge_compaction(self, task: CompactionTask) -> None:
        """Thread-safe merge + build + PUT of a planned task: touches only
        the immutable input SsTables and the object store (the uploader
        discipline of `upload_sealed`). A crash here leaves an orphan
        output object no manifest references."""
        merged: dict[bytes, Optional[bytes]] = {}
        if task.l1_sst is not None:
            merged.update(zip(task.l1_sst.keys, task.l1_sst.vals))
        for sst in reversed(task.ssts):        # oldest -> newest overlay
            merged.update(zip(sst.keys, sst.vals))
        items = sorted((k, v) for k, v in merged.items()
                       if v is not None or not task.into_l1)
        task.keys_out = len(items)
        data = build_sstable(task.out_epoch, items)
        self.objects.upload(_sst_path(task.out_sst_id), data)
        task.data = data

    def install_compaction(self, task: CompactionTask) -> Optional[list[int]]:
        """Commit point of a background merge (event loop only): swap the
        merged output in for its inputs and write ONE manifest. Returns
        the obsolete sst ids (already deleted — strictly after the
        manifest landed), or None when the task no longer applies (the
        manifest was reloaded underneath it: restore, quarantine reopen).
        An abandoned output is an orphan the scrubber sweeps."""
        assert self.manifest_owner and task.data is not None
        k = len(task.run_ids)
        tail = [t.sst_id for t in self._l0[-k:]]
        l1_now = self._l1.sst_id if self._l1 is not None else None
        if tail != task.run_ids \
                or (task.l1_id is not None and l1_now != task.l1_id):
            self.abandon_compaction(task)
            return None
        out = SsTable.parse(task.out_sst_id, task.data)
        if task.into_l1:
            self._l1 = out
            self._l0 = self._l0[:-k]
        else:
            self._l0 = self._l0[:-k] + [out]
        self._write_manifest()
        self.compaction_inflight.discard(task.out_sst_id)
        obsolete = task.input_ids
        for sst_id in obsolete:
            self.objects.delete(_sst_path(sst_id))
        return obsolete

    def abandon_compaction(self, task: CompactionTask) -> None:
        """Drop a planned/merged task without installing it. The output
        object (if uploaded) is left as an orphan for the scrubber."""
        self.compaction_inflight.discard(task.out_sst_id)

    # ------------------------------------------------------------- helpers
    @classmethod
    def open(cls, object_store: ObjectStore) -> "HummockStateStore":
        """Recovery entry: attach to whatever the last manifest committed."""
        return cls(object_store)

"""SSTable — one immutable sorted run of (key, value | tombstone) entries.

Reference: src/storage/src/hummock/sstable/{builder.rs,mod.rs} — block-based
format with bloom filters and a footer. Here one checkpoint flush is a few
MB at most, so the format is a single self-checksummed block parsed whole on
open: entries are stored sorted, tombstones are explicit (a delete must mask
older versions in lower levels until bottom-level compaction drops it).

Layout (little-endian):
    magic "RWS1"
    u32 count | u64 epoch
    count * ( u32 klen | key | u32 vlen_or_TOMB | value )
    u32 crc32(everything after magic)
"""

from __future__ import annotations

import struct
import zlib
from bisect import bisect_left, bisect_right
from typing import Iterator, Optional, Sequence

MAGIC = b"RWS1"
META_MAGIC = b"RWM1"
TOMBSTONE = 0xFFFFFFFF


class SsTableCorruption(Exception):
    pass


class MetaCorruption(SsTableCorruption):
    """A framed meta object (MANIFEST/CATALOG/backup manifest) failed its
    checksum — same detection class as an SST, same quarantine rules."""


def frame_meta(body: bytes) -> bytes:
    """Self-checksummed framing for meta objects — the MANIFEST and
    CATALOG carry the same crc32 integrity envelope SSTs always had, so
    a torn or bit-rotted manifest is DETECTED at open instead of being
    json-decoded into a plausible-but-wrong world."""
    return META_MAGIC + body + struct.pack("<I", zlib.crc32(body))


def unframe_meta(data: bytes, name: str = "meta") -> bytes:
    """Verify + strip the meta frame. Unframed blobs pass through —
    stores written before the framing existed still open (their json
    layer keeps rejecting garbage, just without crc attribution)."""
    if data[:4] != META_MAGIC:
        return data
    body, (crc,) = data[4:-4], struct.unpack("<I", data[-4:])
    if zlib.crc32(body) != crc:
        raise MetaCorruption(f"{name}: checksum mismatch")
    return body


def build_sstable(epoch: int,
                  entries: Sequence[tuple[bytes, Optional[bytes]]]) -> bytes:
    """entries must be key-sorted and key-unique; value None = tombstone."""
    parts = [struct.pack("<IQ", len(entries), epoch)]
    prev = None
    for k, v in entries:
        assert prev is None or prev < k, "entries must be sorted+unique"
        prev = k
        parts.append(struct.pack("<I", len(k)))
        parts.append(k)
        if v is None:
            parts.append(struct.pack("<I", TOMBSTONE))
        else:
            parts.append(struct.pack("<I", len(v)))
            parts.append(v)
    body = b"".join(parts)
    return MAGIC + body + struct.pack("<I", zlib.crc32(body))


class SsTable:
    """Parsed SST: bisectable parallel key/value lists."""

    def __init__(self, sst_id: int, epoch: int, keys: list[bytes],
                 vals: list[Optional[bytes]]):
        self.sst_id = sst_id
        self.epoch = epoch
        self.keys = keys
        self.vals = vals

    @classmethod
    def parse(cls, sst_id: int, data: bytes) -> "SsTable":
        if data[:4] != MAGIC:
            raise SsTableCorruption(f"sst {sst_id}: bad magic")
        body, (crc,) = data[4:-4], struct.unpack("<I", data[-4:])
        if zlib.crc32(body) != crc:
            raise SsTableCorruption(f"sst {sst_id}: checksum mismatch")
        count, epoch = struct.unpack_from("<IQ", body, 0)
        off = 12
        keys: list[bytes] = []
        vals: list[Optional[bytes]] = []
        for _ in range(count):
            (klen,) = struct.unpack_from("<I", body, off)
            off += 4
            keys.append(body[off:off + klen])
            off += klen
            (vlen,) = struct.unpack_from("<I", body, off)
            off += 4
            if vlen == TOMBSTONE:
                vals.append(None)
            else:
                vals.append(body[off:off + vlen])
                off += vlen
        return cls(sst_id, epoch, keys, vals)

    def __len__(self) -> int:
        return len(self.keys)

    def get(self, key: bytes) -> tuple[bool, Optional[bytes]]:
        """(found, value) — found with value None means tombstone."""
        i = bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return True, self.vals[i]
        return False, None

    def iter_range(self, start: bytes, end: bytes
                   ) -> Iterator[tuple[bytes, Optional[bytes]]]:
        i = bisect_left(self.keys, start)
        j = bisect_right(self.keys, end) if end else len(self.keys)
        while i < j and (not end or self.keys[i] < end):
            yield self.keys[i], self.vals[i]
            i += 1

    @property
    def min_key(self) -> bytes:
        return self.keys[0] if self.keys else b""

    @property
    def max_key(self) -> bytes:
        return self.keys[-1] if self.keys else b""
